"""Multi-tenant serving front end: wire ingress -> admission -> cohorts.

One :class:`ServingFrontend` hosts several tenants (models) on one
mesh. Each tenant owns an independent bounded admission queue, credit
ledger, bucket ladder, staleness policy, and round counter — isolation
is per-tenant by construction — while a shared device lock serializes
the actual aggregation dispatches so cohorts from different models
interleave cleanly on the same chips (the Podracer pattern: thousands
of cheap producers, one accelerator consumer).

Client transport reuses the actor wire (``engine.actor.wire``)
verbatim: length-prefixed cloudpickle frames, HMAC-signed when
``BYZPY_TPU_WIRE_KEY`` is set, gradient payloads blockwise-compressed
when ``BYZPY_TPU_WIRE_PRECISION`` is ``bf16``/``int8``. A submission
frame is a dict::

    {"kind": "submit", "tenant": str, "client": str,
     "round": int, "gradient": np.ndarray (d,)}

answered by ``{"kind": "ack", "accepted": bool, "reason": str,
"round": int}``; ``{"kind": "stats", "tenant": str}`` returns the
tenant's accounting snapshot. The analytic per-frame ingress cost is
``parallel.comms.serving_ingress_bytes``.

The admission path (``submit``) is synchronous and cheap — shape gate,
staleness gate, token-bucket spend, bounded enqueue — so the asyncio
loop never blocks on it; aggregation runs through
``loop.run_in_executor`` to keep ingress responsive during a round's
device work.
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from ..engine.actor import wire
from ..observability import metrics as obs_metrics
from ..observability import runtime as obs_runtime
from ..observability import tracing as obs_tracing
from .buckets import BucketLadder
from .cohort import Cohort, CohortAggregator, build_cohort
from .credits import (
    ACCEPTED,
    REJECTED_FULL,
    REJECTED_RATE,
    REJECTED_SHAPE,
    REJECTED_STALE,
    REJECTED_TENANT,
    CreditLedger,
    CreditPolicy,
    RoundStats,
)
from .queue import AdmissionQueue, Submission
from .staleness import StalenessPolicy

#: Called after every closed round: ``(tenant_name, round_id, cohort,
#: aggregate)``. Keep it light — it runs on the scheduler task.
RoundCallback = Callable[[str, int, Cohort, Any], None]

#: A decoded (HMAC-valid) request whose fields are type-nonsense —
#: distinct from a forged frame (peer dropped) and from every admission
#: rejection (all of which name a well-formed submission).
REJECTED_MALFORMED = "rejected_malformed"

#: First 4 bytes of an HTTP GET — the ingress sniffs them where the
#: wire length prefix would sit and serves a Prometheus scrape instead.
_HTTP_GET_PREFIX = b"GET "
_HTTP_MAX_REQUEST = 8192


def _publish_wire_info() -> None:
    """Refresh the ``byzpy_wire_info`` marker gauge (wire precision +
    HMAC signing in effect) so exported metrics carry the parameters
    the ingress-bytes law needs; reflects the env at the last scrape."""
    precision = wire.wire_precision() or "off"
    signed = "1" if os.environ.get("BYZPY_TPU_WIRE_KEY") else "0"
    obs_metrics.registry().gauge(
        "byzpy_wire_info",
        help="wire precision/signing marker (value is always 1)",
        labels={"precision": precision, "signed": signed},
    ).set(1)


@dataclass(frozen=True)
class TenantConfig:
    """One model's serving parameters.

    ``dim`` is the flattened gradient length the tenant accepts (the
    shape gate at admission); ``window_s``/``cohort_cap`` the round
    close triggers; ``queue_capacity`` the admission bound;
    ``min_bucket`` the bottom of the power-of-two bucket ladder."""

    name: str
    aggregator: Any
    dim: int
    window_s: float = 0.02
    cohort_cap: int = 256
    min_cohort: int = 1
    min_bucket: int = 2
    queue_capacity: int = 1024
    credit: CreditPolicy = field(default_factory=CreditPolicy)
    staleness: StalenessPolicy = field(default_factory=StalenessPolicy)

    def __post_init__(self) -> None:
        if self.dim <= 0:
            raise ValueError("dim must be >= 1")
        if self.window_s <= 0:
            raise ValueError("window_s must be > 0")
        if self.cohort_cap <= 0:
            raise ValueError("cohort_cap must be >= 1")
        if not 1 <= self.min_cohort <= self.cohort_cap:
            raise ValueError(
                "min_cohort must satisfy 1 <= min_cohort <= cohort_cap "
                f"(got {self.min_cohort}/{self.cohort_cap}); the tenant "
                "raises it to the aggregator's smallest admissible n "
                "automatically (validate_n probe), so set it only to hold "
                "rounds open BEYOND that floor"
            )


class _TenantTelemetry:
    """One tenant's registry instruments, created ONCE at tenant
    construction so the per-submission path never pays a get-or-create
    lookup — hot paths touch these only behind the telemetry flag
    (``observability.runtime.STATE.enabled``). The instruments mirror
    the tenant's pre-existing stats dict (``ServingFrontend.stats()``
    stays the back-compat view); a Prometheus scrape of the TCP ingress
    renders them in exposition format."""

    __slots__ = (
        "labels", "outcomes", "rounds", "failed", "ingress_bytes",
        "submit_frames", "queue_depth", "outstanding", "latency_s",
        "cohort_m",
    )

    def __init__(self, name: str, dim: int) -> None:
        reg = obs_metrics.registry()
        self.labels = {"tenant": name}
        self.outcomes: Dict[str, obs_metrics.Counter] = {}
        for reason in (
            ACCEPTED, REJECTED_RATE, REJECTED_FULL, REJECTED_STALE,
            REJECTED_SHAPE, REJECTED_MALFORMED,
        ):
            self.outcomes[reason] = reg.counter(
                "byzpy_serving_submissions_total",
                help="serving admissions by outcome",
                labels={"tenant": name, "outcome": reason},
            )
        self.rounds = reg.counter(
            "byzpy_serving_rounds_total",
            help="closed serving rounds", labels=self.labels,
        )
        self.failed = reg.counter(
            "byzpy_serving_failed_rounds_total",
            help="crash-guarded (dropped) serving rounds", labels=self.labels,
        )
        self.ingress_bytes = reg.counter(
            "byzpy_serving_ingress_bytes_total",
            help="wire bytes of submit frames (length prefix included)",
            labels=self.labels,
        )
        self.submit_frames = reg.counter(
            "byzpy_serving_submit_frames_total",
            help="submit frames received on the TCP ingress",
            labels=self.labels,
        )
        self.queue_depth = reg.gauge(
            "byzpy_serving_queue_depth",
            help="admission queue depth", labels=self.labels,
        )
        self.outstanding = reg.gauge(
            "byzpy_serving_outstanding",
            help="admitted-but-not-aggregated submissions", labels=self.labels,
        )
        self.latency_s = reg.histogram(
            "byzpy_serving_round_latency_seconds",
            help="first-arrival-to-close latency of closed rounds",
            labels=self.labels,
        )
        self.cohort_m = reg.histogram(
            "byzpy_serving_cohort_size",
            help="closed-round cohort sizes", labels=self.labels,
            buckets=obs_metrics.SIZE_BUCKETS,
        )
        reg.gauge(
            "byzpy_serving_tenant_dim",
            help="tenant gradient dimension (for the ingress-bytes law)",
            labels=self.labels,
        ).set(dim)

    def outcome(self, reason: str) -> None:
        """Count one admission outcome (unknown reasons get their
        counter on first sight)."""
        c = self.outcomes.get(reason)
        if c is None:
            c = self.outcomes[reason] = obs_metrics.registry().counter(
                "byzpy_serving_submissions_total",
                help="serving admissions by outcome",
                labels={**self.labels, "outcome": reason},
            )
        c.inc()


class _Tenant:
    """Runtime state behind one :class:`TenantConfig`."""

    __slots__ = (
        "cfg", "queue", "ledger", "ladder", "executor", "stats",
        "round_id", "ingress_bytes", "last_aggregate", "min_cohort",
        "outstanding", "round_done", "failed_rounds",
        "last_cohort_clients", "held", "telemetry",
    )

    def __init__(self, cfg: TenantConfig) -> None:
        self.cfg = cfg
        self.queue = AdmissionQueue(cfg.queue_capacity)
        self.ledger = CreditLedger(cfg.credit)
        self.ladder = BucketLadder(cfg.cohort_cap, min_bucket=cfg.min_bucket)
        self.executor = CohortAggregator(cfg.aggregator, tenant=cfg.name)
        # effective round floor: the operator's min_cohort raised to the
        # aggregator's smallest admissible n (probed via validate_n), so
        # the out-of-the-box config can never close a cohort the crash
        # guard would have to discard — accepted submissions must
        # aggregate, not vanish as failed rounds
        floor = cfg.min_cohort
        probe = getattr(cfg.aggregator, "validate_n", None)
        if callable(probe):
            for m in range(1, cfg.cohort_cap + 1):
                try:
                    probe(m)
                except ValueError:
                    continue
                floor = max(floor, m)
                break
            else:
                raise ValueError(
                    f"aggregator {cfg.aggregator!r} admits no cohort size "
                    f"<= cohort_cap={cfg.cohort_cap}"
                )
        self.min_cohort = floor
        self.stats = RoundStats()
        self.round_id = 0
        self.ingress_bytes = 0
        self.last_aggregate: Any = None
        #: admitted-but-not-yet-aggregated submissions (drain watches it)
        self.outstanding = 0
        self.round_done = asyncio.Event()
        #: rounds dropped by the crash guard (inadmissible cohort, OOM…)
        self.failed_rounds = 0
        #: the most recent closed round's cohort membership — the public
        #: acceptance record adaptive clients may observe
        self.last_cohort_clients: Tuple[str, ...] = ()
        #: under-strength submissions held open by the SYNCHRONOUS round
        #: closer (:meth:`ServingFrontend.close_round_nowait`); the async
        #: scheduler keeps its own held list
        self.held: list = []
        self.telemetry = _TenantTelemetry(cfg.name, cfg.dim)


class ServingFrontend:
    """The serving tier's front door (see module docstring)."""

    def __init__(
        self,
        tenants: Sequence[TenantConfig],
        *,
        clock: Callable[[], float] = time.monotonic,
        on_round: Optional[RoundCallback] = None,
    ) -> None:
        if not tenants:
            raise ValueError("at least one tenant is required")
        self._tenants: Dict[str, _Tenant] = {}
        for cfg in tenants:
            if cfg.name in self._tenants:
                raise ValueError(f"duplicate tenant {cfg.name!r}")
            self._tenants[cfg.name] = _Tenant(cfg)
        self._clock = clock
        self._on_round = on_round
        self._device_lock: Optional[asyncio.Lock] = None
        self._tasks: list = []
        self._server: Optional[asyncio.AbstractServer] = None
        self._running = False
        #: frames that failed HMAC verification / deserialization (the
        #: peer is dropped; no tenant can be trusted off a forged frame)
        self.bad_frames = 0
        #: decoded-but-nonsense requests (bad field types from a buggy
        #: client): answered with ``rejected_malformed``, peer kept
        self.malformed_requests = 0
        #: exceptions swallowed from the user's ``on_round`` callback
        #: (an observer bug must not kill a tenant's scheduler)
        self.callback_errors = 0
        # frontend-global registry mirrors of the three counters above
        # (+ unknown-tenant rejections, which name no tenant) — created
        # once; incremented only behind the telemetry flag
        reg = obs_metrics.registry()
        self._m_bad_frames = reg.counter(
            "byzpy_serving_bad_frames_total",
            help="frames dropped at the ingress (HMAC/decode/oversize)",
        )
        self._m_malformed = reg.counter(
            "byzpy_serving_malformed_requests_total",
            help="decoded frames with nonsense fields (peer kept)",
        )
        self._m_callback_errors = reg.counter(
            "byzpy_serving_callback_errors_total",
            help="exceptions swallowed from on_round observers",
        )
        self._m_unknown_tenant = reg.counter(
            "byzpy_serving_unknown_tenant_total",
            help="submissions naming no configured tenant",
        )

    # -- admission (synchronous, cheap) ----------------------------------

    def submit(
        self,
        tenant: str,
        client: str,
        round_submitted: int,
        gradient: Any,
    ) -> Tuple[bool, str]:
        """Admit one submission: ``(accepted, reason)``.

        Gates, in order: tenant exists; gradient is a ``(dim,)`` float
        row (non-finite VALUES pass — adversarial payloads are the
        aggregators' job, shape abuse is the tier's); within the
        staleness cutoff; client has rate credit; queue has capacity."""
        t = self._tenants.get(tenant)
        if t is None:
            if obs_runtime.STATE.enabled:
                self._m_unknown_tenant.inc()
            return False, REJECTED_TENANT
        telemetry = obs_runtime.STATE.enabled
        now = self._clock()
        row = np.asarray(gradient)
        if row.ndim != 1 or row.shape[0] != t.cfg.dim or row.dtype.kind != "f":
            t.ledger.record(REJECTED_SHAPE, client)
            if telemetry:
                t.telemetry.outcome(REJECTED_SHAPE)
            return False, REJECTED_SHAPE
        delta = t.round_id - int(round_submitted)
        if not t.cfg.staleness.admits(delta):
            t.ledger.record(REJECTED_STALE, client)
            if telemetry:
                t.telemetry.outcome(REJECTED_STALE)
            return False, REJECTED_STALE
        if not t.ledger.admit(client, now):
            t.ledger.record(REJECTED_RATE, client)
            if telemetry:
                t.telemetry.outcome(REJECTED_RATE)
            return False, REJECTED_RATE
        ok = t.queue.offer(
            Submission(
                client=client,
                round_submitted=int(round_submitted),
                gradient=row,
                arrived_s=now,
            )
        )
        if not ok:
            t.ledger.record(REJECTED_FULL, client)
            if telemetry:
                t.telemetry.outcome(REJECTED_FULL)
            return False, REJECTED_FULL
        t.outstanding += 1
        t.ledger.record(ACCEPTED, client)
        if telemetry:
            t.telemetry.outcome(ACCEPTED)
            t.telemetry.queue_depth.set(t.queue.depth())
            t.telemetry.outstanding.set(t.outstanding)
        return True, ACCEPTED

    def handle_request(self, request: Any) -> dict:
        """Serve one decoded wire request (``submit``/``stats``).

        A frame that decodes (HMAC-valid) but carries nonsense fields —
        a non-numeric round, an unhashable tenant — is a buggy client,
        not a forged peer: it gets a ``rejected_malformed`` ack and the
        connection stays up, rather than an exception tearing down the
        handler with no accounting."""
        if not isinstance(request, dict):
            return {"kind": "ack", "accepted": False, "reason": "bad_frame"}
        kind = request.get("kind")
        if kind == "submit":
            tenant = request.get("tenant", "")
            try:
                with obs_tracing.span(
                    "serving.admission",
                    tenant=tenant if isinstance(tenant, str) else "?",
                ):
                    accepted, reason = self.submit(
                        tenant if isinstance(tenant, str) else "",
                        str(request.get("client", "")),
                        int(request.get("round", 0)),
                        request.get("gradient"),
                    )
            except Exception:  # noqa: BLE001 — client bug, not ours
                self.malformed_requests += 1
                if obs_runtime.STATE.enabled:
                    self._m_malformed.inc()
                return {
                    "kind": "ack",
                    "accepted": False,
                    "reason": REJECTED_MALFORMED,
                    "round": -1,
                }
            t = (
                self._tenants.get(tenant)
                if isinstance(tenant, str)
                else None
            )
            return {
                "kind": "ack",
                "accepted": accepted,
                "reason": reason,
                "round": t.round_id if t is not None else -1,
            }
        if kind == "stats":
            name = request.get("tenant", "")
            t = self._tenants.get(name) if isinstance(name, str) else None
            if t is not None:
                # snapshot ONLY the requested tenant: a stats poll runs
                # on the admission loop, and each snapshot sorts the
                # latency window + top-ks the rejection map
                return {"kind": "stats", "stats": self._tenant_stats(t)}
            return {"kind": "ack", "accepted": False, "reason": REJECTED_TENANT}
        return {"kind": "ack", "accepted": False, "reason": "bad_frame"}

    # -- scheduling ------------------------------------------------------

    async def start(self) -> None:
        """Launch one cohort-scheduler task per tenant."""
        if self._running:
            return
        self._running = True
        self._device_lock = asyncio.Lock()
        self._tasks = [
            asyncio.create_task(
                self._tenant_loop(t), name=f"serving-{name}"
            )
            for name, t in self._tenants.items()
        ]

    async def close(self) -> None:
        """Stop schedulers and the TCP server (idempotent)."""
        self._running = False
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks = []
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def _fail_round(self, t: _Tenant, cohort: Cohort) -> None:
        """Round-drop bookkeeping shared by both round closers: a
        poisoned cohort counts a ``failed_round`` and releases its
        outstanding rows — never silent, never fatal."""
        t.failed_rounds += 1
        t.outstanding -= cohort.m
        t.round_done.set()
        if obs_runtime.STATE.enabled:
            t.telemetry.failed.inc()
            t.telemetry.outstanding.set(t.outstanding)

    def _finish_round(self, t: _Tenant, cohort: Cohort, vec: Any) -> int:
        """Round-close bookkeeping shared by the async scheduler and
        :meth:`close_round_nowait` (ONE copy, so the async and
        virtual-time paths cannot drift): publish the aggregate and
        cohort membership, record telemetry, advance the round counter,
        release outstanding rows, fire the (crash-guarded) observer.
        Returns the closed round id."""
        t.last_aggregate = vec
        t.last_cohort_clients = cohort.clients
        latency_s = self._clock() - cohort.first_arrival_s
        t.stats.record(latency_s, cohort.m)
        closed = t.round_id
        t.round_id += 1
        t.outstanding -= cohort.m
        t.round_done.set()
        if obs_runtime.STATE.enabled:
            t.telemetry.rounds.inc()
            t.telemetry.latency_s.observe(latency_s)
            t.telemetry.cohort_m.observe(cohort.m)
            t.telemetry.queue_depth.set(t.queue.depth())
            t.telemetry.outstanding.set(t.outstanding)
        with obs_tracing.span(
            "serving.broadcast",
            track=f"tenant:{t.cfg.name}",
            tenant=t.cfg.name,
            round=closed,
        ):
            if self._on_round is not None:
                try:
                    self._on_round(t.cfg.name, closed, cohort, vec)
                except Exception:  # noqa: BLE001 — an observer bug must
                    # not kill the scheduler any more than a poisoned
                    # cohort may; counted, never silent
                    self.callback_errors += 1
                    if obs_runtime.STATE.enabled:
                        self._m_callback_errors.inc()
        return closed

    async def _tenant_loop(self, t: _Tenant) -> None:
        loop = asyncio.get_running_loop()
        # adopt anything a prior synchronous round closer parked in
        # t.held (sequential sync -> async handover): those rows were
        # admitted and count in `outstanding`, so abandoning them would
        # lose submissions and deadlock drain()
        held: list = list(t.held)
        t.held.clear()
        while self._running:
            more = await t.queue.collect(
                t.cfg.cohort_cap - len(held), t.cfg.window_s
            )
            held.extend(more)
            if len(held) < t.min_cohort:
                # under-strength window: hold the round open until the
                # cohort reaches the tenant's floor (the aggregator's
                # smallest admissible n) — the window restarts on the
                # next arrival
                continue
            subs, held = held, []
            track = f"tenant:{t.cfg.name}"
            with obs_tracing.span(
                "serving.round", track=track, tenant=t.cfg.name,
                round=t.round_id, m=len(subs),
            ) as round_span:
                with obs_tracing.span(
                    "serving.cohort_close", track=track,
                    round=t.round_id, m=len(subs),
                ):
                    cohort = build_cohort(
                        subs, t.round_id, t.ladder, t.cfg.staleness,
                        tenant=t.cfg.name,
                    )
                round_span.set(bucket=cohort.bucket)
                assert self._device_lock is not None
                try:
                    async with self._device_lock:
                        # device work off the event loop: ingress keeps
                        # admitting while this tenant's round aggregates
                        vec = await loop.run_in_executor(
                            None, t.executor.aggregate, cohort
                        )
                except Exception:  # noqa: BLE001 — a poisoned cohort must
                    # never kill the scheduler: drop the round, keep serving
                    self._fail_round(t, cohort)
                    continue
                self._finish_round(t, cohort, vec)

    async def drain(self, tenant: str) -> int:
        """Wait until every ADMISSIBLE submission of ``tenant`` has been
        aggregated (queued AND in-flight rounds); returns the tenant's
        round counter (test and shutdown helper).

        Leftovers below ``min_cohort`` are NOT waited for: they cannot
        form an admissible round until more arrive, so waiting on them
        would deadlock the caller against a window the scheduler is
        holding open on purpose — ``stats()``'s ``outstanding`` gauge
        still reports them (the scheduler may have already popped them
        off the queue into its held cohort, so ``queue_depth`` alone
        can read 0 while submissions are pending)."""
        t = self._tenants[tenant]
        while t.outstanding >= t.min_cohort:
            t.round_done.clear()
            await t.round_done.wait()
        return t.round_id

    # -- virtual-time round closing (chaos harness) ----------------------

    def close_round_nowait(self, tenant: str) -> Optional[Tuple[int, Any, Any]]:
        """Synchronously close one round of ``tenant`` from whatever is
        queued — the virtual-clock twin of the async scheduler, used by
        the chaos harness (``byzpy_tpu.chaos``) to replay the REAL
        admission + cohort + masked-aggregate path deterministically.

        Drains the admission queue into the tenant's held list; when the
        held cohort reaches the ``min_cohort`` floor, builds the padded
        cohort, aggregates it (crash-guarded exactly like the scheduler:
        a poisoned cohort counts a ``failed_round`` and is dropped), and
        advances the round counter. Returns ``(closed_round_id, cohort,
        aggregate)``, or ``None`` while the window stays open (or the
        round failed). One round closer per deployment: mixing with the
        async scheduler would split submissions across two held lists
        and double-drive the round counter, so a running scheduler is a
        checked error."""
        if self._tasks:
            raise RuntimeError(
                "close_round_nowait cannot run next to the async cohort "
                "scheduler (start() was called) — use one round closer"
            )
        t = self._tenants[tenant]
        t.held.extend(t.queue.drain_nowait(t.cfg.cohort_cap - len(t.held)))
        if len(t.held) < t.min_cohort:
            return None
        subs, t.held = t.held, []
        track = f"tenant:{t.cfg.name}"
        with obs_tracing.span(
            "serving.round", track=track, tenant=t.cfg.name,
            round=t.round_id, m=len(subs),
        ):
            with obs_tracing.span(
                "serving.cohort_close", track=track,
                round=t.round_id, m=len(subs),
            ):
                cohort = build_cohort(
                    subs, t.round_id, t.ladder, t.cfg.staleness,
                    tenant=t.cfg.name,
                )
            try:
                vec = t.executor.aggregate(cohort)
            except Exception:  # noqa: BLE001 — same contract as the scheduler
                self._fail_round(t, cohort)
                return None
            return self._finish_round(t, cohort, vec), cohort, vec

    def public_state(self, tenant: str) -> Any:
        """The tenant's public per-round feed, as any client —
        including an adaptive adversary — legitimately sees it: the
        broadcast aggregate, the round counter, and the last closed
        round's cohort membership (acceptance record). Per-client
        admission verdicts are NOT included: each client only ever
        learns its own ack reasons (returns a
        :class:`~byzpy_tpu.attacks.adaptive.PublicRoundState` with
        empty ``verdicts``; callers merge their own acks). Raises
        ``ValueError`` before the first round has closed — there is no
        broadcast yet for anyone to observe."""
        from ..attacks.adaptive import PublicRoundState

        t = self._tenants[tenant]
        if t.last_aggregate is None:
            raise ValueError(
                f"tenant {tenant!r} has not closed a round yet — "
                "there is no public state to observe"
            )
        return PublicRoundState(
            round_id=t.round_id - 1,
            aggregate=t.last_aggregate,
            accepted={cid: True for cid in t.last_cohort_clients},
            verdicts={},
            server_round=t.round_id,
        )

    # -- wire transport --------------------------------------------------

    async def serve(self, host: str = "127.0.0.1", port: int = 0) -> tuple:
        """Start the TCP ingress speaking actor wire frames; returns the
        bound ``(host, port)``. Call :meth:`start` first (or after —
        admission only needs the queues)."""
        wire.warn_untrusted_bind(host, "ServingFrontend")
        self._server = await asyncio.start_server(
            self._handle_conn, host=host, port=port
        )
        sock = self._server.sockets[0].getsockname()
        return sock[0], sock[1]

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                try:
                    header = await reader.readexactly(wire._HEADER.size)
                except asyncio.IncompleteReadError:
                    break
                if header == _HTTP_GET_PREFIX:
                    # the same TCP ingress doubles as the Prometheus
                    # scrape endpoint: a peer opening with "GET " is an
                    # HTTP scraper, not a wire client. As a length
                    # prefix those 4 bytes would name a ~1.2 GB frame —
                    # technically under MAX_FRAME, so this sniff does
                    # shadow that one exact frame size, but no serving
                    # client sends GB-scale control frames and before
                    # this branch such a peer just hung for 1.2 GB and
                    # was dropped as a bad frame
                    await self._serve_http_metrics(reader, writer)
                    break
                (length,) = wire._HEADER.unpack(header)
                if length > wire.MAX_FRAME:
                    # an oversized prefix is as hostile as a tampered
                    # frame — count it, never a silent drop
                    self._count_bad_frame()
                    break
                body = await reader.readexactly(length)
                try:
                    with obs_tracing.span(
                        "serving.ingress.decode", bytes=length
                    ):
                        request = wire.decode(body)
                except Exception:  # noqa: BLE001 — forged/tampered frame
                    # a frame that fails HMAC/unpickle names no trustable
                    # tenant; count it at the frontend and drop the peer
                    self._count_bad_frame()
                    break
                name = (
                    request.get("tenant")
                    if isinstance(request, dict)
                    else None
                )
                t = (
                    self._tenants.get(name)
                    if isinstance(name, str)
                    else None
                )
                # ingress accounting mirrors the serving_ingress_bytes
                # law, which prices SUBMISSION frames — stats polls
                # would skew the measured side
                if t is not None and request.get("kind") == "submit":
                    t.ingress_bytes += wire._HEADER.size + length
                    if obs_runtime.STATE.enabled:
                        t.telemetry.ingress_bytes.inc(wire._HEADER.size + length)
                        t.telemetry.submit_frames.inc()
                await wire.send_obj(writer, self.handle_request(request))
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:  # noqa: BLE001 — peer already gone
                pass

    def _count_bad_frame(self) -> None:
        self.bad_frames += 1
        if obs_runtime.STATE.enabled:
            self._m_bad_frames.inc()

    async def _serve_http_metrics(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """Answer one HTTP GET on the wire ingress with the process
        metrics registry in Prometheus text exposition format (0.0.4).
        The request is drained up to its blank line (bounded) so the
        scraper sees a clean close; rendering is an in-memory string
        build, safe on the admission loop."""
        data = b""
        while b"\r\n\r\n" not in data and len(data) < _HTTP_MAX_REQUEST:
            chunk = await reader.read(1024)
            if not chunk:
                break
            data += chunk
        _publish_wire_info()
        body = obs_metrics.registry().prometheus_text().encode()
        writer.write(
            b"HTTP/1.0 200 OK\r\n"
            b"Content-Type: text/plain; version=0.0.4; charset=utf-8\r\n"
            + f"Content-Length: {len(body)}\r\n".encode()
            + b"Connection: close\r\n\r\n"
            + body
        )
        await writer.drain()

    # -- introspection ---------------------------------------------------

    def round_of(self, tenant: str) -> int:
        """Current server round of ``tenant``."""
        return self._tenants[tenant].round_id

    def last_aggregate(self, tenant: str) -> Any:
        """Most recent round's aggregated vector (None before round 0)."""
        return self._tenants[tenant].last_aggregate

    def _tenant_stats(self, t: _Tenant) -> dict:
        p50, p99 = t.stats.latency_percentiles_s(50, 99)
        return {
            "rounds": t.stats.rounds,
            "round_id": t.round_id,
            "ledger": t.ledger.snapshot(),
            "queue_depth": t.queue.depth(),
            "queue_high_water": t.queue.depth_high_water,
            "queue_capacity": t.queue.capacity,
            "rejected_queue_full": t.queue.rejected_full,
            # the effective round floor (config min_cohort raised to the
            # aggregator's smallest admissible n)
            "min_cohort": t.min_cohort,
            # admitted but not yet aggregated — includes rows the
            # scheduler already popped into its held cohort, which
            # queue_depth no longer sees (min_cohort holds them there)
            "outstanding": t.outstanding,
            "p50_round_latency_s": p50,
            "p99_round_latency_s": p99,
            "mean_cohort": (
                float(np.mean(t.stats.cohort_sizes))
                if t.stats.cohort_sizes
                else 0.0
            ),
            "ingress_bytes": t.ingress_bytes,
            "failed_rounds": t.failed_rounds,
            # FRONTEND-GLOBAL counters (not per-tenant — a forged frame
            # names no trustable tenant): nested so a dashboard summing
            # tenant blocks doesn't double-count them
            "frontend": {
                "bad_frames": self.bad_frames,
                "malformed_requests": self.malformed_requests,
                "callback_errors": self.callback_errors,
            },
        }

    def stats(self) -> dict:
        """Per-tenant accounting: admission ledger, rounds, cohort and
        latency telemetry, queue depth high-water, outstanding gauge,
        ingress bytes."""
        return {
            name: self._tenant_stats(t) for name, t in self._tenants.items()
        }


def serve_frame(frontend: ServingFrontend, frame_body: bytes) -> bytes:
    """In-process wire path: decode one frame body, serve it, encode the
    reply — the exact codec/HMAC round the TCP ingress runs, minus the
    socket (the bench's 10k-client swarm exercises the wire cost this
    way without 10k TCP connections)."""
    reply = frontend.handle_request(wire.decode(frame_body))
    return wire.encode(reply)


class ServingClient:
    """Minimal asyncio client for the wire ingress (tests, examples,
    swarm simulators): one connection, frame-per-call submissions."""

    def __init__(self) -> None:
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None

    async def connect(self, host: str, port: int) -> None:
        """Open the connection."""
        self._reader, self._writer = await asyncio.open_connection(host, port)

    async def submit(
        self, tenant: str, client: str, round_submitted: int, gradient: Any
    ) -> dict:
        """Send one submission frame; returns the decoded ack."""
        assert self._writer is not None and self._reader is not None
        await wire.send_obj(
            self._writer,
            {
                "kind": "submit",
                "tenant": tenant,
                "client": client,
                "round": int(round_submitted),
                "gradient": np.asarray(gradient),
            },
        )
        return await wire.recv_obj(self._reader)

    async def stats(self, tenant: str) -> dict:
        """Fetch the tenant's stats snapshot."""
        assert self._writer is not None and self._reader is not None
        await wire.send_obj(self._writer, {"kind": "stats", "tenant": tenant})
        return await wire.recv_obj(self._reader)

    async def close(self) -> None:
        """Close the connection."""
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001 — server already gone
                pass
            self._writer = None
            self._reader = None


__all__ = [
    "RoundCallback",
    "ServingClient",
    "ServingFrontend",
    "TenantConfig",
    "serve_frame",
]

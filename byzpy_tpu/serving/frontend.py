"""Multi-tenant serving front end: wire ingress -> admission -> cohorts.

One :class:`ServingFrontend` hosts several tenants (models) on one
mesh. Each tenant owns an independent bounded admission queue, credit
ledger, bucket ladder, staleness policy, and round counter — isolation
is per-tenant by construction — while a shared device lock serializes
the actual aggregation dispatches so cohorts from different models
interleave cleanly on the same chips (the Podracer pattern: thousands
of cheap producers, one accelerator consumer).

Client transport reuses the actor wire (``engine.actor.wire``)
verbatim: length-prefixed cloudpickle frames, HMAC-signed when
``BYZPY_TPU_WIRE_KEY`` is set, gradient payloads blockwise-compressed
when ``BYZPY_TPU_WIRE_PRECISION`` is ``bf16``/``int8``. A submission
frame is a dict::

    {"kind": "submit", "tenant": str, "client": str,
     "round": int, "gradient": np.ndarray (d,), "seq": int | None}

answered by ``{"kind": "ack", "accepted": bool, "reason": str,
"round": int}``; ``{"kind": "stats", "tenant": str}`` returns the
tenant's accounting snapshot and ``{"kind": "close_round", "tenant":
str}`` drives the synchronous round closer (operator/drill door). The
optional ``seq`` is the per-client monotonic idempotency key — a
replayed ``(client, seq)`` acks accepted without re-folding. The
analytic per-frame ingress cost is
``parallel.comms.serving_ingress_bytes``.

Resilience (``byzpy_tpu.resilience``; docs/fault_tolerance.md): with a
``durability=`` config every accept is write-ahead logged before its
ack and tenants recover across SIGKILL via :meth:`ServingFrontend.
recover`; a per-tenant ``breaker=`` policy quarantines crash-looping
tenants; :class:`ServingClient` reconnects and resends under a
``RetryPolicy``.

The admission path (``submit``) is synchronous and cheap — shape gate,
staleness gate, token-bucket spend, bounded enqueue — so the asyncio
loop never blocks on it; aggregation runs through
``loop.run_in_executor`` to keep ingress responsive during a round's
device work.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis import sanitize
from ..engine.actor import wire
from ..forensics.evidence import evidence_digest
from ..forensics.plane import ForensicsConfig, ForensicsPlane
from ..observability import jitstats as obs_jitstats
from ..observability import metrics as obs_metrics
from ..observability import runtime as obs_runtime
from ..observability import tracing as obs_tracing
from ..resilience.breaker import BreakerPolicy, CircuitBreaker
from ..resilience.durable import DurabilityConfig, TenantDurability
from ..resilience.retry import RetryPolicy, connect_with_retry, retry_async
from ..utils.checkpoint import CheckpointNotFoundError
from .buckets import BucketLadder
from .cohort import Cohort, CohortAggregator, build_cohort
from .ragged import RaggedRuntime, RaggedView, ragged_enabled
from .credits import (
    ACCEPTED,
    REJECTED_FULL,
    REJECTED_RATE,
    REJECTED_SHAPE,
    REJECTED_STALE,
    REJECTED_TENANT,
    CreditLedger,
    CreditPolicy,
    RoundStats,
)
from .queue import AdmissionQueue, Submission
from .staleness import StalenessPolicy

#: Called after every closed round: ``(tenant_name, round_id, cohort,
#: aggregate)``. Keep it light — it runs on the scheduler task.
RoundCallback = Callable[[str, int, Cohort, Any], None]

#: A decoded (HMAC-valid) request whose fields are type-nonsense —
#: distinct from a forged frame (peer dropped) and from every admission
#: rejection (all of which name a well-formed submission).
REJECTED_MALFORMED = "rejected_malformed"

#: A replayed ``(client, seq)`` the tenant already accepted: answered
#: ``accepted=True`` (the retrying client must stop resending) but NOT
#: re-enqueued — the original copy folds exactly once.
DUPLICATE = "duplicate"

#: Tenant quarantined by its circuit breaker (consecutive failed
#: rounds): an explicit per-submission rejection, never a crash loop.
REJECTED_QUARANTINED = "rejected_quarantined"

#: The write-ahead append failed (disk full/unwritable): the ack could
#: not be made a durable promise, so the submission is refused outright
#: — retrying the SAME seq later is legitimate (nothing was enqueued).
REJECTED_UNDURABLE = "rejected_not_durable"

#: Client quarantined by the tenant's forensics trust ledger (opt-in
#: ``ForensicsConfig(quarantine=True)``): an explicit per-submission
#: rejection, WAL-recorded at the transition — never a silent drop.
REJECTED_UNTRUSTED = "rejected_untrusted"

_LOG = logging.getLogger("byzpy_tpu.serving")


#: 16-hex-char fingerprint of an aggregate's exact bits — what the WAL
#: round records carry, so recovery can prove digest continuity. ONE
#: rule, shared with the forensics evidence records: the audit's
#: evidence-vs-round cross-check depends on the two never drifting.
_agg_digest = evidence_digest

#: First 4 bytes of an HTTP GET — the ingress sniffs them where the
#: wire length prefix would sit and serves a Prometheus scrape instead.
_HTTP_GET_PREFIX = b"GET "

#: Pop-key a ``request_hook`` response sets truthy to force its reply
#: frame LOSSLESS (``wire.encode(..., precision="off")``) — replies
#: whose float bits are load-bearing (a shard's ``PartialFold`` rows)
#: must not ride a lossy ``BYZPY_TPU_WIRE_PRECISION`` fabric.
LOSSLESS_REPLY = "_lossless"
_HTTP_MAX_REQUEST = 8192

#: Socket read size of the batched ingress loop — large enough that one
#: event-loop wakeup drains many queued frames into one decode batch,
#: small enough to keep per-connection memory bounded.
_INGRESS_READ_CHUNK = 1 << 18


def _publish_wire_info() -> None:
    """Refresh the ``byzpy_wire_info`` marker gauge (wire precision +
    HMAC signing in effect) so exported metrics carry the parameters
    the ingress-bytes law needs; reflects the env at the last scrape."""
    precision = wire.wire_precision() or "off"
    signed = "1" if os.environ.get("BYZPY_TPU_WIRE_KEY") else "0"
    obs_metrics.registry().gauge(
        "byzpy_wire_info",
        help="wire precision/signing marker (value is always 1)",
        labels={"precision": precision, "signed": signed},
    ).set(1)


@dataclass(frozen=True)
class TenantConfig:
    """One model's serving parameters.

    ``dim`` is the flattened gradient length the tenant accepts (the
    shape gate at admission); ``window_s``/``cohort_cap`` the round
    close triggers; ``queue_capacity`` the admission bound;
    ``min_bucket`` the bottom of the power-of-two bucket ladder."""

    name: str
    aggregator: Any
    dim: int
    window_s: float = 0.02
    cohort_cap: int = 256
    min_cohort: int = 1
    min_bucket: int = 2
    queue_capacity: int = 1024
    credit: CreditPolicy = field(default_factory=CreditPolicy)
    staleness: StalenessPolicy = field(default_factory=StalenessPolicy)
    #: optional degraded-mode policy: ``threshold`` CONSECUTIVE failed
    #: rounds quarantine the tenant (queue drained with accounting, new
    #: submissions rejected with ``rejected_quarantined``) until a
    #: ``cooldown_s`` probe round succeeds. ``None`` = pre-existing
    #: behavior (failed rounds count, serving continues unconditionally).
    breaker: Optional[BreakerPolicy] = None
    #: optional per-client forensics plane (``byzpy_tpu.forensics``):
    #: every closed round yields an evidence record (features +
    #: aggregator score view + detector flags) feeding a trust ledger,
    #: Prometheus metrics, the WAL audit trail, and flight-recorder
    #: dumps. Host-side and bit-effect-free: round aggregates are
    #: digest-identical with this on or off. ``None`` = no forensics.
    forensics: Optional[ForensicsConfig] = None

    def __post_init__(self) -> None:
        if self.dim <= 0:
            raise ValueError("dim must be >= 1")
        if self.window_s <= 0:
            raise ValueError("window_s must be > 0")
        if self.cohort_cap <= 0:
            raise ValueError("cohort_cap must be >= 1")
        if not 1 <= self.min_cohort <= self.cohort_cap:
            raise ValueError(
                "min_cohort must satisfy 1 <= min_cohort <= cohort_cap "
                f"(got {self.min_cohort}/{self.cohort_cap}); the tenant "
                "raises it to the aggregator's smallest admissible n "
                "automatically (validate_n probe), so set it only to hold "
                "rounds open BEYOND that floor"
            )


class _TenantTelemetry:
    """One tenant's registry instruments, created ONCE at tenant
    construction so the per-submission path never pays a get-or-create
    lookup — hot paths touch these only behind the telemetry flag
    (``observability.runtime.STATE.enabled``). The instruments mirror
    the tenant's pre-existing stats dict (``ServingFrontend.stats()``
    stays the back-compat view); a Prometheus scrape of the TCP ingress
    renders them in exposition format."""

    __slots__ = (
        "labels", "outcomes", "rounds", "failed", "ingress_bytes",
        "submit_frames", "queue_depth", "outstanding", "latency_s",
        "cohort_m", "overlap_ratio",
    )

    def __init__(self, name: str, dim: int) -> None:
        reg = obs_metrics.registry()
        self.labels = {"tenant": name}
        self.outcomes: Dict[str, obs_metrics.Counter] = {}
        for reason in (
            ACCEPTED, REJECTED_RATE, REJECTED_FULL, REJECTED_STALE,
            REJECTED_SHAPE, REJECTED_MALFORMED,
        ):
            self.outcomes[reason] = reg.counter(
                "byzpy_serving_submissions_total",
                help="serving admissions by outcome",
                labels={"tenant": name, "outcome": reason},
            )
        self.rounds = reg.counter(
            "byzpy_serving_rounds_total",
            help="closed serving rounds", labels=self.labels,
        )
        self.failed = reg.counter(
            "byzpy_serving_failed_rounds_total",
            help="crash-guarded (dropped) serving rounds", labels=self.labels,
        )
        self.ingress_bytes = reg.counter(
            "byzpy_serving_ingress_bytes_total",
            help="wire bytes of submit frames (length prefix included)",
            labels=self.labels,
        )
        self.submit_frames = reg.counter(
            "byzpy_serving_submit_frames_total",
            help="submit frames received on the TCP ingress",
            labels=self.labels,
        )
        self.queue_depth = reg.gauge(
            "byzpy_serving_queue_depth",
            help="admission queue depth", labels=self.labels,
        )
        self.outstanding = reg.gauge(
            "byzpy_serving_outstanding",
            help="admitted-but-not-aggregated submissions", labels=self.labels,
        )
        self.latency_s = reg.histogram(
            "byzpy_serving_round_latency_seconds",
            help="first-arrival-to-close latency of closed rounds",
            labels=self.labels,
        )
        self.cohort_m = reg.histogram(
            "byzpy_serving_cohort_size",
            help="closed-round cohort sizes", labels=self.labels,
            buckets=obs_metrics.SIZE_BUCKETS,
        )
        self.overlap_ratio = reg.gauge(
            "byzpy_round_overlap_ratio",
            help="fraction of the previous round's fold+device time that "
                 "ran hidden under the next window's admission "
                 "(cross-round pipelining; 0 = fully serial)",
            labels=self.labels,
        )
        reg.gauge(
            "byzpy_serving_tenant_dim",
            help="tenant gradient dimension (for the ingress-bytes law)",
            labels=self.labels,
        ).set(dim)

    def outcome(self, reason: str) -> None:
        """Count one admission outcome (unknown reasons get their
        counter on first sight)."""
        c = self.outcomes.get(reason)
        if c is None:
            c = self.outcomes[reason] = obs_metrics.registry().counter(
                "byzpy_serving_submissions_total",
                help="serving admissions by outcome",
                labels={**self.labels, "outcome": reason},
            )
        c.inc()


class _Tenant:
    """Runtime state behind one :class:`TenantConfig`."""

    __slots__ = (
        "cfg", "queue", "ledger", "ladder", "executor", "stats",
        "round_id", "ingress_bytes", "last_aggregate", "min_cohort",
        "outstanding", "round_done", "failed_rounds",
        "last_cohort_clients", "held", "telemetry", "track",
        "seqs", "duplicates", "durability", "breaker", "next_wal_id",
        "quarantine_drops", "recovered", "forensics", "compile_site",
        "compile_warn_high", "ef_residual",
    )

    def __init__(
        self,
        cfg: TenantConfig,
        *,
        clock: Callable[[], float] = time.monotonic,
        track_prefix: str = "",
    ) -> None:
        self.cfg = cfg
        self.queue = AdmissionQueue(cfg.queue_capacity)
        self.ledger = CreditLedger(cfg.credit)
        self.ladder = BucketLadder(cfg.cohort_cap, min_bucket=cfg.min_bucket)
        #: telemetry track (trace row) this tenant's spans land on —
        #: shard-qualified (``shard:<i>/tenant:<name>``) when the
        #: frontend is one shard of the sharded tier, so a merged
        #: multi-shard trace keeps one lane per (shard, tenant)
        self.track = f"{track_prefix}tenant:{cfg.name}"
        self.executor = CohortAggregator(
            cfg.aggregator, tenant=cfg.name, track=self.track
        )
        # effective round floor: the operator's min_cohort raised to the
        # aggregator's smallest admissible n (probed via validate_n), so
        # the out-of-the-box config can never close a cohort the crash
        # guard would have to discard — accepted submissions must
        # aggregate, not vanish as failed rounds
        floor = cfg.min_cohort
        probe = getattr(cfg.aggregator, "validate_n", None)
        if callable(probe):
            for m in range(1, cfg.cohort_cap + 1):
                try:
                    probe(m)
                except ValueError:
                    continue
                floor = max(floor, m)
                break
            else:
                raise ValueError(
                    f"aggregator {cfg.aggregator!r} admits no cohort size "
                    f"<= cohort_cap={cfg.cohort_cap}"
                )
        self.min_cohort = floor
        self.stats = RoundStats()
        self.round_id = 0
        self.ingress_bytes = 0
        self.last_aggregate: Any = None
        #: admitted-but-not-yet-aggregated submissions (drain watches it)
        self.outstanding = 0
        self.round_done = asyncio.Event()
        #: rounds dropped by the crash guard (inadmissible cohort, OOM…)
        self.failed_rounds = 0
        #: the most recent closed round's cohort membership — the public
        #: acceptance record adaptive clients may observe
        self.last_cohort_clients: Tuple[str, ...] = ()
        #: under-strength submissions held open by the SYNCHRONOUS round
        #: closer (:meth:`ServingFrontend.close_round_nowait`); the async
        #: scheduler keeps its own held list
        self.held: list = []
        #: per-client highest ACCEPTED idempotency key (LRU-bounded like
        #: the credit ledger): a replayed ``(client, seq)`` at or below
        #: it is a duplicate — acked accepted, never re-folded
        self.seqs: "OrderedDict[str, int]" = OrderedDict()
        self.duplicates = 0
        #: write-ahead log + snapshots (attached by the frontend when a
        #: DurabilityConfig is given); ``next_wal_id`` is the per-tenant
        #: accept-record identity counter
        self.durability: Optional[TenantDurability] = None
        self.next_wal_id = 0
        self.breaker: Optional[CircuitBreaker] = (
            CircuitBreaker(cfg.breaker, clock=clock)
            if cfg.breaker is not None
            else None
        )
        #: queued submissions dropped (with accounting) when the breaker
        #: opened
        self.quarantine_drops = 0
        #: recovery provenance (``RecoveredTenant``), None on fresh start
        self.recovered: Any = None
        #: per-client forensics plane (None = not configured)
        self.forensics: Optional[ForensicsPlane] = (
            ForensicsPlane(cfg.name, cfg.forensics)
            if cfg.forensics is not None
            else None
        )
        #: compile-cache observability: the masked-aggregate dispatch
        #: site this tenant reports into, and the cache size already
        #: warned about (each NEW excess size warns once)
        self.compile_site = f"serving.masked_aggregate:{cfg.name}"
        self.compile_warn_high = 0
        #: downlink error-feedback residual (``(dim,)`` f32, lazily
        #: zeros on the first compressed broadcast): what the sub-int8
        #: broadcast fabric lost last round and re-injects this round
        #: (:meth:`ServingFrontend.broadcast_frame`). ROUND STATE —
        #: captured in durable snapshots; a WAL-tail recovery resets it
        #: to None, which is SAFE: any residual start point only shifts
        #: the telescoped stream by one round's bounded quantization
        #: error (pinned by the extended SIGKILL drill)
        self.ef_residual: Optional[np.ndarray] = None
        self.telemetry = _TenantTelemetry(cfg.name, cfg.dim)

    def note_seq(self, client: str, seq: int) -> None:
        """Record an accepted idempotency key (LRU-bounded)."""
        prev = self.seqs.get(client, -1)
        self.seqs[client] = max(prev, int(seq))
        self.seqs.move_to_end(client)
        if len(self.seqs) > self.cfg.credit.max_tracked_clients:
            self.seqs.popitem(last=False)

    def is_duplicate(self, client: str, seq: int) -> bool:
        return self.seqs.get(client, -1) >= int(seq)


class ServingFrontend:
    """The serving tier's front door (see module docstring)."""

    def __init__(
        self,
        tenants: Sequence[TenantConfig],
        *,
        clock: Callable[[], float] = time.monotonic,
        on_round: Optional[RoundCallback] = None,
        durability: Optional[DurabilityConfig] = None,
        shard: Optional[int] = None,
        pipeline_depth: int = 1,
    ) -> None:
        if not tenants:
            raise ValueError("at least one tenant is required")
        if pipeline_depth not in (0, 1):
            raise ValueError("pipeline_depth must be 0 or 1")
        #: cross-round pipelining depth for the async scheduler: 1
        #: (default) lets round N's fold + device step run on the
        #: executor while the NEXT window collects — the settle happens
        #: before the next cohort is built, so round ids, staleness
        #: judgments and aggregate bits are identical to the barrier
        #: path (depth 0). Ragged tenants always run barrier (their
        #: dispatch plane batches across tenants already).
        self.pipeline_depth = int(pipeline_depth)
        #: ingress-shard index when this frontend is one shard of a
        #: sharded tier (``serving.sharded``): stamps a ``shard`` dim
        #: onto the serving spans so a merged trace attributes
        #: admission/round work to the owning shard. None = the classic
        #: single-frontend deployment (no extra span arg).
        self.shard = shard
        self._shard_tag: Dict[str, Any] = (
            {} if shard is None else {"shard": int(shard)}
        )
        # shard-qualified telemetry tracks: every tenant row of a
        # sharded-tier frontend is named shard:<i>/tenant:<name>, so a
        # stitched multi-shard trace renders one lane per (shard,
        # tenant) instead of piling N shards onto one tenant row
        track_prefix = "" if shard is None else f"shard:{int(shard)}/"
        self._tenants: Dict[str, _Tenant] = {}
        for cfg in tenants:
            if cfg.name in self._tenants:
                raise ValueError(f"duplicate tenant {cfg.name!r}")
            self._tenants[cfg.name] = _Tenant(
                cfg, clock=clock, track_prefix=track_prefix
            )
        self._clock = clock
        self._on_round = on_round
        #: the ragged dispatch plane (``serving.ragged``): grouped
        #: one-compile-per-tenant executors + the cross-tenant batcher.
        #: ``BYZPY_TPU_RAGGED=0`` (read HERE, at construction) keeps
        #: every tenant on the bucket ladder; tenants whose aggregator
        #: has no masked program fall back to the ladder automatically.
        self._ragged: Optional[RaggedRuntime] = (
            RaggedRuntime(tenants) if ragged_enabled() else None
        )
        self._device_lock: Optional[asyncio.Lock] = None
        self._tasks: list = []
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self._running = False
        #: optional first-look request handler (see
        #: :meth:`handle_request`) — the process-per-shard runner
        #: mounts its shard control plane here
        self.request_hook: Optional[Callable[[dict], Optional[dict]]] = None
        #: request kinds the mounted hook PROMISES to pass through
        #: (return ``None`` for, with no side effects). The batched
        #: ingress only admits a run of submit frames in one pass when
        #: ``"submit"`` is declared here (the shard runner's control
        #: hook qualifies); otherwise every frame still routes through
        #: :meth:`handle_request` so the hook sees it first.
        self.request_hook_passthrough: frozenset = frozenset()
        self._durability = durability
        #: per-tenant recovery provenance (RecoveredTenant or None) —
        #: populated when a DurabilityConfig points at a directory with
        #: prior life; a fresh directory leaves every value None
        self.recovered: Dict[str, Any] = {}
        #: strong refs to in-flight off-loop snapshot saves
        self._snapshot_futs: list = []
        if durability is not None:
            for name, t in self._tenants.items():
                self._attach_durability(t, durability)
        #: frames that failed HMAC verification / deserialization (the
        #: peer is dropped; no tenant can be trusted off a forged frame)
        self.bad_frames = 0
        #: decoded-but-nonsense requests (bad field types from a buggy
        #: client): answered with ``rejected_malformed``, peer kept
        self.malformed_requests = 0
        #: exceptions swallowed from the user's ``on_round`` callback
        #: (an observer bug must not kill a tenant's scheduler)
        self.callback_errors = 0
        # frontend-global registry mirrors of the three counters above
        # (+ unknown-tenant rejections, which name no tenant) — created
        # once; incremented only behind the telemetry flag
        reg = obs_metrics.registry()
        self._m_bad_frames = reg.counter(
            "byzpy_serving_bad_frames_total",
            help="frames dropped at the ingress (HMAC/decode/oversize)",
        )
        self._m_malformed = reg.counter(
            "byzpy_serving_malformed_requests_total",
            help="decoded frames with nonsense fields (peer kept)",
        )
        self._m_callback_errors = reg.counter(
            "byzpy_serving_callback_errors_total",
            help="exceptions swallowed from on_round observers",
        )
        self._m_unknown_tenant = reg.counter(
            "byzpy_serving_unknown_tenant_total",
            help="submissions naming no configured tenant",
        )
        #: batched-door accounting: every :meth:`serve_frames` call is
        #: one batch (the TCP ingress passes everything a wakeup
        #: drained); ``ingress_max_batch > 1`` is the smoke test's
        #: proof that the door actually amortizes
        self.ingress_batches = 0
        self.ingress_frames_batched = 0
        self.ingress_max_batch = 0
        self._m_batch_size = reg.histogram(
            "byzpy_ingress_batch_size",
            help="frames decoded per ingress batch (serve_frames call)",
            buckets=obs_metrics.SIZE_BUCKETS,
        )

    # -- durability / recovery -------------------------------------------

    @classmethod
    def recover(
        cls,
        tenants: Sequence[TenantConfig],
        durability: DurabilityConfig,
        *,
        clock: Callable[[], float] = time.monotonic,
        on_round: Optional[RoundCallback] = None,
    ) -> "ServingFrontend":
        """Reconstruct a frontend from durable state: every tenant is
        restored from its latest VALID snapshot generation (corrupt ones
        fall back) plus write-ahead-log replay — round numbering resumes
        monotonically, accepted-but-unfolded submissions re-enter the
        queue, and the dedup table rejects stale ``(client, seq)``
        replays. Raises :class:`~byzpy_tpu.utils.checkpoint.
        CheckpointNotFoundError` when NO tenant has prior state (use the
        plain constructor for a maybe-fresh start: it recovers when
        state exists and starts clean when it doesn't)."""
        fe = cls(
            tenants, clock=clock, on_round=on_round, durability=durability
        )
        if not any(r is not None for r in fe.recovered.values()):
            raise CheckpointNotFoundError(
                f"no durable tenant state under {durability.directory} — "
                "nothing to recover"
            )
        return fe

    def _attach_durability(self, t: _Tenant, cfg: DurabilityConfig) -> None:
        t.durability = TenantDurability(cfg, t.cfg.name)
        rec = t.durability.recovered
        self.recovered[t.cfg.name] = rec
        if rec is None:
            return
        t.round_id = rec.round_id
        t.last_aggregate = rec.last_aggregate
        t.seqs = OrderedDict(rec.seqs)
        t.next_wal_id = rec.next_wal_id
        t.ledger.totals = dict(rec.ledger_totals)
        t.failed_rounds = rec.failed_rounds
        t.ingress_bytes = rec.ingress_bytes
        t.stats.rounds = rec.stats_rounds
        # downlink EF residual: bit-exact from the snapshot; rounds the
        # WAL replayed PAST the snapshot make it stale, which error
        # feedback self-corrects within one round's quantization bound
        # (safe-to-reset contract — see _Tenant.ef_residual)
        t.ef_residual = rec.ef_residual
        # accepted-before-death, never folded: back into the queue (the
        # arrival stamp is re-issued on THIS process's clock — monotonic
        # time does not survive a process boundary)
        now = self._clock()
        pending = [
            Submission(
                client=p["c"], round_submitted=int(p["r"]),
                gradient=p["g"], arrived_s=now,
                seq=p["q"], wal_id=int(p["w"]),
                # the ingress-measured pre-decode block ratio survives
                # the crash with its accept record: a shaped frame
                # admitted just before the kill still reaches the
                # residual_shaping detector when its replay folds
                wire_inflation=p.get("wi"),
            )
            for p in rec.pending
        ]
        t.queue.restore(pending)
        t.outstanding = len(pending)
        t.recovered = rec
        obs_metrics.registry().counter(
            "byzpy_recoveries_total",
            help="tenant recoveries from durable round state",
            labels={"tenant": t.cfg.name},
        ).inc()

    def _write_ahead(self, t: _Tenant, sub: Submission) -> None:
        """Append the accept record BEFORE the ack is returned — the ack
        must be a durable promise (module contract)."""
        assert t.durability is not None and sub.wal_id is not None
        t.durability.record_accept(
            sub.wal_id, sub.client, sub.seq, sub.round_submitted,
            sub.arrived_s, sub.gradient,
            wire_inflation=sub.wire_inflation,
        )

    def _maybe_snapshot(self, t: _Tenant) -> None:
        """Periodic durable snapshot: capture state synchronously (no
        awaits — consistent with the WAL rotation), persist off the
        event loop when one is running, inline otherwise. A save that
        never completes is safe: recovery falls back to the previous
        generation and replays one segment more."""
        d = t.durability
        if d is None or not d.snapshot_due():
            return
        state = {
            "round_id": t.round_id,
            "last_aggregate": (
                np.asarray(t.last_aggregate)
                if t.last_aggregate is not None
                else None
            ),
            "seqs": dict(t.seqs),
            "next_wal_id": t.next_wal_id,
            "ledger_totals": dict(t.ledger.totals),
            "failed_rounds": t.failed_rounds,
            "ingress_bytes": t.ingress_bytes,
            "stats_rounds": t.stats.rounds,
            "ef_residual": (
                None if t.ef_residual is None else np.asarray(t.ef_residual)
            ),
            "pending": [
                {
                    "w": s.wal_id, "c": s.client, "q": s.seq,
                    "r": s.round_submitted, "t": s.arrived_s,
                    "g": s.gradient, "wi": s.wire_inflation,
                }
                for s in (*t.queue.snapshot_items(), *t.held)
            ],
        }
        save = d.rotate_and_capture(t.round_id, state)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            save()
            return
        fut = loop.run_in_executor(None, save)
        self._snapshot_futs.append(fut)
        fut.add_done_callback(self._snapshot_done)

    def _snapshot_done(self, fut) -> None:
        try:
            self._snapshot_futs.remove(fut)
        except ValueError:  # pragma: no cover
            pass
        if not fut.cancelled() and fut.exception() is not None:
            # a failed snapshot is a degraded-durability event, not a
            # serving outage: the WAL still has everything
            obs_metrics.registry().counter(
                "byzpy_snapshot_failures_total",
                help="snapshot saves that raised (WAL still authoritative)",
            ).inc()

    def _quarantine_drain(self, t: _Tenant, opened: bool) -> None:
        """On a breaker OPEN transition, drain the admission queue with
        accounting: clients see rejections (and, with durability, the
        WAL records the drop) instead of acks destined for the floor."""
        if not opened:
            return
        dropped = t.queue.drain_nowait(t.queue.capacity + t.cfg.cohort_cap)
        if dropped:
            t.outstanding -= len(dropped)
            t.quarantine_drops += len(dropped)
            t.round_done.set()
            if t.durability is not None:
                t.durability.record_dropped(
                    t.round_id,
                    tuple(
                        s.wal_id for s in dropped if s.wal_id is not None
                    ),
                    "quarantine",
                )
        obs_metrics.registry().counter(
            "byzpy_serving_quarantines_total",
            help="circuit-breaker open transitions (tenant quarantined)",
            labels={"tenant": t.cfg.name},
        ).inc()

    # -- admission (synchronous, cheap) ----------------------------------

    def submit(
        self,
        tenant: str,
        client: str,
        round_submitted: int,
        gradient: Any,
        *,
        seq: Optional[int] = None,
        wire_inflation: Optional[float] = None,
        _now: Optional[float] = None,
    ) -> Tuple[bool, str]:
        """Admit one submission: ``(accepted, reason)``.

        Gates, in order: tenant exists; not a replayed idempotency key
        (a duplicate ``(client, seq)`` answers ``(True, "duplicate")``
        WITHOUT re-enqueuing — the original folds exactly once, so a
        client retrying an ack the wire lost cannot double-fold);
        tenant not quarantined by its circuit breaker; gradient is a
        ``(dim,)`` float row (non-finite VALUES pass — adversarial
        payloads are the aggregators' job, shape abuse is the tier's);
        within the staleness cutoff; client has rate credit; queue has
        capacity. With durability attached, the accept record hits the
        write-ahead log before this returns — the ack is a durable
        promise. ``seq`` keys must be per-client monotonic (the
        :class:`ServingClient` auto-assigns them); only definitively
        un-acked submissions should be retried under the same key.
        ``wire_inflation`` (stamped by the TCP ingress from the
        still-compressed frame) is the pre-decode block-inflation ratio
        the forensics plane's residual-shaping detector screens.

        ``gradient`` may arrive STILL COMPRESSED (a blockwise
        :class:`~byzpy_tpu.engine.actor.wire.QuantizedWireArray` kept
        by the batched ingress): the shape gate reads the codec's
        declared ``(dim,)`` float shape and the row stays codes+scales
        through the queue — dequantization happens in the fold (device-
        side on the ragged door, bit-identical host decode otherwise).
        ``_now`` lets the batched admission stamp one clock read across
        a drained batch (arrival order is preserved; the rows were all
        on the socket at the same wakeup)."""
        t = self._tenants.get(tenant)
        if t is None:
            if obs_runtime.STATE.enabled:
                self._m_unknown_tenant.inc()
            return False, REJECTED_TENANT
        telemetry = obs_runtime.STATE.enabled
        now = self._clock() if _now is None else _now
        if seq is not None and t.is_duplicate(client, seq):
            t.duplicates += 1
            t.ledger.record(DUPLICATE, client)
            if telemetry:
                t.telemetry.outcome(DUPLICATE)
            return True, DUPLICATE
        if t.breaker is not None and not t.breaker.allow():
            t.ledger.record(REJECTED_QUARANTINED, client)
            if telemetry:
                t.telemetry.outcome(REJECTED_QUARANTINED)
            return False, REJECTED_QUARANTINED
        if t.forensics is not None and not t.forensics.allows(
            client, t.round_id
        ):
            # per-CLIENT quarantine (trust ledger), distinct from the
            # breaker's per-TENANT quarantine above; the transition
            # itself is WAL-recorded at round close (never silent)
            t.ledger.record(REJECTED_UNTRUSTED, client)
            if telemetry:
                t.telemetry.outcome(REJECTED_UNTRUSTED)
            return False, REJECTED_UNTRUSTED
        if isinstance(gradient, wire.QuantizedWireArray):
            # still-compressed row: the codec's declared shape/dtype is
            # what the gate judges (the codes were already validated
            # against the honest-encoder layout at decode_batch time)
            row: Any = gradient
            if not (
                gradient.mode in wire.BLOCKWISE_WIRE_MODES
                and len(gradient.shape) == 1
                and int(gradient.shape[0]) == t.cfg.dim
                and np.dtype(gradient.dtype).kind == "f"
            ):
                t.ledger.record(REJECTED_SHAPE, client)
                if telemetry:
                    t.telemetry.outcome(REJECTED_SHAPE)
                return False, REJECTED_SHAPE
        else:
            row = np.asarray(gradient)
            if (
                row.ndim != 1
                or row.shape[0] != t.cfg.dim
                or row.dtype.kind != "f"
            ):
                t.ledger.record(REJECTED_SHAPE, client)
                if telemetry:
                    t.telemetry.outcome(REJECTED_SHAPE)
                return False, REJECTED_SHAPE
        delta = t.round_id - int(round_submitted)
        if not t.cfg.staleness.admits(delta):
            t.ledger.record(REJECTED_STALE, client)
            if telemetry:
                t.telemetry.outcome(REJECTED_STALE)
            return False, REJECTED_STALE
        rate_scale = (
            t.forensics.rate_scale(client) if t.forensics is not None else 1.0
        )
        if not t.ledger.admit(client, now, rate_scale=rate_scale):
            t.ledger.record(REJECTED_RATE, client)
            if telemetry:
                t.telemetry.outcome(REJECTED_RATE)
            return False, REJECTED_RATE
        sub = Submission(
            client=client,
            round_submitted=int(round_submitted),
            gradient=row,
            arrived_s=now,
            seq=None if seq is None else int(seq),
            wal_id=(t.next_wal_id if t.durability is not None else None),
            wire_inflation=(
                None if wire_inflation is None else float(wire_inflation)
            ),
        )
        if t.durability is not None:
            # capacity gate BEFORE the write-ahead append, so a row is
            # only ever logged if it will actually enqueue (a logged-
            # then-rejected row would resurrect on recovery); then the
            # append BEFORE the enqueue, so a row is only ever queued if
            # it is durable (an enqueued-but-unlogged row would fold
            # while its failed ack invites a replay — double fold).
            # Admission is single-threaded on the owning loop, so the
            # pre-check cannot race the offer below.
            if t.queue.depth() >= t.queue.capacity:
                t.queue.rejected_full += 1
                t.ledger.record(REJECTED_FULL, client)
                if telemetry:
                    t.telemetry.outcome(REJECTED_FULL)
                return False, REJECTED_FULL
            try:
                self._write_ahead(t, sub)
            except Exception:  # noqa: BLE001 — ENOSPC etc.: the ack
                # cannot be a durable promise, so refuse it outright
                # (nothing was enqueued; a retry under the same seq is
                # NOT a duplicate and may succeed once the disk heals)
                t.ledger.record(REJECTED_UNDURABLE, client)
                if telemetry:
                    t.telemetry.outcome(REJECTED_UNDURABLE)
                return False, REJECTED_UNDURABLE
            t.next_wal_id += 1
        ok = t.queue.offer(sub)
        if not ok:
            t.ledger.record(REJECTED_FULL, client)
            if telemetry:
                t.telemetry.outcome(REJECTED_FULL)
            return False, REJECTED_FULL
        if seq is not None:
            t.note_seq(client, seq)
        t.outstanding += 1
        t.ledger.record(ACCEPTED, client)
        if telemetry:
            t.telemetry.outcome(ACCEPTED)
            t.telemetry.queue_depth.set(t.queue.depth())
            t.telemetry.outstanding.set(t.outstanding)
        return True, ACCEPTED

    def handle_request(self, request: Any) -> dict:
        """Serve one decoded wire request (``submit``/``stats``).

        A frame that decodes (HMAC-valid) but carries nonsense fields —
        a non-numeric round, an unhashable tenant — is a buggy client,
        not a forged peer: it gets a ``rejected_malformed`` ack and the
        connection stays up, rather than an exception tearing down the
        handler with no accounting.

        ``request_hook`` (when set) sees every dict request FIRST and
        may claim it by returning a response dict (``None`` falls
        through to the built-in kinds) — the process-per-shard runner
        mounts its coordinator control plane (``shard_close``/
        ``confirm``/``requeue``/…) on the existing ingress this way,
        one port per shard for submissions and round control both. A
        hook response carrying ``LOSSLESS_REPLY: True`` is encoded with
        ``precision="off"`` (partial-fold rows must not ride a lossy
        ``BYZPY_TPU_WIRE_PRECISION`` fabric)."""
        if not isinstance(request, dict):
            return {"kind": "ack", "accepted": False, "reason": "bad_frame"}
        if self.request_hook is not None:
            try:
                hooked = self.request_hook(request)
            except Exception:  # noqa: BLE001 — a hook bug is a
                # malformed-op ack, never a torn-down connection
                self.malformed_requests += 1
                if obs_runtime.STATE.enabled:
                    self._m_malformed.inc()
                return {
                    "kind": "ack",
                    "accepted": False,
                    "reason": REJECTED_MALFORMED,
                }
            if hooked is not None:
                return hooked
        kind = request.get("kind")
        if kind == "submit":
            tenant = request.get("tenant", "")
            try:
                seq = request.get("seq")
                wi = request.get("_wire_inflation")
                with obs_tracing.span(
                    "serving.admission",
                    tenant=tenant if isinstance(tenant, str) else "?",
                    **self._shard_tag,
                ):
                    accepted, reason = self.submit(
                        tenant if isinstance(tenant, str) else "",
                        str(request.get("client", "")),
                        int(request.get("round", 0)),
                        request.get("gradient"),
                        seq=None if seq is None else int(seq),
                        wire_inflation=None if wi is None else float(wi),
                    )
            except Exception:  # noqa: BLE001 — client bug, not ours
                self.malformed_requests += 1
                if obs_runtime.STATE.enabled:
                    self._m_malformed.inc()
                return {
                    "kind": "ack",
                    "accepted": False,
                    "reason": REJECTED_MALFORMED,
                    "round": -1,
                }
            t = (
                self._tenants.get(tenant)
                if isinstance(tenant, str)
                else None
            )
            return {
                "kind": "ack",
                "accepted": accepted,
                "reason": reason,
                "round": t.round_id if t is not None else -1,
            }
        if kind == "stats":
            name = request.get("tenant", "")
            t = self._tenants.get(name) if isinstance(name, str) else None
            if t is not None:
                # snapshot ONLY the requested tenant: a stats poll runs
                # on the admission loop, and each snapshot sorts the
                # latency window + top-ks the rejection map
                return {"kind": "stats", "stats": self._tenant_stats(t)}
            return {"kind": "ack", "accepted": False, "reason": REJECTED_TENANT}
        if kind == "close_round":
            # operator/drill door: drive the synchronous round closer
            # over the wire — deterministic round boundaries for the
            # kill-and-recover drill and virtual-clock deployments. Same
            # exclusivity contract as close_round_nowait (errors if the
            # async scheduler owns the rounds).
            name = request.get("tenant", "")
            t = self._tenants.get(name) if isinstance(name, str) else None
            if t is None:
                return {
                    "kind": "ack", "accepted": False,
                    "reason": REJECTED_TENANT,
                }
            try:
                closed = self.close_round_nowait(name)
            except RuntimeError as exc:
                return {
                    "kind": "ack", "accepted": False,
                    "reason": f"close_round_unavailable: {exc}",
                }
            return {
                "kind": "round",
                "closed": None if closed is None else closed[0],
                "digest": None if closed is None else _agg_digest(closed[2]),
                "round": t.round_id,
            }
        return {"kind": "ack", "accepted": False, "reason": "bad_frame"}

    # -- batched ingress -------------------------------------------------

    def serve_frames(
        self, bodies: Sequence[Any]
    ) -> Tuple[List[bytes], int, Optional[BaseException]]:
        """Serve a BATCH of wire frame bodies (bytes or memoryviews,
        length prefixes stripped) through one decode pass — the batched
        front door shared by the TCP ingress (everything one wakeup
        drained) and :func:`serve_frame` (a batch of one).

        HMAC verification, codec decode, and the pre-decode block-
        inflation forensics run vectorized across the whole batch
        (:func:`wire.decode_batch`); quantized gradient rows stay
        codes+scales through admission (``keep_quantized``). Admission
        itself still walks every frame IN ARRIVAL ORDER — consecutive
        submit frames ride one clock read and one span through
        :meth:`_handle_submit_batch`, anything else (stats polls, hook
        control frames, close_round) flushes the run and routes through
        :meth:`handle_request` exactly as before — so acks, ledger
        outcomes, and WAL-before-ack semantics are bit-identical to
        serving the frames one at a time.

        Returns ``(replies, served, error)``: encoded reply frames for
        the ``served`` leading bodies, and the decode/HMAC failure that
        stopped the batch (``None`` when every frame served). Frames
        past a failure are NOT decoded or served — the TCP ingress
        drops the peer there, exactly like the per-frame path."""
        nb = len(bodies)
        self.ingress_batches += 1
        self.ingress_frames_batched += nb
        if nb > self.ingress_max_batch:
            self.ingress_max_batch = nb
        if obs_runtime.STATE.enabled:
            self._m_batch_size.observe(float(nb))
        # same span name as the historical per-frame door — dashboards
        # and the observability smoke key on it; `frames` says how much
        # one decode pass amortized
        with obs_tracing.span(
            "serving.ingress.decode",
            bytes=sum(len(b) for b in bodies), frames=nb,
        ):
            recs = wire.decode_batch(bodies, keep_quantized=True)
        batch_submits = (
            self.request_hook is None
            or "submit" in self.request_hook_passthrough
        )
        replies: List[bytes] = []
        error: Optional[BaseException] = None
        pending: List[Tuple[dict, int, Any]] = []
        telemetry = obs_runtime.STATE.enabled

        def flush() -> None:
            if pending:
                replies.extend(self._handle_submit_batch(pending))
                pending.clear()

        for i, rec in enumerate(recs):
            if rec.error is not None:
                # a frame that fails HMAC/unpickle names no trustable
                # tenant: counted HERE (shared by the TCP and in-process
                # doors), frames behind it not served
                self._count_bad_frame()
                error = rec.error
                break
            request = rec.obj
            if isinstance(request, dict):
                # the ingress is the ONLY author of this key: a client-
                # stamped value is discarded, then the measured pre-
                # decode ratio — when the frame carried a blockwise
                # payload — is stamped fresh (same rule as per-frame)
                request.pop("_wire_inflation", None)
                if rec.stats is not None and request.get("kind") == "submit":
                    request["_wire_inflation"] = rec.stats["max_inflation"]
                if batch_submits and request.get("kind") == "submit":
                    pending.append((request, len(bodies[i]), rec.trace_ctx))
                    continue
            flush()
            # non-submit (or hook-owned) frames keep the per-frame
            # contract exactly: hook first, built-in kinds after —
            # with the frame's own trace context adopted and ingress-
            # bytes accounting mirroring the per-frame read loop
            if telemetry and rec.trace_ctx is not None:
                obs_tracing.adopt_context(rec.trace_ctx)
            if (
                isinstance(request, dict)
                and request.get("kind") == "submit"
            ):
                self._account_submit_bytes(request, len(bodies[i]))
            replies.append(encode_reply(self.handle_request(request)))
        flush()
        return replies, len(replies), error

    def _account_submit_bytes(self, request: dict, length: int) -> None:
        """Ingress accounting for ONE submit frame — mirrors the
        serving_ingress_bytes law (submission frames only; stats polls
        would skew the measured side)."""
        name = request.get("tenant")
        t = self._tenants.get(name) if isinstance(name, str) else None
        if t is None:
            return
        t.ingress_bytes += wire._HEADER.size + length
        if obs_runtime.STATE.enabled:
            t.telemetry.ingress_bytes.inc(wire._HEADER.size + length)
            t.telemetry.submit_frames.inc()

    def _handle_submit_batch(
        self, items: Sequence[Tuple[dict, int, Any]]
    ) -> List[bytes]:
        """Admit a run of consecutive decoded submit frames in one
        pass: one clock read across the run (the frames were all on
        the socket at the same wakeup) and per-tenant ingress-byte
        counters bumped once per run instead of once per frame. Every
        frame still walks the FULL per-frame gate order (dedup →
        breaker → trust → shape → staleness → credit → WAL-before-ack
        → enqueue) in arrival order under its own ``serving.admission``
        span (child of the sending client's stamped context), with the
        same malformed-field guard as :meth:`handle_request` — acks
        are bit-identical to the per-frame door."""
        telemetry = obs_runtime.STATE.enabled
        now = self._clock()
        # bytes first (the per-frame loop counts a frame's bytes before
        # computing its ack), summed per tenant in one pass
        per_tenant: Dict[str, Tuple[int, int]] = {}
        for request, length, _ctx in items:
            name = request.get("tenant")
            if isinstance(name, str) and name in self._tenants:
                nbytes, frames = per_tenant.get(name, (0, 0))
                per_tenant[name] = (
                    nbytes + wire._HEADER.size + length, frames + 1
                )
        for name, (nbytes, frames) in per_tenant.items():
            t = self._tenants[name]
            t.ingress_bytes += nbytes
            if telemetry:
                t.telemetry.ingress_bytes.inc(nbytes)
                t.telemetry.submit_frames.inc(frames)
        replies: List[bytes] = []
        for request, _length, ctx in items:
            tenant = request.get("tenant", "")
            if telemetry and ctx is not None:
                obs_tracing.adopt_context(ctx)
            try:
                seq = request.get("seq")
                wi = request.get("_wire_inflation")
                with obs_tracing.span(
                    "serving.admission",
                    tenant=tenant if isinstance(tenant, str) else "?",
                    **self._shard_tag,
                ):
                    accepted, reason = self.submit(
                        tenant if isinstance(tenant, str) else "",
                        str(request.get("client", "")),
                        int(request.get("round", 0)),
                        request.get("gradient"),
                        seq=None if seq is None else int(seq),
                        wire_inflation=None if wi is None else float(wi),
                        _now=now,
                    )
            except Exception:  # noqa: BLE001 — client bug, not ours
                self.malformed_requests += 1
                if telemetry:
                    self._m_malformed.inc()
                replies.append(encode_reply({
                    "kind": "ack",
                    "accepted": False,
                    "reason": REJECTED_MALFORMED,
                    "round": -1,
                }))
                continue
            t = (
                self._tenants.get(tenant)
                if isinstance(tenant, str)
                else None
            )
            replies.append(encode_reply({
                "kind": "ack",
                "accepted": accepted,
                "reason": reason,
                "round": t.round_id if t is not None else -1,
            }))
        return replies

    # -- scheduling ------------------------------------------------------

    async def start(self) -> None:
        """Launch one cohort-scheduler task per tenant."""
        if self._running:
            return
        self._running = True
        self._device_lock = asyncio.Lock()
        if self._ragged is not None:
            await self._ragged.start(self._device_lock)
        self._tasks = [
            asyncio.create_task(
                self._tenant_loop(t), name=f"serving-{name}"
            )
            for name, t in self._tenants.items()
        ]

    async def close(self) -> None:
        """Stop schedulers and the TCP server (idempotent); settle any
        in-flight snapshot saves and close the WAL segments."""
        self._running = False
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks = []
        if self._ragged is not None:
            await self._ragged.close()
        if self._server is not None:
            self._server.close()
            # drop live ingress connections too: a closed frontend must
            # not keep admitting on old sockets (its WAL is about to
            # close, and clients must fail over to the recovered
            # process — same policy as RemoteActorServer.close)
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()
            self._server = None
        if self._snapshot_futs:
            await asyncio.gather(
                *list(self._snapshot_futs), return_exceptions=True
            )
        for t in self._tenants.values():
            if t.durability is not None:
                t.durability.close()

    def _fail_round(
        self, t: _Tenant, cohort: Cohort, subs: Sequence[Submission] = ()
    ) -> None:
        """Round-drop bookkeeping shared by both round closers: a
        poisoned cohort counts a ``failed_round`` and releases its
        outstanding rows — never silent, never fatal. With durability,
        the drop is WAL-recorded (recovery must not resurrect it); with
        a breaker, the failure counts toward quarantine and an OPEN
        transition drains the queue."""
        t.failed_rounds += 1
        t.outstanding -= cohort.m
        t.round_done.set()
        if t.durability is not None:
            t.durability.record_dropped(
                t.round_id,
                tuple(s.wal_id for s in subs if s.wal_id is not None),
                "failed_round",
            )
        if t.breaker is not None:
            self._quarantine_drain(t, t.breaker.record_failure())
        if obs_runtime.STATE.enabled:
            t.telemetry.failed.inc()
            t.telemetry.outstanding.set(t.outstanding)

    def _finish_round(
        self,
        t: _Tenant,
        cohort: Cohort,
        vec: Any,
        subs: Sequence[Submission] = (),
        forensics_prep: Optional[dict] = None,
    ) -> int:
        """Round-close bookkeeping shared by the async scheduler and
        :meth:`close_round_nowait` (ONE copy, so the async and
        virtual-time paths cannot drift): publish the aggregate and
        cohort membership, persist the round record (+ periodic
        snapshot) when durability is attached, record telemetry, advance
        the round counter, release outstanding rows, fire the
        (crash-guarded) observer. Returns the closed round id."""
        if t.durability is not None:
            t.durability.record_round(
                t.round_id,
                tuple(s.wal_id for s in subs if s.wal_id is not None),
                _agg_digest(vec),
                cohort.m,
            )
            t.durability.note_round_closed()
        if t.breaker is not None:
            t.breaker.record_success()
        if t.forensics is not None:
            self._observe_forensics(t, cohort, vec, subs, forensics_prep)
        self._note_compiles(t)
        t.last_aggregate = vec
        t.last_cohort_clients = cohort.clients
        latency_s = self._clock() - cohort.first_arrival_s
        t.stats.record(latency_s, cohort.m)
        closed = t.round_id
        t.round_id += 1
        t.outstanding -= cohort.m
        t.round_done.set()
        self._maybe_snapshot(t)
        if sanitize.enabled():
            # exactly-once fold audit: both close paths (async scheduler
            # and close_round_nowait) funnel through here, so a repeated
            # round id or a twice-folded idempotency key IS a double fold
            sanitize.audit_fold(
                t.cfg.name, closed, [(s.client, s.seq) for s in subs]
            )
        if obs_runtime.STATE.enabled:
            t.telemetry.rounds.inc()
            t.telemetry.latency_s.observe(latency_s)
            t.telemetry.cohort_m.observe(cohort.m)
            t.telemetry.queue_depth.set(t.queue.depth())
            t.telemetry.outstanding.set(t.outstanding)
        with obs_tracing.span(
            "serving.broadcast",
            track=t.track,
            tenant=t.cfg.name,
            round=closed,
        ):
            if self._on_round is not None:
                try:
                    self._on_round(t.cfg.name, closed, cohort, vec)
                except Exception:  # noqa: BLE001 — an observer bug must
                    # not kill the scheduler any more than a poisoned
                    # cohort may; counted, never silent
                    self.callback_errors += 1
                    if obs_runtime.STATE.enabled:
                        self._m_callback_errors.inc()
        return closed

    def _forensics_prepare(
        self,
        t: _Tenant,
        cohort: Cohort,
        vec: Any,
        subs: Sequence[Submission],
        precomputed: Optional[dict] = None,
    ) -> Optional[dict]:
        """The plane's HEAVY stage (features + the aggregator's score
        view) for one closed round — pure, so the async scheduler runs
        it on the fold executor, off the event loop (the O(m²·d) Krum
        score pass must not stall ingress any more than the fold
        itself would). On the ragged path ``precomputed`` carries the
        score view that rode the aggregation kernel
        (``RaggedView.precomputed``) and the host score pass is
        skipped entirely. Returns None on failure (counted)."""
        assert t.forensics is not None
        try:
            deltas = (
                [t.round_id - s.round_submitted for s in subs]
                if len(subs) == cohort.m
                else None
            )
            wire_inflations = (
                [s.wire_inflation for s in subs]
                if len(subs) == cohort.m
                else None
            )
            return t.forensics.prepare(
                t.round_id,
                cohort.matrix,
                cohort.valid,
                cohort.clients,
                vec,
                aggregator=t.executor.aggregator,
                weights=cohort.weights,
                deltas=deltas,
                bucket=cohort.bucket,
                precomputed=precomputed,
                wire_inflations=wire_inflations,
            )
        except Exception:  # noqa: BLE001 — attribution is an observer,
            # not a round participant
            self.callback_errors += 1
            if obs_runtime.STATE.enabled:
                self._m_callback_errors.inc()
            return None

    def _observe_forensics(
        self,
        t: _Tenant,
        cohort: Cohort,
        vec: Any,
        subs: Sequence[Submission],
        prep: Optional[dict] = None,
    ) -> None:
        """Feed one closed round to the tenant's forensics plane and
        persist the evidence + any quarantine/readmit transitions to
        the WAL (when durability is attached). Host-side work on data
        the round already produced — the aggregate bits are untouched,
        and a plane failure must never fail a round that already
        aggregated (crash-guarded, counted via callback_errors).
        ``prep`` is a precomputed :meth:`ForensicsPlane.prepare` result
        (the async scheduler computes it on the fold executor); without
        one the heavy stage runs inline (sync round closer)."""
        assert t.forensics is not None
        if prep is None:
            prep = self._forensics_prepare(t, cohort, vec, subs)
            if prep is None:
                return
        try:
            ev = t.forensics.apply(prep)
        except Exception:  # noqa: BLE001 — same stance as prepare
            self.callback_errors += 1
            if obs_runtime.STATE.enabled:
                self._m_callback_errors.inc()
            return
        # drain transitions unconditionally (they must not pile up when
        # durability is off); persist them when it is on. A failed
        # append RE-QUEUES the unpersisted transitions — they are
        # one-shot events the audit trail promises to carry, so the
        # next round's close retries them (the round's evidence record
        # itself is not retried: every round produces a fresh one)
        transitions = t.forensics.pop_transitions()
        if t.durability is None or not t.forensics.cfg.wal_evidence:
            return
        try:
            t.durability.record_evidence(t.round_id, ev.to_wire())
            while transitions:
                t.durability.record_evidence(t.round_id, transitions[0])
                transitions.pop(0)
        except Exception:  # noqa: BLE001 — degraded durability, not a
            # serving outage (same stance as snapshot failures)
            t.forensics.requeue_transitions(transitions)
            self.callback_errors += 1
            if obs_runtime.STATE.enabled:
                self._m_callback_errors.inc()

    def _note_compiles(self, t: _Tenant) -> None:
        """Compile-cache observability: report the tenant's
        masked-aggregate jit-cache size (``byzpy_jit_compiles_total``)
        and warn when it exceeds the bucket ladder's shape count — the
        ladder exists so every cohort lands in one of
        ``len(ladder.sizes)`` compiled programs; more entries means an
        unexpected recompile (shape/dtype drift), the silent latency
        cliff."""
        jitted = getattr(t.executor.aggregator, "_masked_jit_cache", None)
        if jitted is None:
            return
        try:
            size = int(jitted._cache_size())
        except Exception:  # noqa: BLE001 — introspection API drift must
            # never fail a round
            return
        obs_jitstats.note_cache_size(t.compile_site, size)
        expected = len(t.ladder.sizes)
        if size > expected and size > t.compile_warn_high:
            t.compile_warn_high = size
            obs_metrics.registry().counter(
                "byzpy_serving_recompile_warnings_total",
                help="masked-aggregate compiles beyond the bucket ladder",
                labels={"tenant": t.cfg.name},
            ).inc()
            _LOG.warning(
                "tenant %r: masked-aggregate jit cache has %d entries but "
                "the bucket ladder only has %d shapes — an unexpected "
                "recompile happened (cohort shape or dtype outside the "
                "ladder); every extra entry is a silent latency cliff",
                t.cfg.name, size, expected,
            )

    async def _tenant_loop(self, t: _Tenant) -> None:
        loop = asyncio.get_running_loop()
        ragged_served = (
            self._ragged is not None and self._ragged.serves(t.cfg.name)
        )
        # cross-round pipelining only applies to the in-process fold
        # path: ragged tenants hand their rounds to the shared dispatch
        # thread (which already overlaps tenants against each other), so
        # they stay on the barrier path regardless of pipeline_depth
        pipelined = self.pipeline_depth > 0 and not ragged_served
        # adopt anything a prior synchronous round closer parked in
        # t.held (sequential sync -> async handover): those rows were
        # admitted and count in `outstanding`, so abandoning them would
        # lose submissions and deadlock drain()
        held: list = list(t.held)
        t.held.clear()
        # the one in-flight (dispatched, unsettled) round when
        # pipelining: settled after the NEXT window's collect returns and
        # BEFORE its cohort is built, so round ids, staleness judgments
        # and aggregate bits are identical to the barrier path — only
        # the admission window overlaps the fold + device step
        pending: Optional[dict] = None

        async def settle() -> None:
            nonlocal pending
            if pending is None:
                return
            p, pending = pending, None
            wait_start = self._clock()
            try:
                vec, prep = await p["fut"]
            except Exception:  # noqa: BLE001 — poisoned cohort: drop
                # the round, keep serving (same contract as the barrier
                # path's crash guard)
                self._fail_round(t, p["cohort"], p["subs"])
                obs_tracing.end_span(p["span"])
                return
            # finish under the round's context so the broadcast span
            # stays a child of the (still-open) round span
            with obs_tracing.context_scope(
                getattr(p["span"], "context", None)
            ):
                self._finish_round(t, p["cohort"], vec, p["subs"], prep)
            obs_tracing.end_span(p["span"])
            done_s = p["done_s"] or wait_start
            span_s = done_s - p["kicked"]
            if obs_runtime.STATE.enabled and span_s > 0:
                hidden = max(0.0, min(done_s, wait_start) - p["kicked"])
                t.telemetry.overlap_ratio.set(
                    max(0.0, min(1.0, hidden / span_s))
                )

        while self._running:
            # stall watchdog: a gap far beyond the admission window
            # means a blocking call rode this loop (threshold generous —
            # collect legitimately waits the full window, folds overlap)
            sanitize.loop_tick(
                f"serving.tenant_loop.{t.cfg.name}",
                threshold_s=max(30.0, 10.0 * t.cfg.window_s),
            )
            more = await t.queue.collect(
                t.cfg.cohort_cap - len(held), t.cfg.window_s
            )
            held.extend(more)
            # settle the overlapped round FIRST: its _finish_round must
            # advance round_id and release outstanding rows before the
            # next cohort is built (bit-identity with the barrier path),
            # and it must settle even on an under-strength window so
            # drain() cannot hang on an already-folded round
            await settle()
            if len(held) < t.min_cohort:
                # under-strength window: hold the round open until the
                # cohort reaches the tenant's floor (the aggregator's
                # smallest admissible n) — the window restarts on the
                # next arrival
                continue
            subs, held = held, []
            track = t.track
            if pipelined:
                sp = obs_tracing.begin_span(
                    "serving.round", track=track, tenant=t.cfg.name,
                    round=t.round_id, m=len(subs), pipelined=True,
                    **self._shard_tag,
                )
                with obs_tracing.context_scope(
                    getattr(sp, "context", None)
                ):
                    with obs_tracing.span(
                        "serving.cohort_close", track=track,
                        round=t.round_id, m=len(subs),
                    ):
                        cohort = build_cohort(
                            subs, t.round_id, t.ladder,
                            t.cfg.staleness, tenant=t.cfg.name,
                            track=track,
                        )
                    sp.set(bucket=cohort.bucket)
                    assert self._device_lock is not None
                    # hold the device lock across the dispatch: other
                    # tenants' rounds queue behind this fold exactly as
                    # on the barrier path; released by the future's done
                    # callback (which runs on this loop)
                    await self._device_lock.acquire()
                    entry: dict = {
                        "subs": subs, "cohort": cohort, "span": sp,
                        "kicked": self._clock(), "done_s": None,
                    }

                    def fold_and_prepare(
                        subs=subs, cohort=cohort, entry=entry
                    ):
                        try:
                            v = t.executor.aggregate(cohort)
                            p = (
                                self._forensics_prepare(t, cohort, v, subs)
                                if t.forensics is not None
                                else None
                            )
                            return v, p
                        finally:
                            # fold-complete timestamp feeds the
                            # overlap-ratio gauge at settle
                            entry["done_s"] = self._clock()

                    fut = loop.run_in_executor(
                        None,
                        obs_tracing.carry_context(fold_and_prepare),
                    )
                    fut.add_done_callback(
                        lambda _f: self._device_lock.release()
                    )
                    entry["fut"] = fut
                    pending = entry
                continue
            with obs_tracing.span(
                "serving.round", track=track, tenant=t.cfg.name,
                round=t.round_id, m=len(subs), **self._shard_tag,
            ) as round_span:
                with obs_tracing.span(
                    "serving.cohort_close", track=track,
                    round=t.round_id, m=len(subs),
                ):
                    # ragged tenants pack at the EXACT cohort size (the
                    # compiled shape lives in the flat batch); ladder
                    # tenants pad to their bucket as before
                    # ragged rounds keep wire-quantized rows compressed
                    # (codes+scales) all the way into the fold — the
                    # executor dequantizes device-side
                    cohort = build_cohort(
                        subs, t.round_id,
                        None if ragged_served else t.ladder,
                        t.cfg.staleness, tenant=t.cfg.name, track=track,
                        quantized=ragged_served,
                    )
                round_span.set(bucket=cohort.bucket)
                assert self._device_lock is not None

                if ragged_served:
                    assert self._ragged is not None
                    try:
                        # ONE awaited hop: the batcher's dispatch thread
                        # gates finiteness, runs the ragged program (or
                        # the exact fallback for a non-finite cohort),
                        # and coalesces other tenants' pending cohorts
                        # into the same device call
                        view = await self._ragged.aggregate_async(
                            t.cfg.name, cohort, t.executor
                        )
                        prep = None
                        if t.forensics is not None:
                            # host features still run off-loop; the
                            # O(m²·d) score pass rode the kernel
                            prep = await loop.run_in_executor(
                                None,
                                obs_tracing.carry_context(
                                    lambda v=view, c=cohort, s=subs:
                                    self._forensics_prepare(
                                        t, c, v.vector, s,
                                        precomputed=v.precomputed(),
                                    )
                                ),
                            )
                    except Exception:  # noqa: BLE001 — poisoned
                        # batch/round: drop it, keep serving
                        self._fail_round(t, cohort, subs)
                        continue
                    self._finish_round(
                        t, cohort, view.vector, subs, prep
                    )
                    continue

                def fold_and_prepare(subs=subs, cohort=cohort):
                    # device work AND the forensics heavy stage (the
                    # O(m²·d) score pass) both off the event loop:
                    # ingress keeps admitting while this tenant's
                    # round aggregates and attributes
                    v = t.executor.aggregate(cohort)
                    p = (
                        self._forensics_prepare(t, cohort, v, subs)
                        if t.forensics is not None
                        else None
                    )
                    return v, p

                try:
                    async with self._device_lock:
                        # context carried across the executor hop: the
                        # fold/device-step spans stay children of this
                        # round's span, not orphan roots
                        vec, prep = await loop.run_in_executor(
                            None,
                            obs_tracing.carry_context(fold_and_prepare),
                        )
                except Exception:  # noqa: BLE001 — a poisoned cohort must
                    # never kill the scheduler: drop the round, keep serving
                    self._fail_round(t, cohort, subs)
                    continue
                self._finish_round(t, cohort, vec, subs, prep)
        # graceful stop (close() flips _running before cancelling): an
        # already-folded in-flight round is published, not lost
        await settle()

    async def drain(self, tenant: str) -> int:
        """Wait until every ADMISSIBLE submission of ``tenant`` has been
        aggregated (queued AND in-flight rounds); returns the tenant's
        round counter (test and shutdown helper).

        Leftovers below ``min_cohort`` are NOT waited for: they cannot
        form an admissible round until more arrive, so waiting on them
        would deadlock the caller against a window the scheduler is
        holding open on purpose — ``stats()``'s ``outstanding`` gauge
        still reports them (the scheduler may have already popped them
        off the queue into its held cohort, so ``queue_depth`` alone
        can read 0 while submissions are pending)."""
        t = self._tenants[tenant]
        while t.outstanding >= t.min_cohort:
            t.round_done.clear()
            await t.round_done.wait()
        return t.round_id

    # -- virtual-time round closing (chaos harness) ----------------------

    def close_round_nowait(self, tenant: str) -> Optional[Tuple[int, Any, Any]]:
        """Synchronously close one round of ``tenant`` from whatever is
        queued — the virtual-clock twin of the async scheduler, used by
        the chaos harness (``byzpy_tpu.chaos``) to replay the REAL
        admission + cohort + masked-aggregate path deterministically.

        Drains the admission queue into the tenant's held list; when the
        held cohort reaches the ``min_cohort`` floor, builds the padded
        cohort, aggregates it (crash-guarded exactly like the scheduler:
        a poisoned cohort counts a ``failed_round`` and is dropped), and
        advances the round counter. Returns ``(closed_round_id, cohort,
        aggregate)``, or ``None`` while the window stays open (or the
        round failed). One round closer per deployment: mixing with the
        async scheduler would split submissions across two held lists
        and double-drive the round counter, so a running scheduler is a
        checked error."""
        if self._tasks:
            raise RuntimeError(
                "close_round_nowait cannot run next to the async cohort "
                "scheduler (start() was called) — use one round closer"
            )
        t = self._tenants[tenant]
        t.held.extend(t.queue.drain_nowait(t.cfg.cohort_cap - len(t.held)))
        if len(t.held) < t.min_cohort:
            return None
        subs, t.held = t.held, []
        ragged_served = (
            self._ragged is not None and self._ragged.serves(t.cfg.name)
        )
        track = t.track
        with obs_tracing.span(
            "serving.round", track=track, tenant=t.cfg.name,
            round=t.round_id, m=len(subs), **self._shard_tag,
        ):
            with obs_tracing.span(
                "serving.cohort_close", track=track,
                round=t.round_id, m=len(subs),
            ):
                cohort = build_cohort(
                    subs, t.round_id,
                    None if ragged_served else t.ladder,
                    t.cfg.staleness, tenant=t.cfg.name, track=track,
                    quantized=ragged_served,
                )
            try:
                view: Optional[RaggedView] = None
                # cohort.finite() judges a quantized cohort from its
                # codes+scales without materializing host f32 rows —
                # exactly np.isfinite(cohort.matrix).all()
                if ragged_served and cohort.finite():
                    assert self._ragged is not None
                    view = self._ragged.aggregate_sync(t.cfg.name, cohort)
                if view is not None:
                    vec = view.vector
                    prep = (
                        self._forensics_prepare(
                            t, cohort, vec, subs,
                            precomputed=view.precomputed(),
                        )
                        if t.forensics is not None
                        else None
                    )
                else:
                    vec = t.executor.aggregate(cohort)
                    prep = None
            except Exception:  # noqa: BLE001 — same contract as the scheduler
                self._fail_round(t, cohort, subs)
                return None
            return (
                self._finish_round(t, cohort, vec, subs, prep), cohort, vec
            )

    def public_state(self, tenant: str) -> Any:
        """The tenant's public per-round feed, as any client —
        including an adaptive adversary — legitimately sees it: the
        broadcast aggregate, the round counter, and the last closed
        round's cohort membership (acceptance record). Per-client
        admission verdicts are NOT included: each client only ever
        learns its own ack reasons (returns a
        :class:`~byzpy_tpu.attacks.adaptive.PublicRoundState` with
        empty ``verdicts``; callers merge their own acks). Raises
        ``ValueError`` before the first round has closed — there is no
        broadcast yet for anyone to observe."""
        from ..attacks.adaptive import PublicRoundState

        t = self._tenants[tenant]
        if t.last_aggregate is None:
            raise ValueError(
                f"tenant {tenant!r} has not closed a round yet — "
                "there is no public state to observe"
            )
        return PublicRoundState(
            round_id=t.round_id - 1,
            aggregate=t.last_aggregate,
            accepted={cid: True for cid in t.last_cohort_clients},
            verdicts={},
            server_round=t.round_id,
        )

    # -- wire transport --------------------------------------------------

    async def serve(self, host: str = "127.0.0.1", port: int = 0) -> tuple:
        """Start the TCP ingress speaking actor wire frames; returns the
        bound ``(host, port)``. Call :meth:`start` first (or after —
        admission only needs the queues)."""
        wire.warn_untrusted_bind(host, "ServingFrontend")
        self._server = await asyncio.start_server(
            self._handle_conn, host=host, port=port
        )
        sock = self._server.sockets[0].getsockname()
        return sock[0], sock[1]

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """Batched TCP read loop: each wakeup drains EVERY complete
        frame queued on the socket into zero-copy memoryview slices
        over one growable receive buffer and serves them as ONE
        :meth:`serve_frames` batch — no per-frame ``readexactly``
        round-trips, no per-frame ``bytes`` copies, one reply write +
        drain per wakeup. Replies stay in arrival order.

        Framing faults resynchronize instead of tearing down the
        queue: an oversized length prefix counts a bad frame and the
        parser discards exactly the declared payload (streaming — the
        buffer never grows past the declared bytes) before resuming at
        the next length prefix, so frames queued behind it still
        serve; a frame torn by EOF (partial header or payload) counts
        a bad frame at close. Only a frame that FAILS decode (forged
        HMAC / tampered pickle) still drops the peer — it names no
        trustable tenant."""
        self._conns.add(writer)
        hdr = wire._HEADER.size
        buf = bytearray()
        skip = 0  # bytes of an oversized frame's payload still to discard
        try:
            while True:
                chunk = await reader.read(_INGRESS_READ_CHUNK)
                at_eof = not chunk
                if chunk and skip:
                    if len(chunk) <= skip:
                        skip -= len(chunk)
                        continue
                    chunk = chunk[skip:]
                    skip = 0
                if chunk:
                    buf += chunk
                pos = 0
                http = False
                drop = False
                mv = memoryview(buf)
                try:
                    bodies: List[Any] = []
                    while len(buf) - pos >= hdr:
                        if bytes(mv[pos:pos + hdr]) == _HTTP_GET_PREFIX:
                            # the same TCP ingress doubles as the
                            # Prometheus scrape endpoint: a peer whose
                            # next frame opens with "GET " is an HTTP
                            # scraper, not a wire client (as a length
                            # prefix those 4 bytes would name a ~1.2 GB
                            # frame no serving client sends)
                            http = True
                            break
                        (length,) = wire._HEADER.unpack(mv[pos:pos + hdr])
                        if length > wire.MAX_FRAME:
                            # oversized prefix: as hostile as a tampered
                            # frame — count it, discard exactly the
                            # declared payload, resync at the next
                            # length prefix (frames queued behind it
                            # still serve)
                            self._count_bad_frame()
                            avail = len(buf) - pos - hdr
                            if avail >= length:
                                pos += hdr + int(length)
                                continue
                            skip = int(length) - avail
                            pos = len(buf)
                            break
                        if len(buf) - pos - hdr < length:
                            break  # incomplete frame: wait for more bytes
                        bodies.append(mv[pos + hdr: pos + hdr + length])
                        pos += hdr + length
                    if bodies:
                        replies, _served, err = self.serve_frames(bodies)
                        if replies:
                            writer.write(b"".join(replies))
                            await writer.drain()
                        if err is not None:
                            drop = True
                finally:
                    # the memoryview slices must die before the buffer
                    # compaction below — bytearray refuses to resize
                    # while exports are live
                    del bodies
                    mv.release()
                del buf[:pos]
                if drop:
                    break
                if http:
                    await self._serve_http_metrics(
                        reader, writer, initial=bytes(buf)
                    )
                    break
                if at_eof:
                    if buf:
                        # torn frame: a partial header or payload cut
                        # off by the close — count it, never silent
                        # (an oversized frame torn mid-discard was
                        # already counted at its header)
                        self._count_bad_frame()
                    break
        finally:
            self._conns.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:  # noqa: BLE001 — peer already gone
                pass

    def _count_bad_frame(self) -> None:
        self.bad_frames += 1
        if obs_runtime.STATE.enabled:
            self._m_bad_frames.inc()

    async def _serve_http_metrics(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        *,
        initial: bytes = b"",
    ) -> None:
        """Answer one HTTP GET on the wire ingress with the process
        metrics registry in Prometheus text exposition format (0.0.4).
        The request is drained up to its blank line (bounded) so the
        scraper sees a clean close; rendering is an in-memory string
        build, safe on the admission loop. ``initial`` is whatever the
        batched read loop already pulled off the socket past the "GET "
        sniff (the request may have arrived whole in one chunk)."""
        data = initial
        while b"\r\n\r\n" not in data and len(data) < _HTTP_MAX_REQUEST:
            chunk = await reader.read(1024)
            if not chunk:
                break
            data += chunk
        _publish_wire_info()
        body = obs_metrics.registry().prometheus_text().encode()
        writer.write(
            b"HTTP/1.0 200 OK\r\n"
            b"Content-Type: text/plain; version=0.0.4; charset=utf-8\r\n"
            + f"Content-Length: {len(body)}\r\n".encode()
            + b"Connection: close\r\n\r\n"
            + body
        )
        await writer.drain()

    # -- introspection ---------------------------------------------------

    def round_of(self, tenant: str) -> int:
        """Current server round of ``tenant``."""
        return self._tenants[tenant].round_id

    def broadcast_frame(
        self, tenant: str, *, precision: Optional[str] = None
    ) -> bytes:
        """Encode the tenant's latest broadcast aggregate as a model
        frame for the client downlink — the frontend→client half of the
        million-client wire, compressed per ``precision`` (default: the
        ``BYZPY_TPU_WIRE_PRECISION`` fabric) with per-round **error
        feedback** on the blockwise modes: the residual the compressed
        broadcast lost last round is folded into this round's payload
        before encoding, so a client integrating the stream sees the
        true aggregate trajectory plus ONE round's bounded error. The
        residual is tenant round state: durable snapshots capture it
        bit-exact; a WAL-tail recovery restarts it at zero (safe —
        documented at ``_Tenant.ef_residual``, drilled by
        ``resilience.drill``). Raises ``ValueError`` for an unknown
        tenant, ``RuntimeError`` before the first closed round."""
        t = self._tenants.get(tenant)
        if t is None:
            raise ValueError(f"unknown tenant {tenant!r}")
        if t.last_aggregate is None:
            raise RuntimeError(
                f"tenant {tenant!r} has not closed a round yet — no "
                "aggregate to broadcast"
            )
        mode = wire.wire_precision() if precision is None else (
            precision if precision in wire.WIRE_MODES else "off"
        )
        agg = np.asarray(t.last_aggregate, np.float32).reshape(-1)
        if mode in wire.BLOCKWISE_WIRE_MODES:
            payload, t.ef_residual = wire.ef_precompensate(
                agg, t.ef_residual, mode
            )
        else:
            payload = agg
        return wire.encode(
            {
                "kind": "model",
                "tenant": tenant,
                "round": t.round_id - 1,
                "aggregate": payload,
            },
            precision=mode,
        )

    def reset_round_stats(self) -> None:
        """Zero every tenant's round-latency/cohort statistics window —
        the warmup→measure boundary for benchmarks (compile-round
        latencies must not pollute the measured p99). Accounting state
        (ledgers, round counters, ingress bytes, dedup tables) is
        untouched."""
        for t in self._tenants.values():
            t.stats = RoundStats()

    def last_aggregate(self, tenant: str) -> Any:
        """Most recent round's aggregated vector (None before round 0)."""
        return self._tenants[tenant].last_aggregate

    def _tenant_stats(self, t: _Tenant) -> dict:
        p50, p99 = t.stats.latency_percentiles_s(50, 99)
        return {
            "rounds": t.stats.rounds,
            "round_id": t.round_id,
            "ledger": t.ledger.snapshot(),
            "queue_depth": t.queue.depth(),
            "queue_high_water": t.queue.depth_high_water,
            "queue_capacity": t.queue.capacity,
            "rejected_queue_full": t.queue.rejected_full,
            # the effective round floor (config min_cohort raised to the
            # aggregator's smallest admissible n)
            "min_cohort": t.min_cohort,
            # admitted but not yet aggregated — includes rows the
            # scheduler already popped into its held cohort, which
            # queue_depth no longer sees (min_cohort holds them there)
            "outstanding": t.outstanding,
            "p50_round_latency_s": p50,
            "p99_round_latency_s": p99,
            "mean_cohort": (
                float(np.mean(t.stats.cohort_sizes))
                if t.stats.cohort_sizes
                else 0.0
            ),
            "ingress_bytes": t.ingress_bytes,
            "failed_rounds": t.failed_rounds,
            # resilience accounting: duplicate replays absorbed by the
            # idempotency layer, breaker state (None = no breaker),
            # recovery provenance (round the tenant resumed from)
            "duplicates": t.duplicates,
            "quarantine_drops": t.quarantine_drops,
            # forensics attribution (None = no plane configured): trust
            # summary, per-client quarantine state, rejected_untrusted
            "forensics": (
                t.forensics.snapshot() if t.forensics is not None else None
            ),
            "breaker": (
                t.breaker.snapshot() if t.breaker is not None else None
            ),
            "recovered_from": (
                {
                    "snapshot": t.recovered.from_snapshot,
                    "round_id": t.recovered.round_id,
                    "replayed_pending": len(t.recovered.pending),
                    "skipped_corrupt": list(t.recovered.skipped_corrupt),
                }
                if t.recovered is not None
                else None
            ),
            # downlink error-feedback residual energy (None = no
            # compressed broadcast yet / reset on WAL-tail recovery) —
            # the SIGKILL drill reads this to prove the residual was
            # either restored bit-exact from the snapshot or safely
            # reset (bounded, non-divergent) after recovery
            "ef_residual_norm": (
                None
                if t.ef_residual is None
                else float(np.linalg.norm(np.asarray(t.ef_residual)))
            ),
            # which door serves this tenant's rounds (False = bucket
            # ladder: ragged disabled, or no masked program)
            "ragged_served": (
                self._ragged is not None
                and self._ragged.serves(t.cfg.name)
            ),
            # FRONTEND-GLOBAL counters (not per-tenant — a forged frame
            # names no trustable tenant): nested so a dashboard summing
            # tenant blocks doesn't double-count them
            "frontend": {
                "bad_frames": self.bad_frames,
                "malformed_requests": self.malformed_requests,
                "callback_errors": self.callback_errors,
                # batched-door accounting: serve_frames calls, frames
                # they carried, and the largest single batch (the
                # smoke's proof the ingress actually amortizes)
                "ingress_batches": self.ingress_batches,
                "ingress_frames": self.ingress_frames_batched,
                "ingress_max_batch": self.ingress_max_batch,
                # ragged dispatch accounting (None = escape hatch on):
                # groups/executors, device calls, batch coalescing
                "ragged": (
                    self._ragged.snapshot()
                    if self._ragged is not None
                    else None
                ),
            },
        }

    def stats(self) -> dict:
        """Per-tenant accounting: admission ledger, rounds, cohort and
        latency telemetry, queue depth high-water, outstanding gauge,
        ingress bytes."""
        return {
            name: self._tenant_stats(t) for name, t in self._tenants.items()
        }


def encode_reply(reply: dict) -> bytes:
    """Encode one ``handle_request`` reply, honoring (and stripping)
    the ``LOSSLESS_REPLY`` pop-key — the ONE place the rule lives, so
    the TCP read loop and the in-process :func:`serve_frame` path
    cannot drift (a hook reply's partial rows must never ride a lossy
    ``BYZPY_TPU_WIRE_PRECISION`` fabric, on either path)."""
    if isinstance(reply, dict) and reply.pop(LOSSLESS_REPLY, False):
        return wire.encode(reply, precision="off")
    return wire.encode(reply)


def serve_frame(frontend: ServingFrontend, frame_body: bytes) -> bytes:
    """In-process wire path: decode one frame body, serve it, encode the
    reply — the exact codec/HMAC round the TCP ingress runs, minus the
    socket (the bench's 10k-client swarm exercises the wire cost this
    way without 10k TCP connections). Routed through the SAME batched
    door as the TCP read loop (:meth:`ServingFrontend.serve_frames`,
    batch of one), so inflation-stamp ownership, quantized-row
    admission, and accounting cannot drift between the two paths; a
    frame that fails HMAC/decode counts in ``bad_frames`` and
    re-raises, mirroring the dropped-peer contract."""
    replies, _served, err = frontend.serve_frames([frame_body])
    if err is not None:
        raise err
    return replies[0]


class ServingClient:
    """Asyncio client for the wire ingress (tests, examples, swarm
    simulators): one connection, frame-per-call submissions.

    Resilience (all opt-out): every submission carries a per-client
    monotonic ``seq`` idempotency key, so with a
    :class:`~byzpy_tpu.resilience.retry.RetryPolicy` attached the client
    may safely reconnect and RESEND after a dropped connection — the
    frontend dedupes replayed ``(client, seq)`` frames instead of
    double-folding them (a replay of an ack the wire lost answers
    ``accepted=True, reason="duplicate"``). Use as an async context
    manager so the writer cannot leak when a test raises between
    ``connect`` and teardown::

        async with ServingClient(retry=RetryPolicy()) as c:
            await c.connect(host, port)
            ack = await c.submit("m0", "client-7", round_id, grad)
    """

    def __init__(
        self,
        *,
        retry: Optional[RetryPolicy] = None,
        rng: Optional[random.Random] = None,
        error_feedback: bool = False,
    ) -> None:
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._addr: Optional[Tuple[str, int]] = None
        self._retry = retry
        self._rng = rng
        self._seq = 0
        #: reconnects performed by the retry driver (introspection)
        self.reconnects = 0
        #: uplink error feedback over the lossy submit fabric: with a
        #: blockwise ``BYZPY_TPU_WIRE_PRECISION`` active, each (tenant,
        #: client) keeps the residual its last frame's quantization
        #: lost and folds it into the next submission BEFORE the wire
        #: encode (``wire.ef_precompensate``) — the client-side half of
        #: the sub-int8 fabric. Off by default: an EF client's payload
        #: deliberately differs from its raw gradient, which a
        #: bit-parity test must opt into.
        self.error_feedback = bool(error_feedback)
        self._ef_residuals: Dict[Tuple[str, str], np.ndarray] = {}

    async def __aenter__(self) -> "ServingClient":
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.close()

    async def connect(self, host: str, port: int) -> None:
        """Open the connection (dial retried under the policy, so a
        frontend restart window is ridden out)."""
        self._addr = (host, port)
        await self._dial()

    async def _dial(self) -> None:
        assert self._addr is not None, "connect() first"
        host, port = self._addr
        if self._retry is not None:
            self._reader, self._writer = await connect_with_retry(
                host, port, policy=self._retry,
                component="serving_client", rng=self._rng,
            )
        else:
            self._reader, self._writer = await asyncio.open_connection(
                host, port
            )

    def _drop_connection(self) -> None:
        if self._writer is not None:
            self._writer.close()  # no wait: the peer is already gone
        self._writer = None
        self._reader = None

    async def _call(self, payload: dict, *, resend: bool = True) -> dict:
        """One request/reply round-trip; with a policy, wire failures
        drop the dead connection, redial, and RESEND the same frame —
        safe for submissions (idempotency key) and stats (read-only).
        ``resend=False`` is for NON-idempotent requests (close_round):
        the dial still retries, but once the frame may have left this
        process an ambiguous wire death raises instead of resending —
        a lost ack must not close two rounds."""
        if self._retry is None:
            assert self._writer is not None and self._reader is not None
            await wire.send_obj(self._writer, payload)
            return await wire.recv_obj(self._reader)

        class _Ambiguous(RuntimeError):
            """Sent (maybe) but no ack — unlisted type, so fatal."""

        async def attempt(n: int) -> dict:
            if n > 0:
                self.reconnects += 1
            if self._writer is None:
                await self._dial()
            try:
                await wire.send_obj(self._writer, payload)
                return await wire.recv_obj(self._reader)
            except Exception as exc:
                # whatever happened mid-round-trip, this connection is
                # no longer trustworthy for framing
                self._drop_connection()
                if not resend:
                    raise _Ambiguous(
                        "connection died mid-request; the request may "
                        "or may not have taken effect — refusing to "
                        "resend a non-idempotent frame"
                    ) from exc
                raise

        return await retry_async(
            attempt, policy=self._retry, component="serving_client",
            rng=self._rng,
        )

    async def submit(
        self,
        tenant: str,
        client: str,
        round_submitted: int,
        gradient: Any,
        *,
        seq: Optional[int] = None,
    ) -> dict:
        """Send one submission frame; returns the decoded ack. ``seq``
        defaults to this client object's own monotonic counter (shared
        across all logical client ids it submits for — still per-client
        monotonic, which is all the dedup layer needs). An explicit
        ``seq`` — e.g. replaying ambiguous submissions after a frontend
        restart — advances the counter past it, so later auto-assigned
        keys can never collide with the server's recovered high-water
        mark and be silently absorbed as duplicates. A client reborn
        WITHOUT its counter must adopt a fresh client id (see
        docs/fault_tolerance.md §idempotency)."""
        if seq is None:
            seq = self._seq
            self._seq += 1
        else:
            self._seq = max(self._seq, int(seq) + 1)
        gradient = np.asarray(gradient)
        if self.error_feedback and wire.wire_precision() in (
            wire.BLOCKWISE_WIRE_MODES
        ):
            gradient, self._ef_residuals[(tenant, client)] = (
                wire.ef_precompensate(
                    gradient, self._ef_residuals.get((tenant, client))
                )
            )
        # the round-causality chain starts HERE: the submit span's
        # context is stamped onto the frame by wire.encode, so the
        # frontend's admission span (possibly another process) links
        # as this span's child
        with obs_tracing.span(
            "serving.client.submit", track="client",
            tenant=tenant, client=client,
        ):
            return await self._call(
                {
                    "kind": "submit",
                    "tenant": tenant,
                    "client": client,
                    "round": int(round_submitted),
                    "gradient": np.asarray(gradient),
                    "seq": int(seq),
                }
            )

    async def stats(self, tenant: str) -> dict:
        """Fetch the tenant's stats snapshot."""
        return await self._call({"kind": "stats", "tenant": tenant})

    async def close_round(self, tenant: str) -> dict:
        """Drive the synchronous round closer over the wire (the drill/
        operator door; errors if the async scheduler owns rounds). NOT
        idempotent — an ambiguous wire failure raises rather than
        resending (a lost ack must not close two rounds)."""
        return await self._call(
            {"kind": "close_round", "tenant": tenant}, resend=False
        )

    async def close(self) -> None:
        """Close the connection (idempotent; safe mid-failure)."""
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001 — server already gone
                pass
            self._writer = None
            self._reader = None


__all__ = [
    "DUPLICATE",
    "REJECTED_MALFORMED",
    "REJECTED_QUARANTINED",
    "REJECTED_UNTRUSTED",
    "RoundCallback",
    "ServingClient",
    "ServingFrontend",
    "TenantConfig",
    "serve_frame",
]

"""The bounded admission queue between client ingress and the cohort
scheduler.

Backpressure contract: capacity is enforced AT THE DOOR — ``offer``
either enqueues or rejects with ``queue_full``, synchronously, so a
burst beyond the tier's capacity surfaces as explicit rejections (the
client retries with backoff) instead of unbounded memory growth or an
ingress stall that starves other tenants. ``depth_high_water`` proves
the bound held (asserted by the CI smoke and the serving bench).

The consumer side is the cohort scheduler's window/size trigger:
``collect`` returns as soon as ``max_items`` submissions are in hand OR
``window_s`` has elapsed since the round's first arrival — the
"aggregate whoever arrived in the window" semantics of the ROADMAP
item.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Submission:
    """One admitted gradient submission.

    ``gradient`` is the host-side flattened row (numpy ``(d,)``, the
    decoded wire payload); ``round_submitted`` the model round the
    client computed against; ``arrived_s`` the admission timestamp on
    the frontend clock (monotonic seconds). ``seq`` is the client's
    idempotency key (``None`` for legacy clients — no dedup) and
    ``wal_id`` the tenant's write-ahead-log identity when durability is
    on (see ``byzpy_tpu.resilience.durable``)."""

    client: str
    round_submitted: int
    gradient: Any
    arrived_s: float
    seq: Optional[int] = None
    wal_id: Optional[int] = None
    #: PRE-decode per-block inflation ratio of the submission's
    #: compressed wire frame (``engine.actor.wire.frame_inflation``;
    #: ``None`` for lossless/in-process submissions) — the forensics
    #: plane's residual-shaping feature
    wire_inflation: Optional[float] = None


class AdmissionQueue:
    """Bounded asyncio FIFO of :class:`Submission` with explicit-reject
    overflow and a high-water depth gauge."""

    def __init__(self, capacity: int) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=capacity)
        self.depth_high_water = 0
        self.rejected_full = 0

    def depth(self) -> int:
        """Submissions currently queued."""
        return self._queue.qsize()

    def offer(self, sub: Submission) -> bool:
        """Enqueue or reject-at-the-door (False = queue full)."""
        try:
            self._queue.put_nowait(sub)
        except asyncio.QueueFull:
            self.rejected_full += 1
            return False
        depth = self._queue.qsize()
        if depth > self.depth_high_water:
            self.depth_high_water = depth
        return True

    def snapshot_items(self) -> Tuple[Submission, ...]:
        """Non-consuming view of everything queued right now — the
        durable-snapshot capture path, which must record pending
        submissions WITHOUT dequeuing them. (Reads the asyncio.Queue's
        internal deque; safe here because all producers/consumers run on
        the owning event loop or synchronously between its steps.)"""
        return tuple(self._queue._queue)  # noqa: SLF001 — see docstring

    def restore(self, items: Sequence[Submission]) -> None:
        """Recovery-time refill: re-enqueue previously-admitted
        submissions BYPASSING the capacity bound (they were admitted
        under the bound in a prior life, plus up to one held cohort the
        scheduler had already popped — rejecting them now would lose
        acked submissions; the next rounds drain the excess first)."""
        for sub in items:
            self._queue._queue.append(sub)  # noqa: SLF001 — see docstring
        depth = self._queue.qsize()
        if depth > self.depth_high_water:
            self.depth_high_water = depth

    def drain_nowait(self, max_items: int) -> List[Submission]:
        """Synchronously pop up to ``max_items`` queued submissions
        (possibly none) without touching the event loop — the chaos
        harness's virtual-time round closer, which replays the admission
        path deterministically and cannot block on a real clock."""
        batch: List[Submission] = []
        while len(batch) < max_items:
            try:
                batch.append(self._queue.get_nowait())
            except asyncio.QueueEmpty:
                break
        return batch

    async def collect(
        self, max_items: int, window_s: float
    ) -> List[Submission]:
        """One round's cohort: block for the first submission, then
        drain until ``max_items`` are in hand or ``window_s`` has
        elapsed since that first arrival (the window/size trigger)."""
        first = await self._queue.get()
        batch = [first]
        loop = asyncio.get_running_loop()
        deadline = loop.time() + window_s
        while len(batch) < max_items:
            # drain whatever is already queued without touching the event
            # loop — a backlogged queue fills the cohort in one pass
            # instead of paying a scheduler round-trip per submission
            try:
                while len(batch) < max_items:
                    batch.append(self._queue.get_nowait())
                break
            except asyncio.QueueEmpty:
                pass
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            try:
                batch.append(
                    await asyncio.wait_for(self._queue.get(), remaining)
                )
            except asyncio.TimeoutError:
                break
        return batch


__all__ = ["AdmissionQueue", "Submission"]

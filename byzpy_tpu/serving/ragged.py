"""Ragged serving dispatch: kill the ladder, batch tenants, one compile.

The bucket ladder (``serving.buckets``) keeps jit caches warm by
padding every cohort into one of ``log2(cap)+1`` shapes — each tenant
compiles a ladder of programs, every non-full cohort pays padded FLOPs,
and every tenant's round serializes on the frontend's device lock one
dispatch at a time. This module is the ragged replacement built on
``ops.ragged``'s flat-rows programs:

* :class:`RaggedExecutor` — ONE jitted program per tenant *group*
  (same aggregator class + static hyperparameters + gradient dim):
  static shapes are the group's row capacity and cohort-count cap, so
  the jit cache holds exactly one entry per group no matter how cohort
  sizes are distributed — compile count == tenant count when every
  tenant aggregates differently (pinned via the ``serving.ragged``
  jitstats site), vs ``tenants × ladder`` on the bucket path.
* :class:`RaggedBatcher` — the cross-tenant coalescer: tenant
  schedulers hand their closed cohorts to a shared dispatcher task
  which drains everything currently pending and issues ONE device call
  per compatible group (the Podracer economics: while one batch runs on
  the device, the next batch accumulates). Multiple tenants' cohorts
  ride one dispatch instead of serializing on the lock.
* fused forensics — selection aggregators' dispatches return the
  per-row score/keep view (it rides the aggregation math for free), so
  the forensics plane skips the host-side O(m²·d) score pass
  (``Aggregator.round_evidence``) entirely; per-row norm/cosine
  feature outputs are additionally available per executor
  (``with_evidence=True`` — extra HBM passes, compiled in only for
  consumers that read them).

Bit-parity contract: per-cohort aggregates are bit-identical (f32,
finite rows) to the exact unpadded ``aggregate`` AND to the bucket
path's masked finalize, for any batch composition — the serving digest
pins (chaos wall, WAL continuity) hold with either door. Non-finite or
inadmissible cohorts never enter a batch: the frontend routes them
through the guarded ``aggregate_masked`` door exactly as before.

Dispatch gates (resolved pre-trace, the PR-2 wrapper pattern; both read
at frontend construction):

* ``BYZPY_TPU_RAGGED=0`` — escape hatch: disable the ragged door
  entirely and serve every tenant through the bucket ladder (default
  ragged wherever the aggregator supports it — i.e. it has a masked
  program; others fall back to the ladder automatically).
* ``BYZPY_TPU_RAGGED_PALLAS=1`` — opt-in: route the final segment-sum
  contraction through the fused Pallas kernel
  (``pallas_kernels.ragged_segment_sum_pallas``). Off by default: the
  XLA program is the authoritative bit-parity path; Mosaic parity is
  expected at ~ulp and is pinned on-chip by the queued rerun bundle.
"""

from __future__ import annotations

import asyncio
import logging
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import jitstats as obs_jitstats
from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing
from ..ops import ragged as ragged_ops
from .cohort import Cohort

_LOG = logging.getLogger("byzpy_tpu.serving")

#: jitstats dispatch site for every ragged executor's compile cache —
#: over a mixed-size swarm ``byzpy_jit_compiles_total{site=
#: "serving.ragged"}`` equals the tenant-group count (== tenant count
#: when every tenant aggregates differently), the ladder-free compile
#: economics the tier promises.
RAGGED_SITE = "serving.ragged"


def ragged_enabled() -> bool:
    """The serving-tier ragged door switch (``BYZPY_TPU_RAGGED``;
    default ON). Read at frontend construction — flipping the env var
    changes the next frontend built, not a live one."""
    return os.environ.get("BYZPY_TPU_RAGGED", "1") != "0"


def ragged_segment_sum_fn(
    rows: int, dim: int
) -> Optional[Callable]:
    """Pre-trace dispatch for the ragged contraction kernel: the fused
    Pallas segment sum on explicit opt-in
    (``BYZPY_TPU_RAGGED_PALLAS=1``), else ``None`` (the XLA per-cohort
    einsum contraction — the authoritative bit-parity path). Resolved
    here, in Python, before the executor's program traces; the tile
    itself resolves inside the kernel wrapper (family ``"ragged"``)."""
    if os.environ.get("BYZPY_TPU_RAGGED_PALLAS", "0") != "1":
        return None
    from ..ops.pallas_kernels import ragged_segment_sum_pallas

    def segment_sum(x, weights):
        return ragged_segment_sum_pallas(x, weights)

    return segment_sum


def ragged_segment_dequant_fn(mode: str, block: int) -> Optional[Callable]:
    """Pre-trace dispatch for the FUSED-dequant contraction kernel
    (``ops.pallas_kernels.ragged_segment_sum_dequant_pallas``): same
    explicit opt-in as :func:`ragged_segment_sum_fn`, additionally
    keyed by the batch's wire codec spec. ``None`` keeps the XLA
    mirror (``flat_dequantize`` at program entry + einsum contraction
    — the authoritative bit-parity path)."""
    if os.environ.get("BYZPY_TPU_RAGGED_PALLAS", "0") != "1":
        return None
    if mode == "s4" and block % 2:
        return None
    from ..ops.pallas_kernels import ragged_segment_sum_dequant_pallas

    def seg_dequant(codes, scales, weights, *, d):
        return ragged_segment_sum_dequant_pallas(
            codes, scales, weights, mode=mode, block=block, d=d
        )

    return seg_dequant


@dataclass(frozen=True)
class RaggedView:
    """One cohort's slice of a ragged dispatch: the aggregate vector
    plus the fused forensics outputs (``scores``/``keep`` are ``None``
    for non-selection aggregators; ``norms``/``cos`` are computed on
    the discounted rows the fold aggregated, and are ``None`` when the
    cohort took the exact non-finite fallback instead of the kernel)."""

    vector: np.ndarray
    score_kind: str
    scores: Optional[np.ndarray]
    keep: Optional[np.ndarray]
    norms: Optional[np.ndarray]
    cos: Optional[np.ndarray]

    def precomputed(self) -> Optional[dict]:
        """The ``ForensicsPlane.prepare(precomputed=...)`` payload —
        ``None`` when this aggregator family publishes no score view
        (the plane then runs its host pass as before)."""
        if self.scores is None:
            return None
        return {
            "kind": self.score_kind,
            "scores": self.scores,
            "keep": self.keep,
        }


class RaggedExecutor:
    """One tenant group's compiled ragged program.

    Static shape contract: ``row_capacity`` flat rows × ``max_cohorts``
    cohorts of dimension ``dim`` — one jit cache entry serves every
    batch this group can produce (each tenant has at most one round in
    flight, so a batch holds at most one cohort per group member and at
    most the sum of their cohort caps in rows). The program applies the
    per-row staleness discounts in-jit (``weight == 1.0`` rows are
    bit-identical, matching the bucket path's host-side scaling),
    aggregates every cohort, and emits the fused evidence outputs."""

    def __init__(
        self,
        aggregator: Any,
        dim: int,
        row_capacity: int,
        max_cohorts: int,
        with_evidence: bool = True,
    ) -> None:
        fn = aggregator.ragged_matrix_fn()
        if fn is None:
            raise ValueError(
                f"{type(aggregator).__name__} has no ragged program"
            )
        self.dim = int(dim)
        self.rows = int(row_capacity)
        self.max_cohorts = int(max_cohorts)
        self.score_kind = aggregator.ragged_score_kind
        self.dispatches = 0
        self.cohorts_dispatched = 0
        #: largest number of cohorts one device call carried
        self.max_batch = 0
        #: device dispatches whose rows entered the program as wire
        #: codes (no host f32 materialization of the batch)
        self.quantized_dispatches = 0
        self._fn = fn
        self._with_evidence = bool(with_evidence)
        self._segment_sum = ragged_segment_sum_fn(self.rows, self.dim)
        #: one lazily-built jitted program per wire codec spec the
        #: batched ingress actually admits ((mode, block) keys; in
        #: practice a deployment pins ONE wire precision, so this adds
        #: a single extra compile-cache entry, accounted like the rest)
        self._jitted_q: Dict[tuple, Any] = {}
        segment_sum = self._segment_sum
        n_cohorts = self.max_cohorts

        def program(flat, seg, offsets, lengths, weights):
            with jax.named_scope("serving.ragged_scale"):
                scaled = flat * weights[:, None].astype(flat.dtype)
            with jax.named_scope("serving.ragged_aggregate"):
                aggs, score, keep = fn(
                    scaled, seg, offsets, lengths,
                    n_cohorts=n_cohorts, segment_sum=segment_sum,
                )
            # the selection families' score/keep ride the aggregation
            # math for free; the norm/cosine features are EXTRA passes
            # compiled in only on request (with_evidence) — no frontend
            # consumer reads them today, so production executors leave
            # them out and pay nothing for attribution nobody reads
            if not with_evidence:
                return aggs, score, keep, None, None
            with jax.named_scope("serving.ragged_evidence"):
                norm, cos = ragged_ops.ragged_evidence(
                    scaled, seg, aggs, n_cohorts=n_cohorts
                )
            return aggs, score, keep, norm, cos

        self._jitted = jax.jit(program)

    def _jitted_quant(self, mode: str, block: int):
        """The quantized-entry twin of the dense program, per wire
        codec spec: consumes the flat batch as stacked codes + scales,
        dequantizes as the FIRST traced op
        (``ops.ragged.flat_dequantize`` — bit-identical to the host
        wire codec), and runs the identical aggregation body, so a
        quantized round's aggregate is bit-for-bit the dense program's
        on the ingress-decoded rows. Under ``BYZPY_TPU_RAGGED_PALLAS=1``
        the trailing segment-sum contraction additionally fuses the
        dequant INTO the kernel (codes travel to the MXU tile), with
        staleness weights folded into the per-cohort weight rows —
        the Pallas path's documented ulp-level contract."""
        key = (mode, block)
        jitted = self._jitted_q.get(key)
        if jitted is not None:
            return jitted
        fn = self._fn
        n_cohorts = self.max_cohorts
        dim = self.dim
        with_evidence = self._with_evidence
        base_segment_sum = self._segment_sum
        fused = (
            ragged_segment_dequant_fn(mode, block)
            if base_segment_sum is not None else None
        )

        def program_q(codes, scales_q, seg, offsets, lengths, weights):
            with jax.named_scope("serving.ragged_dequant"):
                flat = ragged_ops.flat_dequantize(
                    codes, scales_q, mode=mode, block=block, d=dim
                )
            with jax.named_scope("serving.ragged_scale"):
                scaled = flat * weights[:, None].astype(flat.dtype)
            segment_sum = base_segment_sum
            if fused is not None:
                def segment_sum(x, w):
                    # `x is scaled` resolves at TRACE time: only the
                    # contraction over the scaled flat rows may take
                    # the fused kernel (sorted/derived operands keep
                    # the dense kernel — their bits are not wire codes)
                    if x is scaled:
                        return fused(
                            codes, scales_q,
                            w * weights[None, :].astype(w.dtype), d=dim,
                        )
                    return base_segment_sum(x, w)
            with jax.named_scope("serving.ragged_aggregate"):
                aggs, score, keep = fn(
                    scaled, seg, offsets, lengths,
                    n_cohorts=n_cohorts, segment_sum=segment_sum,
                )
            if not with_evidence:
                return aggs, score, keep, None, None
            with jax.named_scope("serving.ragged_evidence"):
                norm, cos = ragged_ops.ragged_evidence(
                    scaled, seg, aggs, n_cohorts=n_cohorts
                )
            return aggs, score, keep, norm, cos

        jitted = self._jitted_q[key] = jax.jit(program_q)
        return jitted

    @staticmethod
    def _quant_spec(cohorts: Sequence[Cohort]) -> Optional[tuple]:
        """The shared wire codec spec when EVERY cohort in the batch is
        still quantized with identical layout — the precondition for
        the quantized-entry program; mixed batches densify (lazily,
        bit-identically) and take the dense program."""
        c0 = cohorts[0]
        if not c0.quantized:
            return None
        spec = (
            c0.qmode, c0.qblock,
            int(c0.qcodes.shape[1]), int(c0.qscales.shape[1]),
        )
        for c in cohorts[1:]:
            if not c.quantized or (
                c.qmode, c.qblock,
                int(c.qcodes.shape[1]), int(c.qscales.shape[1]),
            ) != spec:
                return None
        return spec

    def cache_size(self) -> Optional[int]:
        try:
            return int(self._jitted._cache_size()) + sum(
                int(j._cache_size()) for j in self._jitted_q.values()
            )
        except Exception:  # noqa: BLE001 — introspection API drift
            return None

    def expected_compiles(self) -> int:
        """Compile-cache entries this executor legitimately owns: the
        dense program plus one per wire codec spec seen."""
        return 1 + len(self._jitted_q)

    def aggregate(
        self, cohorts: Sequence[Cohort], tenants: Sequence[str]
    ) -> List[RaggedView]:
        """ONE device dispatch for ``cohorts`` (≤ ``max_cohorts``, rows
        summing to ≤ ``row_capacity``); returns one :class:`RaggedView`
        per cohort, in order. Callers guarantee each cohort is finite
        and admissible (the frontend's door checks)."""
        n = len(cohorts)
        if not 1 <= n <= self.max_cohorts:
            raise ValueError(
                f"batch of {n} cohorts exceeds max_cohorts={self.max_cohorts}"
            )
        sizes = [c.m for c in cohorts]
        fill = sum(sizes)
        if fill > self.rows:
            raise ValueError(
                f"batch of {fill} rows exceeds row capacity {self.rows}"
            )
        seg = np.full((self.rows,), self.max_cohorts, np.int32)
        weights = np.zeros((self.rows,), np.float32)
        offsets = np.full((self.max_cohorts,), fill, np.int32)
        lengths = np.zeros((self.max_cohorts,), np.int32)
        off = 0
        for c, cohort in enumerate(cohorts):
            m = sizes[c]
            weights[off:off + m] = cohort.weights[:m]
            seg[off:off + m] = c
            offsets[c] = off
            lengths[c] = m
            off += m
        qspec = self._quant_spec(cohorts)
        if qspec is not None:
            # batched-ingress hot path: the flat batch stays WIRE codes
            # on host; f32 rows first exist inside the jitted program
            mode, block, ncodes, nb = qspec
            codes = np.zeros((self.rows, ncodes), cohorts[0].qcodes.dtype)
            scales = np.zeros((self.rows, nb), np.float32)
            off = 0
            for c, cohort in enumerate(cohorts):
                m = sizes[c]
                codes[off:off + m] = cohort.qcodes[:m]
                scales[off:off + m] = cohort.qscales[:m]
                off += m
            jitted = self._jitted_quant(mode, block)
            rows_args = (jnp.asarray(codes), jnp.asarray(scales))
            self.quantized_dispatches += 1
        else:
            flat = np.zeros((self.rows, self.dim), np.float32)
            off = 0
            for c, cohort in enumerate(cohorts):
                m = sizes[c]
                flat[off:off + m] = cohort.matrix[:m]
                off += m
            jitted = self._jitted
            rows_args = (jnp.asarray(flat),)
        label = tenants[0] if len(tenants) == 1 else ",".join(tenants)
        track = f"tenant:{tenants[0]}" if len(tenants) == 1 else None
        with obs_tracing.span(
            "serving.fold", track=track, tenant=label,
            cohorts=n, rows=fill, quantized=qspec is not None,
        ):
            with obs_tracing.device_span(
                "serving.device_step", track=track, tenant=label,
                cohorts=n, rows=fill, ragged=True,
            ):
                aggs, score, keep, norm, cos = jitted(
                    *rows_args, jnp.asarray(seg),
                    jnp.asarray(offsets), jnp.asarray(lengths),
                    jnp.asarray(weights),
                )
        aggs = np.asarray(aggs)
        score = None if score is None else np.asarray(score)
        keep = None if keep is None else np.asarray(keep)
        norm = None if norm is None else np.asarray(norm)
        cos = None if cos is None else np.asarray(cos)
        self.dispatches += 1
        self.cohorts_dispatched += n
        self.max_batch = max(self.max_batch, n)
        views = []
        off = 0
        for c, m in enumerate(sizes):
            views.append(
                RaggedView(
                    vector=aggs[c],
                    score_kind=self.score_kind,
                    scores=(
                        None if score is None else score[off:off + m]
                    ),
                    keep=None if keep is None else keep[off:off + m],
                    norms=None if norm is None else norm[off:off + m],
                    cos=None if cos is None else cos[off:off + m],
                )
            )
            off += m
        return views


class RaggedRuntime:
    """The frontend's ragged plane: tenant grouping, per-group
    executors, the cross-tenant batcher, and compile-cache accounting.

    Groups are computed once at construction: tenants sharing an
    aggregator signature (``Aggregator.ragged_group_key``) AND gradient
    dimension share one executor — their cohorts may coalesce into one
    device call. Tenants whose aggregator has no ragged program (no
    masked program: MDA/SMEA/CAF) are simply absent here and keep the
    bucket-ladder path."""

    def __init__(self, tenant_cfgs: Sequence[Any]) -> None:
        self._groups: Dict[tuple, dict] = {}
        self._by_tenant: Dict[str, tuple] = {}
        for cfg in tenant_cfgs:
            agg = cfg.aggregator
            if not getattr(agg, "supports_ragged", False):
                continue
            if agg.ragged_matrix_fn() is None:  # pragma: no cover
                continue
            key = (agg.ragged_group_key(), int(cfg.dim))
            g = self._groups.setdefault(
                key,
                {"aggregator": agg, "dim": int(cfg.dim), "caps": [],
                 "names": [], "executor": None},
            )
            g["caps"].append(int(cfg.cohort_cap))
            g["names"].append(cfg.name)
            self._by_tenant[cfg.name] = key
        self._batcher: Optional["RaggedBatcher"] = None
        #: ragged compiles already warned about (each NEW excess size
        #: warns once, mirroring the bucket ladder's recompile alarm)
        self._warn_high = 0

    # -- introspection ---------------------------------------------------

    def serves(self, tenant: str) -> bool:
        return tenant in self._by_tenant

    def executor_for(self, tenant: str) -> Optional[RaggedExecutor]:
        key = self._by_tenant.get(tenant)
        if key is None:
            return None
        g = self._groups[key]
        if g["executor"] is None:
            # the program's row capacity is the group's LARGEST tenant
            # cap — the compiled shape a full cohort needs anyway. The
            # XLA fallback pays the full static capacity per dispatch
            # (only the Pallas path skips unfilled row tiles), so
            # coalescing packs other tenants' cohorts into capacity a
            # lone cohort would leave empty: strictly more work per
            # call at the same per-call cost. Full cohorts fill the
            # capacity alone and serialize — at exactly the ladder's
            # top-bucket cost. Non-coalescing families (sort-based:
            # nothing shared on XLA) serve one cohort per call.
            coalesce = bool(
                getattr(g["aggregator"], "ragged_coalesce", False)
            )
            g["executor"] = RaggedExecutor(
                g["aggregator"], g["dim"],
                row_capacity=max(g["caps"]),
                max_cohorts=len(g["caps"]) if coalesce else 1,
                # the production plane consumes only the score/keep
                # view (which rides the aggregation math for free);
                # the norm/cos feature passes are extra HBM sweeps no
                # frontend consumer reads, so they stay compiled out —
                # direct RaggedExecutor users opt in per instance
                with_evidence=False,
            )
        return g["executor"]

    def snapshot(self) -> dict:
        """JSON-ready accounting for ``ServingFrontend.stats()``."""
        execs = [
            g["executor"]
            for g in self._groups.values()
            if g["executor"] is not None
        ]
        batched = self._batcher
        return {
            "groups": len(self._groups),
            "tenants": sorted(self._by_tenant),
            "dispatches": sum(e.dispatches for e in execs),
            "cohorts_dispatched": sum(e.cohorts_dispatched for e in execs),
            # dispatches whose rows entered the program as wire codes
            # (device-side dequant; no host f32 batch was built)
            "quantized_dispatches": sum(
                e.quantized_dispatches for e in execs
            ),
            "compile_entries": sum(
                e.cache_size() or 0 for e in execs
            ),
            "batched_calls": 0 if batched is None else batched.batched_calls,
            # largest number of cohorts ONE device call carried (>= 2 =
            # cross-tenant batching happened)
            "max_batch": max(
                [e.max_batch for e in execs],
                default=0,
            ),
        }

    # -- compile-cache accounting ----------------------------------------

    def note_compiles(self) -> None:
        """Report the summed ragged jit-cache size to the
        ``serving.ragged`` jitstats site and warn (once per excess
        size) if it ever exceeds one entry per group — the ragged
        door's whole point is ONE compile per tenant group, so growth
        past that is the same silent latency cliff the bucket ladder's
        alarm watches for."""
        execs = [
            g["executor"]
            for g in self._groups.values()
            if g["executor"] is not None
        ]
        sizes = [e.cache_size() for e in execs]
        if any(s is None for s in sizes):
            return
        total = sum(sizes)
        obs_jitstats.note_cache_size(RAGGED_SITE, total)
        expected = sum(e.expected_compiles() for e in execs)
        if total > expected and total > self._warn_high:
            self._warn_high = total
            obs_metrics.registry().counter(
                "byzpy_serving_ragged_recompile_warnings_total",
                help="ragged-program compiles beyond one per tenant group",
            ).inc()
            _LOG.warning(
                "ragged serving door has %d compiled programs for %d "
                "tenant groups — an unexpected recompile happened "
                "(shape or dtype drift); every extra entry is a silent "
                "latency cliff",
                total, expected,
            )

    # -- dispatch doors --------------------------------------------------

    def aggregate_sync(
        self, tenant: str, cohort: Cohort
    ) -> Optional[RaggedView]:
        """Single-cohort synchronous dispatch (the virtual-time round
        closer's door); ``None`` when the tenant is not ragged-served."""
        ex = self.executor_for(tenant)
        if ex is None:
            return None
        (view,) = ex.aggregate([cohort], [tenant])
        self.note_compiles()
        return view

    async def start(self, device_lock: asyncio.Lock) -> None:
        self._batcher = RaggedBatcher(self, device_lock)
        await self._batcher.start()

    async def close(self) -> None:
        if self._batcher is not None:
            await self._batcher.close()
            self._batcher = None

    async def aggregate_async(
        self, tenant: str, cohort: Cohort, fallback: Any = None
    ) -> RaggedView:
        """Enqueue one closed cohort for batched dispatch and await its
        view (the async scheduler's door; requires :meth:`start`).
        ``fallback`` (a :class:`~byzpy_tpu.serving.cohort.
        CohortAggregator`) serves non-finite cohorts through the exact
        guarded door — the finite gate runs on the dispatch executor
        thread, never on the event loop."""
        assert self._batcher is not None, "RaggedRuntime.start() first"
        return await self._batcher.submit(tenant, cohort, fallback)


def _dispatch_group(
    ex: RaggedExecutor,
    items: Sequence[Tuple[str, Cohort, Any]],
) -> List[Any]:
    """One group's device call, on the dispatch EXECUTOR thread: gate
    each cohort's finiteness (an O(rows·d) host pass that must not run
    on the event loop), send the finite ones through the ragged program
    in ONE dispatch, and route non-finite cohorts through their
    tenant's exact guarded door (``CohortAggregator.aggregate`` — the
    same fallback stance as ``fold_finalize_masked``). Returns one
    ``RaggedView`` or ``Exception`` per item, in order."""
    finite_items: List[Tuple[int, str, Cohort]] = []
    results: List[Any] = [None] * len(items)
    for i, (tenant, cohort, fallback) in enumerate(items):
        # Cohort.finite() == isfinite(matrix).all(), but decided from
        # codes × scales for quantized cohorts — the gate must not be
        # the thing that forces a host dequant of the batched path
        if cohort.finite():
            finite_items.append((i, tenant, cohort))
        else:
            try:
                if fallback is None:
                    raise ValueError(
                        "non-finite cohort and no fallback aggregator"
                    )
                vec = np.asarray(fallback.aggregate(cohort))
                results[i] = RaggedView(
                    vector=vec, score_kind="", scores=None, keep=None,
                    norms=None, cos=None,
                )
            except Exception as exc:  # noqa: BLE001 — poisoned cohort:
                # ITS round fails, the rest of the batch still serves
                results[i] = exc
    # greedy chunking against the program's static capacity: a
    # non-coalescing executor (max_cohorts=1) naturally serves one
    # cohort per call; coalescing ones pack as many as fit
    chunk: List[Tuple[int, str, Cohort]] = []
    rows = 0
    chunks: List[List[Tuple[int, str, Cohort]]] = []
    for item in finite_items:
        m = item[2].m
        if chunk and (
            len(chunk) == ex.max_cohorts or rows + m > ex.rows
        ):
            chunks.append(chunk)
            chunk, rows = [], 0
        chunk.append(item)
        rows += m
    if chunk:
        chunks.append(chunk)
    for chunk in chunks:
        try:
            views = ex.aggregate(
                [c for _, _, c in chunk], [t for _, t, _ in chunk]
            )
        except Exception as exc:  # noqa: BLE001
            for i, _, _ in chunk:
                results[i] = exc
        else:
            for (i, _, _), view in zip(chunk, views, strict=True):
                results[i] = view
    return results


class RaggedBatcher:
    """Cross-tenant cohort coalescer: one dispatcher task owns the
    device lock while a batch runs, and drains EVERYTHING pending the
    moment it reacquires it — cohorts that closed while the previous
    batch was on the device ride the next call together instead of
    serializing one dispatch per cohort."""

    def __init__(
        self, runtime: RaggedRuntime, device_lock: asyncio.Lock
    ) -> None:
        self._runtime = runtime
        self._lock = device_lock
        self._pending: List[Tuple[str, Cohort, Any, asyncio.Future]] = []
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        #: dispatcher wake-ups that reached the device (device-call
        #: counts and per-call batch sizes live on the executors)
        self.batched_calls = 0

    async def start(self) -> None:
        self._task = asyncio.create_task(
            self._run(), name="serving-ragged-batcher"
        )

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
        for _, _, _, fut in self._pending:
            if not fut.done():
                fut.cancel()
        self._pending = []

    async def submit(
        self, tenant: str, cohort: Cohort, fallback: Any = None
    ) -> RaggedView:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending.append((tenant, cohort, fallback, fut))
        self._wake.set()
        return await fut

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            await self._wake.wait()
            self._wake.clear()
            if not self._pending:
                continue
            # one yield so tenant loops whose windows expired in the
            # same scheduler pass can close their cohorts too — they
            # join THIS batch instead of trailing it by a device call
            await asyncio.sleep(0)
            batch: List[Tuple[str, Cohort, Any, asyncio.Future]] = []
            try:
                async with self._lock:
                    # drain at lock ACQUISITION: everything that closed
                    # while the previous batch held the device coalesces
                    batch, self._pending = self._pending, []
                    if not batch:
                        continue
                    by_exec: Dict[int, dict] = {}
                    for tenant, cohort, fallback, fut in batch:
                        ex = self._runtime.executor_for(tenant)
                        assert ex is not None, tenant
                        slot = by_exec.setdefault(
                            id(ex), {"ex": ex, "items": []}
                        )
                        slot["items"].append(
                            (tenant, cohort, fallback, fut)
                        )
                    for slot in by_exec.values():
                        ex = slot["ex"]
                        items = slot["items"]
                        results = await loop.run_in_executor(
                            None, _dispatch_group, ex,
                            [(t, c, fb) for t, c, fb, _ in items],
                        )
                        self.batched_calls += 1
                        for (_, _, _, fut), res in zip(
                            items, results, strict=True
                        ):
                            if fut.done():
                                continue
                            if isinstance(res, Exception):
                                fut.set_exception(res)
                            else:
                                fut.set_result(res)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — the dispatcher
                # must outlive ANY failure (executor construction,
                # shutdown races, grouping bugs): fail the drained
                # batch's rounds (their tenant loops crash-guard each
                # as a failed_round) and keep serving — a dead
                # dispatcher would hang every ragged tenant forever
                for _, _, _, fut in batch:
                    if not fut.done():
                        fut.set_exception(exc)
            self._runtime.note_compiles()


__all__ = [
    "RAGGED_SITE",
    "RaggedBatcher",
    "RaggedExecutor",
    "RaggedRuntime",
    "RaggedView",
    "ragged_enabled",
    "ragged_segment_dequant_fn",
    "ragged_segment_sum_fn",
]

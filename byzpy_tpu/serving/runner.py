"""Process-per-shard runner: real multi-core sharded serving.

PR 12 built the sharded tier in-process and PR 13's blame table sized
its limit: at 4 shards the root ``fold_merge`` is the largest single
critical-path entry (14.4% → 37.5% of round wall-clock at 1→4 shards),
and the whole scale lane still *modeled* the makespan on one core —
``ShardedCoordinator`` owned every shard object, nothing spawned
processes or drove the barrier over sockets. This module is the real
thing, in the actor-vs-learner process-split lineage of Podracer
(arXiv:2104.06272) and the MPMD program-partitioning stance of
arXiv:2412.14374:

* **one OS process per ingress shard** — each child hosts a full
  :class:`~byzpy_tpu.serving.sharded.ShardFrontend` admission plane
  (bounded queue, credits, staleness, ``(client, seq)`` dedup,
  forensics trust gating, write-ahead durability) behind its own TCP
  ingress speaking the existing HMAC/quantized actor wire. The runner
  control plane (``shard_close``/``confirm``/``requeue``/…) mounts on
  the SAME port through ``ServingFrontend.request_hook`` — one socket
  per shard serves submissions, Prometheus scrapes, and round control;
* **optional merge-node processes** — the depth-N merge tree
  (:class:`~byzpy_tpu.serving.sharded.MergeTopology`): a rack/pod-level
  node fans ``shard_close`` to its children, verifies each child frame
  (digest recompute + per-row home-shard ownership), and ships ONE
  combined :class:`~byzpy_tpu.serving.sharded.PartialFold` up
  (:func:`~byzpy_tpu.serving.sharded.combine_partials`) — the
  verification + concatenation + extras work that used to serialize on
  the root's critical path runs level-parallel across processes;
* **a root coordinator process** — a
  :class:`~byzpy_tpu.serving.sharded.ShardedCoordinator` whose shard
  objects are wire-RPC **proxies**: the barrier close, partial
  verification, hierarchical merge, ``fold_merge_finalize`` device
  step, cross-shard dedup, root WAL and per-shard confirmations all
  run over real sockets. The dial leg retries under PR 9's
  ``dial_policy`` (decorrelated jitter), so a recovering shard process
  is ridden out instead of failing the round.

Correctness is inherited, not re-implemented: the shard admission
plane, the verification cross-checks, the exactly-once dedup/WAL
contract and the hierarchical fold are the SAME code the in-process
tier runs — the runner only changes where each stage executes. Bit
parity vs the single frontend therefore holds at every topology
(pinned by ``tests/test_runner.py`` and the bench's ``--processes``
lane), and :func:`~byzpy_tpu.serving.sharded.audit_sharded_exactly_once`
audits the same WAL layout (``dir/shard<i>/…`` + ``dir/root/…``).

Failure drill: :meth:`Runner.kill_shard` SIGKILLs a shard process
(in-memory queues and ledgers GONE, only its WAL survives) and
:meth:`Runner.recover_shard` respawns it on the same durability
directory — the recovered process replays pending accepts, the root
dedup table drops anything already folded (``root_duplicate``), and
the cross-WAL audit must come back clean (the PR 12 failover drill,
promoted to real processes).

Trace stitching: with telemetry on, the root's round span context
rides the ``shard_close`` request frames (``wire.encode`` stamps dict
frames), each shard's ``serving.shard_close`` span adopts it, and the
``PartialFold.trace_ctx`` links ride back — ONE trace id spans the
shard, merge and root processes, and ``trace_export`` control frames
pull each process's events so the exports stitch into a single causal
tree (``observability.critical_path`` attributes the merged export
like any recorded trace).

Threat model: the runner authenticates the FABRIC (shared-key HMAC),
not individual processes. A compromised merge node can forge its whole
subtree's combined frame; the root's per-segment cross-checks bound
the blast radius to that subtree (ownership violations and digest
mismatches discard the frame, never a sibling's), and a deployment
with per-shard trust boundaries should give each process its own wire
key and verify sender↔index at the socket layer (docs/serving.md
§scale-out).
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import os
import random
import socket
import subprocess
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from threading import Lock
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis import sanitize
from ..engine.actor import wire
from ..engine.actor.transports.tcp import dial_policy
from ..observability import metrics as obs_metrics
from ..observability import runtime as obs_runtime
from ..observability import tracing as obs_tracing
from ..resilience.durable import DurabilityConfig
from ..resilience.retry import RetryPolicy
from .frontend import LOSSLESS_REPLY, TenantConfig
from .sharded import (
    MergeTopology,
    PartialFold,
    ShardFrontend,
    ShardedCoordinator,
    combine_partials,
    shard_for,
)

#: Control-plane frame kinds the runner adds on top of the serving wire.
SHARD_CLOSE = "shard_close"
MERGE_CLOSE = "merge_close"
RUNNER_SHUTDOWN = "runner_shutdown"

_ACK = {"kind": "ack", "accepted": True}


@dataclass
class RunnerSpec:
    """Everything a child process needs to build its tier (cloudpickled
    to a spec file the ``--role`` entrypoints load).

    ``fanout=None`` is the flat depth-2 tier (root merges every shard
    directly); a fanout builds the depth-N merge tree —
    ``MergeTopology(n_shards, fanout)`` — with one merge-node process
    per internal group. ``durability_dir`` activates the PR 9 WAL on
    every shard (``dir/shard<i>``) and the root's merge-evidence WAL
    (``dir/root``), the exact layout ``audit_sharded_exactly_once``
    reads. ``shard_timeout_s`` is the leaf barrier budget; each merge
    level above adds ``level_slack_s`` to its parent's wait."""

    tenants: List[TenantConfig]
    n_shards: int
    fanout: Optional[int] = None
    host: str = "127.0.0.1"
    durability: Optional[DurabilityConfig] = None
    shard_timeout_s: float = 30.0
    level_slack_s: float = 15.0
    quorum: Optional[int] = None
    extras_policy: str = "trust"
    telemetry: bool = False
    #: speculative-close repair horizon (rounds) passed through to the
    #: root's :class:`ShardedCoordinator` — 0 keeps the classic
    #: degraded close (stragglers requeue at the barrier)
    repair_horizon_rounds: int = 0

    @property
    def topology(self) -> MergeTopology:
        """The merge-tree shape this spec deploys."""
        return MergeTopology(self.n_shards, self.fanout)

    def shard_durability(self, index: int) -> Optional[DurabilityConfig]:
        """The per-shard WAL config (``dir/shard<i>`` — the audit
        layout), or ``None`` when durability is off."""
        if self.durability is None:
            return None
        return dataclasses.replace(
            self.durability,
            directory=os.path.join(
                self.durability.directory, f"shard{index}"
            ),
        )


# ---------------------------------------------------------------------------
# blocking wire helpers (root + parent side: no event loop, real sockets)
# ---------------------------------------------------------------------------


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Any:
    """Read + decode one length-prefixed wire frame from a blocking
    socket (HMAC verified by ``wire.decode`` when signing is on)."""
    (length,) = wire._HEADER.unpack(_recv_exact(sock, wire._HEADER.size))
    if length > wire.MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return wire.decode(_recv_exact(sock, length))


def send_frame(sock: socket.socket, obj: Any, *, lossless: bool = True) -> None:
    """Encode + write one frame. Runner control frames default to
    LOSSLESS — confirmation aggregates and partial rows are bit
    load-bearing, so ``BYZPY_TPU_WIRE_PRECISION`` must not apply."""
    sock.sendall(wire.encode(obj, precision="off" if lossless else None))


def rpc(sock: socket.socket, obj: Any, *, lossless: bool = True) -> Any:
    """One request/response round-trip on a blocking socket."""
    send_frame(sock, obj, lossless=lossless)
    return recv_frame(sock)


def dial_blocking(
    host: str,
    port: int,
    *,
    policy: Optional[RetryPolicy] = None,
    rng: Optional[random.Random] = None,
) -> socket.socket:
    """Blocking dial under PR 9's ``dial_policy`` (decorrelated-jitter
    backoff, attempt + deadline budgets) — a shard process mid-restart
    is ridden out instead of failing the proxy op."""
    policy = policy if policy is not None else dial_policy()
    rng = rng if rng is not None else random.Random()
    deadline = time.monotonic() + policy.deadline_s
    prev: Optional[float] = None
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        try:
            sock = socket.create_connection((host, port), timeout=10.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock
        except OSError as exc:
            last = exc
        prev = policy.next_backoff_s(prev, rng)
        if attempt + 1 >= policy.max_attempts or (
            time.monotonic() + prev >= deadline
        ):
            break
        time.sleep(prev)
    raise ConnectionError(
        f"dial {host}:{port} failed after {policy.max_attempts} attempts"
    ) from last


# ---------------------------------------------------------------------------
# shard process (--role shard)
# ---------------------------------------------------------------------------


def _shard_hook(shard: ShardFrontend, stop: "asyncio.Event"):
    """The runner control plane, mounted on the shard ingress through
    ``ServingFrontend.request_hook`` (first look at every dict frame;
    returning ``None`` falls through to submit/stats)."""

    def hook(request: dict) -> Optional[dict]:
        kind = request.get("kind")
        if kind == SHARD_CLOSE:
            p = shard.close_partial(str(request.get("tenant")))
            return {
                "kind": "partial",
                "partial": None if p is None else p.to_wire(),
                LOSSLESS_REPLY: True,
            }
        if kind == "confirm":
            shard.confirm(
                str(request["tenant"]),
                int(request["round"]),
                [int(j) for j in request["folded"]],
                [int(j) for j in request["dups"]],
                str(request["digest"]),
                request["aggregate"],
                request.get("pre"),
            )
            return dict(_ACK)
        if kind == "requeue":
            shard.requeue(str(request["tenant"]), int(request["round"]))
            return dict(_ACK)
        if kind == "discard":
            shard.discard_inflight(
                str(request["tenant"]), int(request["round"])
            )
            return dict(_ACK)
        if kind == "account_failed":
            shard.account_failed(
                str(request["tenant"]), int(request["round"])
            )
            return dict(_ACK)
        if kind == "sync_round":
            shard.sync_round(str(request["tenant"]), int(request["round"]))
            return dict(_ACK)
        if kind == "shard_stats":
            return {"kind": "stats", "stats": shard.stats()}
        if kind == "trace_export":
            return {
                "kind": "trace",
                "events": obs_tracing.tracer().events(),
            }
        if kind == RUNNER_SHUTDOWN:
            stop.set()
            return dict(_ACK)
        if kind == "close_round":
            # rounds are coordinator-driven in runner mode: the inner
            # frontend's own closer would fork the round state
            return {
                "kind": "ack",
                "accepted": False,
                "reason": "coordinator_driven",
            }
        return None

    return hook


async def _shard_main(spec: RunnerSpec, index: int) -> None:
    shard = ShardFrontend(
        index, spec.tenants, durability=spec.shard_durability(index)
    )
    stop = asyncio.Event()
    shard.frontend.request_hook = _shard_hook(shard, stop)
    # the control hook returns None for "submit" with no side effects,
    # so the batched ingress may admit drained submit runs in one pass
    # without a per-frame hook call (declared, never inferred)
    shard.frontend.request_hook_passthrough = frozenset({"submit"})
    _host, port = await shard.frontend.serve(spec.host, 0)
    print(f"PORT {port}", flush=True)
    await stop.wait()
    # the shutdown ack is queued on the requesting connection; yield one
    # loop turn so it flushes before the server (and its conns) close
    await asyncio.sleep(0.05)
    await shard.frontend.close()


# ---------------------------------------------------------------------------
# merge-node process (--role merge)
# ---------------------------------------------------------------------------


class _MergeNode:
    """One internal merge-tree node: fans the close to its children,
    verifies every child frame, combines the survivors, ships one
    frame up. Stateless across rounds — all durable state lives at the
    leaves (WALs) and the root (dedup authority + merge evidence), so
    a merge-node crash is a plain partition the parent's timeout
    absorbs."""

    def __init__(
        self,
        spec: RunnerSpec,
        children: Sequence[Tuple[str, str, int, List[int]]],
    ) -> None:
        self.spec = spec
        #: (kind, host, port, covered leaves) per child — "shard"
        #: leaves answer shard_close, "merge" subtrees answer
        #: merge_close; the cover list feeds partition accounting when
        #: a whole child misses the barrier
        self.children = list(children)
        from .sharded import ShardRouter

        #: memoized home-shard lookup (the per-row ownership check
        #: runs every round over every child row)
        self._router = ShardRouter(spec.n_shards)
        self._streams: Dict[int, tuple] = {}
        #: per-child barrier budget, scaled by the child's OWN subtree
        #: depth: a merge child legitimately waits (leaf budget +
        #: slack·sublevels) before it can even reply, so its parent
        #: must wait one slack more — a flat leaf gets the bare budget
        self._child_timeouts = [
            spec.shard_timeout_s
            + spec.level_slack_s * (self._sublevels(cover) + 1)
            if kind == "merge"
            else spec.shard_timeout_s
            for kind, _h, _p, cover in self.children
        ]

    def _sublevels(self, cover: Sequence[int]) -> int:
        """Internal combine levels inside a merge child covering
        ``len(cover)`` leaves (0 when it combines leaves directly)."""
        if self.spec.fanout is None or len(cover) <= self.spec.fanout:
            return 0
        return len(MergeTopology(len(cover), self.spec.fanout).levels)

    async def _child_stream(self, i: int) -> tuple:
        st = self._streams.get(i)
        if st is None:
            from ..resilience.retry import connect_with_retry

            _kind, host, port, _cover = self.children[i]
            reader, writer = await connect_with_retry(
                host, port, policy=dial_policy(), component="merge_node"
            )
            st = self._streams[i] = (reader, writer, asyncio.Lock())
        return st

    async def _child_close(
        self, i: int, tenant: str, frame_bytes: bytes
    ) -> dict:
        timeout = self._child_timeouts[i]
        reader, writer, lock = await self._child_stream(i)
        async with lock:
            writer.write(frame_bytes)
            await writer.drain()
            header = await asyncio.wait_for(
                reader.readexactly(wire._HEADER.size), timeout
            )
            (length,) = wire._HEADER.unpack(header)
            body = await asyncio.wait_for(
                reader.readexactly(length), timeout
            )
            return wire.decode(body)

    def _verify_child(
        self, i: int, reply: dict
    ) -> Tuple[Optional[PartialFold], List[int], List[dict]]:
        """Decode + verify child ``i``'s close reply. Returns
        ``(partial or None, missing leaves, forged events)`` — digest
        recompute, per-row home-shard ownership AND the
        claimed-cover ⊆ child's-registered-cover check run HERE, so a
        poisoned child is excluded before it can brand its siblings'
        combined frame forged (or crash the combine by claiming a
        sibling's shard index) further up the tree."""
        from ..forensics.evidence import evidence_digest

        missing = [int(s) for s in reply.get("missing", ())]
        forged = [dict(ev) for ev in reply.get("forged", ())]
        raw = reply.get("partial")
        if raw is None:
            return None, missing, forged
        try:
            p = PartialFold.from_wire(raw)
        except (ValueError, KeyError, TypeError):
            return None, missing, forged
        registered = set(self.children[i][3])
        if not set(p.covered) <= registered:
            # a frame claiming shards outside this child's subtree: a
            # compromised child may forge a sibling's index with
            # legitimately-hashing client ids — without this check the
            # overlap would surface as a combine_partials ValueError
            # and take the WHOLE level down as missing
            forged.append(
                {
                    "shards": sorted(registered),
                    "claimed_digest": p.digest,
                    "measured_digest": "",
                    "m": p.m,
                }
            )
            return None, missing, forged
        measured = evidence_digest(p.rows)
        ownership_ok = all(
            self._router.shard_for(p.clients[j]) == owner
            for owner, lo, hi in p.segment_spans()
            for j in range(lo, hi)
        )
        if measured != p.digest or not ownership_ok:
            forged.append(
                {
                    "shards": list(p.covered),
                    "claimed_digest": p.digest,
                    "measured_digest": measured if ownership_ok else "",
                    "m": p.m,
                }
            )
            return None, missing, forged
        return p, missing, forged

    async def close(self, tenant: str, round_id: int) -> dict:
        """One level close: barrier the children, verify, combine."""
        with obs_tracing.span(
            "serving.merge_close", track="merge",
            tenant=tenant, round=round_id, children=len(self.children),
        ):
            frames = []
            for kind, _h, _p, _c in self.children:
                op = SHARD_CLOSE if kind == "shard" else MERGE_CLOSE
                frames.append(
                    wire.encode(
                        {"kind": op, "tenant": tenant, "round": round_id},
                        precision="off",
                    )
                )
            loop = asyncio.get_running_loop()

            async def _close_and_verify(i: int) -> tuple:
                # STREAMING fan-in: each child's frame is decoded and
                # verified on the executor the moment it lands, while
                # the siblings' closes are still in flight — by the
                # time the slowest child answers, every other child's
                # verify is already done and only the combine remains
                try:
                    reply = await self._child_close(i, tenant, frames[i])
                except Exception:  # noqa: BLE001 — timeout/reset/late
                    # child: a partition at this level; drop the stream
                    # (it may be mid-frame) and redial next round
                    st = self._streams.pop(i, None)
                    if st is not None:
                        st[1].close()
                    return None, self._leaves_of(i), []
                return await loop.run_in_executor(
                    None,
                    obs_tracing.carry_context(self._verify_child),
                    i, reply,
                )

            results = await asyncio.gather(
                *(_close_and_verify(i) for i in range(len(self.children)))
            )
            partials: List[PartialFold] = []
            missing: List[int] = []
            forged: List[dict] = []
            for p, child_missing, child_forged in results:
                missing.extend(child_missing)
                forged.extend(child_forged)
                if p is not None:
                    partials.append(p)
            combined = None
            if len(partials) == 1:
                combined = partials[0]
            elif partials:
                agg = self.spec.tenants[0].aggregator
                for cfg in self.spec.tenants:
                    if cfg.name == tenant:
                        agg = cfg.aggregator
                        break
                try:
                    combined = combine_partials(agg, partials)
                except ValueError:
                    # belt and braces: _verify_child's cover check
                    # should make this unreachable, but a combine
                    # failure must degrade to "this level missed the
                    # barrier" (missing leaves requeue at the root),
                    # never kill the merge node's connection handler
                    combined = None
                    missing.extend(
                        s for p in partials for s in p.covered
                    )
            return {
                "kind": "partial",
                "partial": None if combined is None else combined.to_wire(),
                "missing": sorted(set(missing)),
                "forged": forged,
                LOSSLESS_REPLY: True,
            }

    def _leaves_of(self, i: int) -> List[int]:
        """Leaf shard indices under child ``i`` (for partition
        accounting when the whole child misses the barrier)."""
        return list(self.children[i][3])

    async def child_moved(self, shard: int, port: int) -> bool:
        """A recovered shard process came back on a new port: update
        the child entry that covers it (or forward down the subtree),
        dropping the stale stream so the next close redials."""
        for j, (kind, host, _old, cover) in enumerate(self.children):
            if shard not in cover:
                continue
            if kind == "shard":
                self.children[j] = (kind, host, int(port), cover)
                st = self._streams.pop(j, None)
                if st is not None:
                    st[1].close()
                return True
            reader, writer, lock = await self._child_stream(j)
            async with lock:
                writer.write(
                    wire.encode(
                        {
                            "kind": "child_moved",
                            "shard": int(shard),
                            "port": int(port),
                        },
                        precision="off",
                    )
                )
                await writer.drain()
                header = await asyncio.wait_for(
                    reader.readexactly(wire._HEADER.size), 30.0
                )
                (length,) = wire._HEADER.unpack(header)
                await asyncio.wait_for(reader.readexactly(length), 30.0)
            return True
        return False


async def _merge_main(
    spec: RunnerSpec, children: Sequence[Tuple[str, str, int, List[int]]]
) -> None:
    node = _MergeNode(spec, children)
    stop = asyncio.Event()

    async def handle(reader, writer):
        try:
            while True:
                try:
                    header = await reader.readexactly(wire._HEADER.size)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                (length,) = wire._HEADER.unpack(header)
                body = await reader.readexactly(length)
                request = wire.decode(body)
                kind = (
                    request.get("kind")
                    if isinstance(request, dict)
                    else None
                )
                if kind == MERGE_CLOSE:
                    resp = await node.close(
                        str(request["tenant"]), int(request["round"])
                    )
                elif kind == "child_moved":
                    moved = await node.child_moved(
                        int(request["shard"]), int(request["port"])
                    )
                    resp = {"kind": "ack", "accepted": bool(moved)}
                elif kind == "trace_export":
                    resp = {
                        "kind": "trace",
                        "events": obs_tracing.tracer().events(),
                    }
                elif kind == RUNNER_SHUTDOWN:
                    resp = dict(_ACK)
                else:
                    resp = {
                        "kind": "ack",
                        "accepted": False,
                        "reason": "bad_frame",
                    }
                lossless = bool(resp.pop(LOSSLESS_REPLY, False))
                writer.write(
                    wire.encode(
                        resp, precision="off" if lossless else None
                    )
                )
                await writer.drain()
                if kind == RUNNER_SHUTDOWN:
                    stop.set()
                    break
        finally:
            writer.close()

    server = await asyncio.start_server(handle, spec.host, 0)
    port = server.sockets[0].getsockname()[1]
    print(f"PORT {port}", flush=True)
    await stop.wait()
    server.close()
    await server.wait_closed()
    for _r, w, _l in node._streams.values():
        w.close()


# ---------------------------------------------------------------------------
# root coordinator process (--role root)
# ---------------------------------------------------------------------------


class _ShardProxy:
    """The root's wire-RPC stand-in for one shard process: answers the
    ``ShardFrontend`` coordinator surface (confirm/requeue/discard/
    account_failed/sync_round/stats) by sending control frames to the
    shard's ingress. Ops are best-effort pushes whose loss maps to
    existing recovery semantics (a lost confirm is the ship-folded-
    but-unconfirmed window the root dedup table already resolves), so
    a dead socket marks the op failed and the next op redials under
    ``dial_policy``."""

    def __init__(self, index: int, host: str, port: int) -> None:
        self.index = int(index)
        self.host = host
        self.port = int(port)
        self.alive = True
        self._sock: Optional[socket.socket] = None
        self.failed_ops = 0
        # pipelined closes run round N's confirm fan-out on the finish
        # thread while the control thread syncs/polls the same shard —
        # the socket carries one op at a time or frames interleave
        self._op_lock = Lock()

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            self._sock = dial_blocking(self.host, self.port)
        return self._sock

    def reset(self) -> None:
        """Drop the cached connection (next op redials)."""
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def move(self, port: int) -> None:
        """Point the proxy at a recovered shard process."""
        self.port = int(port)
        self.reset()
        self.alive = True

    def op(self, frame: dict, *, timeout: float = 30.0) -> Optional[dict]:
        """One control round-trip; one reconnect retry; ``None`` when
        the shard is unreachable (the op is lost, accounted)."""
        if not self.alive:
            return None
        with self._op_lock:
            for _attempt in (0, 1):
                try:
                    sock = self._ensure()
                    sock.settimeout(timeout)
                    return rpc(sock, frame)
                except (OSError, ValueError, ConnectionError):
                    self.reset()
            self.failed_ops += 1
            return None

    # -- the coordinator-facing surface -----------------------------------

    def confirm(
        self, tenant, round_id, folded, dups, digest, aggregate, pre=None
    ) -> None:
        self.op(
            {
                "kind": "confirm",
                "tenant": tenant,
                "round": int(round_id),
                "folded": [int(j) for j in folded],
                "dups": [int(j) for j in dups],
                "digest": digest,
                "aggregate": np.asarray(aggregate, np.float32),
                "pre": pre,
            }
        )

    def requeue(self, tenant, round_id) -> None:
        self.op({"kind": "requeue", "tenant": tenant, "round": int(round_id)})

    def discard_inflight(self, tenant, round_id) -> None:
        self.op({"kind": "discard", "tenant": tenant, "round": int(round_id)})

    def account_failed(self, tenant, round_id) -> None:
        self.op(
            {
                "kind": "account_failed",
                "tenant": tenant,
                "round": int(round_id),
            }
        )

    def sync_round(self, tenant, round_id) -> None:
        self.op(
            {"kind": "sync_round", "tenant": tenant, "round": int(round_id)}
        )

    def stats(self) -> Optional[dict]:
        reply = self.op({"kind": "shard_stats"})
        return None if reply is None else reply.get("stats")

    def shutdown(self) -> None:
        """Lifecycle belongs to the parent Runner — the coordinator's
        close() must not tear down shard processes."""


class _RootServer:
    """The root coordinator process: a proxied ``ShardedCoordinator``
    plus a control-plane TCP server for the operator (close_round /
    stats / shard_down / shard_up / trace_export / shutdown). Round
    closes fan the barrier to the TOP tier (leaf shards on the flat
    topology, merge nodes on a deep one) with one thread per child —
    the close request frames are encoded on the coordinator thread so
    the round span's trace context stamps them (contextvars are
    thread-local)."""

    def __init__(
        self,
        spec: RunnerSpec,
        shard_addrs: Sequence[Tuple[str, int]],
        top_children: Sequence[Tuple[str, str, int, List[int]]],
    ) -> None:
        self.spec = spec
        self.proxies = [
            _ShardProxy(i, host, port)
            for i, (host, port) in enumerate(shard_addrs)
        ]
        self.co = ShardedCoordinator(
            spec.tenants,
            spec.n_shards,
            shard_timeout_s=spec.shard_timeout_s,
            quorum=spec.quorum,
            durability=spec.durability,
            extras_policy=spec.extras_policy,
            shards=self.proxies,
            repair_horizon_rounds=spec.repair_horizon_rounds,
        )
        #: (kind, host, port, covered leaves) per top-tier child
        self.top = list(top_children)
        self._top_socks: Dict[int, socket.socket] = {}
        depth_levels = len(spec.topology.levels)
        self._close_timeout = spec.shard_timeout_s + (
            spec.level_slack_s * max(1, depth_levels)
        )
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, len(self.top)),
            thread_name_prefix="root-barrier",
        )
        self._lock = Lock()
        self._stop = False
        # cross-round pipelining: depth-1 in-flight window per tenant.
        # A pipelined close barriers round N on the control thread,
        # then hands verify+merge+device-step to this 1-worker pool and
        # returns — the shard processes ingest round N+1 while the
        # finish runs. The NEXT close settles the pending finish before
        # barriering, so finishes serialize (WAL round records stay
        # monotonic) and backpressure still reaches the door.
        self._finish_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="root-finish"
        )
        self._pending: Dict[str, dict] = {}
        reg = obs_metrics.registry()
        self._m_overlap = {
            cfg.name: reg.gauge(
                "byzpy_round_overlap_ratio",
                help=(
                    "fraction of the deferred round finish that ran "
                    "hidden behind next-round ingest"
                ),
                labels={"tenant": cfg.name},
            )
            for cfg in spec.tenants
        }

    # -- barrier close -----------------------------------------------------

    def _top_sock(self, i: int) -> socket.socket:
        sock = self._top_socks.get(i)
        if sock is None:
            _kind, host, port, _cover = self.top[i]
            sock = self._top_socks[i] = dial_blocking(host, port)
        return sock

    def _reset_top(self, i: int) -> None:
        sock = self._top_socks.pop(i, None)
        if sock is not None:
            sock.close()

    def _barrier(
        self, tenant: str, round_id: int
    ) -> Tuple[List[PartialFold], List[int], Dict[int, tuple]]:
        """Fan one round's close to the top tier and collect the
        replies: returns ``(partials, missing_set, prechecked)``.
        STREAMING verify: each reader thread decodes its child's frame
        and runs the root's stateless cross-check suite
        (``check_partial`` — digest recompute, ownership, caps) the
        moment the frame lands, overlapped with the siblings still in
        flight — ``prechecked`` maps ``id(partial)`` to the result so
        the merge runs only the dedup. No shard-state side effects —
        requeue/merge policy belongs to the callers (the classic door
        requeues stragglers immediately; a speculative close leaves
        them in flight for the repair horizon)."""
        missing: List[int] = [
            p.index for p in self.proxies if not p.alive
        ]
        live_top = [
            i
            for i, (_k, _h, _p, cover) in enumerate(self.top)
            if any(self.proxies[s].alive for s in cover)
        ]
        # encode on THIS thread: the frames carry the round span's
        # trace context into every child process
        frames = {}
        for i in live_top:
            kind = self.top[i][0]
            op = SHARD_CLOSE if kind == "shard" else MERGE_CLOSE
            frames[i] = wire.encode(
                {"kind": op, "tenant": tenant, "round": round_id},
                precision="off",
            )

        def barrier(i: int) -> tuple:
            sock = self._top_sock(i)
            sock.settimeout(self._close_timeout)
            sock.sendall(frames[i])
            reply = recv_frame(sock)
            raw = reply.get("partial")
            if raw is None:
                return reply, None, None
            try:
                p = PartialFold.from_wire(raw)
            except (ValueError, KeyError, TypeError):
                return reply, None, "bad_partial"
            chk = self.co.check_partial(tenant, p, inflight=True)
            if chk[0]:
                # close-path paydown: stage the dedup verdict + merge
                # input on this reader thread while siblings are still
                # in flight — the root close just promotes
                self.co.stage_partial(tenant, p, chk)
            return reply, p, chk

        futures = {
            self._pool.submit(
                obs_tracing.carry_context(barrier), i
            ): i
            for i in live_top
        }
        partials: List[PartialFold] = []
        prechecked: Dict[int, tuple] = {}
        for fut, i in futures.items():
            cover = self.top[i][3]
            try:
                reply, p, chk = fut.result(
                    timeout=self._close_timeout + 5.0
                )
            except Exception:  # noqa: BLE001 — timeout / dead child:
                # the whole subtree missed the barrier; its socket
                # may be mid-frame, reset it
                self._reset_top(i)
                missing.extend(
                    s for s in cover if self.proxies[s].alive
                )
                continue
            missing.extend(int(s) for s in reply.get("missing", ()))
            for ev in reply.get("forged", ()):
                # one forged FRAME = one count + one evidence
                # event, however many leaves it covered (the
                # flat-root accounting; discard fans per leaf)
                shards = [
                    int(s)
                    for s in ev.get("shards", (ev.get("shard"),))
                    if s is not None
                ]
                if not shards:
                    continue
                self.co.note_forged(
                    tenant,
                    shards,
                    claimed_digest=str(
                        ev.get("claimed_digest", "")
                    ),
                    measured_digest=str(
                        ev.get("measured_digest", "")
                    ),
                    m=int(ev.get("m", 0)),
                )
            if chk == "bad_partial":
                missing.extend(
                    s for s in cover if self.proxies[s].alive
                )
            elif p is not None:
                partials.append(p)
                prechecked[id(p)] = chk
        return partials, sorted(set(missing)), prechecked

    def _requeue_missing(
        self, tenant: str, missing: Sequence[int], round_id: int
    ) -> None:
        """Return missing-but-ALIVE leaves' drained cohorts to their
        held lists. A leaf may have drained for a close whose reply
        never reached us (straggler past the barrier, merge-node
        timeout): requeue it explicitly or its inflight rows strand
        forever — the shard's event loop serializes the frames, so the
        requeue lands AFTER any still-running close finishes
        (idempotent when the leaf drained nothing)."""
        for s in missing:
            if self.proxies[s].alive:
                self.proxies[s].requeue(tenant, round_id)

    def close_round(self, tenant: str) -> Optional[tuple]:
        """One root-driven barrier round over real sockets: fan the
        close to the top tier, decode + account replies, quorum-gate,
        then run the coordinator's verify + hierarchical merge +
        finalize + confirm protocol through the shard proxies. Returns
        ``(closed_round_id, merged_rows, aggregate)`` or ``None``.
        With the repair horizon armed, stragglers are NOT requeued at
        the barrier — the coordinator retains the speculative close's
        repair context and the horizon expiry recycles them."""
        self._settle(tenant)
        rt = self.co._roots[tenant]
        with obs_tracing.span(
            "serving.sharded_round", track="root",
            tenant=tenant, round=rt.round_id,
        ):
            partials, missing_set, prechecked = self._barrier(
                tenant, rt.round_id
            )
            speculative = self.co.repair_horizon > 0
            if not speculative:
                self._requeue_missing(tenant, missing_set, rt.round_id)
            responders = self.spec.n_shards - len(missing_set)
            if responders < self.co.quorum:
                if prechecked:
                    # no merge consumes the arrival checks: unwind
                    self.co._dec_inflight(len(prechecked))
                for p in partials:
                    for s in p.covered:
                        self.proxies[s].requeue(tenant, p.round_id)
                if speculative:
                    self._requeue_missing(
                        tenant, missing_set, rt.round_id
                    )
                rt.quorum_failures += 1
                return None
            if not partials:
                if speculative:
                    self._requeue_missing(
                        tenant, missing_set, rt.round_id
                    )
                return None
            res = self.co.merge_partials(
                tenant, partials, missing=missing_set,
                prechecked=prechecked,
            )
            if res is None and speculative:
                # no close happened — nothing to repair into; recycle
                # the stragglers exactly as the classic path
                self._requeue_missing(tenant, missing_set, rt.round_id)
            return res

    # -- pipelined close (cross-round overlap) -----------------------------

    def close_round_pipelined(self, tenant: str) -> dict:
        """The ALWAYS-ON round door: settle the previous round's
        deferred finish (depth-1 window — this is where backpressure
        bites), barrier round N on this thread, and if quorum fired
        hand verify+merge+device-step to the finish pool and return
        immediately with the next round's admission plane OPEN (shard
        staleness clocks advance optimistically; the ROOT clock stays
        at N until the finish lands, so partial round-id checks still
        pass). Returns ``{"pending": N | None, "prev": <settled round
        N-1 summary | None>, "round": <admitting round>}``. A window
        with no admissible close settles and returns with ``pending:
        None`` — semantics identical to the barrier door."""
        prev = self._settle(tenant)
        rt = self.co._roots[tenant]
        out: dict = {"pending": None, "prev": prev, "round": rt.round_id}
        sp = obs_tracing.begin_span(
            "serving.sharded_round", track="root",
            tenant=tenant, round=rt.round_id, pipelined=True,
        )
        kicked = False
        try:
            with obs_tracing.context_scope(getattr(sp, "context", None)):
                partials, missing_set, prechecked = self._barrier(
                    tenant, rt.round_id
                )
                speculative = self.co.repair_horizon > 0
                if not speculative:
                    self._requeue_missing(
                        tenant, missing_set, rt.round_id
                    )
                responders = self.spec.n_shards - len(missing_set)
                if responders < self.co.quorum:
                    if prechecked:
                        # no merge consumes the arrival checks: unwind
                        self.co._dec_inflight(len(prechecked))
                    for p in partials:
                        for s in p.covered:
                            self.proxies[s].requeue(tenant, p.round_id)
                    if speculative:
                        self._requeue_missing(
                            tenant, missing_set, rt.round_id
                        )
                    rt.quorum_failures += 1
                    return out
                if not partials:
                    if speculative:
                        self._requeue_missing(
                            tenant, missing_set, rt.round_id
                        )
                    return out
            # quorum fired: open round N+1's admission/staleness plane
            # NOW — the shard processes ingest the next round while the
            # finish below runs on the 1-worker pool; the sync fans in
            # PARALLEL (the kick is the serialized part of the pipeline,
            # every sequential round-trip here is unhidden latency)
            closing = rt.round_id
            sync_futs = [
                self._pool.submit(p.sync_round, tenant, closing + 1)
                for p in self.proxies
                if p.alive
            ]
            for f in sync_futs:
                f.result(timeout=self._close_timeout + 5.0)
            entry: dict = {
                "round": closing,
                "kicked": time.monotonic(),
                "done_s": None,
            }
            entry["future"] = self._finish_pool.submit(
                self._deferred_finish,
                tenant, closing, partials, missing_set, prechecked,
                sp, entry,
            )
            self._pending[tenant] = entry
            kicked = True  # span ownership moved to the finish thread
            out["pending"] = closing
            out["round"] = closing + 1
            return out
        finally:
            if not kicked:
                obs_tracing.end_span(sp)

    def _deferred_finish(
        self,
        tenant: str,
        closing: int,
        partials: List[PartialFold],
        missing: List[int],
        prechecked: Dict[int, tuple],
        sp,
        entry: dict,
    ) -> Optional[tuple]:
        """The overlapped half of a pipelined close: verify +
        hierarchical merge + finalize + confirm through the proxies,
        off the control thread. On a failed merge the round is CONSUMED
        anyway (the shard clocks already advanced optimistically, so
        the root clock must follow) — the drained rows requeue and fold
        next round one round staler, the only behavioral divergence
        from the barrier path and only in the failure case."""
        try:
            with obs_tracing.context_scope(getattr(sp, "context", None)):
                res = self.co.merge_partials(
                    tenant, partials, missing=missing,
                    prechecked=prechecked,
                )
            if res is None:
                rt = self.co._roots[tenant]
                rt.round_id = closing + 1
                for p in self.proxies:
                    if p.alive:
                        p.sync_round(tenant, closing + 1)
                if self.co.repair_horizon > 0:
                    self._requeue_missing(tenant, missing, closing)
            return res
        finally:
            entry["done_s"] = time.monotonic()
            obs_tracing.end_span(sp)

    def _settle(self, tenant: str) -> Optional[dict]:
        """Wait out the tenant's pending deferred finish (no-op when
        none): returns the settled round's summary (``closed``/
        ``digest``/``m``/``overlap_ratio``) and publishes the
        ``byzpy_round_overlap_ratio`` gauge — the fraction of the
        finish that ran before anyone had to wait for it, i.e. the
        wall-clock the pipeline actually hid."""
        entry = self._pending.pop(tenant, None)
        if entry is None:
            return None
        wait_start = time.monotonic()
        try:
            res = entry["future"].result(
                timeout=self._close_timeout + 30.0
            )
        except Exception:  # noqa: BLE001 — a crashed finish must not
            # wedge the control door; the round's accounting is
            # whatever the coordinator got to
            res = None
        prev: dict = {"closed": None, "round": int(entry["round"])}
        if res is not None:
            from ..forensics.evidence import evidence_digest

            closed, rows, vec = res
            prev["closed"] = int(closed)
            prev["digest"] = evidence_digest(np.asarray(vec))
            prev["m"] = int(rows.shape[0])
        done_s = entry.get("done_s") or wait_start
        span_s = max(0.0, done_s - entry["kicked"])
        hidden = max(0.0, min(done_s, wait_start) - entry["kicked"])
        ratio = 1.0 if span_s <= 0 else max(0.0, min(1.0, hidden / span_s))
        prev["overlap_ratio"] = round(ratio, 4)
        if obs_runtime.STATE.enabled and tenant in self._m_overlap:
            self._m_overlap[tenant].set(ratio)
        return prev

    # -- control plane -----------------------------------------------------

    def handle(self, request: dict) -> dict:
        kind = request.get("kind")
        if kind == "close_round":
            tenant = str(request.get("tenant"))
            if request.get("pipelined"):
                with self._lock:
                    out = self.close_round_pipelined(tenant)
                return {
                    "kind": "round",
                    "closed": None,
                    "pending": out["pending"],
                    "prev": out["prev"],
                    "round": out["round"],
                    LOSSLESS_REPLY: True,
                }
            with self._lock:
                res = self.close_round(tenant)
            resp: dict = {
                "kind": "round",
                "closed": None,
                "round": self.co.round_of(tenant),
                LOSSLESS_REPLY: True,
            }
            if res is not None:
                from ..forensics.evidence import evidence_digest

                closed, rows, vec = res
                resp["closed"] = closed
                resp["digest"] = evidence_digest(np.asarray(vec))
                resp["m"] = int(rows.shape[0])
                if request.get("return_rows"):
                    resp["rows"] = np.asarray(rows, np.float32)
                    resp["aggregate"] = np.asarray(vec, np.float32)
            return resp
        if kind == "flush_rounds":
            tenant = str(request.get("tenant"))
            with self._lock:
                prev = self._settle(tenant)
                current = self.co.round_of(tenant)
            return {
                "kind": "round",
                "prev": prev,
                "round": current,
                LOSSLESS_REPLY: True,
            }
        if kind == "repair_round":
            tenant = str(request.get("tenant"))
            with self._lock:
                self._settle(tenant)
                try:
                    partial = PartialFold.from_wire(
                        request.get("partial")
                    )
                except (ValueError, KeyError, TypeError):
                    return {
                        "kind": "ack",
                        "accepted": False,
                        "reason": "bad_partial",
                    }
                # arrival-verified once, reused by the repair — a late
                # frame costs ONE cross-check run end to end
                chk = self.co.check_partial(
                    tenant, partial, inflight=True
                )
                res = self.co.repair_round(
                    tenant, partial, prechecked=chk
                )
            resp = {
                "kind": "round",
                "closed": None,
                "round": self.co.round_of(tenant),
                LOSSLESS_REPLY: True,
            }
            if res is not None:
                from ..forensics.evidence import evidence_digest

                closed, rows, vec = res
                resp["closed"] = closed
                resp["digest"] = evidence_digest(np.asarray(vec))
                resp["m"] = int(rows.shape[0])
            return resp
        if kind == "stats":
            with self._lock:
                return {"kind": "stats", "stats": self.co.stats()}
        if kind == "shard_down":
            with self._lock:
                idx = int(request["index"])
                self.proxies[idx].alive = False
                self.proxies[idx].reset()
                self.co._m_live.set(
                    sum(1 for p in self.proxies if p.alive)
                )
            return dict(_ACK)
        if kind == "shard_up":
            with self._lock:
                idx = int(request["index"])
                port = int(request["port"])
                self.proxies[idx].move(port)
                # the barrier path must learn the new address too: a
                # flat top entry is rewritten in place, a merge subtree
                # gets a child_moved frame to route down
                for i, (k, h, _old, cover) in enumerate(self.top):
                    if idx not in cover:
                        continue
                    if k == "shard":
                        self.top[i] = (k, h, port, cover)
                        self._reset_top(i)
                    else:
                        try:
                            sock = self._top_sock(i)
                            sock.settimeout(30.0)
                            rpc(
                                sock,
                                {
                                    "kind": "child_moved",
                                    "shard": idx,
                                    "port": port,
                                },
                            )
                        except (OSError, ValueError, ConnectionError):
                            self._reset_top(i)
                    break
                for name, rt in self.co._roots.items():
                    self.proxies[idx].sync_round(name, rt.round_id)
                self.co._m_live.set(
                    sum(1 for p in self.proxies if p.alive)
                )
            return dict(_ACK)
        if kind == "shard_events":
            with self._lock:
                return {
                    "kind": "events",
                    "events": list(self.co.shard_events),
                }
        if kind == "trace_export":
            return {
                "kind": "trace",
                "events": obs_tracing.tracer().events(),
            }
        if kind == RUNNER_SHUTDOWN:
            self._stop = True
            return dict(_ACK)
        return {"kind": "ack", "accepted": False, "reason": "bad_frame"}

    def shutdown(self) -> None:
        # settle any pending deferred finishes BEFORE tearing sockets
        # down — a mid-flight confirm fan-out must land (WAL round
        # records are the audit trail)
        for tenant in list(self._pending):
            entry = self._pending.pop(tenant, None)
            if entry is None:
                continue
            try:
                entry["future"].result(timeout=self._close_timeout + 30.0)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        with self._lock:
            for rt in self.co._roots.values():
                if rt.durability is not None:
                    rt.durability.close()
            for sock in self._top_socks.values():
                sock.close()
            for p in self.proxies:
                p.reset()
        self._pool.shutdown(wait=False)
        self._finish_pool.shutdown(wait=False)


def _root_main(
    spec: RunnerSpec,
    shard_addrs: Sequence[Tuple[str, int]],
    top_children: Sequence[Tuple[str, str, int, List[int]]],
) -> None:
    root = _RootServer(spec, shard_addrs, top_children)
    server = socket.create_server((spec.host, 0))
    port = server.getsockname()[1]
    print(f"PORT {port}", flush=True)
    server.settimeout(0.5)
    conns: List = []

    def serve_conn(sock: socket.socket) -> None:
        # idle-wait in 1 s slices so every control thread notices
        # _stop and drains (the executor's exit joins them)
        sock.settimeout(1.0)
        try:
            while not root._stop:
                try:
                    request = recv_frame(sock)
                except socket.timeout:
                    continue
                except (ConnectionError, ValueError, OSError):
                    break
                sock.settimeout(None)
                try:
                    resp = root.handle(
                        request if isinstance(request, dict) else {}
                    )
                except Exception as exc:  # noqa: BLE001 — a bad operator
                    # frame must not kill the control plane
                    resp = {
                        "kind": "ack",
                        "accepted": False,
                        "reason": f"error: {type(exc).__name__}: {exc}",
                    }
                lossless = bool(resp.pop(LOSSLESS_REPLY, False))
                try:
                    sock.sendall(
                        wire.encode(
                            resp, precision="off" if lossless else None
                        )
                    )
                except OSError:
                    break
                sock.settimeout(1.0)
        finally:
            sock.close()

    with ThreadPoolExecutor(
        max_workers=8, thread_name_prefix="root-ctl"
    ) as ctl:
        while not root._stop:
            # accept times out every 0.5 s, so a 30 s tick gap means the
            # control plane itself wedged (not an idle fabric)
            sanitize.loop_tick("runner.root_accept", threshold_s=30.0)
            try:
                sock, _addr = server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conns.append(ctl.submit(serve_conn, sock))
    server.close()
    root.shutdown()


# ---------------------------------------------------------------------------
# parent-side runner (spawns + manages the process fleet)
# ---------------------------------------------------------------------------


class _Child:
    """One spawned tier process (shard / merge / root)."""

    def __init__(
        self, role: str, index: int, proc: subprocess.Popen, port: int
    ) -> None:
        self.role = role
        self.index = index
        self.proc = proc
        self.port = port

    def sigkill(self) -> None:
        self.proc.kill()
        self.proc.wait()

    def stop(self, timeout: float = 15.0) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:  # pragma: no cover
                self.proc.kill()
                self.proc.wait()


def _read_port(proc: subprocess.Popen, what: str) -> int:
    import select

    assert proc.stdout is not None
    deadline = time.monotonic() + 180
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        # select before readline: a wedged child that stays alive
        # without printing must trip the deadline, not block the
        # spawner forever (the PORT line is one flushed write, so a
        # ready fd yields a complete line)
        ready, _, _ = select.select(
            [proc.stdout], [], [], min(1.0, remaining)
        )
        if not ready:
            if proc.poll() is not None:
                raise RuntimeError(f"{what} died before printing PORT")
            continue
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(f"{what} died before printing PORT")
        if line.startswith("PORT "):
            return int(line.split()[1])
    raise RuntimeError(f"{what} never printed PORT within 180s")


class Runner:
    """Spawn and drive one process-per-shard deployment: N shard
    processes, the merge-node processes the topology asks for, and the
    root coordinator process — all on this host, all over real TCP
    sockets (the zero-shared-state shape a multi-host deployment
    copies with different addresses).

    Use as a context manager; :meth:`close` performs a DRAINED
    shutdown (control-frame stop to every child, SIGTERM fallback) and
    raises if any process survives — no orphans is part of the
    contract the CI smoke asserts."""

    def __init__(self, spec: RunnerSpec) -> None:
        self.spec = spec
        self.shards: List[_Child] = []
        self.merges: List[_Child] = []
        self.root: Optional[_Child] = None
        self._workdir: Optional[tempfile.TemporaryDirectory] = None
        self._spec_path: Optional[str] = None
        self._ctl: Optional[socket.socket] = None

    def __enter__(self) -> "Runner":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- lifecycle ---------------------------------------------------------

    def _spawn(self, role: str, index: int, extra: List[str]) -> _Child:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.spec.telemetry:
            env["BYZPY_TPU_TELEMETRY"] = "1"
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "byzpy_tpu.serving.runner",
                "--role", role, "--spec", str(self._spec_path),
                "--index", str(index), *extra,
            ],
            stdout=subprocess.PIPE,
            text=True,
            env=env,
        )
        port = _read_port(proc, f"{role}{index}")
        return _Child(role, index, proc, port)

    def start(self) -> None:
        """Spawn the fleet bottom-up (shards → merge levels → root) and
        connect the operator control socket. A spawn failure partway
        tears the already-started children back down (no orphans on
        the failure path either)."""
        if self.root is not None:
            return
        try:
            self._start()
        except BaseException:
            self.close()
            raise

    def _start(self) -> None:
        import cloudpickle

        self._workdir = tempfile.TemporaryDirectory(prefix="byzpy-runner-")
        self._spec_path = os.path.join(self._workdir.name, "spec.pkl")
        with open(self._spec_path, "wb") as f:
            f.write(cloudpickle.dumps(self.spec))
        spec = self.spec
        self.shards = [
            self._spawn("shard", i, []) for i in range(spec.n_shards)
        ]
        # tier: (kind, host, port, covered leaves) per live node,
        # leaf-most first; each merge level groups the previous tier
        tier: List[Tuple[str, str, int, List[int]]] = [
            ("shard", spec.host, c.port, [i])
            for i, c in enumerate(self.shards)
        ]
        merge_index = 0
        for level in spec.topology.levels:
            nxt: List[Tuple[str, str, int, List[int]]] = []
            for group in level:
                children = [
                    node for node in tier if node[3][0] in group
                ]
                child = self._spawn(
                    "merge",
                    merge_index,
                    [
                        "--children",
                        json.dumps(
                            [
                                [k, h, p, cover]
                                for k, h, p, cover in children
                            ]
                        ),
                    ],
                )
                self.merges.append(child)
                merge_index += 1
                nxt.append(
                    (
                        "merge",
                        spec.host,
                        child.port,
                        sorted(s for node in children for s in node[3]),
                    )
                )
            tier = nxt
        self.root = self._spawn(
            "root",
            0,
            [
                "--shards",
                json.dumps([[spec.host, c.port] for c in self.shards]),
                "--children",
                json.dumps([[k, h, p, cover] for k, h, p, cover in tier]),
            ],
        )
        self._ctl = dial_blocking(spec.host, self.root.port)

    @property
    def shard_ports(self) -> List[int]:
        """Ingress port per shard (clients submit here directly)."""
        return [c.port for c in self.shards]

    def _control(self, frame: dict, *, timeout: float = 600.0) -> dict:
        assert self._ctl is not None, "start() first"
        self._ctl.settimeout(timeout)
        return rpc(self._ctl, frame)

    # -- operator surface --------------------------------------------------

    def close_round(
        self, tenant: str, *, return_rows: bool = False
    ) -> dict:
        """Drive one barrier round at the root (over its control
        socket); the reply carries the closed round id + aggregate
        digest (+ merged rows/aggregate bits when asked — the parity
        checks in tests and the bench read them)."""
        return self._control(
            {
                "kind": "close_round",
                "tenant": tenant,
                "return_rows": bool(return_rows),
            }
        )

    def close_round_pipelined(self, tenant: str) -> dict:
        """Kick one PIPELINED round at the root: the reply returns as
        soon as the barrier + quorum gate land — round N's verify/
        merge/device step keeps running at the root while the shards
        admit round N+1. The reply carries ``pending`` (the round now
        finishing, or ``None`` when the window had nothing), ``prev``
        (the PREVIOUS pipelined round's settled summary — closed id,
        digest, m, overlap_ratio) and ``round`` (the round now
        admitting). Call :meth:`flush_rounds` to settle the last
        in-flight round."""
        return self._control(
            {"kind": "close_round", "tenant": tenant, "pipelined": True}
        )

    def flush_rounds(self, tenant: str) -> dict:
        """Settle the tenant's in-flight pipelined round (no-op when
        none): the reply's ``prev`` is the settled summary."""
        return self._control({"kind": "flush_rounds", "tenant": tenant})

    def stats(self) -> dict:
        """Root + per-shard accounting (the proxies poll each shard)."""
        return self._control({"kind": "stats"})["stats"]

    def shard_events(self) -> List[dict]:
        """The root's bounded shard-event tail (forgeries, quorum
        closes)."""
        return self._control({"kind": "shard_events"})["events"]

    def trace_exports(self) -> Dict[str, List[dict]]:
        """Pull every process's tracer events (``{"root": [...],
        "shard0": [...], "merge0": [...]}``) for cross-process
        stitching — each process prefixes its span ids with its pid,
        so the merged event list is collision-free by construction."""
        out: Dict[str, List[dict]] = {}
        out["root"] = self._control({"kind": "trace_export"})["events"]
        for child in [*self.shards, *self.merges]:
            if child.proc.poll() is not None:
                continue
            sock = dial_blocking(self.spec.host, child.port)
            try:
                sock.settimeout(30.0)
                reply = rpc(sock, {"kind": "trace_export"})
                out[f"{child.role}{child.index}"] = reply.get(
                    "events", []
                )
            finally:
                sock.close()
        return out

    # -- failure drill -----------------------------------------------------

    def kill_shard(self, index: int) -> None:
        """SIGKILL the shard process (memory gone, WAL survives) and
        tell the root — its clients get ``rejected_shard_down``-shaped
        connection failures until recovery."""
        self.shards[index].sigkill()
        self._control({"kind": "shard_down", "index": index})

    def recover_shard(self, index: int) -> None:
        """Respawn the killed shard on the SAME durability directory
        (WAL-rebuild: pending accepts re-enter its queue, dedup +
        credit totals replay) and point the root's proxy at the new
        port."""
        child = self._spawn("shard", index, [])
        self.shards[index] = child
        self._control(
            {"kind": "shard_up", "index": index, "port": child.port}
        )

    def close(self) -> None:
        """Drained shutdown: stop children via control frames, SIGTERM
        stragglers, assert nothing survives."""
        children: List[_Child] = []
        if self.root is not None:
            children.append(self.root)
        children.extend(self.merges)
        children.extend(self.shards)
        if self._ctl is not None:
            try:
                self._control({"kind": RUNNER_SHUTDOWN}, timeout=15.0)
            except Exception:  # noqa: BLE001 — root already gone
                pass
            self._ctl.close()
            self._ctl = None
        for child in [*self.merges, *self.shards]:
            if child.proc.poll() is not None:
                continue
            try:
                sock = dial_blocking(
                    self.spec.host, child.port,
                    policy=RetryPolicy(
                        max_attempts=2, base_s=0.05, cap_s=0.2,
                        deadline_s=2.0,
                    ),
                )
                try:
                    sock.settimeout(10.0)
                    rpc(sock, {"kind": RUNNER_SHUTDOWN})
                finally:
                    sock.close()
            except Exception:  # noqa: BLE001 — already exiting
                pass
        for child in children:
            child.stop()
        leaked = [
            f"{c.role}{c.index}" for c in children if c.proc.poll() is None
        ]
        self.root = None
        self.merges = []
        self.shards = []
        if self._workdir is not None:
            self._workdir.cleanup()
            self._workdir = None
        if leaked:  # pragma: no cover — the no-orphans contract
            raise RuntimeError(f"runner leaked processes: {leaked}")


# ---------------------------------------------------------------------------
# client (routing + pipelined submission)
# ---------------------------------------------------------------------------


class RunnerClient:
    """Blocking client for a runner deployment: routes each submission
    to its home shard's ingress (the same sticky blake2s hash every
    tier participant derives) and supports WINDOWED PIPELINING —
    ``submit_many`` keeps up to ``window`` frames in flight per shard
    connection so the wire stays full without unbounded ack buffering
    (the per-frame request/response shape stays intact; only the
    interleaving changes)."""

    def __init__(
        self, host: str, shard_ports: Sequence[int], *, window: int = 256
    ) -> None:
        self.host = host
        self.ports = list(shard_ports)
        self.window = int(window)
        self._socks: Dict[int, socket.socket] = {}

    @property
    def n_shards(self) -> int:
        """Shard count (the routing modulus)."""
        return len(self.ports)

    def _sock(self, shard: int) -> socket.socket:
        sock = self._socks.get(shard)
        if sock is None:
            sock = self._socks[shard] = dial_blocking(
                self.host, self.ports[shard]
            )
        return sock

    def encode_submit(
        self,
        tenant: str,
        client: str,
        round_id: int,
        gradient: np.ndarray,
        *,
        seq: Optional[int] = None,
    ) -> Tuple[int, bytes]:
        """Pre-encode one submit frame; returns ``(home_shard,
        frame_bytes)`` so benches can build a round's traffic outside
        the timed region."""
        return (
            shard_for(client, self.n_shards),
            wire.encode(
                {
                    "kind": "submit",
                    "tenant": tenant,
                    "client": client,
                    "round": int(round_id),
                    "gradient": gradient,
                    "seq": seq,
                }
            ),
        )

    def submit(
        self,
        tenant: str,
        client: str,
        round_id: int,
        gradient: np.ndarray,
        *,
        seq: Optional[int] = None,
    ) -> dict:
        """One routed submission round-trip."""
        shard, frame = self.encode_submit(
            tenant, client, round_id, gradient, seq=seq
        )
        sock = self._sock(shard)
        sock.settimeout(60.0)
        sock.sendall(frame)
        return recv_frame(sock)

    def pipeline(self, shard: int, frames: Sequence[bytes]) -> List[dict]:
        """Send ``frames`` to one shard with windowed pipelining and
        return the acks in order."""
        sock = self._sock(shard)
        sock.settimeout(120.0)
        acks: List[dict] = []
        w = self.window
        for lo in range(0, len(frames), w):
            chunk = frames[lo: lo + w]
            sock.sendall(b"".join(chunk))
            for _ in chunk:
                acks.append(recv_frame(sock))
        return acks

    def submit_many(
        self, frames_by_shard: Dict[int, List[bytes]]
    ) -> Tuple[int, int]:
        """Drive every shard's frame list concurrently (one thread per
        shard — the threads only move bytes, the shard processes do
        the decode + admission work). Returns ``(accepted,
        rejected)``."""
        accepted = 0
        rejected = 0

        def drive(shard: int) -> Tuple[int, int]:
            acks = self.pipeline(shard, frames_by_shard[shard])
            ok = sum(1 for a in acks if a.get("accepted"))
            return ok, len(acks) - ok

        live = [s for s, frames in frames_by_shard.items() if frames]
        if not live:
            return 0, 0
        with ThreadPoolExecutor(max_workers=len(live)) as pool:
            for ok, bad in pool.map(drive, live):
                accepted += ok
                rejected += bad
        return accepted, rejected

    def close(self) -> None:
        """Close every shard connection."""
        for sock in self._socks.values():
            sock.close()
        self._socks.clear()


# ---------------------------------------------------------------------------
# CLI (child roles + the CI smoke)
# ---------------------------------------------------------------------------


def _load_spec(path: str) -> RunnerSpec:
    import cloudpickle

    with open(path, "rb") as f:
        return cloudpickle.loads(f.read())


def _smoke() -> None:
    """CI leg: 2 shard processes + root over real sockets — parity vs
    the single frontend asserted bit-for-bit, bounded wall-clock,
    drained shutdown leaves no orphan processes."""
    from ..aggregators import CoordinateWiseTrimmedMean

    t0 = time.monotonic()
    dim, n_clients, rounds = 64, 12, 3
    spec = RunnerSpec(
        tenants=[
            TenantConfig(
                name="m0",
                aggregator=CoordinateWiseTrimmedMean(f=1),
                dim=dim,
                cohort_cap=64,
                queue_capacity=128,
            )
        ],
        n_shards=2,
        telemetry=True,
    )
    rng = np.random.default_rng(0)
    ref_agg = CoordinateWiseTrimmedMean(f=1)
    barrier_digests: List[str] = []
    with Runner(spec) as runner:
        client = RunnerClient("127.0.0.1", runner.shard_ports)
        try:
            for r in range(rounds):
                frames: Dict[int, List[bytes]] = {0: [], 1: []}
                for i in range(n_clients):
                    shard, frame = client.encode_submit(
                        "m0", f"c{i:03d}", r,
                        rng.normal(size=dim).astype(np.float32), seq=r,
                    )
                    frames[shard].append(frame)
                accepted, rejected = client.submit_many(frames)
                assert accepted == n_clients and rejected == 0, (
                    accepted, rejected,
                )
                reply = runner.close_round("m0", return_rows=True)
                assert reply["closed"] == r, reply
                barrier_digests.append(reply["digest"])
                rows = np.asarray(reply["rows"])
                ref = np.asarray(
                    ref_agg.aggregate(
                        [rows[i] for i in range(rows.shape[0])]
                    )
                )
                assert np.array_equal(
                    np.asarray(reply["aggregate"]), ref
                ), f"runner parity diverged at round {r}"
            # streaming leg: the frames were verified on the reader
            # threads the moment they landed (check_partial at arrival)
            # and every arrival-verified frame was consumed by a close
            st = runner.stats()["root"]["m0"]
            assert st["partial_checks"] >= rounds, st
            assert st["partials_inflight"] == 0, st
            # close-path paydown: every frame's dedup verdict staged on
            # its reader thread, every close settled off the staged
            # accumulator, zero redundant per-partial transforms
            assert st["dedup_staged"] >= 2 * rounds, st
            assert st["dedup_promoted"] >= 2 * rounds, st
            assert st["dedup_restaged"] == 0, st
            assert st["staged_closes"] == rounds, st
            assert st["partial_transforms"] == 0, st
            stream_checks = st["partial_checks"]
            exports = runner.trace_exports()
        finally:
            client.close()
    # -- pipelined leg: IDENTICAL traffic through the always-on door —
    # round N+1's frames must be admitted while round N's finish is
    # still in flight at the root, and every settled digest must match
    # the barrier door's bit-for-bit
    rng = np.random.default_rng(0)
    overlap_admitted = 0
    pipelined_digests: List[str] = []
    with Runner(spec) as runner:
        client = RunnerClient("127.0.0.1", runner.shard_ports)
        try:
            def _build(r: int) -> Dict[int, List[bytes]]:
                frames: Dict[int, List[bytes]] = {0: [], 1: []}
                for i in range(n_clients):
                    shard, frame = client.encode_submit(
                        "m0", f"c{i:03d}", r,
                        rng.normal(size=dim).astype(np.float32), seq=r,
                    )
                    frames[shard].append(frame)
                return frames

            accepted, rejected = client.submit_many(_build(0))
            assert accepted == n_clients and rejected == 0
            for r in range(rounds):
                reply = runner.close_round_pipelined("m0")
                assert reply["pending"] == r, reply
                if r > 0:
                    prev = reply["prev"]
                    assert prev and prev["closed"] == r - 1, reply
                    pipelined_digests.append(prev["digest"])
                if r + 1 < rounds:
                    # admission for round N+1 while round N's verify/
                    # merge/device step runs deferred at the root — the
                    # acks land BEFORE anything settles round N
                    accepted, rejected = client.submit_many(
                        _build(r + 1)
                    )
                    assert accepted == n_clients and rejected == 0, (
                        accepted, rejected,
                    )
                    overlap_admitted += accepted
            tail = runner.flush_rounds("m0")
            prev = tail["prev"]
            assert prev and prev["closed"] == rounds - 1, tail
            pipelined_digests.append(prev["digest"])
            st = runner.stats()["root"]["m0"]
            assert st["partial_checks"] >= rounds, st
            assert st["partials_inflight"] == 0, st
            # pipelined door: staging survives the cross-round overlap
            # (epoch revalidation, never a verdict flip on this traffic)
            assert st["dedup_restaged"] == 0, st
            assert st["staged_closes"] == rounds, st
            assert st["partial_transforms"] == 0, st
        finally:
            client.close()
    assert overlap_admitted > 0, "no frames admitted during overlap"
    assert pipelined_digests == barrier_digests, (
        "pipelined close diverged from the barrier door",
        pipelined_digests, barrier_digests,
    )
    # one causal tree across processes: a root round span's trace id
    # must appear in at least one shard process's export
    root_traces = {
        ev["args"]["trace"]
        for ev in exports["root"]
        if ev.get("name") == "serving.sharded_round"
        and "trace" in ev.get("args", {})
    }
    shard_traces = {
        ev["args"]["trace"]
        for name, events in exports.items()
        if name.startswith("shard")
        for ev in events
        if "trace" in ev.get("args", {})
    }
    assert root_traces & shard_traces, (
        "cross-process trace stitching broke: no shared trace id"
    )
    wall = time.monotonic() - t0
    assert wall < 300, f"runner smoke took {wall:.1f}s (budget 300s)"
    print(
        json.dumps(
            {
                "lane": "runner_smoke",
                "rounds": rounds,
                "parity": "bit-identical",
                "pipelined_parity": "bit-identical",
                "streaming_checks": stream_checks,
                "overlap_admitted": overlap_admitted,
                "stitched_traces": len(root_traces & shard_traces),
                "wall_s": round(wall, 2),
            }
        )
    )
    print("runner smoke OK")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--role", choices=["shard", "merge", "root"])
    ap.add_argument("--spec", type=str, default=None)
    ap.add_argument("--index", type=int, default=0)
    ap.add_argument("--children", type=str, default="[]",
                    help="JSON [[kind, host, port, [leaves]], ...]")
    ap.add_argument("--shards", type=str, default="[]",
                    help="JSON [[host, port], ...] (root role)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI leg: 2-shard runner, parity + no orphans")
    args = ap.parse_args()
    if args.smoke:
        _smoke()
        return
    if not args.role:
        raise SystemExit("need --role or --smoke")
    if not args.spec:
        raise SystemExit("--role requires --spec")
    spec = _load_spec(args.spec)
    if spec.telemetry and not obs_runtime.STATE.enabled:
        from .. import observability

        observability.enable()
    if args.role == "shard":
        asyncio.run(_shard_main(spec, args.index))
        return
    children = [
        (str(k), str(h), int(p), [int(s) for s in cover])
        for k, h, p, cover in json.loads(args.children)
    ]
    if args.role == "merge":
        asyncio.run(_merge_main(spec, children))
        return
    shard_addrs = [
        (str(h), int(p)) for h, p in json.loads(args.shards)
    ]
    _root_main(spec, shard_addrs, children)


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()


__all__ = [
    "MERGE_CLOSE",
    "RUNNER_SHUTDOWN",
    "SHARD_CLOSE",
    "Runner",
    "RunnerClient",
    "RunnerSpec",
    "dial_blocking",
    "recv_frame",
    "rpc",
    "send_frame",
]

"""Sharded frontend tier: N ingress shards + one root merge per round.

PR 6 proved 10k clients through ONE :class:`~byzpy_tpu.serving.
ServingFrontend` — a single asyncio process, one admission queue, one
device lock — and PR 11's ragged door made the device dispatch cheap
enough that the frontend PROCESS is now the throughput ceiling (~9.6k
accepted/sec of wire decode + admission in one Python loop). This
module is the scale-out past that ceiling, in the spirit of Podracer's
pod-scale actor orchestration (arXiv:2104.06272) and the actor-vs-
learner stage split MPMD pipeline work formalizes (arXiv:2412.14374):

* **N frontend shards**, client-hash partitioned (:class:`ShardRouter`,
  sticky: a client's submissions always land on its home shard). Each
  shard is a FULL single-frontend admission plane — bounded queue,
  credit ledger, staleness gate, ``(client, seq)`` dedup, forensics
  trust gating, write-ahead durability — run against its own ledgers,
  so the per-submission work parallelizes across shard processes with
  nothing shared.
* **One root coordinator** that closes rounds with a shard barrier:
  each shard drains its queue, builds its local cohort, and extracts a
  :class:`PartialFold` — a wire type on the PR-3 HMAC frames carrying
  the aggregator's streaming fold contribution (the discounted rows
  plus the family's sublinear accumulators: trimmed-mean running sum +
  extreme buffers, Multi-Krum's local Gram block, CGE's norms — see
  ``Aggregator.fold_partial``). The root verifies, merges
  (``Aggregator.fold_merge``) and finalizes (``fold_merge_finalize``)
  — **bit-identical** (f32, finite cohorts) to the single-frontend
  aggregate of the concatenated cohort, because the merged rows run
  the same masked program the one-frontend path uses (the PR-6 masked-
  finalize parity recipe is the contract, pinned by
  ``tests/test_partial_fold.py`` and the chaos wall's ``shard`` lane).

Round protocol (root-driven barrier):

1. the root opens global round ``r``; every live shard's admission
   plane stamps staleness against ``r``;
2. on the window trigger the root asks every live shard for its
   partial. Shards that answer within ``shard_timeout_s`` form the
   round; stragglers are **accounted as a partition** (their drained
   rows re-enter their held list and fold next round, one round
   staler — never lost, never double-folded) and the round closes
   **degraded** when at least ``quorum`` shards responded;
3. the root cross-checks every partial (below), merges in shard order,
   pads to the root bucket ladder (one compiled program per bucket,
   not per merged size), finalizes, confirms each shard's folded rows
   (the shard then writes its WAL round record), fans the global
   forensics score view back to the shard planes, and broadcasts.

Federated correctness state:

* ``(client, seq)`` dedup is two-level: the home shard's high-water
  table absorbs ordinary retries; the ROOT keeps its own high-water
  table as the cross-shard authority — after a shard failover the
  recovered shard replays its WAL-pending accepts, and any row the
  root already folded is dropped at merge (acked to the shard as
  ``root_duplicate``, WAL-accounted) — exactly-once folding across
  shard death (audited by :func:`audit_sharded_exactly_once`).
* credit/trust ledgers live on the home shard (sticky routing makes
  them authoritative); on failover they are rebuilt by ledger-delta
  replay through the shard's PR-9 WAL (:meth:`ShardedCoordinator.
  recover_shard` reconstructs the shard frontend from its durability
  directory alone — in-memory state is deliberately discarded, the
  SIGKILL shape).

Compromised-shard threat model (the chaos wall's ``shard`` lane): a
Byzantine SHARD is a new adversary class — it can forge its partial
fold wholesale. The root's cross-checks catch, per partial: (a) a rows
↔ digest mismatch (``PartialFold.digest`` is recomputed from the
shipped row bits — any post-hoc tamper, bit rot, or lazy forgery);
(b) rows claiming clients whose home shard is not the sender (sticky
routing makes cross-shard client claims a protocol violation — the
replay-another-shard's-clients attack); (c) ``(client, seq)`` already
folded (the root dedup table); (d) with ``extras_policy="verify"``,
claimed streaming accumulators that do not reproduce from the rows
(extras are deterministic summaries). A shard that forges
*consistently* — fabricated rows with a matching digest for clients it
legitimately owns — is indistinguishable from a shard whose clients
are Byzantine: its influence is bounded by the robust aggregator
itself (its rows are a minority of the merged cohort) plus the
per-shard row cap, which is exactly the f-out-of-n contract the tier
already runs on. Detected forgeries exclude the partial, count
``byzpy_shard_forged_folds_total``, and append an auditable evidence
event to the root WAL (riding the PR-10 forensics schema).

Wire: a :class:`PartialFold` rides the actor wire verbatim
(``PartialFold.to_wire()`` → ``wire.encode`` → HMAC when
``BYZPY_TPU_WIRE_KEY`` is set); the analytic per-frame cost is
``parallel.comms.partial_fold_bytes`` and the whole tier's round law
``parallel.comms.sharded_round_wire_bytes``. In-process deployments
(the bench's Podracer-style N-shards-on-one-host swarm) skip the
socket but keep the frames; docs/serving.md §sharded tier covers the
process-per-shard layout. On the REMOTE-root door
(:meth:`ShardedCoordinator.merge_partials` over decoded frames) the
claimed shard INDEX is only as trustworthy as the transport: the
shared-key HMAC authenticates the fabric, not which shard sent a
frame, so the root rejects unknown indices and a second partial for a
shard it already heard from this round (without touching any real
shard's state) — a deployment where shards may be individually
compromised should give each shard its own wire key and verify
sender↔index at the socket layer.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis import sanitize
from ..engine.actor import wire
from ..forensics.evidence import evidence_digest
from ..observability import metrics as obs_metrics
from ..observability import runtime as obs_runtime
from ..observability import tracing as obs_tracing
from ..resilience.durable import DurabilityConfig, TenantDurability, read_wal
from .cohort import Cohort, build_cohort
from .credits import RoundStats
from .frontend import ServingFrontend, TenantConfig

#: Wire frame kind of one shard's per-round fold contribution.
PARTIAL_FOLD = "partial_fold"

#: Submission ack when the client's home shard is down (sticky routing:
#: the row must not silently land elsewhere — the client retries until
#: the shard recovers or the operator re-provisions).
REJECTED_SHARD_DOWN = "rejected_shard_down"

#: Per-shard WAL drop reason for rows the ROOT refused as already
#: folded (post-failover replays) — the exactly-once account.
ROOT_DUPLICATE = "root_duplicate"


def shard_for(client: str, n_shards: int) -> int:
    """Sticky client→shard assignment: a stable (process- and
    platform-independent) hash of the client id — every participant
    (router, root cross-check, remote shard ingress) derives the same
    home shard for the same client."""
    import hashlib

    h = hashlib.blake2s(str(client).encode(), digest_size=8).digest()
    return int.from_bytes(h, "big") % int(n_shards)


class ShardRouter:
    """Client-hash partitioner over ``n_shards`` frontend shards.

    Assignments are memoized (bounded — cleared past ``2^17`` distinct
    ids): the blake2s costs ~1 µs and sits on BOTH hot paths (every
    submission's routing, every merged row's home-shard cross-check),
    while repeat clients are the common case."""

    __slots__ = ("n_shards", "_cache")

    _CACHE_MAX = 1 << 17

    def __init__(self, n_shards: int) -> None:
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = int(n_shards)
        self._cache: Dict[str, int] = {}

    def shard_for(self, client: str) -> int:
        """Home shard of ``client`` (sticky)."""
        s = self._cache.get(client)
        if s is None:
            s = shard_for(client, self.n_shards)
            if len(self._cache) >= self._CACHE_MAX:
                self._cache.clear()
            self._cache[client] = s
        return s


def _as_trace_ctx(value: Any) -> Optional[Tuple[str, str]]:
    """Best-effort decode of a wire frame's carried trace context —
    telemetry metadata only, so anything malformed becomes ``None``
    instead of rejecting the partial."""
    try:
        trace_id, span_id = value
        return (str(trace_id), str(span_id))
    except Exception:  # noqa: BLE001 — wire-shaped input, never trusted
        return None


@dataclass(frozen=True)
class PartialFold:
    """One shard's per-round streaming fold contribution (wire type).

    ``rows``: the shard cohort's VALID rows, staleness-discounted, in
    admission order — the exact bits the single-frontend fold would
    have aggregated for these submissions. ``extras``: the
    aggregator family's sublinear fold accumulators over those rows
    (``Aggregator._partial_extras``; empty dict when the family has
    none). ``digest``: 16-hex fingerprint of the row bits
    (:func:`~byzpy_tpu.forensics.evidence.evidence_digest`) — the
    root recomputes it from the shipped rows; a mismatch is a forged
    fold. ``clients``/``seqs``/``wal_ids`` align with ``rows`` and
    carry the identities the root's cross-shard dedup and the shard's
    exactly-once WAL accounting need. ``trace_ctx`` (optional) is the
    shard's ``serving.shard_close`` span context ``(trace_id,
    span_id)`` — telemetry-only causality metadata the root's merge
    span records as a cross-process link (never verified, never part
    of the digest: a forged context can at worst mis-draw a trace).

    ``segments`` (optional) makes the frame a COMBINED partial on the
    depth-N merge tree (:func:`combine_partials`): ``((shard, m), …)``
    names, in row order, which leaf shard owns each contiguous row
    block — ``None`` means the flat single-shard frame ``((shard,
    m),)``. The parent's cross-checks (home-shard ownership, per-shard
    row cap, dedup) run per segment, so a rack/pod-level combiner
    changes WHERE verification work happens, never what it checks."""

    tenant: str
    round_id: int
    shard: int
    rows: np.ndarray
    clients: Tuple[str, ...]
    seqs: Tuple[Optional[int], ...]
    wal_ids: Tuple[Optional[int], ...]
    extras: dict
    digest: str
    first_arrival_s: float
    trace_ctx: Optional[Tuple[str, str]] = None
    segments: Optional[Tuple[Tuple[int, int], ...]] = None

    @property
    def m(self) -> int:
        """Row count of this partial."""
        return int(self.rows.shape[0])

    @property
    def covered(self) -> Tuple[int, ...]:
        """Leaf shard indices this partial carries rows for (one index
        for a flat partial, the combined group for a tree partial)."""
        if self.segments is None:
            return (self.shard,)
        return tuple(int(s) for s, _m in self.segments)

    def segment_spans(self) -> Tuple[Tuple[int, int, int], ...]:
        """``(shard, row_lo, row_hi)`` spans in row order — a flat
        partial degenerates to one span covering every row."""
        if self.segments is None:
            return ((self.shard, 0, self.m),)
        spans = []
        lo = 0
        for s, m in self.segments:
            spans.append((int(s), lo, lo + int(m)))
            lo += int(m)
        return tuple(spans)

    def to_wire(self) -> dict:
        """Frame body for the HMAC actor wire (``wire.encode``)."""
        return {
            "kind": PARTIAL_FOLD,
            "tenant": self.tenant,
            "round": int(self.round_id),
            "shard": int(self.shard),
            "rows": np.asarray(self.rows, np.float32),
            "clients": list(self.clients),
            "seqs": list(self.seqs),
            "wal_ids": list(self.wal_ids),
            "extras": self.extras,
            "digest": self.digest,
            "first_arrival_s": float(self.first_arrival_s),
            "trace_ctx": self.trace_ctx,
            "segments": (
                None
                if self.segments is None
                else [[int(s), int(m)] for s, m in self.segments]
            ),
        }

    @classmethod
    def from_wire(cls, frame: dict) -> "PartialFold":
        """Decode one wire frame body (raises ``ValueError`` on a frame
        that is not a well-formed partial fold — malformed frames from
        a buggy shard must be an explicit rejection, not a crash)."""
        if not isinstance(frame, dict) or frame.get("kind") != PARTIAL_FOLD:
            raise ValueError("not a partial_fold frame")
        rows = np.asarray(frame["rows"], np.float32)
        if rows.ndim != 2:
            raise ValueError("partial_fold rows must be (m, d)")
        clients = tuple(str(c) for c in frame["clients"])
        seqs = tuple(
            None if q is None else int(q) for q in frame["seqs"]
        )
        wal_ids = tuple(
            None if w is None else int(w) for w in frame["wal_ids"]
        )
        if not (len(clients) == len(seqs) == len(wal_ids) == rows.shape[0]):
            raise ValueError("partial_fold field lengths disagree")
        segments = frame.get("segments")
        if segments is not None:
            segments = tuple(
                (int(s), int(m)) for s, m in segments
            )
            # an EMPTY segment list would make `covered` empty and the
            # root's verification loop degenerate — a combined frame
            # must name at least one leaf; a DUPLICATE leaf would let
            # one shard appear in several segments, each under the
            # per-shard cohort cap while their sum is not (and would
            # double-confirm the shard at _finish)
            if (
                not segments
                or any(m < 0 for _s, m in segments)
                or sum(m for _s, m in segments) != rows.shape[0]
                or len({s for s, _m in segments}) != len(segments)
            ):
                raise ValueError("partial_fold segments disagree with rows")
        return cls(
            tenant=str(frame["tenant"]),
            round_id=int(frame["round"]),
            shard=int(frame["shard"]),
            rows=rows,
            clients=clients,
            seqs=seqs,
            wal_ids=wal_ids,
            extras=dict(frame.get("extras") or {}),
            digest=str(frame["digest"]),
            first_arrival_s=float(frame.get("first_arrival_s", 0.0)),
            trace_ctx=_as_trace_ctx(frame.get("trace_ctx")),
            segments=segments,
        )


def encode_partial_fold(p: "PartialFold") -> bytes:
    """One shard→root wire frame: the partial fold on the HMAC actor
    wire, payload forced LOSSLESS regardless of
    ``BYZPY_TPU_WIRE_PRECISION`` — the rows' exact bits are
    load-bearing (the digest cross-check and the bit-parity contract
    both read them), so the submit fabric's lossy compression must not
    apply to this hop. Analytic cost:
    ``parallel.comms.partial_fold_bytes``."""
    return wire.encode(p.to_wire(), precision="off")


def decode_partial_fold(body: bytes) -> "PartialFold":
    """Inverse of :func:`encode_partial_fold` (HMAC verified by
    ``wire.decode`` when signing is configured)."""
    return PartialFold.from_wire(wire.decode(body))


def combine_partials(
    aggregator, partials: Sequence[PartialFold]
) -> PartialFold:
    """Combine sibling partials into ONE up-stream partial — the
    depth-N merge tree's internal node (rack/pod combiner).

    ``fold_merge`` composes, and this function is the composition made
    wire-shaped: the children's rows concatenate in shard order (the
    canonical sharded cohort order, so a root that merges combined
    partials sees EXACTLY the row sequence the flat shard→root merge
    would have produced — the bit-parity contract is preserved by
    construction at any tree depth), identities concatenate alongside,
    ``segments`` records which leaf shard owns each row block, and the
    family extras are assembled INCREMENTALLY
    (``Aggregator.combined_extras``): each child's shipped extras land
    verbatim and only the CROSS blocks between children are computed —
    O(m_i·m_j·d) per pair instead of the old full O(m²·d) recompute at
    every tree level. The parent's ``extras_policy="verify"``
    cross-check holds EXACTLY under the block-contraction contract:
    assembly and verifier (``Aggregator.segmented_extras_reference``)
    run the same per-leaf-pair dot program
    (:func:`ops.robust.gram_block`), so parity is bit equality, not
    matmul tolerance — and a child that shipped FORGED extras now
    produces a combined frame the parent's verify excludes (the old
    full recompute silently laundered it). The digest is refreshed
    over the combined row bits; ``shard`` is the lowest covered leaf
    (stable sort key at the parent)."""
    if not partials:
        raise ValueError("combine_partials needs at least one partial")
    ordered = sorted(partials, key=lambda p: p.shard)
    tenants = {p.tenant for p in ordered}
    rounds = {p.round_id for p in ordered}
    if len(tenants) > 1 or len(rounds) > 1:
        raise ValueError(
            "combine_partials across tenants/rounds: "
            f"{sorted(tenants)} / {sorted(rounds)}"
        )
    covered: List[int] = []
    for p in ordered:
        covered.extend(p.covered)
    if len(set(covered)) != len(covered):
        raise ValueError(f"combine_partials shard overlap: {covered}")
    rows = np.ascontiguousarray(
        np.concatenate([p.rows for p in ordered], axis=0)
    )
    segments: List[Tuple[int, int]] = []
    for p in ordered:
        for s, lo, hi in p.segment_spans():
            segments.append((s, hi - lo))
    with obs_tracing.span(
        "serving.merge_combine",
        track="merge",
        tenant=ordered[0].tenant,
        round=ordered[0].round_id,
        children=len(ordered),
        m=int(rows.shape[0]),
        links=[
            f"{p.trace_ctx[0]}:{p.trace_ctx[1]}"
            for p in ordered
            if p.trace_ctx is not None
        ],
    ) as combine_span:
        children = [
            (p.segment_spans(), p.rows, p.extras or None) for p in ordered
        ]
        n_leaves = [len(sp) for sp, _r, _e in children]
        with obs_tracing.span(
            "serving.gram_assemble",
            track="merge",
            tenant=ordered[0].tenant,
            round=ordered[0].round_id,
            children=len(ordered),
            # cross blocks this assembly computes (leaf-pair granular,
            # across children only) and diagonal regions it must
            # recompute because a child shipped no extras — the
            # tree-level zero-redundant-recompute account
            cross_blocks=sum(
                a * b
                for i, a in enumerate(n_leaves)
                for b in n_leaves[i + 1:]
            ),
            transforms=sum(
                1 for _sp, _r, e in children if not e
            ) if any(e for _sp, _r, e in children) else 0,
        ):
            extras = aggregator.combined_extras(children)
        return PartialFold(
            tenant=ordered[0].tenant,
            round_id=ordered[0].round_id,
            shard=min(covered),
            rows=rows,
            clients=tuple(c for p in ordered for c in p.clients),
            seqs=tuple(q for p in ordered for q in p.seqs),
            wal_ids=tuple(w for p in ordered for w in p.wal_ids),
            extras=extras,
            digest=evidence_digest(rows),
            first_arrival_s=min(p.first_arrival_s for p in ordered),
            trace_ctx=getattr(combine_span, "context", None),
            segments=tuple(segments),
        )


class MergeTopology:
    """Depth-N merge-tree shape over ``n_shards`` leaf shards.

    ``fanout=None`` is the flat two-level tier (every shard's partial
    merges directly at the root — PR 12's shape). With a fanout,
    contiguous runs of ``fanout`` children combine at each internal
    level (:func:`combine_partials`) until at most ``fanout`` nodes
    face the root: 4 shards at fanout 2 is the rack→pod→root depth-3
    tree. Contiguity is load-bearing — concatenating groups in group
    order must reproduce concatenation in shard order, the canonical
    row order of the bit-parity contract."""

    __slots__ = ("n_shards", "fanout", "levels")

    def __init__(self, n_shards: int, fanout: Optional[int] = None) -> None:
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        if fanout is not None and fanout < 2:
            raise ValueError("fanout must be >= 2 (or None for flat)")
        self.n_shards = int(n_shards)
        self.fanout = None if fanout is None else int(fanout)
        #: internal combine levels, leaf-most first: each level is a
        #: tuple of groups, each group the tuple of LEAF shard indices
        #: its combined partial covers
        levels: List[Tuple[Tuple[int, ...], ...]] = []
        if self.fanout is not None:
            nodes: List[Tuple[int, ...]] = [
                (i,) for i in range(self.n_shards)
            ]
            while len(nodes) > self.fanout:
                grouped = [
                    tuple(
                        leaf
                        for node in nodes[i: i + self.fanout]
                        for leaf in node
                    )
                    for i in range(0, len(nodes), self.fanout)
                ]
                levels.append(tuple(grouped))
                nodes = grouped
        self.levels: Tuple[Tuple[Tuple[int, ...], ...], ...] = tuple(levels)

    @property
    def depth(self) -> int:
        """Tiers of the tree: 2 = shard→root (flat), 3 = shard→pod→
        root, …"""
        return 2 + len(self.levels)

    def combine(self, aggregator, partials: Sequence[PartialFold]):
        """Run every internal level's combines over ``partials`` (leaf
        partials in, root-facing partials out). Groups with no
        responding member vanish; a group with a single member passes
        through un-recombined (nothing to combine — its frame already
        carries the right segments)."""
        current = list(partials)
        for level in self.levels:
            nxt: List[PartialFold] = []
            for group in level:
                members = [
                    p for p in current if p.covered[0] in group
                ]
                if not members:
                    continue
                if len(members) == 1:
                    nxt.append(members[0])
                else:
                    nxt.append(combine_partials(aggregator, members))
            current = nxt
        return current


class ShardFrontend:
    """One ingress shard: a full single-frontend admission plane whose
    rounds are driven by the coordinator (it never aggregates — its
    round close extracts a :class:`PartialFold` instead).

    Wraps a real :class:`~byzpy_tpu.serving.ServingFrontend` so every
    admission gate — shape, staleness vs the GLOBAL round, credits,
    ``(client, seq)`` dedup, forensics trust, write-ahead durability —
    is the production code path, per shard, against shard-local
    ledgers."""

    def __init__(
        self,
        index: int,
        tenants: Sequence[TenantConfig],
        *,
        clock: Callable[[], float] = time.monotonic,
        durability: Optional[DurabilityConfig] = None,
    ) -> None:
        self.index = int(index)
        self.clock = clock
        self.frontend = ServingFrontend(
            tenants, clock=clock, durability=durability, shard=index
        )
        self.alive = True
        #: injectable close-path delay (seconds) — the straggler drill's
        #: hook: the coordinator's barrier timeout must survive a shard
        #: that answers late
        self.close_delay_s = 0.0
        #: drained-but-unconfirmed rounds: ``(tenant, round) -> (subs,
        #: cohort)`` — requeued on a missed close, retired on confirm
        self._inflight: Dict[Tuple[str, int], Tuple[list, Cohort]] = {}

    # -- admission (delegates to the inner frontend) ----------------------

    def submit(
        self,
        tenant: str,
        client: str,
        round_submitted: int,
        gradient: Any,
        *,
        seq: Optional[int] = None,
    ) -> Tuple[bool, str]:
        """One submission through the shard's full admission plane."""
        return self.frontend.submit(
            tenant, client, round_submitted, gradient, seq=seq
        )

    def sync_round(self, tenant: str, round_id: int) -> None:
        """Advance the shard's staleness clock to the global round (the
        coordinator drives it after every close — including closes this
        shard missed, which is exactly how a partitioned shard's held
        rows become one round staler)."""
        self.frontend._tenants[tenant].round_id = int(round_id)

    # -- round close (coordinator-driven) ---------------------------------

    def drain_cohort(self, tenant: str) -> Optional[Tuple[list, Cohort]]:
        """Loop-side half of the shard close: drain the admission queue
        (plus anything requeued from a missed close) and build the
        shard cohort at its EXACT size — cheap, event-loop-safe work.
        Returns ``None`` when the shard has nothing this round."""
        t = self.frontend._tenants[tenant]
        t.held.extend(
            t.queue.drain_nowait(max(0, t.cfg.cohort_cap - len(t.held)))
        )
        if not t.held:
            return None
        subs, t.held = t.held, []
        cohort = build_cohort(
            subs, t.round_id, None, t.cfg.staleness, tenant=t.cfg.name
        )
        self._inflight[(tenant, t.round_id)] = (subs, cohort)
        return subs, cohort

    def build_partial(
        self, tenant: str, subs: list, cohort: Cohort
    ) -> PartialFold:
        """Executor-side half: extract the aggregator's partial fold
        from the drained cohort and fingerprint the row bits. Pure
        numpy on data the drain already assembled (the O(m·d) copy and
        any family extras — e.g. the Multi-Krum Gram block — run off
        the event loop)."""
        if self.close_delay_s > 0:
            time.sleep(self.close_delay_s)
        t = self.frontend._tenants[tenant]
        with obs_tracing.span(
            "serving.shard_close",
            track=f"shard:{self.index}",
            shard=self.index, tenant=tenant,
            round=t.round_id, m=cohort.m,
        ) as close_span:
            partial = t.executor.aggregator.fold_partial(
                cohort.matrix, cohort.valid, cohort.weights
            )
            rows = partial["rows"]
            return PartialFold(
                tenant=tenant,
                round_id=t.round_id,
                shard=self.index,
                rows=rows,
                clients=cohort.clients,
                seqs=tuple(s.seq for s in subs),
                wal_ids=tuple(s.wal_id for s in subs),
                extras=partial.get("extras", {}),
                digest=evidence_digest(rows),
                first_arrival_s=cohort.first_arrival_s,
                # the shard_close span's identity: stamped onto the
                # wire frame so the root's merge span can link this
                # partial's lane into the round tree across processes
                # (NULL_SPAN with telemetry off → no context)
                trace_ctx=getattr(close_span, "context", None),
            )

    def close_partial(self, tenant: str) -> Optional[PartialFold]:
        """Synchronous shard close (drain + build in one call — the
        sync round closer and the drills use this)."""
        drained = self.drain_cohort(tenant)
        if drained is None:
            return None
        return self.build_partial(tenant, *drained)

    def requeue(self, tenant: str, round_id: int) -> None:
        """A drained-but-unmerged cohort (below-quorum window, straggler
        past the barrier timeout, stale partial) returns to the FRONT
        of the held list — admitted rows are never lost, they fold next
        round (one round staler, the partition account)."""
        entry = self._inflight.pop((tenant, round_id), None)
        if entry is None:
            return
        subs, _cohort = entry
        t = self.frontend._tenants[tenant]
        t.held[:0] = subs

    def discard_inflight(self, tenant: str, round_id: int) -> None:
        """Drop a drained cohort without requeue (the root excluded
        this shard's partial as forged — its rows are untrustworthy),
        WITH the same release accounting as a failed round: the rows'
        ``outstanding`` is freed (a leak here would wedge ``drain()``
        and pin the gauge forever — the chaos drills wrap REAL shards)
        and the drop is WAL-recorded so recovery never resurrects
        rows the root refused."""
        entry = self._inflight.pop((tenant, round_id), None)
        if entry is None:
            return
        subs, _cohort = entry
        t = self.frontend._tenants[tenant]
        t.outstanding -= len(subs)
        t.round_done.set()
        if t.durability is not None:
            t.durability.record_dropped(
                round_id,
                tuple(s.wal_id for s in subs if s.wal_id is not None),
                "forged_partial",
            )

    def confirm(
        self,
        tenant: str,
        round_id: int,
        folded: Sequence[int],
        duplicates: Sequence[int],
        agg_digest: str,
        aggregate: Any,
        precomputed: Optional[dict] = None,
    ) -> None:
        """Root confirmation of a merged round: write the shard's WAL
        round record for the rows the root folded (exactly-once
        accounting joins on these wal_ids), WAL-account rows the root
        refused as already-folded (``root_duplicate``), feed the
        shard's forensics plane (global aggregate + the root's sliced
        score view), release ``outstanding``, and record round stats."""
        entry = self._inflight.pop((tenant, round_id), None)
        if entry is None:
            return
        subs, cohort = entry
        t = self.frontend._tenants[tenant]
        # defensive: the indices describe the PARTIAL's rows; a forged
        # partial (extra fabricated rows) can reference positions the
        # honest inflight record never had — never let a Byzantine
        # payload crash an honest shard's bookkeeping
        folded = [i for i in folded if 0 <= i < len(subs)]
        duplicates = [i for i in duplicates if 0 <= i < len(subs)]
        folded_subs = [subs[i] for i in folded]
        dup_subs = [subs[i] for i in duplicates]
        if t.durability is not None:
            t.durability.record_round(
                round_id,
                tuple(
                    s.wal_id for s in folded_subs if s.wal_id is not None
                ),
                agg_digest,
                len(folded_subs),
            )
            if dup_subs:
                t.durability.record_dropped(
                    round_id,
                    tuple(
                        s.wal_id for s in dup_subs if s.wal_id is not None
                    ),
                    ROOT_DUPLICATE,
                )
            t.durability.note_round_closed()
        if t.forensics is not None and folded_subs:
            fold_cohort = (
                cohort
                if len(folded_subs) == len(subs)
                else build_cohort(
                    folded_subs, round_id, None, t.cfg.staleness,
                    tenant=t.cfg.name,
                )
            )
            prep = self.frontend._forensics_prepare(
                t, fold_cohort, aggregate, folded_subs,
                precomputed=precomputed,
            )
            if prep is not None:
                self.frontend._observe_forensics(
                    t, fold_cohort, aggregate, folded_subs, prep
                )
        t.last_aggregate = aggregate
        t.last_cohort_clients = tuple(s.client for s in folded_subs)
        t.outstanding -= len(subs)
        t.round_done.set()
        t.stats.record(self.clock() - cohort.first_arrival_s, len(folded_subs))
        self.frontend._maybe_snapshot(t)

    def account_failed(self, tenant: str, round_id: int) -> None:
        """The root's merged finalize crashed: this shard's contributed
        rows are dropped WITH accounting (WAL drop record, outstanding
        release) — the single frontend's ``_fail_round`` contract,
        distributed."""
        entry = self._inflight.pop((tenant, round_id), None)
        if entry is None:
            return
        subs, cohort = entry
        t = self.frontend._tenants[tenant]
        t.failed_rounds += 1
        t.outstanding -= len(subs)
        t.round_done.set()
        if t.durability is not None:
            t.durability.record_dropped(
                round_id,
                tuple(s.wal_id for s in subs if s.wal_id is not None),
                "failed_round",
            )

    def shutdown(self) -> None:
        """Release the shard's durable handles (flush-per-append makes
        this equivalent to SIGKILL for WAL purposes — nothing buffered
        is lost either way; the drill kills WITHOUT calling this)."""
        self.alive = False
        for t in self.frontend._tenants.values():
            if t.durability is not None:
                t.durability.close()

    def stats(self) -> dict:
        """The inner frontend's per-tenant accounting snapshot."""
        return self.frontend.stats()


class _RootLadder:
    """Root-merge bucket sizes ``{b·2^k, b·3·2^(k−1)}``: worst-case
    padding overshoot 4/3, where the serving tier's power-of-two
    ladder allows 2×. The trade is right at the root and wrong at the
    tenant frontends: a merged cohort is 10⁴+ rows, the masked program
    streams O(bucket·d) bytes, and the extra padding is real
    milliseconds per round — while the compile count stays O(log cap)
    (~2× the power-of-two ladder's)."""

    __slots__ = ("sizes",)

    def __init__(self, cap: int, *, min_bucket: int = 2) -> None:
        if cap <= 0 or min_bucket <= 0:
            raise ValueError("cap and min_bucket must be >= 1")
        sizes = set()
        b = max(2, int(min_bucket))
        while True:
            sizes.add(b)
            sizes.add(b + b // 2)
            if b >= cap:
                break
            b *= 2
        self.sizes: Tuple[int, ...] = tuple(sorted(sizes))

    @property
    def cap(self) -> int:
        """Largest bucket."""
        return self.sizes[-1]

    def bucket_for(self, m: int) -> int:
        """Smallest ladder size holding an ``m``-row merged cohort."""
        if m <= 0:
            raise ValueError(f"cohort size must be >= 1 (got {m})")
        import bisect

        i = bisect.bisect_left(self.sizes, m)
        if i == len(self.sizes):
            raise ValueError(
                f"merged cohort of {m} exceeds the root cap {self.cap}"
            )
        return self.sizes[i]


class _RootTenant:
    """Root-side per-tenant state: the global round counter, the merged
    bucket ladder, the cross-shard dedup authority, quorum accounting,
    and (optionally) the root's own WAL of merge evidence."""

    __slots__ = (
        "cfg", "round_id", "last_aggregate", "ladder", "stats",
        "min_cohort", "seqs", "max_tracked", "quorum_failures",
        "failed_rounds", "quorum_closes", "partitions", "forged",
        "root_duplicates", "durability", "rounds",
        "speculative_closes", "repairs", "open_repairs",
        "partial_checks", "dedup_lock", "dedup_epoch", "staging",
        "dedup_staged", "dedup_promoted", "dedup_restaged",
        "staged_closes", "gram_cross_blocks", "partial_transforms",
    )

    def __init__(
        self,
        cfg: TenantConfig,
        n_shards: int,
        *,
        max_tracked: int,
        durability: Optional[TenantDurability],
    ) -> None:
        self.cfg = cfg
        self.round_id = 0
        self.last_aggregate: Any = None
        self.rounds = 0
        # merged cohorts can reach n_shards x cohort_cap rows; the root
        # ladder keeps one compiled masked program per bucket, not one
        # per distinct merged size (the single frontend's jit-cache
        # economics, moved up a level — with the finer _RootLadder
        # steps, because padding overshoot is O(bucket·d) device bytes
        # at these row counts)
        self.ladder = _RootLadder(
            max(2, n_shards * cfg.cohort_cap), min_bucket=cfg.min_bucket
        )
        self.stats = RoundStats()
        # the tenant's global admissibility floor (the aggregator's
        # smallest admissible n, same probe the single frontend runs)
        floor = cfg.min_cohort
        for m in range(1, self.ladder.cap + 1):
            try:
                cfg.aggregator.validate_n(m)
            except ValueError:
                continue
            floor = max(floor, m)
            break
        self.min_cohort = floor
        #: cross-shard dedup authority: per-client highest ROOT-FOLDED
        #: seq (LRU-bounded like the shard tables)
        self.seqs: "OrderedDict[str, int]" = OrderedDict()
        self.max_tracked = int(max_tracked)
        self.quorum_failures = 0
        self.failed_rounds = 0
        self.quorum_closes = 0
        self.partitions = 0
        self.forged = 0
        self.root_duplicates = 0
        self.durability = durability
        #: quorum closes taken SPECULATIVELY (repair horizon armed):
        #: the round closed without the stragglers, whose late partials
        #: may still fold as repair deltas within the horizon
        self.speculative_closes = 0
        #: late partials folded into already-closed rounds
        self.repairs = 0
        #: closed-round repair contexts still inside the horizon:
        #: ``round_id -> {"inputs": [(shard, merge_input)], "missing":
        #: set, "digest": str, "m": int}`` — the exact merge inputs the
        #: close used, so a repair re-merge is bit-identical to the
        #: barrier close that would have included the late shard
        self.open_repairs: Dict[int, dict] = {}
        #: stateless cross-check runs (``check_partial``) — the repair
        #: satellite's one-verify-per-repair contract pins this counter
        self.partial_checks = 0
        #: guards ``seqs``/``dedup_epoch``/``staging`` — arrival-time
        #: dedup staging reads the fold table on reader threads / the
        #: async executor while ``_finish`` settles it on the loop or
        #: control thread
        self.dedup_lock = threading.Lock()
        #: bumped once per settle (the ``note_folded`` batch of a close
        #: or repair): a staged verdict tagged with an older epoch may
        #: have been invalidated by the settle and is revalidated with
        #: the cheap dict-lookup loop at promotion — verdicts are
        #: therefore always account-identical to close-time dedup, at
        #: any ``pipeline_depth``
        self.dedup_epoch = 0
        #: round-keyed dedup STAGING tables (arrival-time close-path):
        #: ``round_id -> {"lock", "entries": {id(p): {"partial",
        #: "folded", "dups", "epoch", "input", "valid"}}, "acc",
        #: "acc_ok", "acc_shards"}`` — populated by ``stage_partial``
        #: as each checked frame lands; the close pops its round's
        #: table and just PROMOTES the staged verdicts (and consumes
        #: the pre-assembled merge accumulator when every entry
        #: matches)
        self.staging: Dict[int, dict] = {}
        #: arrival-staged dedup verdicts / settle-time promotions /
        #: verdicts that CHANGED between staging and settle (a
        #: duplicate folded by an interleaved close — the rare path
        #: that rebuilds that shard's merge input)
        self.dedup_staged = 0
        self.dedup_promoted = 0
        self.dedup_restaged = 0
        #: closes that consumed an arrival-populated merge accumulator
        #: wholesale (the staged fast path, vs the close-time rebuild)
        self.staged_closes = 0
        #: extras-assembly accounting from ``merged["merge_stats"]``:
        #: cross-Gram blocks computed and per-partial diagonal
        #: recomputes — k verified partials must cost EXACTLY
        #: k·(k−1)/2 cross blocks per close and zero transforms when
        #: every shard shipped its extras (the
        #: zero-redundant-recompute assert in runner ``--smoke`` and
        #: the chaos ``shard`` lane)
        self.gram_cross_blocks = 0
        self.partial_transforms = 0

    def is_folded(self, client: str, seq: Optional[int]) -> bool:
        if seq is None:
            return False
        return self.seqs.get(client, -1) >= int(seq)

    def note_folded(self, client: str, seq: Optional[int]) -> None:
        if seq is None:
            return
        self.seqs[client] = max(self.seqs.get(client, -1), int(seq))
        self.seqs.move_to_end(client)
        if len(self.seqs) > self.max_tracked:
            self.seqs.popitem(last=False)


class ShardedCoordinator:
    """The sharded tier's root: shard fan-out, barrier close, partial
    verification, hierarchical merge, and failover (module docstring).

    In-process deployment (tests, drills, the Podracer-style bench
    swarm): the coordinator owns its :class:`ShardFrontend` objects
    directly. Process-per-shard deployment: each shard runs its inner
    frontend's TCP ingress (``coordinator.shards[i].frontend.serve()``)
    and ships ``PartialFold.to_wire()`` frames over the HMAC wire; the
    verification, merge and confirm protocol is identical — the root
    decodes with :meth:`PartialFold.from_wire`."""

    def __init__(
        self,
        tenants: Sequence[TenantConfig],
        n_shards: int,
        *,
        clock: Callable[[], float] = time.monotonic,
        shard_timeout_s: float = 0.25,
        quorum: Optional[int] = None,
        durability: Optional[DurabilityConfig] = None,
        on_round: Optional[Callable[[str, int, Any, Any], None]] = None,
        extras_policy: str = "trust",
        max_tracked_clients: int = 1 << 16,
        topology: Optional[MergeTopology] = None,
        shards: Optional[Sequence[Any]] = None,
        repair_horizon_rounds: int = 0,
        pipeline_depth: int = 1,
    ) -> None:
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        if quorum is not None and not 1 <= quorum <= n_shards:
            raise ValueError(f"quorum must be in [1, {n_shards}]")
        if repair_horizon_rounds < 0:
            raise ValueError("repair_horizon_rounds must be >= 0")
        if pipeline_depth not in (0, 1):
            raise ValueError(
                "pipeline_depth must be 0 (barrier) or 1 (depth-1 "
                f"pipelined window), got {pipeline_depth}"
            )
        if extras_policy not in ("trust", "verify", "recompute"):
            raise ValueError(
                "extras_policy must be 'trust', 'verify' or 'recompute' "
                f"(got {extras_policy!r})"
            )
        if topology is not None and topology.n_shards != n_shards:
            raise ValueError(
                f"topology covers {topology.n_shards} shards, "
                f"coordinator has {n_shards}"
            )
        self.router = ShardRouter(n_shards)
        self._clock = clock
        self.shard_timeout_s = float(shard_timeout_s)
        #: shards required for a close; default = majority
        self.quorum = quorum if quorum is not None else n_shards // 2 + 1
        #: speculative-close repair horizon, in ROUNDS: 0 keeps the
        #: classic degraded close (a straggler's rows requeue and fold
        #: one round staler); N > 0 arms the optimistic close — a
        #: quorum close leaves the stragglers' drained cohorts in
        #: flight, and a late partial arriving within N rounds folds
        #: into the closed round as a WAL-recorded repair delta via
        #: :meth:`repair_round` (beyond the horizon the rows requeue
        #: one-round-staler exactly as the classic path)
        self.repair_horizon = int(repair_horizon_rounds)
        self.extras_policy = extras_policy
        #: merge-tree shape driving the round close (None = flat
        #: two-level; the process runner passes the same object so the
        #: in-process and process-per-shard tiers share one topology)
        self.topology = topology
        self._on_round = on_round
        self.callback_errors = 0
        self._durability = durability
        if shards is not None:
            # injected shard objects (the process runner's root passes
            # wire-RPC proxies): anything answering the ShardFrontend
            # coordinator surface — alive/index/confirm/requeue/
            # discard_inflight/account_failed/sync_round
            if len(shards) != n_shards:
                raise ValueError(
                    f"{len(shards)} shard objects for {n_shards} shards"
                )
            self.shards = list(shards)
        else:
            self.shards = [
                ShardFrontend(
                    i, tenants, clock=clock,
                    durability=self._shard_durability(i),
                )
                for i in range(n_shards)
            ]
        self._roots: Dict[str, _RootTenant] = {}
        for cfg in tenants:
            root_dur = None
            if durability is not None:
                root_dur = TenantDurability(
                    dataclasses.replace(
                        durability,
                        directory=os.path.join(durability.directory, "root"),
                    ),
                    cfg.name,
                )
            rt = _RootTenant(
                cfg, n_shards, max_tracked=max_tracked_clients,
                durability=root_dur,
            )
            if root_dur is not None and root_dur.recovered is not None:
                rt.round_id = root_dur.recovered.round_id
            self._roots[cfg.name] = rt
        for shard in self.shards:
            for name, rt in self._roots.items():
                shard.sync_round(name, rt.round_id)
        #: shard events the audit trail sees even without durability
        #: (forged folds, partitions, quorum closes) — bounded tail.
        #: Appended from the loop AND the executor-side merge/verify
        #: helpers, as is ``callback_errors`` — ``_stats_lock``
        #: serializes both (the trim in ``_note_event`` is a
        #: read-modify-write; `+=` on the counter is too)
        self.shard_events: List[dict] = []
        self._stats_lock = threading.Lock()
        self._tenant_cfgs = list(tenants)
        self._running = False
        self._tasks: list = []
        self._device_lock: Optional[asyncio.Lock] = None
        #: async-root pipelining: 1 = round N's merge+device step
        #: settles while round N+1's shard windows admit (the runner
        #: tier's PR-17 contract, now on the in-process root); 0 keeps
        #: the barrier-style loop
        self.pipeline_depth = int(pipeline_depth)
        #: tenant → the one in-flight deferred close (depth-1 window)
        self._pending_async: Dict[str, dict] = {}
        #: arrival-verified partials not yet consumed by a close or
        #: repair — incremented by ``check_partial(inflight=True)`` on
        #: proxy reader threads / the executor, hence the lock
        self._partials_inflight = 0
        self._inflight_lock = threading.Lock()
        reg = obs_metrics.registry()
        self._m_accepted = {
            (cfg.name, i): reg.counter(
                "byzpy_shard_accepted_total",
                help="submissions accepted per frontend shard",
                labels={"tenant": cfg.name, "shard": str(i)},
            )
            for cfg in tenants
            for i in range(n_shards)
        }
        self._m_merge_s = {
            cfg.name: reg.histogram(
                "byzpy_shard_merge_seconds",
                help="root-side verify+merge+finalize latency per round",
                labels={"tenant": cfg.name},
            )
            for cfg in tenants
        }
        self._m_rounds = {
            cfg.name: reg.counter(
                "byzpy_shard_rounds_total",
                help="rounds closed by the sharded root",
                labels={"tenant": cfg.name},
            )
            for cfg in tenants
        }
        self._m_quorum = {
            cfg.name: reg.counter(
                "byzpy_shard_quorum_closes_total",
                help="degraded closes (quorum met, >=1 shard missing)",
                labels={"tenant": cfg.name},
            )
            for cfg in tenants
        }
        self._m_partitions = {
            (cfg.name, i): reg.counter(
                "byzpy_shard_partitions_total",
                help="shard-rounds accounted as a partition",
                labels={"tenant": cfg.name, "shard": str(i)},
            )
            for cfg in tenants
            for i in range(n_shards)
        }
        self._m_forged = {
            (cfg.name, i): reg.counter(
                "byzpy_shard_forged_folds_total",
                help="partial folds excluded by root cross-checks",
                labels={"tenant": cfg.name, "shard": str(i)},
            )
            for cfg in tenants
            for i in range(n_shards)
        }
        self._m_speculative = reg.counter(
            "byzpy_speculative_closes_total",
            help="quorum closes taken with the repair horizon armed",
        )
        self._m_repairs = {
            cfg.name: reg.counter(
                "byzpy_round_repairs_total",
                help="late partials folded into closed rounds as repairs",
                labels={"tenant": cfg.name},
            )
            for cfg in tenants
        }
        self._m_root_merge_s = {
            cfg.name: reg.histogram(
                "byzpy_root_merge_seconds",
                help="root fold_merge+finalize latency per close/repair",
                labels={"tenant": cfg.name},
            )
            for cfg in tenants
        }
        self._m_finalize_s = {
            cfg.name: reg.histogram(
                "byzpy_root_finalize_seconds",
                help=(
                    "off-path root finalize latency (persistent masked "
                    "program dispatch + materialization, donated input)"
                ),
                labels={"tenant": cfg.name},
            )
            for cfg in tenants
        }
        self._m_dedup_staged = {
            cfg.name: reg.counter(
                "byzpy_dedup_staged_total",
                help="dedup verdicts staged at arrival time",
                labels={"tenant": cfg.name},
            )
            for cfg in tenants
        }
        self._m_dedup_restaged = {
            cfg.name: reg.counter(
                "byzpy_dedup_restaged_total",
                help=(
                    "staged dedup verdicts invalidated at promotion "
                    "(an intervening settle moved the verdict)"
                ),
                labels={"tenant": cfg.name},
            )
            for cfg in tenants
        }
        self._m_inflight = reg.gauge(
            "byzpy_root_partials_inflight",
            help="arrival-verified partials awaiting a root close",
        )
        self._m_overlap = {
            cfg.name: reg.gauge(
                "byzpy_round_overlap_ratio",
                help=(
                    "fraction of the deferred round finish that ran "
                    "hidden behind next-round ingest"
                ),
                labels={"tenant": cfg.name},
            )
            for cfg in tenants
        }
        self._m_live = reg.gauge(
            "byzpy_shards_live", help="frontend shards currently alive"
        )
        self._m_live.set(n_shards)

    def _shard_durability(self, index: int) -> Optional[DurabilityConfig]:
        if self._durability is None:
            return None
        return dataclasses.replace(
            self._durability,
            directory=os.path.join(self._durability.directory, f"shard{index}"),
        )

    @property
    def n_shards(self) -> int:
        """Configured shard count (dead shards included)."""
        return self.router.n_shards

    def live_shards(self) -> List[ShardFrontend]:
        """Shards currently serving."""
        return [s for s in self.shards if s.alive]

    # -- admission (sticky routing) ---------------------------------------

    def submit(
        self,
        tenant: str,
        client: str,
        round_submitted: int,
        gradient: Any,
        *,
        seq: Optional[int] = None,
    ) -> Tuple[bool, str]:
        """Route one submission to the client's home shard."""
        shard = self.shards[self.router.shard_for(client)]
        if not shard.alive:
            return False, REJECTED_SHARD_DOWN
        ok, reason = shard.submit(
            tenant, client, round_submitted, gradient, seq=seq
        )
        if ok and obs_runtime.STATE.enabled:
            self._m_accepted[(tenant, shard.index)].inc()
        return ok, reason

    # -- partial verification ---------------------------------------------

    def _inc_inflight(self) -> None:
        with self._inflight_lock:
            self._partials_inflight += 1
            value = self._partials_inflight
        if obs_runtime.STATE.enabled:
            self._m_inflight.set(value)

    def _dec_inflight(self, n: int = 1) -> None:
        with self._inflight_lock:
            self._partials_inflight = max(0, self._partials_inflight - int(n))
            value = self._partials_inflight
        if obs_runtime.STATE.enabled:
            self._m_inflight.set(value)

    def check_partial(
        self, tenant: str, p: PartialFold, *, inflight: bool = False
    ) -> Tuple[bool, str]:
        """The STATELESS half of the root cross-check suite — shape/dim
        sanity, the per-leaf row cap over ``segment_spans``, the digest
        recompute, extras recompute under ``extras_policy='verify'``,
        and per-row ownership against each segment's leaf shard — as an
        arrival-time door: it reads no round state, so it can run the
        moment a partial's frame lands (a proxy reader thread in the
        process runner, the executor in the async root) instead of
        after the barrier. Returns ``(ok, measured_digest)``; the pair
        rides into :meth:`merge_partials` / :meth:`repair_round` as
        ``prechecked`` so the close runs only the order-sensitive
        ``(client, seq)`` dedup — which :meth:`stage_partial` also
        moves to arrival as an epoch-tagged STAGED verdict (under
        pipelining a round-N partial can arrive while round N-1's
        ``note_folded`` updates are still settling, so the close
        revalidates any verdict staged under an older dedup epoch —
        bit- and account-identical either way).
        ``inflight=True`` counts the frame into the
        ``byzpy_root_partials_inflight`` gauge (the close or repair
        that consumes the precheck decrements)."""
        rt = self._roots[tenant]
        agg = rt.cfg.aggregator
        rt.partial_checks += 1
        if inflight:
            self._inc_inflight()
        with obs_tracing.span(
            "serving.partial_verify", track="root", tenant=tenant,
            shard=int(p.shard), round=int(p.round_id), m=int(p.m),
        ):
            rows = p.rows
            spans = p.segment_spans()
            if (
                rows.ndim != 2
                or rows.shape[0] != len(p.clients)
                or (spans and spans[-1][2] != rows.shape[0])
                or any(hi - lo > rt.cfg.cohort_cap for _s, lo, hi in spans)
                or (rows.shape[0] and rows.shape[1] != rt.cfg.dim)
            ):
                return False, ""
            measured = evidence_digest(rows)
            if measured != p.digest:
                return False, measured
            if p.extras and self.extras_policy == "verify":
                # the block-contraction contract: a SEGMENTED (combined)
                # frame's extras were assembled per leaf-segment pair,
                # so the recompute must run the same per-pair dot
                # program — exact bit comparison, not matmul tolerance
                if p.segments is not None:
                    want = agg.segmented_extras_reference(
                        np.asarray(rows, np.float32), spans
                    )
                else:
                    want = agg._partial_extras(
                        np.asarray(rows, np.float32)
                    )
                for key, val in want.items():
                    got = p.extras.get(key)
                    # equal_nan: admission deliberately passes non-finite
                    # VALUES (adversarial payloads are the aggregator's
                    # job), and a NaN gradient propagates into the extras
                    # (a NaN Gram entry, a NaN running sum) — the honest
                    # recompute reproduces the same NaNs, which plain
                    # array_equal would call a mismatch, branding an
                    # honest shard forged off one client's NaN row
                    if got is None or not np.array_equal(
                        np.asarray(val), np.asarray(got), equal_nan=True
                    ):
                        return False, measured
            for owner, lo, hi in spans:
                for j in range(lo, hi):
                    if self.router.shard_for(p.clients[j]) != owner:
                        # a client this segment's shard does not own:
                        # sticky routing makes the claim a protocol
                        # violation — the whole partial is
                        # untrustworthy (the replay-another-shard
                        # attack)
                        return False, measured
        return True, measured

    def stage_partial(
        self,
        tenant: str,
        p: PartialFold,
        prechecked: Optional[Tuple[bool, str]] = None,
    ) -> bool:
        """The ARRIVAL-TIME close-path door (pairs with
        :meth:`check_partial`): stage one checked partial's dedup
        verdict and absorb it into the round's merge accumulator the
        moment its frame lands — on a proxy reader thread or the async
        executor — so the settle half of :meth:`_verify_and_merge`
        just promotes.

        Two pieces move off the close here. (1) **Dedup staging**: the
        ``(client, seq)`` loop runs now against the root fold table,
        tagged with the current ``dedup_epoch``; if a settle intervenes
        before this round closes (pipelining), promotion revalidates
        with the same cheap dict loop — the verdict the close accounts
        is identical at any ``pipeline_depth``. (2) **Arrival merge
        transform**: the staged verdict's merge input feeds
        ``fold_merge_add``, whose family override does the per-partial
        heavy work (Multi-Krum's cross-Gram blocks against the
        partials already parked) under the ``serving.gram_assemble``
        span — by the last arrival the accumulator holds the full
        block set and finish is placement only.

        Returns ``True`` when the frame was staged; ``False`` when it
        was refused (failed precheck, wrong tenant, a round outside
        the staging window, a duplicate shard claim — the close then
        handles the frame through the classic path, bit-identically).
        Purely an optimization door: never a verdict authority (the
        close re-derives anything stale) and never required — callers
        that skip it get PR-18 behavior unchanged."""
        rt = self._roots[tenant]
        if prechecked is not None and not prechecked[0]:
            return False
        if p.tenant != tenant:
            return False
        r = int(p.round_id)
        agg = rt.cfg.aggregator
        with rt.dedup_lock:
            # staging window: the open round and the pipeline's next
            # window. Older rounds are already closed (a late frame is
            # repair_round's business); far-future rounds would grow
            # the table unboundedly off a forged round id.
            if not rt.round_id <= r <= rt.round_id + 1:
                return False
            for stale in [k for k in rt.staging if k < rt.round_id]:
                del rt.staging[stale]
            ctx = rt.staging.get(r)
            if ctx is None:
                ctx = {
                    "lock": threading.Lock(),
                    "entries": {},
                    "acc": None,
                    "acc_ok": True,
                    "acc_shards": set(),
                }
                rt.staging[r] = ctx
            if id(p) in ctx["entries"]:
                return False
            folded: List[int] = []
            dups: List[int] = []
            for j, (client, seq) in enumerate(
                zip(p.clients, p.seqs, strict=True)
            ):
                if rt.is_folded(client, seq):
                    dups.append(j)
                else:
                    folded.append(j)
            entry = {
                "partial": p,
                "folded": folded,
                "dups": dups,
                "epoch": rt.dedup_epoch,
                "valid": True,
            }
            ctx["entries"][id(p)] = entry
            rt.dedup_staged += 1
        if obs_runtime.STATE.enabled:
            self._m_dedup_staged[tenant].inc()
        entry["input"] = inp = self._merge_input(p, folded, dups)
        with ctx["lock"]:
            shard = int(p.shard)
            if not ctx["acc_ok"] or shard in ctx["acc_shards"]:
                # a second frame claiming a shard this window already
                # staged: the close's duplicate-shard rule decides —
                # drop the accumulator fast path, keep the verdicts
                ctx["acc_ok"] = False
                return False
            if ctx["acc"] is None:
                ctx["acc"] = agg.fold_merge_begin()
            with obs_tracing.span(
                "serving.gram_assemble", track="root", tenant=tenant,
                round=r, shard=shard, m=int(p.m),
                parked=len(ctx["acc_shards"]),
            ):
                try:
                    agg.fold_merge_add(ctx["acc"], shard, inp)
                except Exception:  # noqa: BLE001 — an accumulator the
                    # family refuses (dim mismatch, duplicate key race)
                    # only costs the fast path, never the close
                    ctx["acc_ok"] = False
                    return False
            ctx["acc_shards"].add(shard)
        return True

    def _verify_partial(
        self,
        rt: _RootTenant,
        p: PartialFold,
        prechecked: Optional[Tuple[bool, str]] = None,
        staged: Optional[dict] = None,
    ) -> Tuple[Optional[Tuple[List[int], List[int]]], str]:
        """Root cross-checks of one shard's partial. Returns
        ``((folded row indices, duplicate row indices), measured_digest)``
        — the first element ``None`` when the whole partial is excluded
        as forged (digest mismatch, field nonsense, row-cap abuse,
        extras inconsistency under ``extras_policy='verify'``,
        cross-shard ownership claims). The measured digest rides back
        so the evidence event does not hash the same rows a second
        time. Combined partials from the depth-N merge tree run the
        same checks PER SEGMENT (ownership against the segment's leaf
        shard, the row cap per leaf). The stateless suite lives in
        :meth:`check_partial`; an arrival-verified result arrives as
        ``prechecked`` and is NOT re-run. ``staged`` is this frame's
        :meth:`stage_partial` entry when the arrival path also staged
        the dedup verdict: a verdict staged under the CURRENT
        ``dedup_epoch`` promotes without touching the fold table; one
        staged under an older epoch (a settle intervened — pipelining)
        is revalidated with the same cheap loop, and if the verdict
        moved the stale entry is invalidated (``dedup_restaged``) so
        the close's accumulator fast path stands down. Either way the
        verdict the close accounts is bit- and account-identical to
        the classic loop at any ``pipeline_depth``."""
        if prechecked is None:
            prechecked = self.check_partial(rt.cfg.name, p)
        ok, measured = prechecked
        if not ok:
            return None, measured
        with rt.dedup_lock:
            if (
                staged is not None
                and staged.get("partial") is p
                and staged["epoch"] == rt.dedup_epoch
            ):
                rt.dedup_promoted += 1
                return (staged["folded"], staged["dups"]), measured
            folded: List[int] = []
            dups: List[int] = []
            for j, (client, seq) in enumerate(
                zip(p.clients, p.seqs, strict=True)
            ):
                if rt.is_folded(client, seq):
                    dups.append(j)
                else:
                    folded.append(j)
            if staged is not None and staged.get("partial") is p:
                if (
                    staged["folded"] == folded
                    and staged["dups"] == dups
                ):
                    # stale epoch, same verdict: the staged merge
                    # input is still the bit-exact one — refresh
                    staged["epoch"] = rt.dedup_epoch
                    rt.dedup_promoted += 1
                else:
                    staged["valid"] = False
                    rt.dedup_restaged += 1
                    if obs_runtime.STATE.enabled:
                        self._m_dedup_restaged[rt.cfg.name].inc()
        return (folded, dups), measured

    def _note_callback_error(self) -> None:
        with self._stats_lock:
            self.callback_errors += 1

    def _note_event(self, event: dict) -> None:
        with self._stats_lock:
            self.shard_events.append(event)
            if len(self.shard_events) > 1024:
                del self.shard_events[:512]

    def note_forged(
        self,
        tenant: str,
        shards,
        *,
        claimed_digest: str = "",
        measured_digest: str = "",
        m: int = 0,
        discard: bool = True,
    ) -> None:
        """Account ONE forged partial detected UPSTREAM of the root —
        a merge-tree node that excluded a child's frame reports it
        here so the counters, evidence trail and inflight accounting
        stay identical to a root-detected forgery: the FRAME counts
        once (``forged_partials``, one evidence event) however many
        leaves it covered, while the per-leaf side effects (forged
        metric, inflight discard — the rows are untrustworthy) fan out
        over ``shards`` (an int or a sequence of leaf indices)."""
        if isinstance(shards, int):
            shards = (shards,)
        shards = [int(s) for s in shards]
        rt = self._roots[tenant]
        rt.forged += 1
        event = {
            "event": "shard_forged",
            "tenant": tenant,
            "round": rt.round_id,
            "shard": shards[0] if len(shards) == 1 else None,
            "shards": shards,
            "claimed_digest": claimed_digest,
            "measured_digest": measured_digest,
            "m": int(m),
        }
        self._note_event(event)
        if rt.durability is not None:
            rt.durability.record_evidence(rt.round_id, event)
        for shard in shards:
            if obs_runtime.STATE.enabled and (
                (tenant, shard) in self._m_forged
            ):
                self._m_forged[(tenant, shard)].inc()
            if discard and 0 <= shard < len(self.shards):
                self.shards[shard].discard_inflight(tenant, rt.round_id)

    # -- round close (sync door) ------------------------------------------

    def close_round_nowait(
        self, tenant: str
    ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        """Synchronously drive one root round: barrier every live shard
        (a shard whose close raises is accounted as a partition), check
        quorum, verify + merge + finalize, confirm, broadcast. Returns
        ``(closed_round_id, merged_rows, aggregate)`` or ``None`` while
        the window stays open (no admissible cohort / below quorum).
        The virtual-clock twin of the async scheduler — the chaos
        ``shard`` lane and the drills run rounds through here."""
        if self._tasks:
            raise RuntimeError(
                "close_round_nowait cannot run next to the async root "
                "scheduler (start() was called) — use one round closer"
            )
        rt = self._roots[tenant]
        # ONE trace root per sharded round: the shard closes below run
        # in this thread, so their serving.shard_close spans (and the
        # merge chain under merge_partials) all link as children —
        # the causal tree the critical-path summarizer reconstructs
        with obs_tracing.span(
            "serving.sharded_round", track="root",
            tenant=tenant, round=rt.round_id,
        ):
            partials: List[PartialFold] = []
            responders = 0
            missing: List[int] = []
            for shard in self.shards:
                if not shard.alive:
                    missing.append(shard.index)
                    continue
                try:
                    p = shard.close_partial(tenant)
                except Exception:  # noqa: BLE001 — a crashing shard close
                    # is a partition, not a root outage; anything it
                    # drained before crashing returns to its held list
                    # (the async twin's contract — rows are never lost)
                    shard.requeue(tenant, rt.round_id)
                    missing.append(shard.index)
                    continue
                responders += 1
                if p is not None:
                    partials.append(p)
            if responders < self.quorum:
                for p in partials:
                    self.shards[p.shard].requeue(tenant, p.round_id)
                rt.quorum_failures += 1
                return None
            if self.topology is not None and partials:
                # run the internal merge-tree levels (rack→pod combines)
                # before the root merge — in-process this is the same
                # thread; the process runner distributes each level to
                # its own merge-node process
                partials = self.topology.combine(
                    rt.cfg.aggregator, partials
                )
            return self.merge_partials(tenant, partials, missing=missing)

    def merge_partials(
        self,
        tenant: str,
        partials: Sequence[PartialFold],
        *,
        missing: Sequence[int] = (),
        prechecked: Optional[Dict[int, Tuple[bool, str]]] = None,
    ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        """The ROOT half of a round close, as a standalone door: verify
        + hierarchical merge + finalize + confirm/broadcast for
        already-extracted partials (quorum is the caller's call —
        :meth:`close_round_nowait` and the async scheduler both land
        here; a remote-root deployment feeds it
        :func:`decode_partial_fold` results off the wire). ``missing``
        names shards to account as a partition in this close.
        ``prechecked`` maps ``id(partial)`` to an arrival-time
        :meth:`check_partial` result — streaming callers verified each
        frame the moment it landed, so the close skips the stateless
        suite and runs only the dedup."""
        rt = self._roots[tenant]
        actions: List[tuple] = []
        computed = self._verify_and_merge(rt, partials, actions, prechecked)
        self._apply_shard_actions(tenant, actions)
        if computed is None:
            return None
        verified, merged, vec, t0, view = computed
        return self._finish(
            rt, verified, merged, vec, list(missing), t0, view
        )

    def repair_round(
        self,
        tenant: str,
        partial: PartialFold,
        *,
        prechecked: Optional[Tuple[bool, str]] = None,
    ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        """Fold one LATE partial into an already-closed round within
        the repair horizon: verify it with the same cross-checks a
        barrier close runs, re-merge the close's retained inputs with
        the late input inserted in shard order (bit-identical to the
        barrier close that would have included it — same
        :meth:`_merge_input` construction, same shard-order concat),
        re-finalize at the repaired cohort's bucket, confirm the late
        shard (its WAL round record + forensics + ``outstanding``
        release), and append the bit-auditable WAL repair record
        (old/new/delta aggregate digests + folded pairs, which
        :func:`audit_sharded_exactly_once` joins against merge evidence
        so no row can fold twice). Returns ``(round_id, merged_rows,
        aggregate)`` or ``None`` when the round is outside the horizon
        (caller requeues one-round-staler as today) or the partial is
        excluded as forged. ``rt.last_aggregate`` is updated only when
        the repaired round is still the most recent close — an older
        repair must not resurrect a superseded broadcast.

        A repair costs ONE verify, not N: the close retained its
        verified merge inputs (``open_repairs``), so only the late
        partial is cross-checked — and when the caller verified it at
        arrival (``prechecked`` from :meth:`check_partial`), the
        stateless suite is not re-run here either (the
        ``partial_checks`` counter pins this contract)."""
        rt = self._roots[tenant]
        if prechecked is not None:
            # the arrival-verified frame is consumed by this repair,
            # whatever the outcome — release its inflight slot
            self._dec_inflight(1)
        r = int(partial.round_id)
        ctx = rt.open_repairs.get(r)
        if ctx is None or partial.tenant != tenant:
            return None
        covered = partial.covered
        known = (
            bool(covered)
            and len(set(covered)) == len(covered)
            and partial.shard == covered[0]
            and all(0 <= s < len(self.shards) for s in covered)
        )
        if not known or not set(covered) <= ctx["missing"]:
            # a repair claiming a shard the close already folded (or a
            # nonsense cover): protocol violation — reject WITHOUT
            # touching any real shard's state, exactly the duplicate-
            # shard rule of the round close
            rt.forged += 1
            self._note_event(
                {
                    "event": "shard_forged",
                    "tenant": tenant,
                    "round": r,
                    "shard": int(partial.shard),
                    "reason": (
                        "unknown_shard" if not known else "repair_not_missing"
                    ),
                    "m": partial.m,
                }
            )
            return None
        checks, measured = self._verify_partial(rt, partial, prechecked)
        if checks is None:
            # forged late partial: digest/ownership/cap cross-checks
            # failed — the repair horizon is NOT a forensics bypass;
            # the shard's in-flight rows are discarded with accounting
            rt.forged += 1
            for s in covered:
                if obs_runtime.STATE.enabled:
                    self._m_forged[(tenant, s)].inc()
                self.shards[s].discard_inflight(tenant, r)
            event = {
                "event": "shard_forged",
                "tenant": tenant,
                "round": r,
                "shard": int(partial.shard),
                "claimed_digest": partial.digest,
                "measured_digest": measured,
                "m": partial.m,
            }
            self._note_event(event)
            if rt.durability is not None:
                rt.durability.record_evidence(r, event)
            ctx["missing"] -= set(covered)
            if not ctx["missing"]:
                del rt.open_repairs[r]
            return None
        folded, dups = checks
        agg = rt.cfg.aggregator
        late = (int(partial.shard), self._merge_input(partial, folded, dups))
        inputs = sorted(ctx["inputs"] + [late], key=lambda e: e[0])
        new_m = int(ctx["m"]) + len(folded)
        old_vec = np.asarray(ctx["vec"])
        with obs_tracing.span(
            "serving.round.repair", track="root", tenant=tenant,
            round=r, shard=int(partial.shard), m=new_m,
        ):
            t_merge = self._clock()
            # the incremental accumulator keys by shard and closes in
            # shard order — the exact `sorted` concat the barrier close
            # would have produced with the late input present
            acc = agg.fold_merge_begin()
            for s, inp in inputs:
                agg.fold_merge_add(acc, s, inp)
            merged = agg.fold_merge_finish(acc)
            ms = merged.get("merge_stats") or {}
            rt.gram_cross_blocks += int(ms.get("cross_blocks", 0))
            rt.partial_transforms += int(ms.get("transforms", 0))
            t_fin = self._clock()
            try:
                with obs_tracing.device_span(
                    "serving.device_step", track="root", tenant=tenant,
                    m=new_m, bucket=rt.ladder.bucket_for(new_m),
                ):
                    vec = np.asarray(
                        agg.fold_merge_finalize(
                            merged,
                            bucket=rt.ladder.bucket_for(new_m),
                            donate=True,
                        )
                    )
            except Exception:  # noqa: BLE001 — a poisoned repair must
                # not kill the root: the already-broadcast close
                # stands, the late rows drop with failed-round account
                rt.failed_rounds += 1
                for s in covered:
                    self.shards[s].account_failed(tenant, r)
                ctx["missing"] -= set(covered)
                if not ctx["missing"]:
                    del rt.open_repairs[r]
                return None
        if obs_runtime.STATE.enabled:
            self._m_finalize_s[tenant].observe(self._clock() - t_fin)
            self._m_root_merge_s[tenant].observe(self._clock() - t_merge)
        digest = evidence_digest(vec)
        delta_digest = evidence_digest(vec - old_vec)
        rt.root_duplicates += len(dups)
        with rt.dedup_lock:
            for j in folded:
                rt.note_folded(partial.clients[j], partial.seqs[j])
            # a repair is a settle too: staged verdicts that predate it
            # must revalidate (the repaired pairs are now folded)
            rt.dedup_epoch += 1
        for owner, lo, hi in partial.segment_spans():
            if not 0 <= owner < len(self.shards):
                continue
            loc_folded = [j - lo for j in folded if lo <= j < hi]
            loc_dups = [j - lo for j in dups if lo <= j < hi]
            self.shards[owner].confirm(
                tenant, r, loc_folded, loc_dups, digest, vec, None
            )
        payload = {
            "event": "repair",
            "round": r,
            "shards": sorted(int(s) for s in covered),
            "m": new_m,
            "folded": [
                [partial.clients[j], partial.seqs[j]] for j in folded
            ],
            "duplicates": len(dups),
            "old_digest": ctx["digest"],
            "agg_digest": digest,
            "delta_digest": delta_digest,
        }
        if rt.durability is not None:
            rt.durability.record_repair(r, payload)
        self._note_event(
            {
                "event": "round_repair",
                "tenant": tenant,
                "round": r,
                "shards": sorted(int(s) for s in covered),
                "m": new_m,
                "delta_digest": delta_digest,
            }
        )
        rt.repairs += 1
        if obs_runtime.STATE.enabled:
            self._m_repairs[tenant].inc()
        ctx["inputs"] = inputs
        ctx["missing"] -= set(covered)
        ctx["digest"] = digest
        ctx["vec"] = vec
        ctx["m"] = new_m
        if not ctx["missing"]:
            del rt.open_repairs[r]
        if r == rt.round_id - 1:
            rt.last_aggregate = vec
        return r, merged["rows"], vec

    def _apply_shard_actions(
        self, tenant: str, actions: Sequence[tuple]
    ) -> None:
        """Execute the shard-state side effects :meth:`_verify_and_merge`
        deferred — requeues, forged-partial discards, failed-round
        accounting. Runs on the EVENT LOOP in the async path: these
        mutate loop-confined tenant state (``outstanding``, held lists,
        ``round_done``) that the admission path touches concurrently,
        so the executor half must only describe them. Shard indices
        are bounds-checked here: a forged frame on the remote-root door
        may claim any index. Each action names the covered LEAF shards
        (one for a flat partial, the whole group for a merge-tree
        partial) — the side effect fans out to every leaf whose rows
        rode the frame."""
        for kind, indices, round_id in actions:
            if isinstance(indices, int):
                indices = (indices,)
            for idx in indices:
                if not 0 <= idx < len(self.shards):
                    continue
                shard = self.shards[idx]
                if kind == "requeue":
                    shard.requeue(tenant, round_id)
                elif kind == "discard":
                    shard.discard_inflight(tenant, round_id)
                elif kind == "fail":
                    shard.account_failed(tenant, round_id)

    def _merge_input(
        self, p: PartialFold, folded: List[int], dups: List[int]
    ) -> dict:
        """Build the aggregator ``fold_merge`` input for one verified
        partial. ONE code path shared by the round close and
        :meth:`repair_round`: a repair re-merge must feed the merge the
        exact bits the barrier close would have — a second construction
        here is a bit-parity bug waiting to happen."""
        if dups:
            # rows were dropped: the shipped extras describe the
            # full row set and no longer apply — recompute at merge
            return {"rows": p.rows[folded], "m": len(folded)}
        if self.extras_policy == "recompute" or not p.extras:
            return {"rows": p.rows, "m": p.m}
        return {"rows": p.rows, "m": p.m, "extras": p.extras}

    def _verify_and_merge(
        self,
        rt: _RootTenant,
        partials: Sequence[PartialFold],
        actions: List[tuple],
        prechecked: Optional[Dict[int, Tuple[bool, str]]] = None,
    ) -> Optional[tuple]:
        """The heavy, loop-free middle of a close: verify every partial
        (forged → excluded + counted + evidence event; stale → requeued
        as a partition), merge the survivors in shard order, finalize
        at the root bucket shape under the device span. Shard-state
        side effects are NOT applied here — they are appended to
        ``actions`` for :meth:`_apply_shard_actions` to run loop-side
        (the async path executes this whole method on an executor
        thread, and ``outstanding``/held-list/``round_done`` state is
        loop-confined). Returns ``(verified, merged, vec, t0)``;
        ``None`` means no close this window (below the admissibility
        floor, or the finalize failed — accounting described in
        ``actions``). ``prechecked`` carries arrival-time
        :meth:`check_partial` results keyed by ``id(partial)`` — every
        entry counted as inflight is consumed by this close (the gauge
        decrements for all of them, including frames a merge-tree level
        combined away), and an id-matched entry skips the stateless
        re-verify. When the arrival path also ran :meth:`stage_partial`
        this close becomes the PAID-DOWN settle: staged dedup verdicts
        promote (epoch-checked), and if every verified frame's merge
        input is already parked in the staged accumulator the per-
        partial ``fold_merge_add`` loop — the heavy half of the merge —
        is skipped entirely and only the cheap shard-order
        ``fold_merge_finish`` placement runs. Any mismatch (requeued or
        forged frame, duplicate shard claim, verdict moved under
        pipelining) falls back to the classic bit-identical rebuild."""
        tenant = rt.cfg.name
        t0 = self._clock()
        if prechecked:
            self._dec_inflight(len(prechecked))
        with rt.dedup_lock:
            ctx = rt.staging.pop(rt.round_id, None)
            for stale in [k for k in rt.staging if k < rt.round_id]:
                del rt.staging[stale]
        staged_entries = ctx["entries"] if ctx is not None else {}
        verified: List[Tuple[PartialFold, List[int], List[int]]] = []
        seen_shards: set = set()
        for p in sorted(partials, key=lambda p: p.shard):
            covered = p.covered
            # bool(covered) + the uniqueness check guard hand-built
            # PartialFolds with empty or duplicate-leaf segments
            # (from_wire already rejects both wire forms): an empty
            # cover must read as forged, never index-error the close
            # mid-verify with honest partials unapplied; a repeated
            # leaf must not ride several under-cap segments past the
            # per-shard row cap
            known = (
                bool(covered)
                and len(set(covered)) == len(covered)
                and p.shard == covered[0]
                and all(0 <= s < len(self.shards) for s in covered)
            )
            overlap = known and any(s in seen_shards for s in covered)
            if (
                not known
                or overlap
                or p.tenant != tenant
                or p.round_id != rt.round_id
            ):
                if not known or overlap:
                    # an unknown shard index, or a second partial
                    # claiming a shard this close already heard from —
                    # only possible on the remote-root door (in-process
                    # closes iterate the coordinator's own shards):
                    # reject WITHOUT touching any real shard's state (a
                    # forged index must not discard a victim's cohort)
                    rt.forged += 1
                    self._note_event(
                        {
                            "event": "shard_forged",
                            "tenant": tenant,
                            "round": rt.round_id,
                            "shard": int(p.shard),
                            "reason": (
                                "unknown_shard" if not known
                                else "duplicate_shard"
                            ),
                            "m": p.m,
                        }
                    )
                    continue
                # stale or misaddressed partial: the shard's rows go
                # back to its held list (a partition, not a forgery)
                actions.append(("requeue", covered, p.round_id))
                rt.partitions += len(covered)
                if obs_runtime.STATE.enabled:
                    for s in covered:
                        self._m_partitions[(tenant, s)].inc()
                continue
            seen_shards.update(covered)
            pre = prechecked.get(id(p)) if prechecked else None
            checks, measured = self._verify_partial(
                rt, p, pre, staged=staged_entries.get(id(p))
            )
            if checks is None:
                rt.forged += 1
                actions.append(("discard", covered, p.round_id))
                if obs_runtime.STATE.enabled:
                    for s in covered:
                        self._m_forged[(tenant, s)].inc()
                event = {
                    "event": "shard_forged",
                    "tenant": tenant,
                    "round": rt.round_id,
                    "shard": p.shard,
                    "claimed_digest": p.digest,
                    "measured_digest": measured,
                    "m": p.m,
                }
                self._note_event(event)
                if rt.durability is not None:
                    rt.durability.record_evidence(rt.round_id, event)
                continue
            verified.append((p, *checks))
        m_total = sum(len(f) for _, f, _ in verified)
        if m_total < rt.min_cohort:
            # under the global admissibility floor: hold the window
            # open — every shard's rows return to its held list (and
            # the duplicate rows are NOT counted: they will be
            # re-verified when the window finally closes)
            for p, _f, _d in verified:
                actions.append(("requeue", p.covered, p.round_id))
            return None
        rt.root_duplicates += sum(len(d) for _, _, d in verified)
        agg = rt.cfg.aggregator
        # staged-accumulator fast path: valid ONLY when the staging
        # table covers exactly this close's verified set — same frames
        # (by identity), every staged verdict still valid under the
        # current epoch, no duplicate-shard poisoning, and the
        # accumulator parked precisely the verified shards. Anything
        # else (a requeued frame, a forged sibling, a verdict that
        # moved) rebuilds classically — bit-identical either way.
        use_staged = (
            ctx is not None
            and ctx["acc"] is not None
            and ctx["acc_ok"]
            and all(e["valid"] for e in staged_entries.values())
            and {id(p) for p, _f, _d in verified}
            == set(staged_entries)
            and ctx["acc_shards"]
            == {int(p.shard) for p, _f, _d in verified}
        )
        merge_partials = (
            None
            if use_staged
            else [
                self._merge_input(p, folded, dups)
                for p, folded, dups in verified
            ]
        )
        t_merge = self._clock()
        with obs_tracing.span(
            "serving.fold_merge", track="root", tenant=tenant,
            round=rt.round_id, shards=len(verified), m=m_total,
            # cross-process causality: each verified partial's carried
            # shard_close span identity ("trace:span") — a merged
            # multi-process export stitches the shard lanes to this
            # merge through these links even when the shard spans live
            # in another process's trace file
            links=[
                f"{p.trace_ctx[0]}:{p.trace_ctx[1]}"
                for p, _f, _d in verified
                if p.trace_ctx is not None
            ],
        ):
            if use_staged:
                # the arrival path already parked every merge input
                # (and ran the per-partial transforms — Multi-Krum's
                # cross-Gram blocks) as each frame landed: finish is
                # the cheap sorted-shard-order placement only
                merged = agg.fold_merge_finish(ctx["acc"])
                rt.staged_closes += 1
            else:
                # incremental accumulator, closed in shard order —
                # `verified` is already shard-sorted, so this is the
                # exact concat `fold_merge(merge_partials)` produced
                # (bit-identity pinned by tests/test_streaming_root.py)
                acc = agg.fold_merge_begin()
                for (p, _f, _d), inp in zip(
                    verified, merge_partials, strict=True
                ):
                    agg.fold_merge_add(acc, p.shard, inp)
                merged = agg.fold_merge_finish(acc)
            ms = merged.get("merge_stats") or {}
            rt.gram_cross_blocks += int(ms.get("cross_blocks", 0))
            rt.partial_transforms += int(ms.get("transforms", 0))
            t_fin = self._clock()
            view = _UNSET = object()
            try:
                with obs_tracing.device_span(
                    "serving.device_step", track="root", tenant=tenant,
                    m=m_total, bucket=rt.ladder.bucket_for(m_total),
                ):
                    # OFF-PATH finalize: the masked program is a
                    # persistent jitted computation with a donated
                    # input buffer keyed by the bucket shape; JAX's
                    # async dispatch returns an unmaterialized handle,
                    # so the host computes the merged score view (for
                    # families whose view reads only the merged fold
                    # state) WHILE the device program is in flight,
                    # then blocks on materialization
                    handle = agg.fold_merge_finalize(
                        merged,
                        bucket=rt.ladder.bucket_for(m_total),
                        donate=True,
                    )
                    if (
                        getattr(agg, "merged_view_from_extras", False)
                        and merged.get("extras")
                    ):
                        try:
                            view = agg.merged_score_view(
                                merged, aggregate=None
                            )
                        except Exception:  # noqa: BLE001 — forensics
                            # input, never a round participant
                            view = None
                            self._note_callback_error()
                    vec = np.asarray(handle)
            except Exception:  # noqa: BLE001 — a poisoned merged cohort
                # must not kill the root: the round fails with per-shard
                # accounting, serving continues
                rt.failed_rounds += 1
                for p, _f, _d in verified:
                    actions.append(("fail", p.covered, rt.round_id))
                return None
        if obs_runtime.STATE.enabled:
            self._m_finalize_s[tenant].observe(self._clock() - t_fin)
            self._m_root_merge_s[tenant].observe(self._clock() - t_merge)
        return verified, merged, vec, t0, (
            None if view is _UNSET else view
        )

    def _finish(
        self,
        rt: _RootTenant,
        verified: Sequence[Tuple[PartialFold, List[int], List[int]]],
        merged: dict,
        vec: np.ndarray,
        missing: Sequence[int],
        t0: float,
        view: Optional[dict] = None,
    ) -> Tuple[int, np.ndarray, np.ndarray]:
        """Bookkeeping half of a successful close (loop-side on the
        async path): root dedup update, root WAL merge evidence, shard
        confirmations + forensics fan-out, stats, round advance.
        ``view`` carries a merged score view the off-path finalize
        already computed during the device program's flight; ``None``
        computes it here (families whose view needs the aggregate)."""
        tenant = rt.cfg.name
        digest = evidence_digest(vec)
        if view is None:
            try:
                view = rt.cfg.aggregator.merged_score_view(
                    merged, aggregate=vec
                )
            except Exception:  # noqa: BLE001 — the score view is
                # forensics input, never a round participant
                self._note_callback_error()
        offsets = list(merged.get("offsets", []))
        m_total = int(merged["m"])
        closed = rt.round_id
        with rt.dedup_lock:
            for p, folded, _d in verified:
                for j in folded:
                    rt.note_folded(p.clients[j], p.seqs[j])
            # ONE settle per close: verdicts staged for the next window
            # before this batch landed are now epoch-stale and will
            # revalidate at promotion (bit-identical either way)
            rt.dedup_epoch += 1
        for idx, (p, folded, dups) in enumerate(verified):
            # confirmation (WAL round record, forensics fan-out, stats)
            # goes to each LEAF shard whose rows rode this frame — a
            # merge-tree partial fans back per segment, with the row
            # indices re-localized to the leaf's own inflight order
            start = offsets[idx] if idx < len(offsets) else None
            for owner, lo, hi in p.segment_spans():
                if not 0 <= owner < len(self.shards):
                    continue
                loc_folded = [j - lo for j in folded if lo <= j < hi]
                loc_dups = [j - lo for j in dups if lo <= j < hi]
                pre = None
                if view is not None and not dups and start is not None:
                    pre = {
                        "kind": view["kind"],
                        "scores": (
                            None
                            if view.get("scores") is None
                            else np.asarray(view["scores"])[
                                start + lo: start + hi
                            ]
                        ),
                        "keep": (
                            None
                            if view.get("keep") is None
                            else np.asarray(view["keep"])[
                                start + lo: start + hi
                            ]
                        ),
                    }
                self.shards[owner].confirm(
                    tenant, closed, loc_folded, loc_dups, digest, vec, pre
                )
        if rt.durability is not None:
            rt.durability.record_evidence(
                closed,
                {
                    "event": "merge",
                    "round": closed,
                    "m": m_total,
                    "agg_digest": digest,
                    "shards": {
                        int(p.shard): {
                            "digest": p.digest,
                            "m": p.m,
                            "folded": [
                                [p.clients[j], p.seqs[j]] for j in folded
                            ],
                            "duplicates": len(dups),
                        }
                        for p, folded, dups in verified
                    },
                },
            )
            rt.durability.record_round(closed, (), digest, m_total)
            rt.durability.note_round_closed()
        rt.last_aggregate = vec
        rt.rounds += 1
        first_arrival = min(
            (p.first_arrival_s for p, _f, _d in verified), default=t0
        )
        rt.stats.record(self._clock() - first_arrival, m_total)
        degraded = bool(missing)
        if degraded:
            rt.quorum_closes += 1
            for i in missing:
                rt.partitions += 1
                if obs_runtime.STATE.enabled:
                    self._m_partitions[(tenant, i)].inc()
            self._note_event(
                {
                    "event": "quorum_close",
                    "tenant": tenant,
                    "round": closed,
                    "missing": list(missing),
                }
            )
            if rt.durability is not None:
                rt.durability.record_evidence(
                    closed,
                    {
                        "event": "quorum_close",
                        "round": closed,
                        "missing": list(missing),
                    },
                )
            if self.repair_horizon > 0:
                # SPECULATIVE close: retain the exact merge inputs so a
                # straggler's late partial can fold as a repair delta
                # whose re-merge is bit-identical to the barrier close
                # that would have included it. The caller must NOT
                # requeue the missing shards' drained cohorts — they
                # stay in flight until repair_round folds them or the
                # horizon expires them back to the held lists.
                rt.speculative_closes += 1
                rt.open_repairs[closed] = {
                    "inputs": [
                        (int(p.shard), self._merge_input(p, folded, dups))
                        for p, folded, dups in verified
                    ],
                    "missing": set(int(i) for i in missing),
                    "digest": digest,
                    "vec": vec,
                    "m": m_total,
                }
                if obs_runtime.STATE.enabled:
                    self._m_speculative.inc()
        rt.round_id += 1
        for shard in self.shards:
            if shard.alive:
                shard.sync_round(tenant, rt.round_id)
        if rt.open_repairs:
            # horizon expiry: a closed round that fell out of the
            # repair window releases its still-missing shards' drained
            # cohorts back to their held lists — the rows fold in a
            # later round one-round-staler, exactly the classic
            # degraded-close account
            expired = [
                r for r in rt.open_repairs
                if r < rt.round_id - self.repair_horizon
            ]
            for r in expired:
                ctx = rt.open_repairs.pop(r)
                for i in ctx["missing"]:
                    if 0 <= i < len(self.shards) and self.shards[i].alive:
                        self.shards[i].requeue(tenant, r)
        if obs_runtime.STATE.enabled:
            self._m_rounds[tenant].inc()
            self._m_merge_s[tenant].observe(self._clock() - t0)
            if degraded:
                self._m_quorum[tenant].inc()
        if self._on_round is not None:
            try:
                self._on_round(tenant, closed, merged, vec)
            except Exception:  # noqa: BLE001 — observer bug, counted
                self._note_callback_error()
        return closed, merged["rows"], vec

    # -- async root scheduler ---------------------------------------------

    async def start(self) -> None:
        """Launch one root round loop per tenant (window-triggered
        barrier closes with the straggler timeout)."""
        if self._running:
            return
        self._running = True
        self._device_lock = asyncio.Lock()
        self._tasks = [
            asyncio.create_task(
                self._root_loop(cfg), name=f"sharded-root-{cfg.name}"
            )
            for cfg in self._tenant_cfgs
        ]

    async def close(self) -> None:
        """Stop the root scheduler and release shard durable handles
        (idempotent). Pending deferred merges settle BEFORE the shards
        shut down — a kicked round's WAL records must land."""
        self._running = False
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks = []
        for entry in list(self._pending_async.values()):
            task = entry.get("task")
            if task is not None:
                try:
                    await task
                except Exception:  # noqa: BLE001 — a crashed finish
                    # must not wedge shutdown
                    pass
        self._pending_async.clear()
        for shard in self.shards:
            if shard.alive:
                shard.shutdown()
        for rt in self._roots.values():
            if rt.durability is not None:
                rt.durability.close()
        # quiescence invariant: every staged partial was merged or
        # repaired away — a nonzero residue is a lost decrement
        sanitize.check_drained(
            "byzpy_root_partials_inflight", self._partials_inflight
        )

    async def _root_loop(self, cfg: TenantConfig) -> None:
        while self._running:
            sanitize.loop_tick(
                f"serving.root_loop.{cfg.name}",
                threshold_s=max(30.0, 10.0 * cfg.window_s),
            )
            await asyncio.sleep(cfg.window_s)
            try:
                await self._close_async(cfg.name)
            except Exception:  # noqa: BLE001 — a failed window must not
                # kill the root scheduler
                self._note_callback_error()

    async def _close_async(self, tenant: str) -> Optional[tuple]:
        """One async close, ARRIVAL-DRIVEN: the previous window's
        deferred merge settles FIRST (settle-before-build — the
        bit-identity proof: the partials this window builds see exactly
        the post-merge dedup/round state a barrier close would have),
        then every live shard's drain+build is FUSED with the stateless
        cross-check suite on the executor, so each partial is verified
        the moment it exists and only dedup + merge + finalize remain
        after the barrier. With ``pipeline_depth=1`` (default) a quorum
        close advances the shard staleness clocks optimistically and
        kicks the merge+device step to a background task — round N+1's
        admission windows fill while round N settles (the runner tier's
        PR-17 contract, on the in-process root). ``pipeline_depth=0``
        keeps the barrier-style close inline."""
        loop = asyncio.get_running_loop()
        rt = self._roots[tenant]
        await self._settle_async(tenant)
        sp = obs_tracing.begin_span(
            "serving.sharded_round", track="root",
            tenant=tenant, round=rt.round_id,
            pipelined=bool(self.pipeline_depth),
        )
        kicked = False
        try:
            with obs_tracing.context_scope(getattr(sp, "context", None)):
                prepared = await self._gather_checked_async(
                    tenant, loop, rt
                )
                if prepared is None:
                    return None
                partials, prechecked, missing = prepared
                if self.pipeline_depth == 0:
                    return await self._merge_async(
                        tenant, loop, rt, rt.round_id,
                        partials, prechecked, missing, consume=False,
                    )
            # quorum fired: open round N+1's admission/staleness plane
            # NOW — the ROOT clock stays at N until the deferred merge
            # lands, so partial round-id checks still pass
            closing = rt.round_id
            for shard in self.shards:
                if shard.alive:
                    shard.sync_round(tenant, closing + 1)
            entry: dict = {
                "round": closing,
                "kicked": self._clock(),
                "done_s": None,
            }
            entry["task"] = asyncio.create_task(
                self._deferred_close_async(
                    tenant, loop, rt, closing,
                    partials, prechecked, missing, sp, entry,
                ),
                name=f"sharded-finish-{tenant}-{closing}",
            )
            self._pending_async[tenant] = entry
            kicked = True  # span ownership moved to the deferred task
            return None
        finally:
            if not kicked:
                obs_tracing.end_span(sp)

    async def _gather_checked_async(
        self, tenant: str, loop, rt: _RootTenant
    ) -> Optional[tuple]:
        """Drain every live shard on the loop (queue access is
        loop-confined), then build AND arrival-verify the partials
        concurrently on the executor under the straggler timeout.
        Returns ``(partials, prechecked, missing)`` ready for
        :meth:`_merge_async`, or ``None`` when no close happens this
        window (below quorum / nothing drained) — with any inflight
        accounting already unwound."""
        drained: Dict[int, tuple] = {}
        missing: List[int] = []
        responders = 0
        for shard in self.shards:
            if not shard.alive:
                missing.append(shard.index)
                continue
            responders += 1
            d = shard.drain_cohort(tenant)
            if d is not None:
                drained[shard.index] = d
        if responders < self.quorum:
            for i, (subs, _c) in drained.items():
                self.shards[i].requeue(tenant, rt.round_id)
            rt.quorum_failures += 1
            return None
        # flat root: fuse the stateless cross-check suite onto the
        # build thread — the partial is verified the moment it exists,
        # overlapped across shards, leaving only dedup at merge time.
        # With a merge tree the leaves are combined first and the
        # COMBINED frames are checked (per segment), exactly the frames
        # the root will merge.
        fuse = self.topology is None

        def _build(shard, subs, cohort):
            p = shard.build_partial(tenant, subs, cohort)
            chk = (
                self.check_partial(tenant, p, inflight=True)
                if fuse else None
            )
            if chk is not None and chk[0]:
                # close-path paydown: stage the dedup verdict and park
                # the merge input (per-partial heavy transform included)
                # the moment this frame passes its arrival check — the
                # close's settle half just promotes
                self.stage_partial(tenant, p, chk)
            return p, chk

        futs = {
            loop.run_in_executor(
                None,
                obs_tracing.carry_context(_build),
                self.shards[i], subs, cohort,
            ): i
            for i, (subs, cohort) in drained.items()
        }
        built: List[Tuple[PartialFold, Optional[Tuple[bool, str]]]] = []
        crashed = 0
        if futs:
            done, pending = await asyncio.wait(
                futs.keys(), timeout=self.shard_timeout_s
            )
            for fut in done:
                i = futs[fut]
                try:
                    built.append(fut.result())
                except Exception:  # noqa: BLE001 — crashing shard close
                    crashed += 1
                    missing.append(i)
                    self.shards[i].requeue(tenant, rt.round_id)
            stragglers = sorted(futs[f] for f in pending)
            missing.extend(stragglers)
            round_id = rt.round_id

            def _late(f, i, r):
                # past the barrier: when the late build completes, its
                # rows return to the shard's held list for next round —
                # and its arrival-verify (if it got that far) is
                # consumed by no close, so the inflight slot releases
                try:
                    p_chk = f.result()
                except Exception:  # noqa: BLE001
                    p_chk = None
                if p_chk is not None and p_chk[1] is not None:
                    self._dec_inflight(1)
                self.shards[i].requeue(tenant, r)

            for fut in pending:
                fut.add_done_callback(
                    lambda f, i=futs[fut], r=round_id: _late(f, i, r)
                )
            # stragglers and crashes ate into the quorum: re-check with
            # the shards that actually answered the barrier
            responders -= len(stragglers) + crashed
            if responders < self.quorum:
                checked = sum(1 for _p, chk in built if chk is not None)
                if checked:
                    self._dec_inflight(checked)
                for p, _chk in built:
                    self.shards[p.shard].requeue(tenant, p.round_id)
                rt.quorum_failures += 1
                return None
        if not built:
            return None
        partials = [p for p, _chk in built]
        prechecked: Dict[int, Tuple[bool, str]] = {
            id(p): chk for p, chk in built if chk is not None
        }
        if self.topology is not None:
            # internal merge-tree levels off the loop (pure numpy
            # concatenation + extras recompute — the work a pod-level
            # merge process owns in the runner deployment), then the
            # arrival check runs per COMBINED frame on the executor
            partials = await loop.run_in_executor(
                None,
                obs_tracing.carry_context(self.topology.combine),
                rt.cfg.aggregator, partials,
            )

            def _check_all(ps):
                out = {}
                for p in ps:
                    chk = self.check_partial(tenant, p, inflight=True)
                    out[id(p)] = chk
                    if chk[0]:
                        self.stage_partial(tenant, p, chk)
                return out

            prechecked = await loop.run_in_executor(
                None, obs_tracing.carry_context(_check_all), partials
            )
        return partials, prechecked, missing

    async def _merge_async(
        self,
        tenant: str,
        loop,
        rt: _RootTenant,
        closing: int,
        partials: List[PartialFold],
        prechecked: Dict[int, Tuple[bool, str]],
        missing: List[int],
        *,
        consume: bool,
    ) -> Optional[tuple]:
        """Merge+finalize off-loop under the device lock, then finish
        on the loop (WAL writes stay loop-confined). ``consume=True``
        is the pipelined contract: the shard clocks already advanced
        optimistically, so a failed merge still consumes the round —
        the drained rows requeue and fold one round staler, the only
        behavioral divergence from the barrier path and only in the
        failure case."""
        assert self._device_lock is not None
        actions: List[tuple] = []
        async with self._device_lock:
            computed = await loop.run_in_executor(
                None,
                obs_tracing.carry_context(self._verify_and_merge),
                rt, partials, actions, prechecked,
            )
        # shard-state side effects (requeues/discards/failure accounting)
        # run HERE, back on the loop — the executor half only described
        # them (outstanding/held/round_done are loop-confined state the
        # admission path touches concurrently)
        self._apply_shard_actions(tenant, actions)
        if computed is None:
            if consume:
                rt.round_id = closing + 1
                for shard in self.shards:
                    if shard.alive:
                        shard.sync_round(tenant, closing + 1)
            return None
        verified, merged, vec, t0, view = computed
        return self._finish(rt, verified, merged, vec, missing, t0, view)

    async def _deferred_close_async(
        self,
        tenant: str,
        loop,
        rt: _RootTenant,
        closing: int,
        partials: List[PartialFold],
        prechecked: Dict[int, Tuple[bool, str]],
        missing: List[int],
        sp,
        entry: dict,
    ) -> Optional[tuple]:
        """The overlapped half of a pipelined async close — round N's
        verify(dedup-only)+merge+device-step settling while round N+1's
        shard windows admit."""
        try:
            with obs_tracing.context_scope(getattr(sp, "context", None)):
                return await self._merge_async(
                    tenant, loop, rt, closing,
                    partials, prechecked, missing, consume=True,
                )
        finally:
            entry["done_s"] = self._clock()
            obs_tracing.end_span(sp)

    async def _settle_async(self, tenant: str) -> Optional[dict]:
        """Await the tenant's pending deferred merge (no-op when none):
        returns the settled round's summary (``closed``/``digest``/
        ``m``/``overlap_ratio``) and publishes the
        ``byzpy_round_overlap_ratio`` gauge — the fraction of the
        deferred merge that ran before anyone had to wait for it, i.e.
        the wall-clock the pipeline actually hid."""
        entry = self._pending_async.pop(tenant, None)
        if entry is None:
            return None
        wait_start = self._clock()
        try:
            res = await asyncio.shield(entry["task"])
        except asyncio.CancelledError:
            # WE were cancelled mid-settle (shutdown): the deferred
            # task survives the shield — put it back for close()
            self._pending_async.setdefault(tenant, entry)
            raise
        except Exception:  # noqa: BLE001 — a crashed finish must not
            # wedge the scheduler; the round's accounting is whatever
            # the coordinator got to
            res = None
        prev: dict = {"closed": None, "round": int(entry["round"])}
        if res is not None:
            closed, rows, vec = res
            prev["closed"] = int(closed)
            prev["digest"] = evidence_digest(np.asarray(vec))
            prev["m"] = int(rows.shape[0])
        done_s = entry.get("done_s") or wait_start
        span_s = max(0.0, done_s - entry["kicked"])
        hidden = max(0.0, min(done_s, wait_start) - entry["kicked"])
        ratio = 1.0 if span_s <= 0 else max(0.0, min(1.0, hidden / span_s))
        prev["overlap_ratio"] = round(ratio, 4)
        if obs_runtime.STATE.enabled and tenant in self._m_overlap:
            self._m_overlap[tenant].set(ratio)
        return prev

    # -- failover ----------------------------------------------------------

    def kill_shard(self, index: int) -> None:
        """Drill door: the shard is dead (its in-memory queues, held
        cohorts and ledgers are GONE — the SIGKILL shape; only its WAL
        survives). Routing keeps its clients sticky: their submissions
        are rejected ``rejected_shard_down`` until recovery."""
        shard = self.shards[index]
        shard.alive = False
        shard._inflight.clear()
        self._m_live.set(len(self.live_shards()))

    def recover_shard(self, index: int) -> ShardFrontend:
        """Rebuild a dead shard FROM ITS WAL ALONE (ledger-delta
        replay): a fresh inner frontend recovers pending accepts, the
        dedup table, and credit-ledger totals from the shard's
        durability directory; the staleness clock is re-synced to the
        global round. Rows the dead shard had acked-but-not-folded
        re-enter its queue and fold in a later round — the root dedup
        table guarantees exactly-once if any were already merged."""
        if self._durability is None:
            raise ValueError(
                "recover_shard needs the coordinator's durability config"
            )
        old = self.shards[index]
        if old.alive:
            raise ValueError(f"shard {index} is still alive")
        shard = ShardFrontend(
            index,
            self._tenant_cfgs,
            clock=self._clock,
            durability=self._shard_durability(index),
        )
        for name, rt in self._roots.items():
            shard.sync_round(name, rt.round_id)
        self.shards[index] = shard
        self._m_live.set(len(self.live_shards()))
        return shard

    # -- introspection ------------------------------------------------------

    def round_of(self, tenant: str) -> int:
        """Current global round of ``tenant``."""
        return self._roots[tenant].round_id

    def last_aggregate(self, tenant: str) -> Any:
        """Most recent merged broadcast (None before round 0)."""
        return self._roots[tenant].last_aggregate

    def reset_round_stats(self) -> None:
        """Zero the root latency/cohort windows (bench warmup boundary;
        accounting state untouched — the single-frontend contract)."""
        for rt in self._roots.values():
            rt.stats = RoundStats()

    def stats(self) -> dict:
        """Root + per-shard accounting snapshot."""
        out: dict = {"shards": {}, "root": {}}
        for shard in self.shards:
            out["shards"][shard.index] = (
                shard.stats() if shard.alive else None
            )
        for name, rt in self._roots.items():
            p50, p99 = rt.stats.latency_percentiles_s(50, 99)
            out["root"][name] = {
                "round_id": rt.round_id,
                "rounds": rt.rounds,
                "min_cohort": rt.min_cohort,
                "quorum": self.quorum,
                "quorum_failures": rt.quorum_failures,
                "quorum_closes": rt.quorum_closes,
                "speculative_closes": rt.speculative_closes,
                "repairs": rt.repairs,
                "open_repairs": len(rt.open_repairs),
                "partitions": rt.partitions,
                "forged_partials": rt.forged,
                "root_duplicates": rt.root_duplicates,
                "failed_rounds": rt.failed_rounds,
                "partial_checks": rt.partial_checks,
                "partials_inflight": self._partials_inflight,
                "pipeline_depth": self.pipeline_depth,
                "dedup_staged": rt.dedup_staged,
                "dedup_promoted": rt.dedup_promoted,
                "dedup_restaged": rt.dedup_restaged,
                "staged_closes": rt.staged_closes,
                "gram_cross_blocks": rt.gram_cross_blocks,
                "partial_transforms": rt.partial_transforms,
                "p50_round_latency_s": p50,
                "p99_round_latency_s": p99,
                "mean_cohort": (
                    float(np.mean(rt.stats.cohort_sizes))
                    if rt.stats.cohort_sizes
                    else 0.0
                ),
                "ladder": list(rt.ladder.sizes),
            }
        return out


def audit_sharded_exactly_once(
    directory: str, tenant: str, n_shards: int
) -> dict:
    """Cross-WAL exactly-once audit of one sharded deployment: reads
    every shard's WAL plus the root's merge evidence and checks the
    tier's invariants —

    1. every ``(client, seq)`` the root folded appears in EXACTLY one
       merge record (no double-folds across failovers);
    2. per shard, every wal_id named by a round record was accepted in
       that shard's WAL (no folds of phantom rows);
    3. no shard wal_id is both round-folded and drop-accounted (a row
       either folded or was dropped with accounting, never both);
    4. every accepted wal_id is folded, dropped, or still pending (no
       silent loss).

    Returns ``{"violations": [...], "folded": n, "accepted": n,
    "root_rounds": n, "pending": n}`` — the drill asserts an empty
    violations list over many seeds."""
    violations: List[str] = []
    folded_pairs: Dict[Tuple[str, int], int] = {}
    root_rounds = 0
    root_repairs = 0
    root_dir = os.path.join(directory, "root", tenant)
    if os.path.isdir(root_dir):
        records, _torn = read_wal(root_dir)
        for rec in records:
            if rec[0] == "e" and isinstance(rec[2], dict):
                ev = rec[2]
                if ev.get("event") != "merge":
                    continue
                root_rounds += 1
                for info in ev.get("shards", {}).values():
                    for client, seq in info.get("folded", ()):
                        if seq is None:
                            continue
                        key = (str(client), int(seq))
                        folded_pairs[key] = folded_pairs.get(key, 0) + 1
            elif rec[0] == "p" and isinstance(rec[2], dict):
                # speculative-close repair records join the same
                # exactly-once ledger: a row that folded in a merge AND
                # a repair (or in two repairs) is a double-fold
                root_repairs += 1
                for client, seq in rec[2].get("folded", ()):
                    if seq is None:
                        continue
                    key = (str(client), int(seq))
                    folded_pairs[key] = folded_pairs.get(key, 0) + 1
    for key, count in folded_pairs.items():
        if count > 1:
            violations.append(
                f"(client, seq) {key} folded {count} times at the root"
            )
    accepted_total = 0
    pending_total = 0
    for i in range(n_shards):
        shard_dir = os.path.join(directory, f"shard{i}", tenant)
        if not os.path.isdir(shard_dir):
            continue
        records, _torn = read_wal(shard_dir)
        accepted: Dict[int, tuple] = {}
        folded: set = set()
        dropped: set = set()
        for rec in records:
            kind = rec[0]
            if kind == "a":
                accepted[int(rec[1])] = (rec[2], rec[3])
            elif kind == "r":
                for w in rec[2]:
                    if w in folded:
                        violations.append(
                            f"shard{i} wal_id {w} folded twice"
                        )
                    if w not in accepted:
                        violations.append(
                            f"shard{i} folded phantom wal_id {w}"
                        )
                    folded.add(w)
            elif kind == "f":
                dropped.update(int(w) for w in rec[2])
        both = folded & dropped
        for w in sorted(both):
            violations.append(
                f"shard{i} wal_id {w} both folded and dropped"
            )
        accepted_total += len(accepted)
        pending_total += len(
            set(accepted) - folded - dropped
        )
    return {
        "violations": violations,
        "folded": sum(folded_pairs.values()),
        "accepted": accepted_total,
        "pending": pending_total,
        "root_rounds": root_rounds,
        "root_repairs": root_repairs,
    }


__all__ = [
    "PARTIAL_FOLD",
    "REJECTED_SHARD_DOWN",
    "ROOT_DUPLICATE",
    "MergeTopology",
    "PartialFold",
    "ShardFrontend",
    "ShardRouter",
    "ShardedCoordinator",
    "audit_sharded_exactly_once",
    "combine_partials",
    "decode_partial_fold",
    "encode_partial_fold",
    "shard_for",
]

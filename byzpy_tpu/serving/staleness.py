"""Stale-gradient handling: fold a round-``k`` submission into round
``k + δ`` with a configurable discount.

Clients of a continuous-ingestion tier compute against whatever model
round they last pulled; by the time a submission reaches the scheduler
the server may be δ rounds ahead. The standard asynchronous-SGD remedy
(staleness-aware scaling, à la Zhang et al. 2016) multiplies the
gradient by a decreasing function of δ before it enters the aggregate —
robust aggregators then see stale contributions shrunk toward zero
instead of voting at full weight with outdated geometry.

Pinned semantics (``tests/test_masked_finalize.py``):

* ``discount(0) == 1.0`` EXACTLY, and a weight-1.0 row is bit-identical
  through the fold (IEEE ``1.0 * x == x``) — fresh submissions are
  untouched;
* ``cutoff`` turns "too stale" into an admission rejection rather than
  a zero-weight row wasting a cohort slot.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

_KINDS = ("none", "exponential", "polynomial")


@dataclass(frozen=True)
class StalenessPolicy:
    """Discount policy ``w = discount(δ)`` for a δ-rounds-stale gradient.

    ``kind``:

    * ``"none"`` — every admitted submission folds at full weight;
    * ``"exponential"`` — ``w = gamma ** δ``;
    * ``"polynomial"`` — ``w = 1 / (1 + δ) ** alpha``.

    ``cutoff`` (optional): submissions with ``δ > cutoff`` are rejected
    at admission (reason ``rejected_too_stale``) instead of discounted.
    """

    kind: str = "none"
    gamma: float = 0.5
    alpha: float = 1.0
    cutoff: Optional[int] = None

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {self.kind!r}")
        if not 0.0 < self.gamma <= 1.0:
            raise ValueError("gamma must be in (0, 1]")
        if self.alpha < 0:
            raise ValueError("alpha must be >= 0")
        if self.cutoff is not None and self.cutoff < 0:
            raise ValueError("cutoff must be >= 0")

    def admits(self, delta: int) -> bool:
        """False when the submission is beyond the staleness cutoff."""
        return self.cutoff is None or delta <= self.cutoff

    def discount(self, delta: int) -> float:
        """Weight for a δ-rounds-stale gradient; ``discount(0) == 1.0``
        exactly for every policy (δ ≤ 0 — a client somehow ahead of the
        server — also folds at full weight)."""
        if delta <= 0 or self.kind == "none":
            return 1.0
        if self.kind == "exponential":
            return float(self.gamma) ** int(delta)
        return 1.0 / float(1 + delta) ** float(self.alpha)


__all__ = ["StalenessPolicy"]

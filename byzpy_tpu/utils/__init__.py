from .trees import stack_gradients, unstack_rows

__all__ = ["stack_gradients", "unstack_rows"]

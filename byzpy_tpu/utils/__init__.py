from .checkpoint import CheckpointManager, restore_checkpoint, save_checkpoint
from .metrics import MetricsLogger, StepTimer, trace
from .trees import stack_gradients, unstack_rows
from .training import train_with_progress, train_with_progress_async

__all__ = [
    "stack_gradients",
    "unstack_rows",
    "train_with_progress",
    "train_with_progress_async",
    "CheckpointManager",
    "save_checkpoint",
    "restore_checkpoint",
    "MetricsLogger",
    "StepTimer",
    "trace",
]

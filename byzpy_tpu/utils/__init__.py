"""Utility subpackage.

Lazy re-exports: submodules here (checkpoint, metrics, training) import
jax at module import time, but some consumers — example launcher
processes, ``utils.platform`` callers racing a plugin sitecustomize —
must be importable before/without the jax backend. Mirrors the lazy
``__getattr__`` pattern of the top-level package.
"""

from typing import Any

_EXPORTS = {
    "stack_gradients": ("trees", "stack_gradients"),
    "unstack_rows": ("trees", "unstack_rows"),
    "train_with_progress": ("training", "train_with_progress"),
    "train_with_progress_async": ("training", "train_with_progress_async"),
    "CheckpointManager": ("checkpoint", "CheckpointManager"),
    "save_checkpoint": ("checkpoint", "save_checkpoint"),
    "restore_checkpoint": ("checkpoint", "restore_checkpoint"),
    "MetricsLogger": ("metrics", "MetricsLogger"),
    "StepTimer": ("metrics", "StepTimer"),
    "trace": ("metrics", "trace"),
}

__all__ = list(_EXPORTS)


def __getattr__(name: str) -> Any:
    try:
        mod_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(f".{mod_name}", __name__), attr)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))

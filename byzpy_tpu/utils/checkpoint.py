"""Sharded checkpoint/resume.

The reference has **no** checkpointing (SURVEY §5: examples pull
``dump_state_dict()`` off a node actor, ``byzpy/examples/ps/thread/
mnist.py:117-119``); the survey flags orbax-style sharded checkpointing as
a required addition for the TPU build. This wraps orbax so training state
(params / opt state / round counters, arbitrary pytrees) saves and
restores with shardings preserved — a restore onto a mesh re-shards
automatically via each array's sharding spec.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

import jax


class CheckpointManager:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    >>> ckpt = CheckpointManager("/tmp/run1", max_to_keep=3)
    >>> ckpt.save(step=10, state={"params": params, "round": 10})
    >>> state = ckpt.restore()                  # latest
    >>> state = ckpt.restore(step=10, like=abstract_state)  # resharded
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._ocp = ocp

    # -- write ---------------------------------------------------------------

    def save(self, step: int, state: Any, *, wait: bool = True) -> None:
        """Save a pytree of (possibly sharded) arrays at ``step``."""
        self._mgr.save(
            step, args=self._ocp.args.StandardSave(state)
        )
        if wait:
            self._mgr.wait_until_finished()

    # -- read ----------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mgr.all_steps())

    def restore(self, step: Optional[int] = None, *, like: Any = None) -> Any:
        """Restore ``step`` (default: latest). ``like`` is an abstract or
        concrete pytree prescribing dtypes/shapes/shardings — pass one built
        on the target mesh to restore directly into a sharded layout."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        if like is not None:
            abstract = jax.tree_util.tree_map(_as_abstract, like)
            args = self._ocp.args.StandardRestore(abstract)
        else:
            args = self._ocp.args.StandardRestore()
        return self._mgr.restore(step, args=args)

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def _as_abstract(leaf: Any) -> Any:
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=leaf.sharding
        )
    return leaf


def save_checkpoint(directory: str, step: int, state: Any) -> None:
    """One-shot save (convenience)."""
    with CheckpointManager(directory) as mgr:
        mgr.save(step, state)


def restore_checkpoint(
    directory: str, step: Optional[int] = None, *, like: Any = None
) -> Any:
    """One-shot restore (convenience)."""
    with CheckpointManager(directory) as mgr:
        return mgr.restore(step, like=like)


__all__ = ["CheckpointManager", "save_checkpoint", "restore_checkpoint"]

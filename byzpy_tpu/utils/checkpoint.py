"""Sharded checkpoint/resume + durable host-state snapshots.

The reference has **no** checkpointing (SURVEY §5: examples pull
``dump_state_dict()`` off a node actor, ``byzpy/examples/ps/thread/
mnist.py:117-119``); the survey flags orbax-style sharded checkpointing as
a required addition for the TPU build. Two tiers live here:

* :class:`CheckpointManager` wraps orbax so training state (params / opt
  state / round counters, arbitrary pytrees) saves and restores with
  shardings preserved — a restore onto a mesh re-shards automatically via
  each array's sharding spec. Missing/corrupt state surfaces as the typed
  :class:`CheckpointNotFoundError` / :class:`CheckpointCorruptError`
  (never a bare orbax internal error).
* :class:`SnapshotStore` is the lightweight sibling for HOST-side runtime
  state (the serving tier's durable round state, dedup tables, credit
  summaries): one self-contained file per generation with an atomic
  rename and an embedded SHA-256 integrity digest, so a process killed
  mid-save can never leave a half-written generation that restore would
  trust — a torn or tampered file is detected and the PREVIOUS generation
  answers instead. Saves can run off the event loop
  (:meth:`SnapshotStore.save_async`).
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import pickle
import re
import time
from typing import Any, List, Optional, Tuple

import jax

from ..observability import metrics as _obs_metrics


class CheckpointNotFoundError(FileNotFoundError):
    """No usable checkpoint exists where one was asked for; the message
    always names the directory searched."""


class CheckpointCorruptError(RuntimeError):
    """State exists but failed integrity/decode checks (every retained
    generation, for stores that keep several)."""


class CheckpointManager:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    >>> ckpt = CheckpointManager("/tmp/run1", max_to_keep=3)
    >>> ckpt.save(step=10, state={"params": params, "round": 10})
    >>> state = ckpt.restore()                  # latest
    >>> state = ckpt.restore(step=10, like=abstract_state)  # resharded
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._ocp = ocp

    # -- write ---------------------------------------------------------------

    def save(self, step: int, state: Any, *, wait: bool = True) -> None:
        """Save a pytree of (possibly sharded) arrays at ``step``."""
        self._mgr.save(
            step, args=self._ocp.args.StandardSave(state)
        )
        if wait:
            self._mgr.wait_until_finished()

    # -- read ----------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mgr.all_steps())

    def restore(self, step: Optional[int] = None, *, like: Any = None) -> Any:
        """Restore ``step`` (default: latest). ``like`` is an abstract or
        concrete pytree prescribing dtypes/shapes/shardings — pass one built
        on the target mesh to restore directly into a sharded layout.

        An empty directory (or an explicit ``step`` that does not exist)
        raises :class:`CheckpointNotFoundError` naming the directory; a
        present-but-unreadable step (truncated/tampered files, a ``like``
        tree that does not match what was saved) raises
        :class:`CheckpointCorruptError` with the orbax error chained —
        callers get ONE typed surface instead of whatever orbax's
        internals raise that week."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise CheckpointNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        elif step not in self._mgr.all_steps():
            raise CheckpointNotFoundError(
                f"no checkpoint for step {step} under {self.directory} "
                f"(available: {self.all_steps()})"
            )
        if like is not None:
            abstract = jax.tree_util.tree_map(_as_abstract, like)
            args = self._ocp.args.StandardRestore(abstract)
        else:
            args = self._ocp.args.StandardRestore()
        try:
            return self._mgr.restore(step, args=args)
        except (KeyboardInterrupt, SystemExit):
            raise
        except FileNotFoundError as exc:
            raise CheckpointNotFoundError(
                f"checkpoint step {step} under {self.directory} is missing "
                f"pieces: {exc}"
            ) from exc
        except Exception as exc:  # noqa: BLE001 — typed surface for callers
            raise CheckpointCorruptError(
                f"checkpoint step {step} under {self.directory} failed to "
                f"restore: {type(exc).__name__}: {exc}"
            ) from exc

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def _as_abstract(leaf: Any) -> Any:
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=leaf.sharding
        )
    return leaf


def save_checkpoint(directory: str, step: int, state: Any) -> None:
    """One-shot save (convenience)."""
    with CheckpointManager(directory) as mgr:
        mgr.save(step, state)


def restore_checkpoint(
    directory: str, step: Optional[int] = None, *, like: Any = None
) -> Any:
    """One-shot restore (convenience)."""
    with CheckpointManager(directory) as mgr:
        return mgr.restore(step, like=like)


# ---------------------------------------------------------------------------
# host-state snapshot store (atomic rename + integrity digest)
# ---------------------------------------------------------------------------

_SNAP_MAGIC = b"BZSNAP1\n"
_SNAP_RE = re.compile(r"^snap-(\d{12})\.bzs$")


def _snapshot_latency() -> Any:
    return _obs_metrics.registry().histogram(
        "byzpy_checkpoint_save_seconds",
        help="host-state snapshot save latency (serialize + fsync + rename)",
    )


class SnapshotStore:
    """Generational, digest-verified pickle snapshots of host state.

    Layout: ``snap-<step:012d>.bzs`` files, each ``MAGIC + sha256-hex +
    "\\n" + pickle(state)``. A save serializes, writes to a dot-tmp file
    (flushed; fsync'd when ``fsync=True``), then ``os.replace``\\ s into
    place — readers only ever see absent or complete generations.
    :meth:`restore_latest` walks generations newest-first and returns the
    first that verifies; corrupt generations are reported in the result,
    and exhaustion raises :class:`CheckpointCorruptError`
    (:class:`CheckpointNotFoundError` when the directory holds nothing at
    all). Not a pytree checkpoint: values must pickle (numpy arrays,
    scalars, containers) — device arrays belong in
    :class:`CheckpointManager`."""

    def __init__(
        self, directory: str, *, max_to_keep: int = 3, fsync: bool = False
    ) -> None:
        if max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1 (got {max_to_keep})")
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.fsync = fsync
        os.makedirs(self.directory, exist_ok=True)

    # -- paths ---------------------------------------------------------------

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"snap-{step:012d}.bzs")

    def all_steps(self) -> List[int]:
        """Every generation present on disk, ascending (no verification)."""
        steps = []
        for name in os.listdir(self.directory):
            m = _SNAP_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- write ---------------------------------------------------------------

    def save(self, step: int, state: Any) -> str:
        """Atomically persist ``state`` as generation ``step``; returns
        the final path. Older generations beyond ``max_to_keep`` are
        pruned AFTER the new one is durable."""
        t0 = time.monotonic()
        payload = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
        digest = hashlib.sha256(payload).hexdigest().encode()
        final = self._path(step)
        tmp = os.path.join(
            self.directory, f".tmp-{step:012d}-{os.getpid()}.bzs"
        )
        with open(tmp, "wb") as fh:
            fh.write(_SNAP_MAGIC + digest + b"\n" + payload)
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
        os.replace(tmp, final)
        for old in self.all_steps()[: -self.max_to_keep]:
            try:
                os.remove(self._path(old))
            except OSError:  # pragma: no cover — already gone
                pass
        _snapshot_latency().observe(time.monotonic() - t0)
        return final

    async def save_async(self, step: int, state: Any) -> str:
        """:meth:`save` on the default executor — the serving scheduler
        calls this so snapshot IO never stalls the admission loop."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self.save, step, state)

    # -- read ----------------------------------------------------------------

    def load(self, step: int) -> Any:
        """Load and verify ONE generation; raises
        :class:`CheckpointNotFoundError` if absent,
        :class:`CheckpointCorruptError` on any integrity failure."""
        path = self._path(step)
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except FileNotFoundError as exc:
            raise CheckpointNotFoundError(
                f"no snapshot for step {step} under {self.directory}"
            ) from exc
        if not blob.startswith(_SNAP_MAGIC):
            raise CheckpointCorruptError(f"{path}: bad magic")
        rest = blob[len(_SNAP_MAGIC):]
        nl = rest.find(b"\n")
        if nl != 64:  # sha256 hex is exactly 64 bytes
            raise CheckpointCorruptError(f"{path}: malformed digest header")
        digest, payload = rest[:nl], rest[nl + 1:]
        if hashlib.sha256(payload).hexdigest().encode() != digest:
            raise CheckpointCorruptError(f"{path}: integrity digest mismatch")
        try:
            return pickle.loads(payload)
        except Exception as exc:  # noqa: BLE001 — typed surface
            raise CheckpointCorruptError(
                f"{path}: digest ok but unpicklable: {exc}"
            ) from exc

    def restore_latest(self) -> Tuple[int, Any, List[int]]:
        """Newest generation that VERIFIES, as ``(step, state,
        skipped_corrupt_steps)`` — a torn/tampered newest generation
        falls back to the previous one instead of failing recovery.
        Raises :class:`CheckpointNotFoundError` on an empty store,
        :class:`CheckpointCorruptError` when every generation is bad."""
        steps = self.all_steps()
        if not steps:
            raise CheckpointNotFoundError(
                f"no snapshots under {self.directory}"
            )
        skipped: List[int] = []
        for step in reversed(steps):
            try:
                return step, self.load(step), skipped
            except CheckpointCorruptError:
                skipped.append(step)
        raise CheckpointCorruptError(
            f"every snapshot generation under {self.directory} is corrupt "
            f"(tried {list(reversed(steps))})"
        )


__all__ = [
    "CheckpointCorruptError",
    "CheckpointManager",
    "CheckpointNotFoundError",
    "SnapshotStore",
    "restore_checkpoint",
    "save_checkpoint",
]

"""Lexicographic combination unranking.

MDA/SMEA fan combination ranges out to pool workers; each worker must start
enumerating at its range's first combination in O(n*m) instead of skipping
``start`` tuples with ``islice`` (which would make total enumeration cost
quadratic in the number of subsets).
"""

from __future__ import annotations

from math import comb
from typing import Iterator, Tuple


def unrank_combination(n: int, m: int, rank: int) -> Tuple[int, ...]:
    """The ``rank``-th (0-based) m-combination of ``range(n)`` in
    lexicographic order."""
    total = comb(n, m)
    if not 0 <= rank < total:
        raise ValueError(f"rank must be in [0, {total}) (got {rank})")
    combo = []
    e = 0
    for i in range(m):
        # combos beginning with element e number comb(n-1-e, m-1-i)
        while comb(n - 1 - e, m - 1 - i) <= rank:
            rank -= comb(n - 1 - e, m - 1 - i)
            e += 1
        combo.append(e)
        e += 1
    return tuple(combo)


def iter_combinations(n: int, m: int, start: int = 0) -> Iterator[Tuple[int, ...]]:
    """Lexicographic m-combinations of ``range(n)`` starting at rank
    ``start`` (equivalent to ``islice(combinations(range(n), m), start, None)``
    but O(n*m) to position)."""
    if m == 0:
        if start == 0:
            yield ()
        return
    if start >= comb(n, m):
        return
    c = list(unrank_combination(n, m, start))
    while True:
        yield tuple(c)
        i = m - 1
        while i >= 0 and c[i] == n - m + i:
            i -= 1
        if i < 0:
            return
        c[i] += 1
        for j in range(i + 1, m):
            c[j] = c[j - 1] + 1


__all__ = ["unrank_combination", "iter_combinations"]

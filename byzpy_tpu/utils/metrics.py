"""Deprecated shim — the metrics/profiling helpers moved to
:mod:`byzpy_tpu.observability.compat`.

The seed-era :class:`MetricsLogger`/:class:`StepTimer` now live in the
telemetry subsystem and publish into its process-wide metrics registry
(``byzpy_logged_<key>`` gauges, the ``byzpy_step_seconds`` histogram)
while keeping their exact public behavior; :func:`trace`,
:func:`force_result` and :func:`timed_call_s` moved with them. This
module re-exports everything so existing imports keep working, and
will be removed in a future major version — import from
``byzpy_tpu.observability`` instead.
"""

from __future__ import annotations

import warnings

from ..observability.compat import (  # noqa: F401 — re-exports
    MetricsLogger,
    StepTimer,
    force_result,
    timed_call_s,
    trace,
)

warnings.warn(
    "byzpy_tpu.utils.metrics is deprecated; import MetricsLogger/StepTimer/"
    "trace from byzpy_tpu.observability (registry-backed ports)",
    DeprecationWarning,
    stacklevel=2,
)

__all__ = ["MetricsLogger", "trace", "StepTimer", "force_result", "timed_call_s"]

"""Structured metrics + profiling hooks.

The reference is ``print()``-based (SURVEY §5: ``context.py:805-808``,
``remote.py:290``); the survey flags structured metrics and jax.profiler
integration as required additions. This module provides:

* :class:`MetricsLogger` — step-keyed scalar metrics with an in-memory
  history, optional JSONL sink, and summaries;
* :func:`trace` — context manager around ``jax.profiler`` trace capture;
* :class:`StepTimer` — wall-clock timing with ``block_until_ready`` so
  device async dispatch doesn't fake the numbers.
"""

from __future__ import annotations

import contextlib
import json
import time
from collections import defaultdict
from typing import Any, Dict, Iterator, List, Optional

import jax


def _scalar(value: Any) -> Any:
    """Coerce device values to JSON-able python, recursively: 0-d arrays
    become numbers, n-d arrays nested lists, containers are walked, and
    anything else non-serializable falls back to ``str``."""
    ndim = getattr(value, "ndim", None)
    if ndim == 0 and hasattr(value, "item"):
        try:
            return value.item()
        except Exception:  # noqa: BLE001
            return str(value)
    if ndim is not None and ndim > 0 and hasattr(value, "tolist"):
        try:
            return value.tolist()
        except Exception:  # noqa: BLE001
            return str(value)
    if isinstance(value, dict):
        return {str(k): _scalar(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_scalar(v) for v in value]
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    return str(value)


class MetricsLogger:
    """Step-keyed metrics with history and an optional JSONL file sink."""

    def __init__(self, sink_path: Optional[str] = None) -> None:
        self.history: List[Dict[str, Any]] = []
        self._sink_path = sink_path
        self._sink = open(sink_path, "a") if sink_path else None

    def log(self, step: int, **values: Any) -> Dict[str, Any]:
        record = {"step": int(step), "time": time.time()}
        record.update({k: _scalar(v) for k, v in values.items()})
        self.history.append(record)
        if self._sink is not None:
            self._sink.write(json.dumps(record) + "\n")
            self._sink.flush()
        return record

    def series(self, key: str) -> List[Any]:
        return [r[key] for r in self.history if key in r]

    def latest(self, key: str) -> Any:
        for r in reversed(self.history):
            if key in r:
                return r[key]
        raise KeyError(key)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """min/max/mean/last per numeric key."""
        by_key: Dict[str, List[float]] = defaultdict(list)
        for r in self.history:
            for k, v in r.items():
                if k in ("step", "time"):
                    continue
                if isinstance(v, (int, float)):
                    by_key[k].append(float(v))
        return {
            k: {
                "min": min(vs),
                "max": max(vs),
                "mean": sum(vs) / len(vs),
                "last": vs[-1],
                "count": len(vs),
            }
            for k, vs in by_key.items()
        }

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace (view with TensorBoard / Perfetto)."""
    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def force_result(out: Any) -> Any:
    """Synchronize harder than ``block_until_ready``: materialize one
    element of every array output on the host. Remote-device tunnels have
    been observed to return from ``block_until_ready`` before the compute
    chain finishes; a host copy cannot."""
    import numpy as np

    def pull(leaf: Any) -> Any:
        if isinstance(leaf, jax.Array):
            return np.asarray(leaf.ravel()[:1] if leaf.ndim else leaf)
        return leaf

    return jax.tree_util.tree_map(pull, out)


def timed_call_s(fn, *args: Any, warmup: int = 2, repeat: int = 20) -> float:
    """Mean wall seconds per call over a chained loop, synchronized by host
    materialization of the final output (:func:`force_result`) — on remote
    tunnel devices ``block_until_ready`` has been observed returning before
    the compute chain finishes (sub-physical sub-ms readings); a host copy
    of the last output cannot. Input perturbation per rep was tried and
    rejected: the extra 256MB-scale allocation per rep cost ~5x the actual
    workload through the tunnel allocator, and no result-caching effect is
    observable once force_result is the sync."""
    import time as _time

    for _ in range(warmup):
        force_result(fn(*args))
    t0 = _time.perf_counter()
    out = None
    for _ in range(repeat):
        out = fn(*args)
    force_result(out)
    return (_time.perf_counter() - t0) / repeat


class StepTimer:
    """Accurate step timing: blocks on the step's outputs before reading
    the clock, so XLA async dispatch can't make steps look instant."""

    def __init__(self) -> None:
        self.times_s: List[float] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, *outputs: Any) -> float:
        if self._t0 is None:
            raise RuntimeError("StepTimer.stop() without start()")
        if outputs:
            jax.block_until_ready(outputs)
        dt = time.perf_counter() - self._t0
        self.times_s.append(dt)
        self._t0 = None
        return dt

    @contextlib.contextmanager
    def measure(self, *outputs_holder: list) -> Iterator[None]:
        self.start()
        try:
            yield
        finally:
            self.stop(*outputs_holder)

    @property
    def mean_s(self) -> float:
        return sum(self.times_s) / len(self.times_s) if self.times_s else 0.0

    @property
    def median_s(self) -> float:
        if not self.times_s:
            return 0.0
        s = sorted(self.times_s)
        return s[len(s) // 2]


__all__ = ["MetricsLogger", "trace", "StepTimer", "force_result", "timed_call_s"]

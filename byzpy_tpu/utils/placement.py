"""Latency-aware compute placement for actor-mode operators.

Actor-mode nodes (threads, processes, remote hosts) hand the framework
*host-resident* gradients — numpy arrays, or jax arrays already on the
CPU backend. For small payloads, shipping them to an accelerator costs
more than the whole robust aggregate: through a network-tunneled chip a
single host->device transfer of a 10x21,840 f32 stack measures ~4 ms and
each dispatch ~3.4 ms, while the same Multi-Krum aggregate runs in well
under a millisecond on the host CPU backend. The reference's CPU nodes
never pay this tax — aggregation happens where the gradients live
(``byzpy/engine/parameter_server/ps.py:131-137``) — and neither should
actor-mode rounds here.

Policy (``compute_device``): run on the CPU backend iff

* every array leaf of the inputs is host-resident (numpy scalar/array,
  Python number, or a jax array on a CPU device) — if anything already
  lives on an accelerator, moving it *back* would pay the same tax; and
* the total payload is at most ``BYZPY_TPU_HOST_COMPUTE_BYTES`` (default
  8 MiB — well below the crossover where accelerator bandwidth wins even
  through a tunnel); and
* the default backend is an accelerator (on a CPU-only host there is
  nothing to avoid).

Fused SPMD paths (``byzpy_tpu.parallel``) are untouched: their data is
born sharded on the mesh and never passes through this policy.

Opt out with ``BYZPY_TPU_HOST_COMPUTE_BYTES=0``; force a device with
``jax.default_device`` (an explicit caller context wins — the policy
only ever *narrows* to the host, and only when no context is active).
"""

from __future__ import annotations

import os
from contextlib import nullcontext
from typing import Any, ContextManager, Optional

import jax
import numpy as np

DEFAULT_HOST_COMPUTE_BYTES = 8 << 20


def host_compute_max_bytes() -> int:
    """Payload cap for host placement (env-overridable, 0 disables)."""
    try:
        return int(
            os.environ.get(
                "BYZPY_TPU_HOST_COMPUTE_BYTES", str(DEFAULT_HOST_COMPUTE_BYTES)
            )
        )
    except ValueError:
        return DEFAULT_HOST_COMPUTE_BYTES


def _leaf_host_bytes(leaf: Any) -> Optional[int]:
    """Size in bytes if ``leaf`` is host-resident, else ``None``."""
    if isinstance(leaf, (bool, int, float, complex)) or leaf is None:
        return 0
    if isinstance(leaf, np.ndarray) or np.isscalar(leaf):
        return int(getattr(leaf, "nbytes", 0))
    if isinstance(leaf, jax.Array):
        try:
            devices = leaf.devices()
        except Exception:  # deleted/donated buffers: not placeable
            return None
        if all(d.platform == "cpu" for d in devices):
            return int(leaf.nbytes)
        return None
    return None


def compute_device(*trees: Any) -> Optional[Any]:
    """The CPU device to run on, or ``None`` for the default device.

    ``trees`` are the operator inputs (any pytrees). See the module
    docstring for the policy.
    """
    cap = host_compute_max_bytes()
    if cap <= 0:
        return None
    if jax.config.jax_default_device is not None:
        return None  # explicit caller placement wins
    if jax.default_backend() == "cpu":
        return None  # already on the host backend
    total = 0
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            nbytes = _leaf_host_bytes(leaf)
            if nbytes is None:
                return None
            total += nbytes
    if total > cap:
        return None
    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None


def on(device: Optional[Any]) -> ContextManager[Any]:
    """Context manager placing jax computation on ``device`` (no-op for
    ``None``)."""
    if device is None:
        return nullcontext()
    return jax.default_device(device)


__all__ = ["compute_device", "host_compute_max_bytes", "on"]

"""Platform selection helper for script entry points.

A sitecustomize that registers an accelerator PJRT plugin (e.g. a
tunneled-TPU image) can force its platform at jax import time, at which
point the ``JAX_PLATFORMS`` environment variable is silently ignored.
Benchmarks/examples that document ``JAX_PLATFORMS=cpu python ...``
invocations call :func:`apply_env_platform` first so the documented
environment override actually wins (tests/conftest.py does the
unconditional-CPU version of the same dance for the suite).
"""

from __future__ import annotations

import os


def apply_env_platform() -> str | None:
    """Re-assert ``JAX_PLATFORMS`` from the environment through
    ``jax.config`` (which beats any import-time plugin default). Returns
    the applied platform string, or None when the env var is unset.
    Must run before the first jax backend touch (``jax.devices()``,
    any computation)."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return None
    import jax

    jax.config.update("jax_platforms", platforms)
    return platforms


__all__ = ["apply_env_platform"]

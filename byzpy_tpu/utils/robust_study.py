"""Accuracy-under-attack study harness: robust *learning*, not just
robust arithmetic.

The reference demonstrates that robust aggregation rescues training on a
real dataset where plain averaging fails (MNIST + accuracy eval in
``byzpy/examples/ps/thread/mnist.py:114-119``, and the aggregator-vs-attack
accuracy sweeps in ``byzpy/benchmarks/byzfl/*_compare.py``). This module is
the TPU-native equivalent: a grid of (aggregator x attack) cells, each a
full training run through the fused SPMD parameter-server step
(:mod:`byzpy_tpu.parallel.ps` — the whole Byzantine round is one jitted
program over the mesh), evaluated on held-out real data.

Data defaults to the real handwritten-digits set bundled with the image
(:func:`byzpy_tpu.models.data.load_digits_dataset`); pass MNIST IDX tensors
from :func:`byzpy_tpu.models.data.load_mnist_idx` for the full-size study.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.bundle import ModelBundle
from ..models.data import ShardedDataset, sample_node_batches
from ..ops import attack_ops, robust
from ..parallel.ps import PSStepConfig, build_ps_train_step

AggFn = Callable[[jnp.ndarray], jnp.ndarray]

#: the study zoo names (CLI `byzpy-tpu study` mirrors these as choices)
STUDY_AGGREGATORS = (
    "mean",
    "median",
    "trimmed_mean",
    "multi_krum",
    "geometric_median",
    "nnm_trimmed_mean",
)
STUDY_ATTACKS = ("none", "sign_flip", "empire", "little", "gaussian", "mimic")


@dataclass(frozen=True)
class StudyConfig:
    n_nodes: int = 8
    n_byzantine: int = 2
    rounds: int = 300
    batch_size: int = 32
    learning_rate: float = 0.1
    momentum: float = 0.9
    eval_every: int = 50
    seed: int = 0
    # dtype the per-node gradients are cast to BEFORE attack +
    # aggregation (None = keep f32). "bfloat16" halves the robust
    # pipeline's HBM traffic on TPU; params/optimizer stay f32 (the
    # aggregated update is cast back — mixed-precision trainer shape).
    grad_dtype: Optional[str] = None


def named_attack(
    name: str, *, n_byzantine: int, n_nodes: int
) -> Optional[Callable[[jnp.ndarray, jax.Array], jnp.ndarray]]:
    """Build the PS-step attack callback for a named attack.

    ``honest`` rows arrive as ``(h, d)``; the callback returns the
    ``(n_byzantine, d)`` malicious rows (colluding byzantine nodes all
    send the same vector, as in the reference's studies).
    """
    b = n_byzantine

    def rows(vec: jnp.ndarray) -> jnp.ndarray:
        return jnp.tile(vec[None, :], (b, 1))

    if name == "none":
        return None
    if name == "sign_flip":
        return lambda honest, key: rows(
            attack_ops.sign_flip(jnp.mean(honest, axis=0), scale=-4.0)
        )
    if name == "empire":
        # scale must beat -h/b for the poisoned mean to ascend (h honest,
        # b byzantine rows); -4 flips it for any b >= n/5, so the study
        # actually separates robust aggregators from the mean baseline
        return lambda honest, key: rows(attack_ops.empire(honest, scale=-4.0))
    if name == "little":
        return lambda honest, key: rows(
            attack_ops.little(honest, f=b, n_total=n_nodes)
        )
    if name == "gaussian":
        return lambda honest, key: rows(
            attack_ops.gaussian(key, (honest.shape[1],), honest.dtype, sigma=10.0)
        )
    if name == "mimic":
        return lambda honest, key: rows(attack_ops.mimic(honest, epsilon=0))
    raise ValueError(f"unknown attack {name!r}")


def named_aggregator(name: str, *, n_nodes: int, n_byzantine: int) -> AggFn:
    """The study's aggregator zoo, keyed the way the results tables name
    them. ``mean`` is the non-robust baseline every attack defeats."""
    f = n_byzantine
    if name == "mean":
        return lambda x: jnp.mean(x, axis=0)
    if name == "median":
        return robust.coordinate_median
    if name == "trimmed_mean":
        return partial(robust.trimmed_mean, f=f)
    if name == "multi_krum":
        return partial(robust.multi_krum, f=f, q=n_nodes - f)
    if name == "geometric_median":
        return partial(robust.geometric_median, max_iter=64)
    if name == "nnm_trimmed_mean":
        from ..ops import preagg

        def agg(x: jnp.ndarray) -> jnp.ndarray:
            return robust.trimmed_mean(preagg.nnm(x, f=f), f=f)

        return agg
    raise ValueError(f"unknown aggregator {name!r}")


@dataclass
class CellResult:
    aggregator: str
    attack: str
    final_accuracy: float
    history: List[Tuple[int, float]] = field(default_factory=list)

    def row(self) -> Dict[str, Any]:
        return {
            "aggregator": self.aggregator,
            "attack": self.attack,
            "final_accuracy": round(self.final_accuracy, 4),
            "history": [(r, round(a, 4)) for r, a in self.history],
        }


def _train_eval_history(
    step_fn: Callable,
    state: Any,
    xs_all: jnp.ndarray,
    ys_all: jnp.ndarray,
    accuracy_fn: Callable,
    cfg: StudyConfig,
) -> List[Tuple[int, float]]:
    """The shared round loop: sample per-node batches, step, record
    held-out accuracy every ``eval_every`` rounds (and the last).
    ``step_fn(state, xs, ys, key) -> state``; ``accuracy_fn(state)``."""
    key = jax.random.PRNGKey(cfg.seed)
    history: List[Tuple[int, float]] = []
    for r in range(cfg.rounds):
        key, bkey, skey = jax.random.split(key, 3)
        xs, ys = sample_node_batches(xs_all, ys_all, bkey, cfg.batch_size)
        state = step_fn(state, xs, ys, skey)
        if (r + 1) % cfg.eval_every == 0 or r == cfg.rounds - 1:
            history.append((r + 1, float(accuracy_fn(state))))
    return history


def run_cell(
    bundle_factory: Callable[[], ModelBundle],
    data: Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray],
    aggregator: str,
    attack: str,
    cfg: StudyConfig,
    *,
    mesh: Any = None,
) -> CellResult:
    """Train one (aggregator, attack) cell from scratch and return its
    held-out accuracy trajectory."""
    if cfg.rounds < 1:
        raise ValueError(f"rounds must be >= 1 (got {cfg.rounds})")
    x_train, y_train, x_test, y_test = data
    bundle = bundle_factory()
    ps_cfg = PSStepConfig(
        n_nodes=cfg.n_nodes,
        n_byzantine=cfg.n_byzantine,
        learning_rate=cfg.learning_rate,
        momentum=cfg.momentum,
    )
    step, opt_state = build_ps_train_step(
        bundle,
        named_aggregator(aggregator, n_nodes=cfg.n_nodes, n_byzantine=cfg.n_byzantine),
        ps_cfg,
        attack=named_attack(
            attack, n_byzantine=cfg.n_byzantine, n_nodes=cfg.n_nodes
        ),
        mesh=mesh,
        grad_dtype=None if cfg.grad_dtype is None else jnp.dtype(cfg.grad_dtype),
    )
    jit_step = jax.jit(step, donate_argnums=(0, 1))

    sharded = ShardedDataset(x_train, y_train, cfg.n_nodes)
    xs_all, ys_all = sharded.stacked_shards()

    @jax.jit
    def accuracy(params) -> jnp.ndarray:
        logits = bundle.apply_fn(params, x_test)
        return jnp.mean(jnp.argmax(logits, -1) == y_test)

    def step_fn(state, xs, ys, skey):
        params, opt = state
        params, opt, _ = jit_step(params, opt, xs, ys, skey)
        return params, opt

    history = _train_eval_history(
        step_fn, (bundle.params, opt_state), xs_all, ys_all,
        lambda state: accuracy(state[0]), cfg,
    )
    return CellResult(aggregator, attack, history[-1][1], history)


def run_gossip_cell(
    bundle_factory: Callable[[], ModelBundle],
    data: Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray],
    aggregator: str,
    attack: str,
    cfg: StudyConfig,
    *,
    mesh: Any = None,
) -> CellResult:
    """Decentralized counterpart of :func:`run_cell`: the same
    (aggregator, attack) cell trained by P2P gossip — every honest node
    half-steps on its shard, byzantine nodes broadcast the attack vector
    over the complete topology, each node robust-aggregates its
    in-neighborhood (:func:`byzpy_tpu.parallel.gossip.build_gossip_train_step`).
    Accuracy is node 0's (honest) model on held-out data.

    Note: the gossip half-step is plain SGD by construction (parameters
    themselves gossip; there is no per-node optimizer state to carry
    momentum) — ``cfg.momentum`` applies only to the PS cells."""
    if cfg.rounds < 1:
        raise ValueError(f"rounds must be >= 1 (got {cfg.rounds})")
    if cfg.grad_dtype is not None:
        raise ValueError(
            "grad_dtype is a PS-study knob (the gossip step exchanges "
            "parameters, not gradients — there is no gradient cast point); "
            "run the gossip cell with grad_dtype=None"
        )
    from ..engine.peer_to_peer import Topology
    from ..parallel.gossip import GossipStepConfig, build_gossip_train_step
    from .trees import ravel_pytree_fn

    x_train, y_train, x_test, y_test = data
    bundle = bundle_factory()
    gcfg = GossipStepConfig(
        n_nodes=cfg.n_nodes,
        n_byzantine=cfg.n_byzantine,
        learning_rate=cfg.learning_rate,
    )
    agg_fn = named_aggregator(
        aggregator, n_nodes=cfg.n_nodes, n_byzantine=cfg.n_byzantine
    )
    step, init = build_gossip_train_step(
        bundle, agg_fn, Topology.complete(cfg.n_nodes), gcfg,
        attack=named_attack(
            attack, n_byzantine=cfg.n_byzantine, n_nodes=cfg.n_nodes
        ),
        mesh=mesh,
    )
    jit_step = jax.jit(step, donate_argnums=(0,))

    sharded = ShardedDataset(x_train, y_train, cfg.n_nodes)
    xs_all, ys_all = sharded.stacked_shards()
    _, unravel = ravel_pytree_fn(bundle.params)

    @jax.jit
    def accuracy(theta) -> jnp.ndarray:
        logits = bundle.apply_fn(unravel(theta[0]), x_test)
        return jnp.mean(jnp.argmax(logits, -1) == y_test)

    def step_fn(theta, xs, ys, skey):
        theta, _ = jit_step(theta, xs, ys, skey)
        return theta

    history = _train_eval_history(
        step_fn, init(), xs_all, ys_all, accuracy, cfg
    )
    return CellResult(aggregator, attack, history[-1][1], history)


def run_study(
    *,
    aggregators: Sequence[str] = (
        "mean",
        "median",
        "trimmed_mean",
        "multi_krum",
        "nnm_trimmed_mean",
    ),
    attacks: Sequence[str] = ("none", "sign_flip", "little", "empire"),
    cfg: StudyConfig = StudyConfig(),
    bundle_factory: Optional[Callable[[], ModelBundle]] = None,
    data: Optional[Tuple[jnp.ndarray, ...]] = None,
    mesh: Any = None,
    verbose: bool = True,
    mode: str = "ps",
) -> List[CellResult]:
    """The full accuracy-under-attack grid on real data.

    ``mode="ps"`` trains each cell through the fused SPMD
    parameter-server round; ``mode="gossip"`` through the decentralized
    gossip step (complete topology, parameters themselves gossip — see
    :func:`run_gossip_cell` for the semantic differences)."""
    if mode not in ("ps", "gossip"):
        raise ValueError(f"mode must be 'ps' or 'gossip' (got {mode!r})")
    if data is None:
        from ..models.data import load_digits_dataset

        data = load_digits_dataset(seed=cfg.seed)
    if bundle_factory is None:
        from ..models.nets import digits_mlp

        bundle_factory = partial(digits_mlp, seed=cfg.seed)
    cell_fn = run_cell if mode == "ps" else run_gossip_cell
    results: List[CellResult] = []
    for attack in attacks:
        for agg in aggregators:
            cell = cell_fn(bundle_factory, data, agg, attack, cfg, mesh=mesh)
            results.append(cell)
            if verbose:
                print(
                    f"{attack:>10} x {agg:<18} final_acc={cell.final_accuracy:.3f}",
                    flush=True,
                )
    return results


def results_table(results: Sequence[CellResult]) -> str:
    """Markdown accuracy matrix: rows = aggregators, columns = attacks."""
    attacks = list(dict.fromkeys(r.attack for r in results))
    aggs = list(dict.fromkeys(r.aggregator for r in results))
    cell = {(r.aggregator, r.attack): r.final_accuracy for r in results}
    lines = ["| aggregator | " + " | ".join(attacks) + " |"]
    lines.append("|---" * (len(attacks) + 1) + "|")
    for a in aggs:
        row = [a] + [
            f"{cell.get((a, atk), float('nan')):.3f}" for atk in attacks
        ]
        lines.append("| " + " | ".join(row) + " |")
    return "\n".join(lines)


__all__ = [
    "StudyConfig",
    "CellResult",
    "named_attack",
    "named_aggregator",
    "run_cell",
    "run_gossip_cell",
    "run_study",
    "results_table",
]

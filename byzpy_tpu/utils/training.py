"""Training loop helper (API parity: ``byzpy/utils/training.py:7-34``).

``train_with_progress`` drives a ParameterServer (or anything with an async
``round()``) for N rounds with optional periodic evaluation, returning the
evaluation history. Progress rendering uses tqdm when available and
degrades to silence otherwise (tqdm is not a hard dependency).
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, Awaitable, Callable, List, Optional, Tuple

EvalCallback = Callable[[int], Any]


async def train_with_progress_async(
    ps: Any,
    rounds: int,
    *,
    eval_callback: Optional[EvalCallback] = None,
    eval_interval: int = 10,
    progress: bool = True,
) -> List[Tuple[int, Any]]:
    """Run ``rounds`` rounds of ``ps.round()``; call ``eval_callback(i)``
    every ``eval_interval`` rounds (and after the last). Returns
    ``[(round_index, eval_result), ...]``."""
    bar = None
    if progress:
        try:
            from tqdm import tqdm

            bar = tqdm(total=rounds, desc="training", leave=False)
        except ImportError:
            bar = None
    history: List[Tuple[int, Any]] = []
    try:
        for i in range(rounds):
            out = ps.round()
            if inspect.isawaitable(out):
                await out
            if eval_callback is not None and (
                (i + 1) % eval_interval == 0 or i == rounds - 1
            ):
                result = eval_callback(i)
                if inspect.isawaitable(result):
                    result = await result
                history.append((i, result))
                if bar is not None and result is not None:
                    bar.set_postfix_str(str(result))
            if bar is not None:
                bar.update(1)
    finally:
        if bar is not None:
            bar.close()
    return history


def train_with_progress(
    ps: Any,
    rounds: int,
    *,
    eval_callback: Optional[EvalCallback] = None,
    eval_interval: int = 10,
    progress: bool = True,
) -> List[Tuple[int, Any]]:
    """Sync wrapper (owns an event loop), matching the reference signature."""
    return asyncio.run(
        train_with_progress_async(
            ps,
            rounds,
            eval_callback=eval_callback,
            eval_interval=eval_interval,
            progress=progress,
        )
    )


__all__ = ["train_with_progress", "train_with_progress_async"]

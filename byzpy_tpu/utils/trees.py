"""Gradient <-> matrix conversion utilities.

The reference framework flattens lists of per-node gradient tensors into an
``(n, d)`` matrix backed by POSIX shared memory before fanning work out to
pool workers (ref: ``byzpy/aggregators/coordinate_wise/_tiling.py:18-38``,
``byzpy/engine/storage/shared_store.py``).  On TPU there is no host-side
shared-memory dance: gradients are JAX pytrees (or arrays) and the stacked
matrix is a single device array that jitted aggregation kernels consume
directly — sharding it over a mesh replaces chunking it over workers.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def stack_gradients(
    gradients: Sequence[Any] | jnp.ndarray,
) -> Tuple[jnp.ndarray, Callable[[jnp.ndarray], Any]]:
    """Stack a sequence of gradient pytrees/arrays into an ``(n, d)`` matrix.

    Accepts:

    * a sequence of same-structure pytrees (dicts/lists of arrays, flax
      parameter trees, plain arrays of any rank), or
    * an already-stacked 2-D array (returned unchanged).

    Returns ``(matrix, unravel)`` where ``unravel(row)`` maps a flat ``(d,)``
    vector back to the structure/shape of a single input gradient.
    """
    if isinstance(gradients, jnp.ndarray) or hasattr(gradients, "ndim"):
        arr = jnp.asarray(gradients)
        if arr.ndim != 2:
            raise ValueError(
                f"stacked gradient array must be 2-D (n, d); got shape {arr.shape}"
            )
        return arr, lambda row: row
    if len(gradients) == 0:
        raise ValueError("gradients must be a non-empty sequence")

    flat0, unravel = ravel_pytree(gradients[0])
    d = flat0.shape[0]
    rows = [flat0]
    for g in gradients[1:]:
        flat, _ = ravel_pytree(g)
        if flat.shape[0] != d:
            raise ValueError(
                f"all gradients must flatten to the same length (got {flat.shape[0]} != {d})"
            )
        rows.append(flat)
    matrix = jnp.stack(rows, axis=0)
    if not jnp.issubdtype(matrix.dtype, jnp.floating):
        matrix = matrix.astype(jnp.float32)
    return matrix, unravel


def unstack_rows(matrix: jnp.ndarray, unravel: Callable[[jnp.ndarray], Any]) -> List[Any]:
    """Split an ``(n, d)`` matrix back into a list of per-node gradients."""
    return [unravel(matrix[i]) for i in range(matrix.shape[0])]


def tree_size(tree: Any) -> int:
    """Total number of elements across all leaves of a pytree."""
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(tree))


def ravel_pytree_fn(
    example: Any,
) -> Tuple[Callable[[Any], jnp.ndarray], Callable[[jnp.ndarray], Any]]:
    """``(ravel, unravel)`` closures for pytrees shaped like ``example``.

    Both are trace-safe, so jitted training steps can flatten per-node
    gradient trees into rows of the aggregation matrix and back.
    """
    _, unravel = ravel_pytree(example)

    def ravel(tree: Any) -> jnp.ndarray:
        return ravel_pytree(tree)[0]

    return ravel, unravel

__version__ = "0.10.0"

__version__ = "0.15.0"

__version__ = "0.19.0"

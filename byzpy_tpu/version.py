__version__ = "0.9.0"

__version__ = "0.14.0"

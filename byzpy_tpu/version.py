__version__ = "0.17.0"

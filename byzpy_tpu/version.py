__version__ = "0.16.0"

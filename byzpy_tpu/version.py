__version__ = "0.7.0"

__version__ = "0.11.0"

__version__ = "0.8.0"

__version__ = "0.13.0"

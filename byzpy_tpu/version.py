__version__ = "0.12.0"

"""Autogenerate the full API reference from docstrings.

The reference publishes a Sphinx tree (``/root/reference/docs/source/
index.rst`` + ``api/*.rst``); this repo's docs are plain markdown, so the
equivalent is a generator that walks every ``byzpy_tpu`` module's public
surface (``__all__``, falling back to non-underscore attributes defined in
the module) and emits one table row per symbol: signature + first docstring
sentence. Output is committed as ``docs/api_reference.md`` and checked in
CI (regenerate-and-diff, see ``.github/workflows/tests.yml``) so the page
cannot rot.

Run: ``python docs/gen_api.py [--check]``
"""

from __future__ import annotations

import argparse
import importlib
import inspect
import os
import pkgutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "docs", "api_reference.md")

SKIP_MODULES = {
    # private/namespace-only modules
}


def public_symbols(mod) -> list:
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [
            n
            for n, v in vars(mod).items()
            if not n.startswith("_")
            and getattr(v, "__module__", None) == mod.__name__
        ]
    out = []
    for n in names:
        try:
            out.append((n, getattr(mod, n)))
        except AttributeError:
            out.append((n, None))
    return out


import re as _re


def first_sentence(doc: str | None) -> str:
    if not doc:
        return ""
    text = inspect.cleandoc(doc).split("\n\n", 1)[0].replace("\n", " ").strip()
    for stop in (". ", ".\n"):
        if stop in text:
            text = text.split(stop, 1)[0] + "."
            break
    # dataclass-generated docstrings repr default objects with their memory
    # address — nondeterministic across runs, which would make --check flap
    text = _re.sub(r"at 0x[0-9a-fA-F]+", "at 0x...", text)
    if text in ("Initialize self.", "str(object='') -> str"):
        return ""  # inherited object.__init__/str docs carry no information
    return text.replace("|", "\\|")


def signature_of(obj) -> str:
    try:
        sig = str(inspect.signature(obj))
    except (TypeError, ValueError):
        return ""
    if len(sig) > 80:
        sig = sig[:77] + "...)"
    return sig.replace("|", "\\|")


def walk_modules(pkg_name: str = "byzpy_tpu"):
    pkg = importlib.import_module(pkg_name)
    yield pkg_name, pkg
    for info in pkgutil.walk_packages(pkg.__path__, prefix=pkg_name + "."):
        if info.name in SKIP_MODULES or ".legacy." in info.name:
            continue
        base = info.name.rsplit(".", 1)[-1]
        if base.startswith("_"):
            continue
        try:
            yield info.name, importlib.import_module(info.name)
        except Exception as exc:  # pragma: no cover — broken module = broken docs
            raise RuntimeError(f"cannot import {info.name}: {exc}") from exc


def generate() -> str:
    lines = [
        "# API reference (generated)",
        "",
        "Every public symbol in `byzpy_tpu`, by module — regenerate with",
        "`python docs/gen_api.py` (CI diffs this file; see the curated",
        "by-layer overview in [api.md](api.md)).",
        "",
    ]
    seen_objs: dict = {}
    missing: list = []
    for mod_name, mod in walk_modules():
        syms = public_symbols(mod)
        if not syms:
            continue
        mod_doc = first_sentence(mod.__doc__)
        lines.append(f"## `{mod_name}`")
        lines.append("")
        if mod_doc:
            lines.append(mod_doc)
            lines.append("")
        lines.append("| Symbol | Kind | Summary |")
        lines.append("|---|---|---|")
        for name, obj in sorted(syms):
            kind = (
                "class"
                if inspect.isclass(obj)
                else "function"
                if callable(obj)
                else "value"
            )
            doc = first_sentence(getattr(obj, "__doc__", "") or "")
            if (
                not doc
                and inspect.isclass(obj)
                and getattr(obj, "__init__", None) is not None
            ):
                doc = first_sentence(obj.__init__.__doc__ or "")
            home = getattr(obj, "__module__", mod_name)
            key = id(obj) if obj is not None else (mod_name, name)
            if (
                not doc
                and kind != "value"
                and home == mod_name
                and not name.startswith("_")
            ):
                missing.append(f"{mod_name}.{name}")
            if key in seen_objs and home != mod_name:
                doc = doc or f"re-export of `{home}.{name}`"
            else:
                seen_objs[key] = f"{mod_name}.{name}"
            sig = signature_of(obj) if kind == "function" else ""
            lines.append(f"| `{name}{sig}` | {kind} | {doc} |")
        lines.append("")
    if missing:
        raise SystemExit(
            "symbols missing docstrings (add them):\n  " + "\n  ".join(missing)
        )
    return "\n".join(lines) + "\n"


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--check",
        action="store_true",
        help="fail if docs/api_reference.md is stale (CI mode)",
    )
    args = parser.parse_args()
    text = generate()
    if args.check:
        with open(OUT) as fh:
            if fh.read() != text:
                print("docs/api_reference.md is stale: run python docs/gen_api.py")
                return 1
        print("api_reference.md up to date")
        return 0
    with open(OUT, "w") as fh:
        fh.write(text)
    n_rows = text.count("\n| `")
    print(f"wrote {OUT}: {n_rows} symbols")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

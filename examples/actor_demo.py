"""Actor runtime demo (ref: ``byzpy/examples/actor_demo/actor_demo.py:1-40``).

Spawns a counter actor on the thread backend, calls it over async RPC,
and passes messages through a named channel.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import asyncio

from byzpy_tpu.engine.actor.base import spawn_actor
from byzpy_tpu.engine.actor.factory import resolve_backend


class Counter:
    def __init__(self, start=0):
        self.value = start

    def add(self, k):
        self.value += k
        return self.value

    def get(self):
        return self.value


async def main():
    backend = resolve_backend("thread")
    ref = await spawn_actor(backend, Counter, 10)

    print("add(5) ->", await ref.add(5))
    print("add(2) ->", await ref.add(2))
    print("get()  ->", await ref.get())

    # named channels: a mailbox on the actor anyone can post to
    await backend.chan_open("inbox")
    await backend.chan_put("inbox", {"hello": "world"})
    print("chan_get ->", await backend.chan_get("inbox"))

    await backend.close()


if __name__ == "__main__":
    asyncio.run(main())

"""The fused SPMD parameter-server round ACROSS HOSTS.

Where the reference spans machines by pickling gradients through TCP actor
servers (ref: ``examples/distributed/mnist.py:1-28`` + ``server.py``), the
TPU-native deployment is: every host joins the JAX distributed runtime,
the ``Mesh`` spans all hosts' devices, and the SAME one-program PS step
from :mod:`byzpy_tpu.parallel.ps` runs unchanged — the gradient transpose
and aggregation collectives simply ride DCN between hosts instead of ICI
within a slice. No per-host orchestration code exists at all; that is the
point.

Self-launching demo (two processes on this machine = two "hosts", one CPU
device each, 4 logical nodes per host)::

    python examples/distributed/ps_two_hosts.py
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)


ROUNDS = int(os.environ.get("PS_ROUNDS", 40))


def worker(coordinator: str, num_processes: int, process_id: int) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from byzpy_tpu.parallel.collectives import initialize_multihost

    initialize_multihost(coordinator, num_processes, process_id)

    from functools import partial

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from byzpy_tpu.models.data import (
        ShardedDataset,
        load_digits_dataset,
        sample_node_batches,
    )
    from byzpy_tpu.models.nets import digits_mlp
    from byzpy_tpu.ops import attack_ops, robust
    from byzpy_tpu.parallel.ps import PSStepConfig, build_ps_train_step

    n_devices = len(jax.devices())
    assert jax.process_count() == num_processes
    mesh = Mesh(np.array(jax.devices()), ("nodes",))

    n_nodes, n_byz = 8, 2
    bundle = digits_mlp(seed=0)
    cfg = PSStepConfig(n_nodes=n_nodes, n_byzantine=n_byz, learning_rate=0.1)

    def attack(honest, key):
        return jnp.tile(
            attack_ops.sign_flip(jnp.mean(honest, axis=0), scale=-4.0)[None, :],
            (n_byz, 1),
        )

    step, opt_state = build_ps_train_step(
        bundle, partial(robust.trimmed_mean, f=n_byz), cfg,
        attack=attack, mesh=mesh,
    )
    jit_step = jax.jit(step)

    # Same seed everywhere -> identical host-side data; each process feeds
    # its LOCAL slice of the node axis and the runtime assembles the
    # global batch (make_array_from_process_local_data).
    x_train, y_train, x_test, y_test = load_digits_dataset(seed=0)
    data = ShardedDataset(x_train, y_train, n_nodes)
    xs_all, ys_all = data.stacked_shards()
    node_sh = NamedSharding(mesh, P("nodes"))
    nodes_here = n_nodes // num_processes
    lo = process_id * nodes_here

    params = bundle.params
    key = jax.random.PRNGKey(0)
    batch = 32
    for r in range(ROUNDS):
        key, bkey, skey = jax.random.split(key, 3)
        xs, ys = sample_node_batches(xs_all, ys_all, bkey, batch)
        xs = jax.make_array_from_process_local_data(
            node_sh, np.asarray(xs[lo : lo + nodes_here])
        )
        ys = jax.make_array_from_process_local_data(
            node_sh, np.asarray(ys[lo : lo + nodes_here])
        )
        params, opt_state, metrics = jit_step(params, opt_state, xs, ys, skey)

    logits = bundle.apply_fn(params, x_test)
    acc = float(jnp.mean(jnp.argmax(logits, -1) == y_test))
    print(f"[proc {process_id}] final held-out accuracy {acc:.3f}", flush=True)
    if ROUNDS >= 30:  # smoke runs use PS_ROUNDS=2 — too few to learn
        assert acc > 0.7, (
            "robust aggregation should learn under attack across hosts"
        )


def launch(num_processes: int, port: int) -> int:
    procs = [
        subprocess.Popen(
            [
                sys.executable, os.path.abspath(__file__),
                "--coordinator", f"localhost:{port}",
                "--num-processes", str(num_processes),
                "--process-id", str(i),
            ]
        )
        for i in range(num_processes)
    ]
    rc = 0
    for p in procs:
        rc |= p.wait()
    print("OK: robust PS round spanned processes" if rc == 0 else f"FAILED rc={rc}")
    return rc


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--coordinator", default=None)
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--port", type=int, default=12356)
    args = parser.parse_args()
    if args.process_id is None:
        return launch(args.num_processes, args.port)
    worker(args.coordinator, args.num_processes, args.process_id)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

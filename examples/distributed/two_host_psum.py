"""Multi-host bring-up: ``initialize_multihost`` + one cross-process psum.

The reference spans machines with TCP actor servers
(ref: ``examples/distributed/mnist.py:1-28``, ``server.py``); the TPU-native
control plane is the JAX distributed runtime — each host calls
:func:`byzpy_tpu.parallel.collectives.initialize_multihost`, after which
``jax.devices()`` is GLOBAL (every host's chips) and one ``Mesh`` spans the
pod. Bulk tensors then move as XLA collectives over ICI/DCN; no sockets in
user code.

Self-launching demo (two processes on this machine, one CPU device each)::

    python examples/distributed/two_host_psum.py

Real deployment: run the same worker code on every host with
``--coordinator host0:12355 --num-processes N --process-id <i>``.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)



def worker(coordinator: str, num_processes: int, process_id: int) -> None:
    # Platform choice must precede any jax backend touch — and must go
    # through jax.config, not the environment: a sitecustomize (or any
    # earlier import) may already have imported jax, after which env vars
    # are ignored. One CPU device per process plays one chip per host.
    import jax

    jax.config.update("jax_platforms", "cpu")

    from byzpy_tpu.parallel.collectives import initialize_multihost

    started = initialize_multihost(coordinator, num_processes, process_id)

    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from byzpy_tpu.parallel.collectives import sharded_fn

    assert started, "initialize_multihost should have initialized the runtime"
    assert jax.process_count() == num_processes, jax.process_count()

    # After initialize, jax.devices() is global: one mesh over every
    # host's devices. local_devices() is what this host contributes
    # (device count per host varies — e.g. XLA_FLAGS can expose several
    # virtual CPU devices — so everything below is count-agnostic).
    n_local = len(jax.local_devices())
    mesh = Mesh(np.array(jax.devices()), ("nodes",))
    print(
        f"[proc {process_id}] global devices={len(jax.devices())} "
        f"local={n_local}",
        flush=True,
    )

    # Each process contributes one row per local device, filled with its
    # process id + 1; the psum crosses the process boundary over the DCN
    # control plane's data channels.
    local = np.full((n_local, 4), float(process_id + 1), np.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("nodes")), local
    )
    psum = sharded_fn(
        mesh, "nodes", lambda s: lax.psum(s, "nodes"),
        in_spec=P("nodes"), out_spec=P("nodes"),
    )
    out = psum(arr)
    mine = np.asarray(out.addressable_data(0))
    # each global device's row carries (owner process + 1); hosts may
    # contribute different device counts, so sum over the real ownership
    want = sum(dev.process_index + 1 for dev in jax.devices())
    assert (mine == want).all(), (mine, want)
    print(f"[proc {process_id}] cross-host psum OK: {mine[0, 0]} == {want}", flush=True)


def launch(num_processes: int, port: int) -> int:
    procs = [
        subprocess.Popen(
            [
                sys.executable, os.path.abspath(__file__),
                "--coordinator", f"localhost:{port}",
                "--num-processes", str(num_processes),
                "--process-id", str(i),
            ],
        )
        for i in range(num_processes)
    ]
    rc = 0
    for p in procs:
        rc |= p.wait()
    print("all processes done" if rc == 0 else f"FAILED rc={rc}")
    return rc


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--coordinator", default=None)
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--port", type=int, default=12355)
    args = parser.parse_args()
    if args.process_id is None:
        return launch(args.num_processes, args.port)
    worker(args.coordinator, args.num_processes, args.process_id)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Byzantine-robust LONG-CONTEXT LM training: ring attention + Multi-Krum.

The framework's two pillars in one loop (no reference equivalent — the
reference has no transformer/long-context code at all, SURVEY §5):

* **sequence parallelism**: the context is sharded over a mesh axis; each
  device holds an L/n block, K/V rotate over the ICI ring inside exact
  ring attention (`byzpy_tpu.parallel.ring_attention`), so per-device
  activation memory is O(L/n) and the context length scales with the mesh;
* **robust aggregation**: several nodes compute LM gradients on their own
  long sequences, a byzantine node flips its sign, Multi-Krum
  (`byzpy_tpu.ops.robust.multi_krum`) discards it.

Runs out of the box on the 8-virtual-device CPU mesh (set by default when
no TPU mesh is available); on a TPU slice the same code rides the ICI.

    python examples/long_context_lm.py          # 6 nodes, 1 byzantine
    N_NODES=8 N_BYZ=2 ROUNDS=30 python examples/long_context_lm.py
    # the other sequence-parallel scheme, and sparse FFNs:
    ATTENTION=ulysses python examples/long_context_lm.py
    MLP=moe python examples/long_context_lm.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

if __name__ == "__main__":
    # virtual 8-device CPU mesh when this host has fewer than 8 devices
    # (set BYZPY_TPU_PLATFORM=cpu to skip probing an accelerator at all)
    import jax

    import jax.extend.backend as _backend

    if os.environ.get("BYZPY_TPU_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BYZPY_TPU_PLATFORM"])
    if len(jax.devices()) < 8:
        jax.config.update("jax_platforms", "cpu")
        _backend.clear_backends()
        jax.config.update("jax_num_cpu_devices", 8)
        _backend.clear_backends()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from byzpy_tpu.models.transformer import TransformerLM  # noqa: E402
from byzpy_tpu.ops import robust  # noqa: E402
from byzpy_tpu.parallel.collectives import sharded_fn  # noqa: E402
from byzpy_tpu.parallel.mesh import make_mesh  # noqa: E402
from byzpy_tpu.utils.trees import stack_gradients  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402


def main() -> None:
    n_nodes = int(os.environ.get("N_NODES", "6"))
    n_byz = int(os.environ.get("N_BYZ", "1"))
    rounds = int(os.environ.get("ROUNDS", "20"))
    L = int(os.environ.get("SEQ_LEN", "256"))  # long context, sharded /8
    # ATTENTION=ring|ulysses picks the sequence-parallel scheme; MLP=moe
    # swaps the block FFNs for routed mixtures (experts local per shard).
    # Invalid values would silently fall back to block-local attention
    # (no cross-shard mixing), so reject them loudly.
    attention = os.environ.get("ATTENTION", "ring")
    mlp = os.environ.get("MLP", "dense")
    if attention not in ("ring", "ulysses"):
        raise SystemExit(f"ATTENTION must be ring|ulysses (got {attention!r})")
    if mlp not in ("dense", "moe"):
        raise SystemExit(f"MLP must be dense|moe (got {mlp!r})")
    vocab, dim, depth, heads = 64, 64, 2, 8 if attention == "ulysses" else 4

    mesh = make_mesh([8], ("sp",))
    model = TransformerLM(
        vocab_size=vocab, dim=dim, depth=depth, num_heads=heads,
        max_len=L, attention=attention, ring_axis="sp",
        mlp=mlp, n_experts=4,
    )
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    flat0, unravel = stack_gradients([params])
    print(f"{attention} LM ({mlp} FFN) over L={L} (8 x {L // 8} per device), "
          f"{flat0.shape[1]} params, {n_nodes} honest + {n_byz} byzantine")

    # sequence-parallel loss: logits stay sequence-sharded; the per-block
    # cross-entropy reduces locally and psums over the ring
    def sp_loss(p, tokens):
        def block_loss(toks):
            logits = model.apply(p, toks[:, :-1])
            tgt = toks[:, 1:]
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, tgt)
            return jax.lax.pmean(ce.mean(), "sp")

        fn = sharded_fn(mesh, "sp", block_loss, in_spec=P(None, "sp"),
                        out_spec=P())
        return fn(tokens)

    grad_fn = jax.jit(jax.grad(sp_loss))
    loss_fn = jax.jit(sp_loss)

    # synthetic long-sequence corpus: each node learns the same repeating
    # pattern (so the robust mean is meaningful), different phases
    def batch_for(node: int, rnd: int) -> jnp.ndarray:
        base = (np.arange(L + 2) + node * 7 + rnd * 3) % vocab
        return jnp.asarray(
            np.stack([base[i : i + L] for i in range(2)]), jnp.int32
        )

    opt = optax.adam(3e-3)
    opt_state = opt.init(params)
    f = n_byz

    for rnd in range(rounds):
        grads = []
        for node in range(n_nodes):
            g = grad_fn(params, batch_for(node, rnd))
            grads.append(g)
        flat, unravel = stack_gradients(grads)
        byz_rows = -4.0 * flat[:n_byz]  # sign-flip attackers
        stacked = jnp.concatenate([flat, byz_rows], axis=0)
        agg = robust.multi_krum(stacked, f=f, q=max(1, n_nodes - f))
        update_tree = unravel(agg)
        updates, opt_state = opt.update(update_tree, opt_state, params)
        params = optax.apply_updates(params, updates)
        if rnd % 5 == 0 or rnd == rounds - 1:
            val = float(loss_fn(params, batch_for(0, 0)))
            print(f"round {rnd:3d}  loss {val:.4f}")

    if rounds >= 10:
        assert val < 3.0, f"loss failed to decrease: {val}"
    print("long-context robust training OK")


if __name__ == "__main__":
    main()

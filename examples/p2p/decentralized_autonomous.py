"""Autonomous decentralized cluster: nodes self-drive via background
tasks and messages — no central round loop.

Reference semantics: ``byzpy/examples/p2p/decentralized_autonomous_mnist.py``
— each DecentralizedNode starts an autonomous task that repeatedly
half-steps, broadcasts its vector, collects neighbors' vectors, and
robust-aggregates; the main coroutine just waits for everyone to report
done.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))  # repo root

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import asyncio
import os

import jax.numpy as jnp
import numpy as np

from byzpy_tpu.aggregators import CoordinateWiseMedian
from byzpy_tpu.engine.node import DecentralizedCluster, DecentralizedNode, InProcessContext
from byzpy_tpu.engine.peer_to_peer import Topology

N_NODES = int(os.environ.get("N_NODES", 4))
ROUNDS = int(os.environ.get("P2P_ROUNDS", 15))
DIM = 32


def autonomous_loop(target, done_event):
    """Build the per-node background coroutine: descend ||w - target||²,
    gossip, aggregate, repeat."""

    async def run(node: DecentralizedNode):
        agg = CoordinateWiseMedian()
        w = jnp.zeros((DIM,))
        n_in = len(node.router.in_neighbor_ids())
        for _ in range(ROUNDS):
            w = w - 0.3 * 2.0 * (w - target)          # local half step
            await node.broadcast_message("gossip", w)  # tell out-neighbors
            received = [
                jnp.asarray((await node.wait_for_message("gossip")).payload)
                for _ in range(n_in)
            ]
            w = agg.aggregate([w] + received)           # robust consensus
        node.final_w = w
        done_event.set()

    return run


async def main():
    topology = Topology.complete(N_NODES)
    cluster = DecentralizedCluster(topology)
    nodes, events = [], []
    targets = np.linspace(0.0, 2.0, N_NODES)  # median target is the goal
    for i in range(N_NODES):
        node = DecentralizedNode(f"auto-{i}", InProcessContext(f"auto-{i}"))
        cluster.add_node(node)
        nodes.append(node)
        events.append(asyncio.Event())

    async with cluster:
        for node, target, event in zip(nodes, targets, events, strict=False):
            node.start_autonomous_task(autonomous_loop(float(target), event))
        await asyncio.gather(*(e.wait() for e in events))

    finals = np.stack([np.asarray(n.final_w) for n in nodes])
    print("per-node final w[0]:", np.round(finals[:, 0], 3))
    spread = finals[:, 0].max() - finals[:, 0].min()
    print(f"consensus spread: {spread:.4f}")
    assert spread < 0.15, "nodes did not reach consensus"


if __name__ == "__main__":
    asyncio.run(main())

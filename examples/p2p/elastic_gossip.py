"""Elastic gossip: P2P training through a peer death (no reference analogue).

The decentralized twin of ``examples/ps/elastic_crash_recovery.py``:
four peers gossip toward consensus under coordinate-wise median; peer 3
dies unannounced mid-training; the built-in elastic policy
(``PeerToPeer(..., elastic=HeartbeatPolicy(...))``) suspects it via
heartbeats and excises it from the fabric, after which rounds keep
completing over the induced 3-node topology and consensus re-forms
WITHOUT the dead peer's (outlier) target. No monitor/callback wiring in
application code — detection and excision ship as one constructor knob.

Run: ``python examples/p2p/elastic_gossip.py``.
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import jax.numpy as jnp
import numpy as np

from byzpy_tpu.aggregators import CoordinateWiseMedian
from byzpy_tpu.engine.peer_to_peer import HeartbeatPolicy, PeerToPeer, Topology
from byzpy_tpu.engine.peer_to_peer.nodes import HonestP2PWorker

ROUNDS = int(os.environ.get("P2P_ROUNDS", 30))
DIM = 8


class QuadWorker(HonestP2PWorker):
    """Descends ||w - target||^2; gossip payload is the half-stepped w."""

    def __init__(self, target):
        self.target = jnp.full((DIM,), float(target), jnp.float32)
        self.w = jnp.zeros((DIM,), jnp.float32)

    def half_step(self, lr):
        self.w = self.w - lr * 2.0 * (self.w - self.target)
        return self.w

    def parameters(self):
        return self.w

    def apply_aggregate(self, vector):
        self.w = jnp.asarray(vector)


async def main() -> None:
    workers = [QuadWorker(t) for t in (0.0, 1.0, 2.0, 50.0)]
    p2p = PeerToPeer(
        workers, aggregator=CoordinateWiseMedian(),
        topology=Topology.complete(4), learning_rate=0.3,
        elastic=HeartbeatPolicy(interval=0.1, max_missed=3),
    )
    runner = p2p.runner
    async with runner:
        for r in range(ROUNDS):
            await p2p.round()
            if r == ROUNDS // 3 and 3 in runner.nodes:
                victim_id = runner.node_ids[3]
                print(f"round {r + 1}: killing peer {victim_id} (target 50)")
                await runner.nodes[3].shutdown()
                # the shipped policy notices and excises — just wait for it
                for _ in range(300):
                    if (victim_id, "removed") in runner.elastic_events:
                        print(f"  [policy] suspected {victim_id} -> excised")
                        break
                    await asyncio.sleep(0.05)
                else:
                    raise TimeoutError("policy never excised the dead peer")
            if (r + 1) % 10 == 0:
                ws = [float(np.mean(workers[i].w)) for i in (0, 1, 2)]
                print(f"round {r + 1:3d}: survivor means "
                      f"{['%.3f' % v for v in ws]}")

    if ROUNDS >= 20:
        for i in (0, 1, 2):
            err = abs(float(np.mean(workers[i].w)) - 1.0)
            assert err < 0.2, (i, workers[i].w)
        print("consensus re-formed at the survivors' median target (1.0), "
              "free of the dead peer's outlier (50.0)")


if __name__ == "__main__":
    asyncio.run(main())

"""Elastic gossip: P2P training through a peer death (no reference analogue).

The decentralized twin of ``examples/ps/elastic_crash_recovery.py``:
four peers gossip toward consensus under coordinate-wise median; peer 3
dies unannounced mid-training; the observer's heartbeat monitor suspects
it and excises it from the fabric (``PeerToPeer.remove_node``), after
which rounds keep completing over the induced 3-node topology and
consensus re-forms WITHOUT the dead peer's (outlier) target.

Run: ``python examples/p2p/elastic_gossip.py``.
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import jax.numpy as jnp
import numpy as np

from byzpy_tpu.aggregators import CoordinateWiseMedian
from byzpy_tpu.engine.node.liveness import HeartbeatMonitor
from byzpy_tpu.engine.peer_to_peer import PeerToPeer, Topology
from byzpy_tpu.engine.peer_to_peer.nodes import HonestP2PWorker

ROUNDS = int(os.environ.get("P2P_ROUNDS", 30))
DIM = 8


class QuadWorker(HonestP2PWorker):
    """Descends ||w - target||^2; gossip payload is the half-stepped w."""

    def __init__(self, target):
        self.target = jnp.full((DIM,), float(target), jnp.float32)
        self.w = jnp.zeros((DIM,), jnp.float32)

    def half_step(self, lr):
        self.w = self.w - lr * 2.0 * (self.w - self.target)
        return self.w

    def parameters(self):
        return self.w

    def apply_aggregate(self, vector):
        self.w = jnp.asarray(vector)


async def main() -> None:
    workers = [QuadWorker(t) for t in (0.0, 1.0, 2.0, 50.0)]
    p2p = PeerToPeer(
        workers, aggregator=CoordinateWiseMedian(),
        topology=Topology.complete(4), learning_rate=0.3,
    )
    runner = p2p.runner
    async with runner:
        removed = asyncio.Event()

        def on_suspect(peer_id):
            victim = next(
                gi for gi, nid in runner.node_ids.items() if nid == peer_id
            )

            async def act():
                await p2p.remove_node(victim)
                removed.set()
                print(f"  [monitor] suspected {peer_id} -> excised")

            asyncio.get_running_loop().create_task(act())

        for gi, node in runner.nodes.items():
            if gi != 0:
                HeartbeatMonitor.install_responder(node)
        mon = HeartbeatMonitor(
            runner.nodes[0], interval=0.1, max_missed=3, on_suspect=on_suspect
        )
        await mon.start()
        try:
            for r in range(ROUNDS):
                await p2p.round()
                if r == ROUNDS // 3 and 3 in runner.nodes:
                    print(f"round {r + 1}: killing peer node-3 (target 50)")
                    await runner.nodes[3].shutdown()
                    await asyncio.wait_for(removed.wait(), timeout=15.0)
                if (r + 1) % 10 == 0:
                    ws = [float(np.mean(workers[i].w)) for i in (0, 1, 2)]
                    print(f"round {r + 1:3d}: survivor means "
                          f"{['%.3f' % v for v in ws]}")
        finally:
            await mon.stop()

    if ROUNDS >= 20:
        for i in (0, 1, 2):
            err = abs(float(np.mean(workers[i].w)) - 1.0)
            assert err < 0.2, (i, workers[i].w)
        print("consensus re-formed at the survivors' median target (1.0), "
              "free of the dead peer's outlier (50.0)")


if __name__ == "__main__":
    asyncio.run(main())

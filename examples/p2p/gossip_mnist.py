"""Peer-to-peer gossip training with a byzantine peer.

Reference semantics: ``byzpy/examples/p2p/`` — every peer half-steps on
its shard, gossips θ½ over the topology, robust-aggregates what it
received; one byzantine peer broadcasts an Empire vector.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))  # repo root

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import os

import jax
import jax.numpy as jnp
import numpy as np

from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
from byzpy_tpu.attacks import EmpireAttack
from byzpy_tpu.engine.peer_to_peer import (
    AttackP2PWorker,
    PeerToPeer,
    SGDModelWorker,
    Topology,
)
from byzpy_tpu.models.data import ShardedDataset, synthetic_classification
from byzpy_tpu.models.nets import mnist_mlp

N_NODES = int(os.environ.get("N_NODES", 5))
N_BYZ = int(os.environ.get("N_BYZ", 1))
ROUNDS = int(os.environ.get("P2P_ROUNDS", 40))
BATCH = 64


def make_worker(data, i):
    bundle = mnist_mlp(seed=0)
    sx, sy = data.node_slice(i)
    rng = np.random.default_rng(i)

    def batch_fn():
        idx = rng.integers(0, sx.shape[0], size=BATCH)
        return sx[idx], sy[idx]

    return SGDModelWorker(bundle, batch_fn)


def main():
    x, y = synthetic_classification(n_samples=4096, seed=0)
    n_honest = N_NODES - N_BYZ
    data = ShardedDataset(x, y, n_honest)
    workers = [make_worker(data, i) for i in range(n_honest)]
    byz = [AttackP2PWorker(EmpireAttack(scale=-3.0)) for _ in range(N_BYZ)]

    p2p = PeerToPeer(
        workers,
        byz,
        aggregator=CoordinateWiseTrimmedMean(f=N_BYZ),
        topology=Topology.complete(N_NODES),
        learning_rate=0.1,
    )
    p2p.run(rounds=ROUNDS)

    bundle = mnist_mlp(seed=0).with_params(workers[0].params)
    logits = bundle.apply_fn(bundle.params, x)
    acc = float(jnp.mean(jnp.argmax(logits, -1) == y))
    print(f"{ROUNDS} rounds: worker-0 accuracy {acc:.3f}")
    assert acc > 0.5, "did not learn"


if __name__ == "__main__":
    main()

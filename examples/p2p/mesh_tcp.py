"""Serverless full-mesh TCP cluster on loopback.

Reference semantics: ``byzpy/examples/p2p/remote_tcp/mesh_client.py`` —
every node runs its own TCP server and dials its peers; in production each
node is a separate host process (fill the address book with real
host:port pairs), here all three live in one event loop on loopback.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))  # repo root

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import asyncio
import os

import jax.numpy as jnp
import numpy as np

from byzpy_tpu.engine.node import DecentralizedNode, MeshRemoteContext
from byzpy_tpu.engine.peer_to_peer import Topology

N_NODES = int(os.environ.get("N_NODES", 3))


async def main():
    topology = Topology.complete(N_NODES)
    ids = {i: f"mesh-{i}" for i in range(N_NODES)}

    # start every node's server on an ephemeral port, then share the book
    ctxs = [MeshRemoteContext(ids[i]) for i in range(N_NODES)]
    nodes = []
    received = {ids[i]: [] for i in range(N_NODES)}
    all_in = asyncio.Event()

    def check_done() -> None:
        if all(len(v) >= N_NODES - 1 for v in received.values()):
            all_in.set()

    for i, ctx in enumerate(ctxs):
        node = DecentralizedNode(ids[i], ctx)
        node.bind_topology(topology, ids)

        async def keep(message, store=received[ids[i]]):
            store.append(message)
            check_done()

        node.register_handler("gradient", keep)
        await node.start()
        nodes.append(node)
    book = {c.node_id: (c.host, c.port) for c in ctxs}
    for ctx in ctxs:
        for pid, addr in book.items():
            if pid != ctx.node_id:
                ctx.add_peer(pid, addr)

    # everyone gossips a vector; everyone receives from all peers
    # (event-driven, not a sleep-poll loop: the handler signals arrival)
    for i, node in enumerate(nodes):
        await node.broadcast_message("gradient", jnp.full((8,), float(i)))
    await asyncio.wait_for(all_in.wait(), timeout=30.0)

    for nid, msgs in received.items():
        senders = sorted(m.sender for m in msgs)
        print(f"{nid} received from {senders}")
        assert len(msgs) == N_NODES - 1
        assert all(isinstance(m.payload, np.ndarray) for m in msgs)

    for node in nodes:
        await node.shutdown()
    print("mesh OK")


if __name__ == "__main__":
    asyncio.run(main())

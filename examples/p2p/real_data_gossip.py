"""Decentralized (P2P) robust learning on REAL data.

The fully-decentralized counterpart of ``examples/ps/real_data_robust.py``:
every honest peer half-steps SGD on its own shard of the real
handwritten-digits dataset, gossips parameters over the topology, and
robust-aggregates what it received; byzantine peers broadcast a sign-flip
vector. The whole round — n half-steps, the broadcast matrix, per-node
trimmed-mean over in-neighborhoods — is ONE jitted SPMD program
(:func:`byzpy_tpu.parallel.gossip.build_gossip_train_step`).

Compare the two runs it prints: with plain-mean gossip the byzantine
broadcasts poison every node (accuracy collapses to ~10%); trimmed-mean
gossip learns through them.

Reference analogue: ``byzpy/examples/p2p/`` trains MNIST with torch
workers over actor topologies.

Run: ``XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu python examples/p2p/real_data_gossip.py``
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))  # repo root

from functools import partial

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

ROUNDS = int(os.environ.get("P2P_ROUNDS", 200))


def run(aggregator_fn, label):
    import jax
    import jax.numpy as jnp

    from byzpy_tpu.engine.peer_to_peer import Topology
    from byzpy_tpu.models.data import (
        ShardedDataset,
        load_digits_dataset,
        sample_node_batches,
    )
    from byzpy_tpu.models.nets import digits_mlp
    from byzpy_tpu.ops import attack_ops
    from byzpy_tpu.parallel.gossip import GossipStepConfig, build_gossip_train_step

    n_nodes, n_byz = 8, 2
    x_train, y_train, x_test, y_test = load_digits_dataset(seed=0)
    bundle = digits_mlp(seed=0)
    cfg = GossipStepConfig(n_nodes=n_nodes, n_byzantine=n_byz, learning_rate=0.1)

    def attack(honest_thetas, key):
        return jnp.tile(
            attack_ops.sign_flip(jnp.mean(honest_thetas, axis=0), scale=-3.0)[None, :],
            (n_byz, 1),
        )

    step, init = build_gossip_train_step(
        bundle, aggregator_fn, Topology.complete(n_nodes), cfg, attack=attack
    )
    jit_step = jax.jit(step)

    data = ShardedDataset(x_train, y_train, n_nodes)
    xs_all, ys_all = data.stacked_shards()
    theta = init()
    key = jax.random.PRNGKey(0)
    batch = 32
    for _ in range(ROUNDS):
        key, bkey, skey = jax.random.split(key, 3)
        xs, ys = sample_node_batches(xs_all, ys_all, bkey, batch)
        theta, _ = jit_step(theta, xs, ys, skey)

    # evaluate node 0's model (honest) on held-out data
    from byzpy_tpu.utils.trees import ravel_pytree_fn

    _, unravel = ravel_pytree_fn(bundle.params)
    params0 = unravel(theta[0])
    logits = bundle.apply_fn(params0, x_test)
    acc = float(jnp.mean(jnp.argmax(logits, -1) == y_test))
    print(f"{label}: node-0 held-out accuracy {acc:.3f}")
    return acc


def main():
    import jax.numpy as jnp

    from byzpy_tpu.ops import robust

    acc_mean = run(lambda m: jnp.mean(m, axis=0), "plain-mean gossip ")
    acc_tm = run(partial(robust.trimmed_mean, f=2), "trimmed-mean gossip")
    if ROUNDS >= 100:  # smoke runs with tiny ROUNDS can't reach the contract
        assert acc_mean < 0.5, "mean gossip should be poisoned"
        assert acc_tm > 0.8, "robust gossip should learn"
    print(
        f"\nsign-flip broadcasters: mean gossip ends at {acc_mean:.1%} "
        f"(poisoned), trimmed-mean at {acc_tm:.1%} (rescued)"
    )


if __name__ == "__main__":
    main()

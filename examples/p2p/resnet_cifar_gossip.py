"""Byzantine-robust ResNet-18 training over P2P gossip (BASELINE config #4).

CIFAR-shaped ResNet-18 (GroupNorm, pure-functional), n nodes gossiping on
a ring, aggregation = NNM pre-mixing then geometric median — the
composition the reference benchmarks for P2P CIFAR. Data is synthetic
class-conditional blobs (no downloads); swap in real CIFAR by replacing
the (x, y) arrays.

Two execution modes:

* default — the fused single-program gossip step
  (``build_gossip_train_step``): all node states live as one stacked
  ``(n, d)`` matrix on the default device. Works on CPU and a single TPU.
* ``P2P_RING=1`` with >= n devices — the ``shard_map`` ring
  (``build_ring_gossip_train_step``): one node per device, parameters
  move only as ``ppermute`` neighbor traffic.

    python examples/p2p/resnet_cifar_gossip.py
    P2P_STEPS=20 P2P_FILTERS=64 python examples/p2p/resnet_cifar_gossip.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import jax

if os.environ.get("BYZPY_TPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["BYZPY_TPU_PLATFORM"])

import math
from functools import partial

import flax.linen as nn
import jax.numpy as jnp

from byzpy_tpu.engine.peer_to_peer import Topology
from byzpy_tpu.models.data import ShardedDataset, synthetic_classification
from byzpy_tpu.models.nets import ResNet18, make_bundle
from byzpy_tpu.ops import preagg, robust
from byzpy_tpu.parallel import (
    GossipStepConfig,
    build_gossip_train_step,
    build_ring_gossip_train_step,
)
from byzpy_tpu.parallel.mesh import make_mesh

N_NODES = int(os.environ.get("N_NODES", 8))
N_BYZ = int(os.environ.get("N_BYZ", 1))
STEPS = int(os.environ.get("P2P_STEPS", 10))
FILTERS = int(os.environ.get("P2P_FILTERS", 64))  # 64 = real ResNet-18
BATCH = int(os.environ.get("P2P_BATCH", 32))


def robust_aggregate(m: jnp.ndarray) -> jnp.ndarray:
    """NNM mixing then geometric median over the (k+1, d) received stack."""
    mixed = preagg.nnm(m, f=min(N_BYZ, m.shape[0] - 1))
    return robust.geometric_median(mixed, max_iter=32)


def main() -> None:
    # GroupNorm groups must divide every stage's channel count (multiples
    # of FILTERS); gcd keeps tiny test widths valid
    norm = partial(nn.GroupNorm, num_groups=math.gcd(32, FILTERS))
    bundle = make_bundle(
        ResNet18(num_classes=10, num_filters=FILTERS, norm=norm),
        (1, 32, 32, 3), seed=0,
    )
    d = sum(p.size for p in jax.tree_util.tree_leaves(bundle.params))
    print(f"ResNet-18 (filters={FILTERS}): {d:,} params, "
          f"{N_NODES} nodes ({N_BYZ} byzantine), device={jax.devices()[0]}")

    # 4 rotating batches per node
    n_batches = 4
    x, y = synthetic_classification(
        n_samples=N_NODES * BATCH * n_batches, input_shape=(32, 32, 3), seed=0
    )
    xs_all, ys_all = ShardedDataset(x, y, n_nodes=N_NODES).stacked_shards()

    def batch_at(s):
        start = (s % n_batches) * BATCH
        return xs_all[:, start:start + BATCH], ys_all[:, start:start + BATCH]

    cfg = GossipStepConfig(n_nodes=N_NODES, n_byzantine=N_BYZ, learning_rate=0.05)
    ring_mode = os.environ.get("P2P_RING") == "1"
    if ring_mode:
        if len(jax.devices()) < N_NODES:
            raise SystemExit(
                f"P2P_RING=1 needs >= {N_NODES} devices (have {len(jax.devices())})"
            )
        mesh = make_mesh([N_NODES], ("nodes",))
        step, init = build_ring_gossip_train_step(
            bundle, robust_aggregate, cfg, mesh, k=2
        )
        print(f"ring mode: shard_map over {N_NODES} devices (ppermute ring)")
    else:
        step, init = build_gossip_train_step(
            bundle, robust_aggregate, Topology.ring(N_NODES, 2), cfg
        )
    theta = init()
    jit_step = jax.jit(step)

    from byzpy_tpu.utils.metrics import force_result

    key = jax.random.PRNGKey(0)
    device_losses = []
    xs, ys = batch_at(0)
    theta1, metrics = jit_step(theta, xs, ys, key)  # compile
    force_result(theta1)  # terminal host copy: block_until_ready can return
    t0 = time.perf_counter()  # early through a tunnel (see RESULTS.md notes)
    for s in range(STEPS):
        key, sub = jax.random.split(key)
        xs, ys = batch_at(s)
        theta, metrics = jit_step(theta, xs, ys, sub)
        # keep losses on device: a float() here would sync every step and
        # time the host round-trip instead of the step
        device_losses.append(
            metrics["honest_loss"] if isinstance(metrics, dict) else metrics
        )
    force_result(theta)
    dt = time.perf_counter() - t0
    losses = [float(l) for l in device_losses]
    for s, l in enumerate(losses):
        print(f"step {s + 1:3d}  honest loss {l:.4f}")
    print(f"{STEPS / dt:.2f} steps/sec  ({dt / STEPS * 1e3:.1f} ms/step)")
    if STEPS >= 5:  # smoke runs (P2P_STEPS=2) are too short to descend
        assert losses[-1] < losses[0], "loss did not decrease"
        print("loss decreased:", f"{losses[0]:.4f} -> {losses[-1]:.4f}")


if __name__ == "__main__":
    main()

"""Elastic PS training through node crashes (no reference analogue).

The reference's PS round dies with any node
(``byzpy/engine/parameter_server/ps.py:103-144``): a worker that loses
its link mid-training kills the job. With
``ParameterServer(elastic=ElasticPolicy(...))`` a crash costs the node
its slot for the round; the server keeps training on the survivors,
probes the suspect every round, and re-admits it on the first success —
while ``min_quorum`` refuses to continue below the aggregator's f-of-n
assumption.

This demo trains a linear regression on synthetic data with 6 honest
nodes + 1 sign-flipping byzantine node under Multi-Krum. Node 2 "dies"
for rounds 10-19 (raises ConnectionError) and recovers at round 20.
Watch the loss keep falling through the outage and the suspect set empty
itself after recovery.

Run: ``python examples/ps/elastic_crash_recovery.py`` (any backend).
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()

import numpy as np

from byzpy_tpu.aggregators import MultiKrum
from byzpy_tpu.engine.parameter_server import ElasticPolicy, ParameterServer

RNG = np.random.default_rng(0)
DIM = 32
W_TRUE = RNG.standard_normal(DIM).astype(np.float32)
ROUNDS = int(os.environ.get("PS_ROUNDS", 40))
LR = 0.05


class RegressionNode:
    """Least-squares worker on its own data shard (host-resident)."""

    def __init__(self, seed: int, crash_rounds=()):
        rng = np.random.default_rng(seed)
        self.x = rng.standard_normal((128, DIM)).astype(np.float32)
        self.y = self.x @ W_TRUE + 0.01 * rng.standard_normal(128).astype(
            np.float32
        )
        self.w = np.zeros(DIM, np.float32)
        self.round_no = 0
        self.crash_rounds = set(crash_rounds)

    def honest_gradient_for_next_batch(self):
        self.round_no += 1
        if self.round_no in self.crash_rounds:
            raise ConnectionError("simulated link failure")
        resid = self.x @ self.w - self.y
        return [(self.x.T @ resid / len(self.y)).astype(np.float32)]

    def apply_server_gradient(self, g):
        self.w = self.w - LR * np.asarray(g[0])

    def loss(self) -> float:
        return float(np.mean((self.x @ self.w - self.y) ** 2))


class SignFlipNode(RegressionNode):
    def byzantine_gradient_for_next_batch(self, honest):
        stacked = np.stack([np.asarray(g[0]) for g in honest])
        return [(-4.0 * stacked.mean(axis=0)).astype(np.float32)]


async def main() -> None:
    nodes = [
        RegressionNode(i, crash_rounds=range(10, 20) if i == 2 else ())
        for i in range(6)
    ]
    ps = ParameterServer(
        honest_nodes=nodes,
        byzantine_nodes=[SignFlipNode(99)],
        aggregator=MultiKrum(f=1, q=3),
        elastic=ElasticPolicy(min_quorum=4, call_timeout=10.0),
    )
    for r in range(ROUNDS):
        await ps.round()
        if (r + 1) % 5 == 0:
            alive = [n.loss() for i, n in enumerate(nodes) if i != 2]
            print(
                f"round {r + 1:3d}  loss={np.mean(alive):.5f}  "
                f"suspects={sorted(ps.elastic_state.suspects) or '-'}"
            )
    if ROUNDS >= 20:  # smoke runs use PS_ROUNDS=2 and never reach the crash
        assert ps.elastic_state.suspects == {}, "node 2 should have re-admitted"
        kinds = {
            k for _, nid, k in ps.elastic_state.events if nid == "honest:2"
        }
        assert {"suspected", "readmitted"} <= kinds
        print("\nnode 2 died rounds 10-19, re-admitted on recovery; "
              f"final mean loss {np.mean([n.loss() for n in nodes]):.5f}")


if __name__ == "__main__":
    asyncio.run(main())

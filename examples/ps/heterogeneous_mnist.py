"""Heterogeneous parameter server: device-pinned nodes + process nodes.

Reference semantics: ``byzpy/examples/ps/heterogenous/`` — a mixed fleet
where some workers sit on accelerators and others in host processes, all
driven by one PS round loop. Here the fast nodes use the ``tpu`` actor
backend (state pinned as device arrays on a chip; falls back to ``thread``
off-TPU) and the slow cohort lives in spawned OS processes, exercising the
shm payload path. The aggregation itself is scheduled on a mixed
ActorPool whose chunk subtasks carry capability affinities.

    python examples/ps/heterogeneous_mnist.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import asyncio

import jax

if os.environ.get("BYZPY_TPU_PLATFORM"):  # see remote_tcp/node_server.py
    jax.config.update("jax_platforms", os.environ["BYZPY_TPU_PLATFORM"])

import jax.numpy as jnp

from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
from byzpy_tpu.engine.graph.pool import ActorPool, ActorPoolConfig
from byzpy_tpu.engine.node.actors import ByzantineNodeActor, HonestNodeActor
from byzpy_tpu.engine.node.base import ByzantineNode, HonestNode
from byzpy_tpu.engine.parameter_server import ParameterServer
from byzpy_tpu.models.data import ShardedDataset, sample_batch, synthetic_classification
from byzpy_tpu.models.nets import mnist_mlp

N_FAST = int(os.environ.get("N_FAST", 2))     # device-pinned nodes
N_SLOW = int(os.environ.get("N_SLOW", 2))     # process nodes
N_BYZ = int(os.environ.get("N_BYZ", 1))
ROUNDS = int(os.environ.get("PS_ROUNDS", 10))
BATCH = 64
LR = 0.1


class MnistNode(HonestNode):
    def __init__(self, shard_x, shard_y, seed):
        self.bundle = mnist_mlp(seed=0)
        self.x, self.y = jnp.asarray(shard_x), jnp.asarray(shard_y)
        self.key = jax.random.PRNGKey(seed)
        self._grad = jax.jit(jax.grad(self.bundle.loss_fn))

    def next_batch(self):
        self.key, sub = jax.random.split(self.key)
        return sample_batch(self.x, self.y, sub, BATCH)

    def honest_gradient(self, x, y):
        return self._grad(self.bundle.params, x, y)

    def apply_server_gradient(self, gradient):
        self.bundle = self.bundle.with_params(
            jax.tree_util.tree_map(
                lambda p, g: p - LR * jnp.asarray(g), self.bundle.params, gradient
            )
        )

    def accuracy(self, x, y):
        logits = self.bundle.apply_fn(self.bundle.params, jnp.asarray(x))
        return float(jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(y)))


class SignFlipNode(ByzantineNode):
    def next_batch(self):
        return None, None

    def byzantine_gradient(self, honest_gradients):
        mean = jax.tree_util.tree_map(
            lambda *gs: sum(jnp.asarray(g) for g in gs) / len(gs), *honest_gradients
        )
        return jax.tree_util.tree_map(lambda g: -3.0 * g, mean)

    def apply_server_gradient(self, gradient):
        pass


def fast_backend() -> str:
    return "tpu" if jax.default_backend() == "tpu" else "thread"


async def main() -> None:
    import numpy as np

    n_honest = N_FAST + N_SLOW
    x, y = synthetic_classification(n_samples=4096, seed=0)
    data = ShardedDataset(x, y, n_honest)

    honest = []
    for i in range(n_honest):
        backend = fast_backend() if i < N_FAST else "process"
        sx, sy = data.node_slice(i)
        honest.append(
            await HonestNodeActor.spawn(
                MnistNode, np.asarray(sx), np.asarray(sy), i, backend=backend
            )
        )
    byz = [
        await ByzantineNodeActor.spawn(SignFlipNode, backend="thread")
        for _ in range(N_BYZ)
    ]

    # mixed aggregation pool: one device-capable worker + two host workers;
    # the trimmed-mean feature chunks carry no affinity so any worker takes
    # them, while device-affine subtasks would route to the tpu worker
    pool_cfg = [
        ActorPoolConfig(backend=fast_backend(), count=1, name="devw"),
        ActorPoolConfig(backend="process", count=2, name="hostw"),
    ]
    async with ActorPool(pool_cfg) as pool:
        print("pool workers:", {n: sorted(c) for n, c in pool.worker_capabilities.items()})
        ps = ParameterServer(
            honest, byz,
            aggregator=CoordinateWiseTrimmedMean(f=N_BYZ, chunk_size=16384),
            pool=pool,
        )
        for r in range(ROUNDS):
            await ps.round()
            if (r + 1) % 5 == 0 or r == ROUNDS - 1:
                acc = await honest[0].accuracy(x[:512], y[:512])
                print(f"round {r + 1:3d}  accuracy {acc:.3f}", flush=True)

    for actor in honest + byz:
        await actor.close()
    print("done", flush=True)


if __name__ == "__main__":
    asyncio.run(main())

"""Parameter-server training with process-actor nodes.

Reference semantics: ``byzpy/examples/ps/process/`` — nodes live in
spawned OS processes; gradients cross the boundary through the native shm
store (``byzpy_tpu.engine.storage``) rather than the pickle pipe. Children
run on CPU (a TPU chip admits one process); this layout fits host-side
workloads or CPU-only robust-aggregation research.
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

from byzpy_tpu.aggregators import CoordinateWiseMedian
from byzpy_tpu.engine.node.actors import HonestNodeActor
from byzpy_tpu.engine.parameter_server import ParameterServer

# node classes are shared with the thread example; import from a module so
# the spawned child can re-import them (cloudpickle ships the class, but
# module-level definitions keep the pickles small)
from examples.ps.thread_mnist import MnistNode

N_NODES = int(os.environ.get("N_NODES", 3))
ROUNDS = int(os.environ.get("PS_ROUNDS", 10))


async def main():
    from byzpy_tpu.models.data import ShardedDataset, synthetic_classification

    x, y = synthetic_classification(n_samples=1024, seed=0)
    data = ShardedDataset(x, y, N_NODES)
    honest = [
        await HonestNodeActor.spawn(
            MnistNode, *map(lambda a: a.__array__(), data.node_slice(i)), i,
            backend="process",
        )
        for i in range(N_NODES)
    ]
    ps = ParameterServer(honest, aggregator=CoordinateWiseMedian())
    for r in range(ROUNDS):
        await ps.round()
        if (r + 1) % 5 == 0:
            acc = await honest[0].accuracy(x.__array__(), y.__array__())
            print(f"round {r + 1}: accuracy {acc:.3f}")
    for a in honest:
        await a.close()


if __name__ == "__main__":
    asyncio.run(main())

"""Robust learning on REAL data: byzantine nodes vs robust aggregation.

The reference's flagship demo trains MNIST under attack and shows accuracy
rescued by a robust aggregator (ref: ``examples/ps/thread/mnist.py``).
This is the TPU-native equivalent on the real handwritten-digits dataset
bundled with the image: the whole Byzantine round — per-node grads,
colluding sign-flip rows, trimmed-mean aggregation, SGD — is ONE jitted
SPMD step (``byzpy_tpu.parallel.ps``). Compare the two runs it prints:
plain mean collapses to ~10% (random) accuracy; trimmed mean learns.

Run: ``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
python examples/ps/real_data_robust.py`` (or on a TPU mesh as-is).

For full-size MNIST, point ``byzpy_tpu.models.data.load_mnist_idx`` at a
directory of IDX files and swap the loader + ``mnist_mlp`` below.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

from byzpy_tpu.utils.robust_study import StudyConfig, results_table, run_study

ROUNDS = int(os.environ.get("PS_ROUNDS", 200))


def main():
    cfg = StudyConfig(rounds=ROUNDS, eval_every=max(1, ROUNDS // 4))
    results = run_study(
        aggregators=("mean", "trimmed_mean"),
        attacks=("sign_flip",),
        cfg=cfg,
    )
    print()
    print(results_table(results))
    by_agg = {r.aggregator: r.final_accuracy for r in results}
    if ROUNDS >= 100:  # smoke runs with tiny ROUNDS can't reach the contract
        assert by_agg["mean"] < 0.5, "mean should be destroyed by the attack"
        assert by_agg["trimmed_mean"] > 0.8, "trimmed mean should rescue training"
    print(
        f"\nsign-flip attack: mean ends at {by_agg['mean']:.1%} (destroyed), "
        f"trimmed mean at {by_agg['trimmed_mean']:.1%} (rescued)"
    )


if __name__ == "__main__":
    main()

"""Multi-machine parameter-server coordinator.

Reads a YAML/JSON node manifest (ref:
``byzpy/examples/ps/remote_tcp/nodes_example.yaml``), spawns each training
node on its machine's actor server over ``tcp://``, and drives robust PS
rounds from here. Gradient payloads travel the control wire as host
arrays; on a real deployment keep this for orchestration and let bulk
tensors ride jax multi-host collectives (see ``byzpy_tpu.parallel``).

    BYZPY_TPU_WIRE_KEY=cluster-secret \
    python examples/ps/remote_tcp/coordinator.py --manifest nodes.yaml
"""

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), *[".."] * 3))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import jax

# honor a platform override BEFORE any jax use: on shared single-chip dev
# hosts the demo pins workers to CPU (real deployments use each machine's
# own accelerators and leave this unset)
if os.environ.get("BYZPY_TPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["BYZPY_TPU_PLATFORM"])

import jax.numpy as jnp

from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
from byzpy_tpu.engine.node.actors import ByzantineNodeActor, HonestNodeActor
from byzpy_tpu.engine.node.base import ByzantineNode, HonestNode
from byzpy_tpu.engine.parameter_server import ParameterServer
from byzpy_tpu.models.data import ShardedDataset, sample_batch, synthetic_classification
from byzpy_tpu.models.nets import mnist_mlp

ROUNDS = int(os.environ.get("PS_ROUNDS", 10))
BATCH = 64
LR = 0.1


class RemoteMnistNode(HonestNode):
    """Honest worker constructed BY VALUE on its hosting machine: the class
    and its shard ship through cloudpickle at spawn."""

    def __init__(self, shard_x, shard_y, seed):
        self.bundle = mnist_mlp(seed=0)
        self.x, self.y = jnp.asarray(shard_x), jnp.asarray(shard_y)
        self.key = jax.random.PRNGKey(seed)
        self._grad = jax.jit(jax.grad(self.bundle.loss_fn))

    def next_batch(self):
        self.key, sub = jax.random.split(self.key)
        return sample_batch(self.x, self.y, sub, BATCH)

    def honest_gradient(self, x, y):
        return self._grad(self.bundle.params, x, y)

    def apply_server_gradient(self, gradient):
        self.bundle = self.bundle.with_params(
            jax.tree_util.tree_map(
                lambda p, g: p - LR * jnp.asarray(g), self.bundle.params, gradient
            )
        )

    def accuracy(self, x, y):
        logits = self.bundle.apply_fn(self.bundle.params, jnp.asarray(x))
        return float(jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(y)))


class EmpireNode(ByzantineNode):
    def next_batch(self):
        return None, None

    def byzantine_gradient(self, honest_gradients):
        mean = jax.tree_util.tree_map(
            lambda *gs: sum(jnp.asarray(g) for g in gs) / len(gs), *honest_gradients
        )
        return jax.tree_util.tree_map(lambda g: -1.0 * g, mean)

    def apply_server_gradient(self, gradient):
        pass


def load_manifest(path: str) -> dict:
    with open(path) as fh:
        text = fh.read()
    try:
        import yaml
    except ImportError:
        try:
            return json.loads(text)
        except json.JSONDecodeError as exc:
            raise RuntimeError(
                f"{path} is not JSON and PyYAML is not installed; "
                "pip install pyyaml or supply a JSON manifest"
            ) from exc
    return yaml.safe_load(text)


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--manifest",
        default=os.path.join(os.path.dirname(os.path.abspath(__file__)), "nodes.yaml"),
    )
    args = ap.parse_args()
    manifest = load_manifest(args.manifest)
    if not os.environ.get(manifest.get("secret_env", "BYZPY_TPU_WIRE_KEY")):
        print("warning: wire key unset — frames are unsigned", file=sys.stderr)

    entries = manifest["nodes"]
    honest_entries = [e for e in entries if e["role"] == "honest"]
    byz_entries = [e for e in entries if e["role"] == "byzantine"]

    x, y = synthetic_classification(n_samples=4096, seed=0)
    data = ShardedDataset(x, y, len(honest_entries))

    honest = []
    for i, entry in enumerate(honest_entries):
        sx, sy = data.node_slice(i)
        import numpy as np

        actor = await HonestNodeActor.spawn(
            RemoteMnistNode, np.asarray(sx), np.asarray(sy), i,
            backend=f"tcp://{entry['address']}",
        )
        honest.append(actor)
    byz = [
        await ByzantineNodeActor.spawn(EmpireNode, backend=f"tcp://{e['address']}")
        for e in byz_entries
    ]

    ps = ParameterServer(honest, byz, aggregator=CoordinateWiseTrimmedMean(f=max(1, len(byz))))
    for r in range(ROUNDS):
        await ps.round()
        if (r + 1) % 5 == 0 or r == ROUNDS - 1:
            acc = await honest[0].accuracy(x[:512], y[:512])
            print(f"round {r + 1:3d}  accuracy {acc:.3f}", flush=True)

    for actor in honest + byz:
        await actor.close()
    print("done", flush=True)


if __name__ == "__main__":
    asyncio.run(main())

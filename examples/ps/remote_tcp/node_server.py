"""Actor-hosting server for the multi-machine parameter server.

Run one per machine (ref: ``byzpy/examples/ps/remote_tcp/ps_node.py``):

    BYZPY_TPU_WIRE_KEY=cluster-secret \
    python examples/ps/remote_tcp/node_server.py --host 0.0.0.0 --port 7781

The coordinator constructs node actors here over ``tcp://``; frames are
HMAC-signed when ``BYZPY_TPU_WIRE_KEY`` is set (strongly recommended —
see ``byzpy_tpu.engine.actor.wire``).
"""

import argparse
import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), *[".."] * 3))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import jax

# honor a platform override BEFORE any jax use: on shared single-chip dev
# hosts the demo pins workers to CPU (real deployments use each machine's
# own accelerators and leave this unset)
if os.environ.get("BYZPY_TPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["BYZPY_TPU_PLATFORM"])

from byzpy_tpu.engine.actor.backends.remote import RemoteActorServer


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    args = ap.parse_args()

    if not os.environ.get("BYZPY_TPU_WIRE_KEY"):
        print("warning: BYZPY_TPU_WIRE_KEY unset — frames are unsigned", file=sys.stderr)
    server = RemoteActorServer(host=args.host, port=args.port)
    await server.start()
    print(f"node server ready on {server.address}", flush=True)
    try:
        await asyncio.Event().wait()  # serve forever
    finally:
        await server.close()


if __name__ == "__main__":
    asyncio.run(main())

"""Single-host demo of the multi-machine PS: starts the three loopback
actor servers from ``nodes.yaml`` as subprocesses, then runs the
coordinator against the manifest — the same commands you would run by
hand across real machines.

    python examples/ps/remote_tcp/run_local_demo.py
"""

import os
import signal
import subprocess
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
_root = os.path.abspath(os.path.join(_here, *[".."] * 3))


def main() -> None:
    env = dict(os.environ)
    env.setdefault("BYZPY_TPU_WIRE_KEY", "local-demo-secret")
    env.setdefault("PS_ROUNDS", "5")
    # single-host demo: all processes on CPU (see BYZPY_TPU_PLATFORM note
    # in node_server.py/coordinator.py)
    env.setdefault("BYZPY_TPU_PLATFORM", "cpu")
    env["PYTHONPATH"] = _root + os.pathsep + env.get("PYTHONPATH", "")

    manifest_path = os.path.join(_here, "nodes.yaml")
    import yaml

    with open(manifest_path) as fh:
        manifest = yaml.safe_load(fh)
    ports = sorted({
        int(e["address"].rsplit(":", 1)[1]) for e in manifest["nodes"]
    })

    servers = []
    try:
        for port in ports:
            servers.append(
                subprocess.Popen(
                    [sys.executable, os.path.join(_here, "node_server.py"),
                     "--host", "127.0.0.1", "--port", str(port)],
                    env=env,
                )
            )
        time.sleep(2.0)  # let servers bind
        rc = subprocess.call(
            [sys.executable, os.path.join(_here, "coordinator.py"),
             "--manifest", manifest_path],
            env=env,
        )
        sys.exit(rc)
    finally:
        for proc in servers:
            proc.send_signal(signal.SIGTERM)
        for proc in servers:
            proc.wait(timeout=10)


if __name__ == "__main__":
    main()

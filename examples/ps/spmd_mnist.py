"""Fused SPMD parameter server: the TPU-native fast path.

The whole Byzantine-robust round — per-node gradients, sign-flip attack on
the byzantine shard, clipping pre-aggregation, trimmed-mean aggregation,
SGD update — is ONE jitted step over a device mesh. On a pod slice each
node's forward/backward runs on its own chip and the robust aggregation
shards over ICI; here it falls back to however many devices are visible
(force 8 virtual CPU devices with ``XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu``).

No reference equivalent — the reference's round always hops through host
actors (``byzpy/engine/parameter_server/ps.py:103-144``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))  # repo root

import os
from functools import partial

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import jax
import jax.numpy as jnp

from byzpy_tpu.models.data import (
    ShardedDataset,
    sample_node_batches,
    synthetic_classification,
)
from byzpy_tpu.models.nets import mnist_mlp
from byzpy_tpu.ops import attack_ops, preagg, robust
from byzpy_tpu.parallel.mesh import node_mesh, sharding
from byzpy_tpu.parallel.ps import PSStepConfig, build_ps_train_step

ROUNDS = int(os.environ.get("PS_ROUNDS", 30))
BATCH = 64


def main():
    n_devices = len(jax.devices())
    n_nodes = max(4, n_devices)
    n_byz = max(1, n_nodes // 4)
    mesh = node_mesh(min(n_nodes, n_devices))

    bundle = mnist_mlp(seed=0)
    cfg = PSStepConfig(n_nodes=n_nodes, n_byzantine=n_byz, learning_rate=0.1)

    def attack(honest, key):
        base = jnp.mean(honest, axis=0, keepdims=True)
        return jnp.tile(attack_ops.sign_flip(base, scale=-3.0), (n_byz, 1))

    step, opt_state = build_ps_train_step(
        bundle,
        partial(robust.trimmed_mean, f=n_byz),
        cfg,
        attack=attack,
        pre_aggregate=partial(preagg.clip_rows, threshold=100.0),
        mesh=mesh,
    )
    jit_step = jax.jit(step)

    x, y = synthetic_classification(n_samples=4096, seed=0)
    data = ShardedDataset(x, y, n_nodes)
    xs_all, ys_all = data.stacked_shards()
    node_shard = sharding(mesh, "nodes") if n_nodes == mesh.devices.size else None

    params = bundle.params
    key = jax.random.PRNGKey(0)
    for r in range(ROUNDS):
        key, bkey, skey = jax.random.split(key, 3)
        xs, ys = sample_node_batches(xs_all, ys_all, bkey, BATCH)
        if node_shard is not None:
            xs, ys = jax.device_put(xs, node_shard), jax.device_put(ys, node_shard)
        params, opt_state, metrics = jit_step(params, opt_state, xs, ys, skey)
        if (r + 1) % 10 == 0:
            logits = bundle.apply_fn(params, x)
            acc = float(jnp.mean(jnp.argmax(logits, -1) == y))
            print(
                f"round {r + 1}: honest_loss {float(metrics['honest_loss']):.3f} "
                f"accuracy {acc:.3f}"
            )
    final_acc = float(
        jnp.mean(jnp.argmax(bundle.apply_fn(params, x), -1) == y)
    )
    print(f"final accuracy after {ROUNDS} rounds: {final_acc:.3f}")
    assert final_acc > 0.5, "did not learn"


if __name__ == "__main__":
    main()

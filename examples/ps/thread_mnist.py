"""Parameter-server training with thread-actor nodes.

Reference semantics: ``byzpy/examples/ps/thread/mnist.py`` — n honest
nodes each training an MLP on their shard, f byzantine nodes sign-flipping,
robust aggregation with coordinate-wise trimmed mean, accuracy printed
every few rounds.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))  # repo root

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import asyncio
import os

import jax
import jax.numpy as jnp

from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
from byzpy_tpu.engine.node.actors import ByzantineNodeActor, HonestNodeActor
from byzpy_tpu.engine.node.base import ByzantineNode, HonestNode
from byzpy_tpu.engine.parameter_server import ParameterServer
from byzpy_tpu.models.data import ShardedDataset, sample_batch, synthetic_classification
from byzpy_tpu.models.nets import mnist_mlp
from byzpy_tpu.utils.training import train_with_progress_async

N_NODES = int(os.environ.get("N_NODES", 6))
N_BYZ = int(os.environ.get("N_BYZ", 2))
ROUNDS = int(os.environ.get("PS_ROUNDS", 30))
BATCH = 64
LR = 0.1


class MnistNode(HonestNode):
    """One honest worker: its own shard, jitted grad, SGD apply."""

    def __init__(self, shard_x, shard_y, seed):
        self.bundle = mnist_mlp(seed=0)  # common init across nodes
        self.x, self.y = shard_x, shard_y
        self.key = jax.random.PRNGKey(seed)
        self._grad = jax.jit(jax.grad(self.bundle.loss_fn))

    def next_batch(self):
        self.key, sub = jax.random.split(self.key)
        return sample_batch(self.x, self.y, sub, BATCH)

    def honest_gradient(self, x, y):
        return self._grad(self.bundle.params, x, y)

    def apply_server_gradient(self, gradient):
        self.bundle = self.bundle.with_params(
            jax.tree_util.tree_map(
                lambda p, g: p - LR * g, self.bundle.params, gradient
            )
        )

    def accuracy(self, x, y):
        logits = self.bundle.apply_fn(self.bundle.params, x)
        return float(jnp.mean(jnp.argmax(logits, -1) == y))


class SignFlipNode(ByzantineNode):
    def next_batch(self):
        return None, None

    def byzantine_gradient(self, honest_gradients):
        mean = jax.tree_util.tree_map(
            lambda *gs: sum(gs) / len(gs), *honest_gradients
        )
        return jax.tree_util.tree_map(lambda g: -3.0 * g, mean)

    def apply_server_gradient(self, gradient):
        pass


async def main():
    x, y = synthetic_classification(n_samples=4096, seed=0)
    data = ShardedDataset(x, y, N_NODES)

    honest = [
        await HonestNodeActor.spawn(MnistNode, *data.node_slice(i), i, backend="thread")
        for i in range(N_NODES)
    ]
    byz = [
        await ByzantineNodeActor.spawn(SignFlipNode, backend="thread")
        for _ in range(N_BYZ)
    ]
    ps = ParameterServer(
        honest, byz, aggregator=CoordinateWiseTrimmedMean(f=N_BYZ)
    )

    async def evaluate(i):
        acc = await honest[0].accuracy(x, y)
        print(f"round {i + 1}: accuracy {acc:.3f}")
        return acc

    history = await train_with_progress_async(
        ps, ROUNDS, eval_callback=evaluate, eval_interval=10, progress=False
    )
    assert history[-1][1] > 0.5, "did not learn"
    for a in honest + byz:
        await a.close()


if __name__ == "__main__":
    asyncio.run(main())

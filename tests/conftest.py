"""Test configuration: run everything on a simulated 8-device CPU mesh.

Multi-chip sharding logic is validated without TPU hardware by forcing the
host platform to expose 8 virtual devices (the reference validates its
multi-node logic analogously with an in-process cluster registry, ref:
``byzpy/engine/node/context.py:56-123``).

Note: the session environment pins ``JAX_PLATFORMS=axon`` (real TPU) and a
sitecustomize imports jax at interpreter start, so the platform must be
overridden via ``jax.config`` (env vars are too late for JAX_PLATFORMS and
just-in-time for XLA_FLAGS, which is read at first backend init).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache: the suite's wall time is dominated by
# compiles on the 8-virtual-device mesh, and they repeat identically
# between runs. First run populates tests/.jax_cache (gitignored); later
# runs — including the driver's repeated green checks — start warm
# (~40% faster measured on this box). Override/disable with
# JAX_COMPILATION_CACHE_DIR.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


# -- shared decentralized-cluster helpers (used by the node-layer suites) ----

@pytest.fixture(autouse=True)
def _clear_node_registries():
    """Every test starts with clean in-process/process node registries."""
    from byzpy_tpu.engine.node import InProcessContext, ProcessContext

    InProcessContext.clear_registry()
    ProcessContext.clear_registry()
    yield
    InProcessContext.clear_registry()
    ProcessContext.clear_registry()


@pytest.fixture
def make_cluster():
    from byzpy_tpu.engine.node import (
        DecentralizedCluster, DecentralizedNode, InProcessContext,
    )
    from byzpy_tpu.engine.peer_to_peer import Topology

    def factory(n, topology=None):
        topo = topology or Topology.complete(n)
        cluster = DecentralizedCluster(topo)
        for i in range(n):
            nid = f"node-{i}"
            cluster.add_node(DecentralizedNode(nid, InProcessContext(nid)))
        return cluster

    return factory

"""Test configuration: run everything on a simulated 8-device CPU mesh.

Multi-chip sharding logic is validated without TPU hardware by forcing the
host platform to expose 8 virtual devices (the reference validates its
multi-node logic analogously with an in-process cluster registry, ref:
``byzpy/engine/node/context.py:56-123``).

Note: the session environment pins ``JAX_PLATFORMS=axon`` (real TPU) and a
sitecustomize imports jax at interpreter start, so the platform must be
overridden via ``jax.config`` (env vars are too late for JAX_PLATFORMS and
just-in-time for XLA_FLAGS, which is read at first backend init).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs

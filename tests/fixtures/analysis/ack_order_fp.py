"""byzlint fixture: ACK-ORDER false-positive guards.

The module contract done right — append-before-ack on every path —
plus the shapes the flow pass must not over-flag: dead paths after a
returning send, events split across functions, and the documented
one-pass loop treatment (no loop-carry: precision over completeness).
"""


class Frontend:
    def handle_submit(self, writer, sub):
        # the PR 9 fix: the accept record lands BEFORE the ack returns
        self.durability.record_accept(sub.client, sub.seq)
        writer.write(b"ok")

    def handle_reject(self, writer, sub, full):
        if full:
            writer.write(b"rejected")  # no promise made — nothing owed
            return
        self.durability.record_accept(sub.client, sub.seq)
        writer.write(b"ok")

    def handle_guarded(self, writer, sub):
        try:
            self.durability.record_accept(sub.client, sub.seq)
        except OSError:
            writer.write(b"error")
            return
        writer.write(b"ok")

    def drain(self, writer, subs):
        for sub in subs:
            # per-item append→send inside one iteration: in order
            self.durability.record_accept(sub.client, sub.seq)
            writer.write(b"ok")

    def append_only(self, sub):
        self.durability.record_accept(sub.client, sub.seq)


def send_only(writer, replies):
    writer.write(b"".join(replies))

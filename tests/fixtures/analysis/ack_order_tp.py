"""byzlint fixture: ACK-ORDER true positives (never imported).

Minimized PR 9 incident: the ack left the process before the WAL
append that makes it a durable promise — a crash between the two
replayed the submission into a double fold on recovery.
"""


class Frontend:
    def handle_submit(self, writer, sub):
        writer.write(b"ok")  # ack first...
        # finding: ...then the append that was supposed to back it
        self.durability.record_accept(sub.client, sub.seq)

    def handle_branchy(self, writer, sub, fast):
        if fast:
            writer.write(b"ok")
        else:
            self.prepare(sub)
        # finding: the fast path acked before this append
        self.durability.record_accept(sub.client, sub.seq)

    def prepare(self, sub):
        return sub


def helper_ack_first(wal, conn, record):
    send_ack(conn, b"ok")
    wal.append(record)  # finding: bare-function ack preceded the append


def send_ack(conn, payload):
    return conn, payload

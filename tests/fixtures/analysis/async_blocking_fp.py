"""byzlint fixture: ASYNC-BLOCKING false-positive guards."""

import asyncio
import time


async def cooperative_poll(flag):
    while not flag.is_set():
        await asyncio.sleep(0.05)  # awaited asyncio sleep: fine


def sync_retry_helper():
    time.sleep(0.05)  # plain sync function: blocking is allowed
    return True


async def offloaded_join(proc):
    # the sanctioned pattern: blocking join runs on an executor thread
    loop = asyncio.get_running_loop()
    await loop.run_in_executor(None, proc.join, 5)


async def executor_target_is_exempt(conn):
    loop = asyncio.get_running_loop()

    def pump():
        # nested sync def = executor target; its blocking calls are fine
        return conn.recv(4096)

    return await loop.run_in_executor(None, pump)


async def string_join(parts):
    return ", ".join(parts)  # str.join is not a process join

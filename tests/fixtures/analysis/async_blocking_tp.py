"""byzlint fixture: ASYNC-BLOCKING true positives (never imported)."""

import select
import time


async def busy_poll(flag):
    while not flag.is_set():
        time.sleep(0.05)  # finding: blocks the shared event loop


async def dump_state(state, path):
    with open(path, "w") as sink:  # finding: blocking file I/O on the loop
        sink.write(repr(state))


async def reap(worker_proc):
    worker_proc.join(5)  # finding: blocking process join


async def wait_readable(sock):
    select.select([sock], [], [], 1.0)  # finding
    return sock.recv(4096)  # finding: sync socket read

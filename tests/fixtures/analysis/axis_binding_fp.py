"""byzlint fixture: AXIS-BINDING false-positive guards."""

from functools import partial

import jax
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AXIS = "nodes"

mesh = Mesh(jax.devices(), ("nodes", "feat"))


@partial(shard_map, mesh=mesh, in_specs=(P("nodes"),), out_specs=P())
def bound_axis(x):
    return lax.psum(x, "nodes")


@partial(shard_map, mesh=mesh, in_specs=(P("nodes"),), out_specs=P())
def mesh_axis_not_in_specs(x):
    # legal: "feat" is a mesh axis even though no spec mentions it
    return lax.pmean(x, "feat")


@partial(shard_map, mesh=mesh, in_specs=(P(AXIS),), out_specs=P())
def const_resolved_axis(x):
    return lax.psum(x, AXIS)


def pmap_bound(xs):
    return jax.pmap(lambda x: lax.psum(x, "i"), axis_name="i")(xs)


def in_spmd_primitive(x, axis_name):
    # axis arrives as a parameter — not statically checkable, stays silent
    return lax.psum(x, axis_name)

"""byzlint fixture: AXIS-BINDING true positives (never imported)."""

from functools import partial

import jax
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("nodes",))


@partial(shard_map, mesh=mesh, in_specs=(P("nodes"),), out_specs=P())
def wrong_axis(x):
    return lax.psum(x, "feat")  # finding: mesh binds only "nodes"


@partial(shard_map, mesh=mesh, in_specs=(P("nodes"),), out_specs=P("nodes"))
def wrong_axis_gather(x):
    g = lax.all_gather(x, "batch", axis=0, tiled=True)  # finding
    return g


def pmap_wrong_axis(xs):
    return jax.pmap(lambda x: lax.psum(x, "j"), axis_name="i")(xs)  # finding

"""byzlint fixture: DONATION false-positive guards — the sanctioned
rebind-the-result idioms must stay silent."""

from functools import partial

import jax


def rebind_result(step_fn, state, batch):
    step = jax.jit(step_fn, donate_argnums=(0,))
    state = step(state, batch)  # rebound: later reads see the new buffer
    return state.mean()


def loop_with_rebind(step_fn, state, opt_state, batches):
    step = jax.jit(step_fn, donate_argnums=(0, 1))
    for batch in batches:
        state, opt_state = step(state, opt_state, batch)
    return state, opt_state


@partial(jax.jit, donate_argnums=(0,))
def fold(buf, row):
    return buf.at[0].add(row)


def decorated_rebind(buf, rows):
    for row in rows:
        buf = fold(buf, row)
    return buf


def non_donating_call(step_fn, state, batch):
    step = jax.jit(step_fn)  # no donation: free to keep reading state
    out = step(state, batch)
    return out, state.mean()

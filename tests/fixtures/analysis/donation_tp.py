"""byzlint fixture: DONATION true positives (never imported)."""

from functools import partial

import jax


def read_after_donate(step_fn, state, batch):
    step = jax.jit(step_fn, donate_argnums=(0,))
    new_state = step(state, batch)
    return new_state, state.mean()  # finding: state's buffer was donated


def loop_without_rebind(step_fn, state, batches):
    step = jax.jit(step_fn, donate_argnums=(0,))
    losses = []
    for batch in batches:
        losses.append(step(state, batch))  # finding: iteration 2 reuses state
    return losses


@partial(jax.jit, donate_argnums=(0,))
def fold(buf, row):
    return buf.at[0].add(row)


def decorated_read_after_donate(buf, row):
    out = fold(buf, row)
    return out + buf  # finding: buf donated to fold above


def read_and_rebind(step_fn, state, batch):
    step = jax.jit(step_fn, donate_argnums=(0,))
    out = step(state, batch)
    state = state + out  # finding: RHS reads the donated buffer first
    return state

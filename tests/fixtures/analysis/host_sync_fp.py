"""byzlint fixture: HOST-SYNC false-positive guards."""

from functools import partial

import numpy as np

import jax


def host_metrics(x):
    # not traced: .item()/np.asarray are ordinary host code here
    return float(np.asarray(x).mean()), x.sum().item()


@partial(jax.jit, static_argnames=("scale",))
def static_arg_conversion(x, scale):
    # scale is static: float() runs on a real python value pre-bake
    return x * float(scale)


def wrapper(x):
    arr = np.asarray(x)  # pre-trace staging is fine

    @jax.jit
    def inner(y):
        return y * 2

    return inner(arr).item()  # host boundary, outside the traced body

"""byzlint fixture: HOST-SYNC true positives (never imported)."""

import numpy as np

import jax
import jax.numpy as jnp


@jax.jit
def item_under_jit(x):
    return jnp.full((3,), x.mean().item())  # finding: host sync in trace


@jax.jit
def numpy_under_jit(x):
    return jnp.asarray(np.asarray(x) * 2)  # finding: numpy materialization


@jax.jit
def float_of_param(x):
    return x / float(x)  # finding: python conversion of traced arg

"""byzlint fixture: METRIC-CONTRACT false-positive guards.

Catalogued names with matching types, declared dynamic families, and
the shapes the rule must resolve to nothing: computed names (silent by
design) and non-registry ``.counter``/``.span`` lookalikes.
"""

import re

from byzpy_tpu.observability import tracing


def register(reg, tenant):
    rounds = reg.counter("byzpy_serving_rounds_total", help="catalogued")
    depth = reg.gauge("byzpy_serving_queue_depth", help="catalogued")
    logged = reg.gauge("byzpy_logged_loss", help="dynamic family")
    # computed names can't be checked statically — silent by design
    custom = reg.counter(f"byzpy_{tenant}_total", help="computed")
    return rounds, depth, logged, custom


def run_round(payload, kind):
    with tracing.span("serving.round", tenant="t0", round=1):
        tracing.instant(f"chaos.{kind}", vt=0.0)  # computed: silent
        tracing.instant("chaos.drop", vt=0.0)  # declared prefix family
        return payload


def lookalikes(text):
    match = re.match(r"(a)(b)", text)
    span = match.span(1)  # re.Match.span is not a tracing span
    parser = _FieldParser()
    return span, parser.counter("fields")  # non-registry receiver


class _FieldParser:
    def counter(self, name):
        return name

"""byzlint fixture: METRIC-CONTRACT true positives (never imported).

Instruments drifting from the observability catalog: an uncatalogued
metric name, a catalogued name registered under the wrong type, and a
span label the taxonomy has never heard of.
"""

from byzpy_tpu.observability import tracing


def register(reg):
    # finding: not in byzpy_tpu/observability/catalog.py
    bogus = reg.counter("byzpy_bogus_total", help="made-up counter")
    # finding: catalogued as a counter, registered as a gauge
    drift = reg.gauge("byzpy_serving_rounds_total", help="wrong type")
    return bogus, drift


def run_phase(payload):
    # finding: span label missing from the taxonomy
    with tracing.span("serving.bogus_phase", tenant="t0"):
        return payload

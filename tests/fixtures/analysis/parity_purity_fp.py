"""byzlint fixture: PARITY-PURITY false-positive guards.

Determinism done right inside the parity set — ``sorted(...)``
launders set order — and nondeterminism that is fine because it never
reaches parity-pinned code.
"""

import time


def combine_partials(parts):
    total = 0.0
    for digest in sorted({p for p in parts}):  # sorted: order is pinned
        total += len(digest)
    for p in parts:  # list iteration keeps arrival order
        total += 1.0
    return total


def evidence_digest(vec):
    return sum(vec)


def observe_latency(metrics_sink):
    # clocks are fine outside the parity set
    metrics_sink.observe(time.monotonic())


def _timer_helper():
    return time.perf_counter()


def report_stats(sink):
    # _timer_helper is only ever called from non-parity code
    sink.push(_timer_helper())

"""byzlint fixture: PARITY-PURITY true positives (never imported).

The PR 7 class of bug: nondeterminism inside functions on the
digest-parity contract — a clock read, an RNG draw, and bare-set
iteration order leaking into folded bytes.
"""

import random
import time

import numpy as np


def fold_merge_add(acc, row):
    acc["stamp"] = time.monotonic()  # finding: clock in a parity fold
    acc["rows"].append(row)
    return acc


def combine_partials(parts):
    jitter = random.random()  # finding: RNG in a parity combine
    total = 0.0
    for digest in {p for p in parts}:  # finding: bare-set iteration
        total += len(digest)
    return total + jitter


def evidence_digest(vec):
    return _score_helper(vec)


def _score_helper(vec):
    # finding: parity-reachable from evidence_digest
    return vec + np.random.normal()

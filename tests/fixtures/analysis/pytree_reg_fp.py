"""byzlint fixture: PYTREE-REG false-positive guards."""

from typing import NamedTuple

import jax
from jax import lax


@jax.tree_util.register_pytree_node_class
class RegisteredPacket:
    """QuantizedBlocks-style registered container."""

    def __init__(self, codes, scales):
        self.codes = codes
        self.scales = scales

    def tree_flatten(self):
        return (self.codes, self.scales), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


class TuplePacket(NamedTuple):
    codes: object
    scales: object


def exchange_registered(codes, scales, perm):
    pkt = RegisteredPacket(codes, scales)
    return lax.ppermute(pkt, "ring", perm)


def exchange_namedtuple(codes, scales, perm):
    return lax.ppermute(TuplePacket(codes, scales), "ring", perm)


def exchange_array(x, perm):
    # plain arrays / externally-defined types are out of scope
    return lax.ppermute(x, "ring", perm)

"""byzlint fixture: PYTREE-REG true positives (never imported)."""

from dataclasses import dataclass

from jax import lax


@dataclass
class WirePacket:
    codes: object
    scales: object


def exchange(codes, scales, perm):
    pkt = WirePacket(codes, scales)
    return lax.ppermute(pkt, "ring", perm)  # finding: not a pytree


def gather(codes, scales):
    # constructed inline in the collective call
    return lax.all_gather(WirePacket(codes, scales), "nodes")  # finding

"""byzlint fixture: suppression syntax + the unused-suppression check."""

import time


async def tolerated_block():
    # deliberate: fixture exercises the trailing-comment suppression form
    time.sleep(0.01)  # byzlint: ignore[ASYNC-BLOCKING]


async def tolerated_block_ownline():
    # byzlint: ignore[ASYNC-BLOCKING]
    time.sleep(0.01)


async def tolerated_multiline(worker_proc):
    # trailing comment on the LAST line of a wrapped statement must still
    # reach the finding anchored on its first line
    worker_proc.join(
        5,
    )  # byzlint: ignore[ASYNC-BLOCKING]


def perfectly_fine():
    return 1  # byzlint: ignore[DONATION] — stale: must raise UNUSED-IGNORE

"""byzlint fixture: THREAD-SHARED false-positive guards.

The sanctioned patterns: every cross-context write under one common
lock, single-context confinement (the PR 19 epoch-stamped handoff
settles on the loop only), and construction-time initialization.
"""

import threading


class LockedCoordinator:
    """Every cross-context write serialized under the same lock."""

    def __init__(self):
        self.staging = {}
        self._stats_lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._reader_loop, daemon=True).start()

    def _reader_loop(self):
        with self._stats_lock:
            self.staging["k"] = "verdict"

    async def _finish(self):
        with self._stats_lock:
            self.staging = {}


class ConfinedCoordinator:
    """Single-context confinement: only the loop ever writes; the
    reader thread hands work over via a queue (reads don't count)."""

    def __init__(self):
        self.staging = {}
        self.pending = []

    def start(self):
        threading.Thread(target=self._reader_loop, daemon=True).start()

    def _reader_loop(self):
        while self.staging:  # read-only on the thread side
            pass

    async def _finish(self, key, verdict):
        self.staging[key] = verdict
        self.staging = dict(self.staging)


class InitOnlyState:
    """__init__ writes happen before the object is published."""

    def __init__(self):
        self.table = {}
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        self.table["k"] = 1  # the only post-publication writer


class TwoLoopMethods:
    """Two writers, both on the event loop: one context, no race."""

    def __init__(self):
        self.rounds = 0

    async def close(self):
        self.rounds += 1

    async def repair(self):
        self.rounds += 1

"""byzlint fixture: THREAD-SHARED true positives (never imported).

Minimized PR 19 incident: the root's arrival-time dedup staging table
was written by proxy reader threads while the loop-side close settled
it — no common lock, so staged verdicts vanished mid-settle.
"""

import threading


class RootCoordinator:
    def __init__(self):
        self.staging = {}
        self.callback_errors = 0
        self._reader = None

    def start(self):
        self._reader = threading.Thread(
            target=self._reader_loop, daemon=True
        )
        self._reader.start()

    def _recv(self):
        return object()

    def _reader_loop(self):
        while True:
            partial = self._recv()
            if partial is None:
                self._on_observer_error()
                continue
            # finding: thread-side write, loop-side settle, no lock
            self.staging[partial] = "verdict"

    def _on_observer_error(self):
        # called from the reader loop too — lost-update increment
        self.callback_errors += 1

    async def _finish(self, closed):
        try:
            for key in closed:
                self._publish(key)
        except Exception:  # noqa: BLE001 — observer bug, counted
            self.callback_errors += 1  # finding: `+=` from two contexts
        self.staging = {}  # settles the table on the event loop

    def _publish(self, key):
        return key

"""byzlint fixture: TRACE-DISPATCH false-positive guards — the PR-2
wrapper pattern (env/tile dispatch resolved pre-trace) must stay silent.
"""

import os
from functools import partial

import jax


def dispatch_wrapper(x):
    # env + tile-cache reads OUTSIDE the traced body: the sanctioned spot
    tile = int(os.environ.get("BYZPY_TPU_FAKE_TILE", "128"))
    mode = os.getenv("BYZPY_TPU_FAKE_MODE", "auto")

    @partial(jax.jit, static_argnums=(1, 2))
    def inner(y, tile, mode):
        return y * tile if mode == "auto" else y

    return inner(x, tile, mode)


def plain_helper():
    # not traced at all: env reads are ordinary host code here
    return os.environ.get("HOME")

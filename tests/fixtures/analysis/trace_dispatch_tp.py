"""byzlint fixture: TRACE-DISPATCH true positives (never imported)."""

import os
from functools import partial

import jax


@jax.jit
def env_read_under_jit(x):
    flag = os.environ.get("BYZPY_TPU_FAKE_FLAG")  # finding: env read in trace
    return -x if flag else x


@partial(jax.jit, static_argnames=("n",))
def getenv_under_jit(x, n):
    return x * int(os.getenv("BYZPY_TPU_FAKE_TILE", "128"))  # finding


def make_kernel(x):
    def traced(y):
        tile = _tuned_tile("sort", 8, y.shape[0])  # finding: dispatch helper
        return y * tile

    return jax.jit(traced)(x)


def _tuned_tile(family, n, d):
    return 128

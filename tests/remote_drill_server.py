"""Standalone actor-server host for the multi-host fault drills.

Runs a :class:`RemoteActorServer` on a loopback port in its OWN OS
process, prints ``PORT <n>`` once ready, and serves until killed — the
drills in ``test_multihost.py`` SIGKILL it mid-round to exercise the
elastic PS path against a genuine host death (not a graceful close).

The node class lives here (not in the test module) so the server process
can resolve it by reference when the client ships it over the wire.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root
sys.path.insert(0, _HERE)  # this dir, for class-by-reference resolution

import numpy as np

from byzpy_tpu.engine.node.base import HonestNode

D = 32


class SlowRemoteNode(HonestNode):
    """Gradient takes ``delay`` seconds — a window wide enough for the
    drill to SIGKILL this host while the call is in flight."""

    def __init__(self, value: float, delay: float = 3.0) -> None:
        self.value = float(value)
        self.delay = float(delay)

    def next_batch(self):
        return None, None

    def honest_gradient(self, x, y):
        time.sleep(self.delay)
        return [np.full(D, self.value, np.float32)]

    def apply_server_gradient(self, g) -> None:
        pass


async def _serve() -> None:
    from byzpy_tpu.engine.actor.backends.remote import RemoteActorServer

    server = RemoteActorServer("127.0.0.1", 0)
    await server.start()
    print(f"PORT {server.port}", flush=True)
    await asyncio.Event().wait()  # until killed


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from byzpy_tpu.utils.platform import apply_env_platform

    apply_env_platform()
    asyncio.run(_serve())

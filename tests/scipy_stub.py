"""Inverse normal CDF oracle for attack tests: scipy if present, else the
Acklam rational approximation (the same family the reference hand-rolls)."""

import math

try:
    from scipy.special import ndtri as ndtri_oracle  # type: ignore
except Exception:  # pragma: no cover - environment-dependent

    def ndtri_oracle(p: float) -> float:
        eps = 1e-12
        p = min(max(p, eps), 1.0 - eps)
        a = [-3.969683028665376e01, 2.209460984245205e02, -2.759285104469687e02,
             1.383577518672690e02, -3.066479806614716e01, 2.506628277459239e00]
        b = [-5.447609879822406e01, 1.615858368580409e02, -1.556989798598866e02,
             6.680131188771972e01, -1.328068155288572e01]
        c = [-7.784894002430293e-03, -3.223964580411365e-01, -2.400758277161838e00,
             -2.549732539343734e00, 4.374664141464968e00, 2.938163982698783e00]
        d = [7.784695709041462e-03, 3.224671290700398e-01, 2.445134137142996e00,
             3.754408661907416e00]
        plow, phigh = 0.02425, 1.0 - 0.02425
        if p < plow:
            q = math.sqrt(-2.0 * math.log(p))
            return ((((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4]) * q + c[5])
                    / ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1.0))
        if p > phigh:
            q = math.sqrt(-2.0 * math.log(1.0 - p))
            return -((((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4]) * q + c[5])
                     / ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1.0))
        q = p - 0.5
        r = q * q
        return ((((((a[0] * r + a[1]) * r + a[2]) * r + a[3]) * r + a[4]) * r + a[5]) * q
                / (((((b[0] * r + b[1]) * r + b[2]) * r + b[3]) * r + b[4]) * r + 1.0))

"""Actor runtime tests: thread / tpu / process / remote-TCP backends.

pytest-asyncio is not available in this environment; tests drive their own
event loop with asyncio.run().
"""

import asyncio

import numpy as np
import pytest

from byzpy_tpu.engine.actor import resolve_backend
from byzpy_tpu.engine.actor.base import ActorRef, spawn_actor
from byzpy_tpu.engine.actor.backends.remote import RemoteActorServer
from byzpy_tpu.engine.actor.channels import Endpoint


class Counter:
    def __init__(self, start=0):
        self.value = start

    def incr(self, by=1):
        self.value += by
        return self.value

    async def async_incr(self, by=1):
        await asyncio.sleep(0)
        self.value += by
        return self.value

    def boom(self):
        raise ValueError("kaboom")

    def echo_array(self, arr):
        return arr * 2


def test_thread_backend_rpc_and_errors():
    async def main():
        ref = await spawn_actor(resolve_backend("thread"), Counter, 10)
        assert await ref.incr() == 11
        assert await ref.incr(by=5) == 16
        assert await ref.async_incr() == 17
        with pytest.raises(ValueError, match="kaboom"):
            await ref.boom()
        await ref.backend.close()

    asyncio.run(main())


def test_thread_backend_channels_cross_actor():
    async def main():
        a = resolve_backend("thread")
        b = resolve_backend("thread")
        ra = await spawn_actor(a, Counter)
        rb = await spawn_actor(b, Counter)
        await a.chan_open("gossip")
        await b.chan_open("gossip")
        # a sends into b's mailbox via the router
        await a.chan_put("gossip", {"v": 42}, endpoint=b.get_endpoint())
        got = await b.chan_get("gossip")
        assert got == {"v": 42}
        # send to an unknown endpoint errors
        with pytest.raises(LookupError):
            await a.chan_put("gossip", 1, endpoint=Endpoint("thread", "local", "nope"))
        await a.close()
        await b.close()

    asyncio.run(main())


def test_tpu_backend_pins_device():
    import jax

    async def main():
        backend = resolve_backend("tpu:3")
        ref = await spawn_actor(backend, Counter)

        # method that creates a device array must land on the pinned device
        class Maker:
            def make(self):
                import jax.numpy as jnp

                return jnp.ones((4,))

        mk = resolve_backend("tpu:3")
        mref = await spawn_actor(mk, Maker)
        arr = await mref.make()
        assert list(arr.devices())[0] == jax.devices()[3]
        assert await ref.incr() == 1
        await backend.close()
        await mk.close()

    asyncio.run(main())


def test_process_backend_rpc_channels_and_errors():
    async def main():
        backend = resolve_backend("process")
        ref = await spawn_actor(backend, Counter, 100)
        assert await ref.incr(by=2) == 102
        # numpy payload round-trip
        out = await ref.echo_array(np.arange(4.0))
        np.testing.assert_allclose(out, np.arange(4.0) * 2)
        # concurrent chan_get + call must not deadlock (req-id protocol)
        await backend.chan_open("inbox")
        getter = asyncio.ensure_future(backend.chan_get("inbox"))
        await asyncio.sleep(0.05)
        assert await ref.incr() == 103  # call completes while chan_get blocked
        await backend.chan_put("inbox", "hello")
        assert await getter == "hello"
        with pytest.raises(RuntimeError, match="kaboom"):
            await ref.boom()
        await backend.close()

    asyncio.run(main())


def test_process_backend_close_keeps_loop_responsive():
    # regression (byzlint ASYNC-BLOCKING): close() used to call
    # self._proc.join(timeout=5) directly on the event loop — a slow
    # child froze every other actor for the full timeout. The join must
    # run on an executor thread so the loop keeps ticking.
    import time

    from byzpy_tpu.engine.actor.backends.process import ProcessActorBackend

    class SlowJoinProc:
        def join(self, timeout=None):
            time.sleep(0.5)  # simulated slow child shutdown (sync thread)

        def is_alive(self):
            return False

        def kill(self):
            pass

    async def main():
        backend = ProcessActorBackend()
        backend._started = True
        backend._proc = SlowJoinProc()

        gaps = []

        async def ticker():
            loop = asyncio.get_running_loop()
            prev = loop.time()
            while True:
                await asyncio.sleep(0.01)
                now = loop.time()
                gaps.append(now - prev)
                prev = now

        t = asyncio.ensure_future(ticker())
        await backend.close()
        t.cancel()
        # the 0.5s join ran off-loop: no tick gap anywhere near it
        assert gaps and max(gaps) < 0.3, f"loop stalled {max(gaps):.3f}s"
        assert backend._proc is None and not backend._started

    asyncio.run(main())


def test_remote_tcp_backend():
    async def main():
        server = RemoteActorServer("127.0.0.1", 0)
        await server.start()
        try:
            spec = f"tcp://127.0.0.1:{server.port}"
            backend = resolve_backend(spec)
            ref = await spawn_actor(backend, Counter, 5)
            assert await ref.incr() == 6
            out = await ref.echo_array(np.ones(3))
            np.testing.assert_allclose(out, 2 * np.ones(3))
            # channels on the server-hosted actor
            await backend.chan_open("c")
            getter = asyncio.ensure_future(backend.chan_get("c"))
            await asyncio.sleep(0.05)
            assert await ref.incr() == 7  # interleaved call while get pending
            await backend.chan_put("c", {"x": 1})
            assert await getter == {"x": 1}
            with pytest.raises(RuntimeError, match="kaboom"):
                await ref.boom()
            await backend.close()
        finally:
            await server.close()

    asyncio.run(main())


def test_remote_server_close_with_live_connections():
    """Server close must not hang while clients are connected (py3.12
    Server.wait_closed waits on handlers) and must fail pending requests."""

    async def main():
        server = RemoteActorServer("127.0.0.1", 0)
        await server.start()
        backend = resolve_backend(f"tcp://127.0.0.1:{server.port}")
        ref = await spawn_actor(backend, Counter)
        assert await ref.incr() == 1
        pending = asyncio.ensure_future(backend.chan_get("never"))
        await asyncio.sleep(0.05)
        await asyncio.wait_for(server.close(), timeout=5)  # must not hang
        with pytest.raises((ConnectionError, asyncio.TimeoutError)):
            await asyncio.wait_for(pending, 5)
        await backend.close()

    asyncio.run(main())


def test_factory_specs():
    assert resolve_backend("thread").scheme == "thread"
    assert resolve_backend("process").scheme == "process"
    assert resolve_backend("tpu").scheme == "tpu"
    assert resolve_backend("tpu:1").device_index == 1
    b = resolve_backend("tcp://h:1234")
    assert (b.host, b.port) == ("h", 1234)
    with pytest.raises(ValueError):
        resolve_backend("gpu")
    with pytest.raises(ValueError):
        resolve_backend("tcp://missingport")

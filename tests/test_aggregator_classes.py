"""Class-layer tests: every aggregator/pre-aggregator class, pytree I/O,
direct-vs-pool-subtask parity (the reference's key invariant), and
run_operator integration."""

import asyncio

import numpy as np
import jax.numpy as jnp
import pytest

from byzpy_tpu import run_operator
from byzpy_tpu.aggregators import (
    CAF,
    CenteredClipping,
    ComparativeGradientElimination,
    CoordinateWiseMedian,
    CoordinateWiseTrimmedMean,
    GeometricMedian,
    Krum,
    MeanOfMedians,
    MinimumDiameterAveraging,
    MoNNA,
    MultiKrum,
    SMEA,
)
from byzpy_tpu.engine.graph import ActorPool, ActorPoolConfig
from byzpy_tpu.pre_aggregators import ARC, Bucketing, Clipping, NearestNeighborMixing


def grads(n=10, d=65, seed=0):
    r = np.random.default_rng(seed)
    return [jnp.asarray(r.normal(size=d).astype(np.float32)) for _ in range(n)]


def tree_grads(n=8, seed=1):
    r = np.random.default_rng(seed)
    return [
        {"w": jnp.asarray(r.normal(size=(5, 4)).astype(np.float32)),
         "b": jnp.asarray(r.normal(size=4).astype(np.float32))}
        for _ in range(n)
    ]


ALL_AGGREGATORS = [
    CoordinateWiseMedian(),
    CoordinateWiseTrimmedMean(f=2),
    MeanOfMedians(f=2),
    MultiKrum(f=2, q=3),
    Krum(f=2),
    GeometricMedian(),
    MinimumDiameterAveraging(f=2),
    MoNNA(f=2),
    SMEA(f=2),
    CenteredClipping(c_tau=1.0, M=5),
    CAF(f=2),
    ComparativeGradientElimination(f=2),
]


@pytest.mark.parametrize("agg", ALL_AGGREGATORS, ids=lambda a: a.name)
def test_aggregate_returns_input_shape(agg):
    gs = grads()
    out = agg.aggregate(gs)
    assert out.shape == gs[0].shape
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("agg", ALL_AGGREGATORS, ids=lambda a: a.name)
def test_aggregate_pytree_roundtrip(agg):
    gs = tree_grads()
    out = agg.aggregate(gs)
    assert set(out.keys()) == {"w", "b"}
    assert out["w"].shape == (5, 4)
    assert out["b"].shape == (4,)


SUBTASK_AGGREGATORS = [
    CoordinateWiseMedian(chunk_size=16),
    CoordinateWiseTrimmedMean(f=2, chunk_size=16),
    MeanOfMedians(f=2, chunk_size=16),
    MultiKrum(f=2, q=3, chunk_size=3),
    Krum(f=2, chunk_size=3),
    MoNNA(f=2, chunk_size=3),
    ComparativeGradientElimination(f=2, chunk_size=3),
    MinimumDiameterAveraging(f=2, chunk_size=10),
    SMEA(f=2, chunk_size=10),
]


@pytest.mark.parametrize("agg", SUBTASK_AGGREGATORS, ids=lambda a: a.name)
def test_direct_vs_pool_subtask_parity(agg):
    """The pool-chunked path must produce the same result as aggregate()."""
    gs = grads(n=9, d=47, seed=3)
    direct = np.asarray(agg.aggregate(gs))

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=3)) as pool:
            return await run_operator(agg, gs, pool=pool)

    pooled = np.asarray(asyncio.run(main()))
    np.testing.assert_allclose(pooled, direct, rtol=1e-5, atol=1e-6)


def test_robustness_under_outliers():
    """All f-tolerant aggregators must shrug off f strong outliers."""
    gs = grads(n=8, d=30, seed=5)
    honest_mean = np.stack([np.asarray(g) for g in gs]).mean(0)
    poisoned = gs + [jnp.full((30,), 1e4), jnp.full((30,), -1e4)]
    for agg in [
        CoordinateWiseMedian(),
        CoordinateWiseTrimmedMean(f=2),
        MeanOfMedians(f=2),
        MultiKrum(f=2, q=3),
        MinimumDiameterAveraging(f=2),
        MoNNA(f=2),
        SMEA(f=2),
        ComparativeGradientElimination(f=2),
        CAF(f=2),
    ]:
        out = np.asarray(agg.aggregate(poisoned))
        assert np.linalg.norm(out - honest_mean) < 10.0, agg.name


def test_validation_errors():
    gs = grads(n=5)
    with pytest.raises(ValueError):
        CoordinateWiseTrimmedMean(f=3).aggregate(gs)
    with pytest.raises(ValueError):
        MultiKrum(f=4, q=1).aggregate(gs)
    with pytest.raises(ValueError):
        MoNNA(f=3).aggregate(gs)
    with pytest.raises(ValueError):
        CoordinateWiseTrimmedMean(f=-1)
    with pytest.raises(ValueError):
        MultiKrum(f=1, q=0)
    with pytest.raises(ValueError):
        GeometricMedian(init="bogus")


def test_matrix_input_accepted():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(6, 12)).astype(np.float32))
    out = CoordinateWiseMedian().aggregate(x)
    np.testing.assert_allclose(
        np.asarray(out), np.median(np.asarray(x), axis=0), rtol=1e-5, atol=1e-6
    )


# ---------------------------------------------------------------------------
# pre-aggregators
# ---------------------------------------------------------------------------


def test_clipping_class():
    vs = grads(n=6, d=20, seed=7)
    out = Clipping(threshold=0.5).pre_aggregate(vs)
    assert len(out) == 6
    for v in out:
        assert float(jnp.linalg.norm(v)) <= 0.5 + 1e-4


def test_bucketing_class_counts_and_mean_preservation():
    vs = grads(n=10, d=8, seed=8)
    b = Bucketing(bucket_size=3, seed=42)
    out = b.pre_aggregate(vs)
    assert len(out) == 4  # ceil(10/3)
    # bucketing preserves the weighted overall mean up to ragged-bucket weights
    out2 = Bucketing(bucket_size=3, seed=42).pre_aggregate(vs)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out2[0]), rtol=1e-6)
    # explicit identity perm gives deterministic buckets
    ident = Bucketing(bucket_size=5, perm=list(range(10))).pre_aggregate(vs)
    np.testing.assert_allclose(
        np.asarray(ident[0]),
        np.stack([np.asarray(v) for v in vs[:5]]).mean(0),
        rtol=1e-5, atol=1e-6,
    )


def test_nnm_class():
    vs = grads(n=8, d=10, seed=9)
    out = NearestNeighborMixing(f=2).pre_aggregate(vs)
    assert len(out) == 8
    with pytest.raises(ValueError):
        NearestNeighborMixing(f=8).pre_aggregate(vs)


def test_combination_unranking():
    from itertools import combinations, islice

    from byzpy_tpu.utils.combinatorics import iter_combinations, unrank_combination

    for n, m in [(6, 3), (8, 5), (5, 1), (5, 5)]:
        ref = list(combinations(range(n), m))
        assert list(iter_combinations(n, m)) == ref
        for start in sorted({0, len(ref) // 2, len(ref) - 1}):
            assert unrank_combination(n, m, start) == ref[start]
            assert list(iter_combinations(n, m, start)) == ref[start:]
    with pytest.raises(ValueError):
        unrank_combination(5, 2, 10)


def test_arc_class():
    vs = grads(n=8, d=10, seed=10)
    vs[3] = vs[3] * 100
    out = ARC(f=2).pre_aggregate(vs)
    norms_in = [float(jnp.linalg.norm(v)) for v in vs]
    norms_out = [float(jnp.linalg.norm(v)) for v in out]
    assert norms_out[3] < norms_in[3]  # big vector clipped
    assert len(out) == 8


@pytest.mark.parametrize(
    "agg", [GeometricMedian(), CenteredClipping(c_tau=1.0, M=5)],
    ids=lambda a: a.name,
)
def test_barriered_pool_parity(agg):
    """The barriered pool path (per-iteration fan-out + coordinator reduce,
    the reference's third execution mode) matches the fused lax-loop path."""
    assert type(agg).supports_barriered_subtasks
    agg.row_chunk_size = 3  # force several chunks with n=9
    gs = grads(n=9, d=47, seed=4)
    direct = np.asarray(agg.aggregate(gs))

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=3)) as pool:
            return await run_operator(agg, gs, pool=pool)

    pooled = np.asarray(asyncio.run(main()))
    np.testing.assert_allclose(pooled, direct, rtol=1e-4, atol=1e-5)


def test_barriered_single_worker_falls_back_to_fused():
    """With one worker the barriered dispatch routes to the single compiled
    program (strictly better on one device)."""
    agg = GeometricMedian()
    gs = grads(n=6, d=31, seed=5)
    direct = np.asarray(agg.aggregate(gs))

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=1)) as pool:
            return await run_operator(agg, gs, pool=pool)

    pooled = np.asarray(asyncio.run(main()))
    np.testing.assert_allclose(pooled, direct, rtol=1e-6)


def test_barriered_pytree_roundtrip():
    agg = CenteredClipping(c_tau=0.7, M=3)
    agg.row_chunk_size = 2
    gs = tree_grads(n=6, seed=7)
    direct = agg.aggregate(gs)

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            return await run_operator(agg, gs, pool=pool)

    pooled = asyncio.run(main())
    np.testing.assert_allclose(
        np.asarray(pooled["w"]), np.asarray(direct["w"]), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(pooled["b"]), np.asarray(direct["b"]), rtol=1e-4, atol=1e-5
    )


def test_smea_host_scorer_matches_device_op():
    """The host LAPACK scorer (production path) and the jitted device op
    robust.subset_max_eigvals are two implementations of one formula;
    divergence is a bug (ops/robust.py vs smea.py)."""
    import math

    from byzpy_tpu.aggregators.geometric_wise.minimum_diameter_average import (
        _combo_batches,
    )
    from byzpy_tpu.aggregators.geometric_wise.smea import _score_combo_range_smea
    from byzpy_tpu.ops import robust

    n, f = 9, 3
    m = n - f
    x = jnp.asarray(np.random.default_rng(0).normal(size=(n, 64)).astype(np.float32))
    gram = robust.gram_matrix(x)
    total = math.comb(n, m)
    combos = np.concatenate(list(_combo_batches(n, m, total)))[:total]
    device_scores = np.asarray(robust.subset_max_eigvals(gram, jnp.asarray(combos)))
    host_best_score, host_best = _score_combo_range_smea(
        np.asarray(gram), n, m, 0, total
    )
    np.testing.assert_allclose(
        host_best_score, float(device_scores.min()), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_array_equal(host_best, combos[int(device_scores.argmin())])


def test_smea_tolerates_nonfinite_byzantine_rows():
    """An adversary submitting NaN/inf gradients must neither crash the
    LAPACK eigensolver nor be selected into the winning subset."""
    r = np.random.default_rng(1)
    honest = [jnp.asarray(r.normal(size=128).astype(np.float32)) for _ in range(7)]
    nan_row = jnp.full((128,), jnp.nan)
    inf_row = jnp.full((128,), jnp.inf)
    agg = SMEA(f=2)
    out = np.asarray(agg.aggregate(honest + [nan_row, inf_row]))
    assert np.isfinite(out).all()
    # with n=9, f=2 the only finite-scoring subset is exactly the 7 honest
    # rows, so the result must be their mean — the bad rows were excluded
    honest_mean = np.stack([np.asarray(h) for h in honest]).mean(0)
    np.testing.assert_allclose(out, honest_mean, rtol=1e-5, atol=1e-6)


def test_smea_device_path_matches_host_path():
    """The device-pure Jacobi path (combo spaces <= _DEVICE_COMBO_CAP) and
    the host LAPACK path must pick the same subset."""
    import math

    from byzpy_tpu.aggregators.geometric_wise import smea as smea_mod

    rng = np.random.default_rng(5)
    grads = [jnp.asarray(rng.normal(size=(96,)).astype(np.float32)) for _ in range(12)]
    agg = SMEA(f=3)
    got = np.asarray(agg.aggregate(grads))
    x = np.stack([np.asarray(g) for g in grads])
    n, m = 12, 9
    gram = x @ x.T
    _, best = smea_mod._score_combo_range_smea(gram, n, m, 0, math.comb(n, m))
    want = x[best].mean(axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_aggregate_stream_class_api_matches_per_round():
    """K buffered rounds through Aggregator.aggregate_stream must equal K
    separate aggregate() calls — for a class with a fused stream override
    (MultiKrum), a coordinate-wise one (median), and the default scan
    path (CenteredClipping)."""
    rng = np.random.default_rng(9)
    rounds = [
        [jnp.asarray(rng.normal(size=(40,)).astype(np.float32)) for _ in range(9)]
        for _ in range(3)
    ]
    for agg in (MultiKrum(f=2, q=4), CoordinateWiseMedian(), CenteredClipping(c_tau=1.0)):
        got = agg.aggregate_stream(rounds)
        assert len(got) == 3
        for k in range(3):
            want = agg.aggregate(rounds[k])
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(want), rtol=1e-5, atol=1e-6
            )
    assert MultiKrum(f=2, q=4).aggregate_stream([]) == []


def test_aggregate_stream_preserves_pytree_structure():
    rng = np.random.default_rng(10)
    def tree():
        return {"w": jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32)),
                "b": jnp.asarray(rng.normal(size=(5,)).astype(np.float32))}
    rounds = [[tree() for _ in range(6)] for _ in range(2)]
    out = CoordinateWiseMedian().aggregate_stream(rounds)
    assert set(out[0].keys()) == {"w", "b"}
    assert out[0]["w"].shape == (4, 3)
    want = CoordinateWiseMedian().aggregate(rounds[1])
    np.testing.assert_allclose(
        np.asarray(out[1]["b"]), np.asarray(want["b"]), rtol=1e-6
    )


def test_cge_monna_stream_overrides_match_per_round():
    rng = np.random.default_rng(11)
    rounds = [
        [jnp.asarray(rng.normal(size=(32,)).astype(np.float32)) for _ in range(8)]
        for _ in range(2)
    ]
    for agg in (ComparativeGradientElimination(f=2), MoNNA(f=2)):
        got = agg.aggregate_stream(rounds)
        for k in range(2):
            want = agg.aggregate(rounds[k])
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(want), rtol=1e-5, atol=1e-6
            )


def test_smea_large_subset_takes_host_path(monkeypatch):
    """m > 32 exceeds the fixed-sweep Jacobi precision envelope: the
    aggregate must route to exact host LAPACK even when the combo count
    fits the device cap."""
    from byzpy_tpu.aggregators.geometric_wise import smea as smea_mod

    def boom(*a, **k):
        raise AssertionError("device Jacobi path used for m > 32")

    monkeypatch.setattr(smea_mod, "_smea_select_mean", boom)
    rng = np.random.default_rng(6)
    grads = [jnp.asarray(rng.normal(size=(48,)).astype(np.float32)) for _ in range(36)]
    agg = SMEA(f=2)  # m = 34 > 32, comb(36, 34) = 630 <= cap
    out = np.asarray(agg.aggregate(grads))
    assert np.isfinite(out).all()

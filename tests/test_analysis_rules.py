"""Per-rule golden tests for the byzlint engine.

Every rule has at least one true-positive fixture (must fire) and one
false-positive guard (must stay silent), run against the checked-in
fixture files under ``tests/fixtures/analysis/`` — the same corpus a
rule author reaches for when extending the engine (see
``docs/static_analysis.md``).
"""

from __future__ import annotations

import os

import pytest

from byzpy_tpu.analysis import UNUSED_IGNORE, scan_paths
from byzpy_tpu.analysis.rules import (
    ACK_ORDER,
    ALL_RULES,
    ASYNC_BLOCKING,
    AXIS_BINDING,
    DONATION,
    HOST_SYNC,
    METRIC_CONTRACT,
    PARITY_PURITY,
    PYTREE_REG,
    THREAD_SHARED,
    TRACE_DISPATCH,
)

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "fixtures", "analysis")


def fixture(name: str) -> str:
    path = os.path.join(FIXTURES, name)
    assert os.path.exists(path), f"missing fixture {name}"
    return path


def findings_for(name: str, rule: str):
    result = scan_paths([fixture(name)], select=[rule])
    return [f for f in result.findings if f.rule == rule]


RULE_FIXTURES = {
    TRACE_DISPATCH: ("trace_dispatch_tp.py", "trace_dispatch_fp.py", 3),
    DONATION: ("donation_tp.py", "donation_fp.py", 4),
    AXIS_BINDING: ("axis_binding_tp.py", "axis_binding_fp.py", 3),
    HOST_SYNC: ("host_sync_tp.py", "host_sync_fp.py", 3),
    ASYNC_BLOCKING: ("async_blocking_tp.py", "async_blocking_fp.py", 5),
    PYTREE_REG: ("pytree_reg_tp.py", "pytree_reg_fp.py", 2),
    THREAD_SHARED: ("thread_shared_tp.py", "thread_shared_fp.py", 2),
    ACK_ORDER: ("ack_order_tp.py", "ack_order_fp.py", 3),
    PARITY_PURITY: ("parity_purity_tp.py", "parity_purity_fp.py", 4),
    METRIC_CONTRACT: ("metric_contract_tp.py", "metric_contract_fp.py", 3),
}


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_true_positive_fires(rule):
    tp, _fp, expected = RULE_FIXTURES[rule]
    found = findings_for(tp, rule)
    assert len(found) == expected, (
        f"{rule} on {tp}: expected {expected} findings, got "
        f"{[f.render() for f in found]}"
    )
    # findings carry usable locations
    for f in found:
        assert f.line > 0 and f.message


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_false_positive_guard_silent(rule):
    _tp, fp, _n = RULE_FIXTURES[rule]
    found = findings_for(fp, rule)
    assert found == [], (
        f"{rule} fired on its false-positive guard {fp}: "
        f"{[f.render() for f in found]}"
    )


def test_every_shipped_rule_has_fixture_coverage():
    assert {r.id for r in ALL_RULES} == set(RULE_FIXTURES)


def test_suppression_silences_and_unused_is_reported():
    result = scan_paths([fixture("suppressions.py")])
    rules = [f.rule for f in result.findings]
    # all three ASYNC-BLOCKING hits are suppressed (trailing, own-line,
    # and trailing-on-the-last-line-of-a-wrapped-statement forms)
    assert ASYNC_BLOCKING not in rules
    assert result.suppressed == 3
    # the stale ignore[DONATION] surfaces as UNUSED-IGNORE
    assert rules == [UNUSED_IGNORE]


def test_select_filters_and_rejects_unknown_rules():
    result = scan_paths([fixture("donation_tp.py")], select=[DONATION])
    assert {f.rule for f in result.findings} == {DONATION}
    result = scan_paths([fixture("donation_tp.py")], select=[TRACE_DISPATCH])
    assert result.findings == []
    with pytest.raises(ValueError, match="unknown rule"):
        scan_paths([fixture("donation_tp.py")], select=["NO-SUCH-RULE"])


def test_docstring_mention_is_not_a_suppression():
    # the analysis package's own docs quote the ignore[...] syntax; the
    # tokenizer-based parser must not read docstrings as suppressions
    import byzpy_tpu.analysis as pkg

    pkg_dir = os.path.dirname(os.path.abspath(pkg.__file__))
    result = scan_paths([pkg_dir])
    assert [f.render() for f in result.findings] == []
    assert result.suppressed == 0


def test_json_and_text_rendering():
    import json

    from byzpy_tpu.analysis import render_json, render_text

    result = scan_paths([fixture("donation_tp.py")])
    text = render_text(result)
    assert "DONATION" in text and text.strip().endswith("0 suppressed")
    blob = json.loads(render_json(result))
    assert blob["clean"] is False
    assert blob["files_scanned"] == 1
    assert all(
        set(f) == {"rule", "path", "line", "col", "message"}
        for f in blob["findings"]
    )


def test_cli_exit_codes(capsys):
    from byzpy_tpu.analysis import main

    assert main([fixture("donation_fp.py")]) == 0
    assert main([fixture("donation_tp.py")]) == 1
    assert main(["--list-rules"]) == 0
    assert main([os.path.join(FIXTURES, "no_such_file.py")]) == 2
    capsys.readouterr()  # drain

"""Runtime invariant sanitizer (``byzpy_tpu.analysis.sanitize``).

The dynamic half of byzlint: hook-level teeth (the stall watchdog
fires on a deliberate block, the drain check trips on a leaked
partial, the fold audit catches a double fold) plus the wiring — a
real :class:`ServingFrontend` round close drives the exactly-once
audit, and a clean run records nothing. Digest parity of a sanitized
chaos run is pinned by the chaos bench's ``sanitize`` lane.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from byzpy_tpu.analysis import sanitize


@pytest.fixture(autouse=True)
def _sanitizer_on():
    """Each test gets a fresh, ENABLED sanitizer and leaves the
    process-wide singleton the way it found it."""
    was = sanitize.enabled()
    sanitize.enable()
    sanitize.reset()
    yield
    sanitize.reset()
    if not was:
        sanitize.disable()


def test_stall_watchdog_fires_on_deliberate_block():
    sanitize.loop_tick("t.loop", threshold_s=0.05)
    time.sleep(0.12)  # the blocking call the static rule couldn't see
    sanitize.loop_tick("t.loop", threshold_s=0.05)
    (violation,) = sanitize.violations()
    assert "loop-stall[t.loop]" in violation
    assert sanitize.counters()["loop_ticks"] == 2
    with pytest.raises(AssertionError, match="loop-stall"):
        sanitize.assert_clean()


def test_ticks_within_threshold_stay_clean():
    for _ in range(5):
        sanitize.loop_tick("t.loop", threshold_s=10.0)
    # independent loops do not share watchdog marks
    sanitize.loop_tick("t.other", threshold_s=1e-9)
    assert sanitize.violations() == []
    sanitize.assert_clean()


def test_drain_check_trips_on_leaked_partial():
    sanitize.check_drained("byzpy_root_partials_inflight", 0)
    assert sanitize.violations() == []
    sanitize.check_drained("byzpy_root_partials_inflight", 3)
    (violation,) = sanitize.violations()
    assert "leak[byzpy_root_partials_inflight]" in violation
    assert "3 still in flight" in violation
    assert sanitize.counters()["drain_checks"] == 2


def test_fold_audit_catches_double_fold_and_skips_legacy_seq():
    sanitize.audit_fold("m0", 0, [("a", 1), ("b", None)])
    sanitize.audit_fold("m0", 1, [("a", 2), ("b", None)])
    # seq=None (legacy clients) never dedups across rounds
    assert sanitize.violations() == []
    # replaying a round id = the PR 9 double-fold shape
    sanitize.audit_fold("m0", 1, [("c", 9)])
    # an idempotency key folding twice is its own violation
    sanitize.audit_fold("m0", 2, [("a", 2)])
    found = sanitize.violations()
    assert len(found) == 2
    assert "round 1 closed after round 1" in found[0]
    assert "(a, seq=2) folded twice" in found[1]
    # tenants are independent streams
    sanitize.audit_fold("m1", 0, [("a", 2)])
    assert len(sanitize.violations()) == 2


def test_disabled_hooks_are_inert():
    sanitize.disable()
    sanitize.loop_tick("t.loop", threshold_s=0.0)
    sanitize.audit_fold("m0", 0, [("a", 1)])
    sanitize.audit_fold("m0", 0, [("a", 1)])
    sanitize.check_drained("x", 99)
    assert sanitize.violations() == []
    assert all(v == 0 for v in sanitize.counters().values())


def test_env_flag_enables_at_construction(monkeypatch):
    from byzpy_tpu.analysis.sanitize import _Sanitizer

    monkeypatch.setenv("BYZPY_TPU_SANITIZE", "1")
    assert _Sanitizer().enabled
    monkeypatch.setenv("BYZPY_TPU_SANITIZE", "0")
    assert not _Sanitizer().enabled
    monkeypatch.delenv("BYZPY_TPU_SANITIZE")
    assert not _Sanitizer().enabled


def test_frontend_round_close_drives_the_fold_audit():
    """The wiring, not just the API: a real round close through
    ``close_round_nowait`` funnels into ``audit_fold`` with the
    cohort's (client, seq) keys, and a clean close records nothing."""
    from byzpy_tpu.aggregators import CoordinateWiseMedian
    from byzpy_tpu.serving import ServingFrontend, TenantConfig

    fe = ServingFrontend(
        [
            TenantConfig(
                name="m0",
                aggregator=CoordinateWiseMedian(),
                dim=4,
                window_s=0.02,
                cohort_cap=8,
            )
        ]
    )
    rng = np.random.default_rng(0)
    for i, cid in enumerate(("a", "b", "c")):
        ok, reason = fe.submit(
            "m0", cid, 0,
            rng.normal(size=4).astype(np.float32), seq=100 + i,
        )
        assert ok, reason
    assert fe.close_round_nowait("m0") is not None
    counters = sanitize.counters()
    assert counters["folds_audited"] == 1
    assert sanitize.violations() == []
    # a second round with FRESH seqs is still exactly-once
    for i, cid in enumerate(("a", "b", "c")):
        assert fe.submit(
            "m0", cid, 1,
            rng.normal(size=4).astype(np.float32), seq=200 + i,
        )[0]
    assert fe.close_round_nowait("m0") is not None
    assert sanitize.counters()["folds_audited"] == 2
    sanitize.assert_clean()

"""The shipped tree must scan byzlint-clean — this is the tier-1 twin of
the CI gate (`python -m byzpy_tpu.analysis byzpy_tpu benchmarks examples`
exits 0), so a PR that introduces a trace-safety/donation/axis/async
hazard fails the suite even before CI runs the standalone leg."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from byzpy_tpu.analysis import scan_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE_PATHS = ["byzpy_tpu", "benchmarks", "examples"]


def test_shipped_tree_scans_clean():
    result = scan_paths([os.path.join(REPO, p) for p in GATE_PATHS])
    assert result.findings == [], "\n".join(
        f.render() for f in result.findings
    )
    # sanity: the walk really covered the tree (engine + kernels + all)
    assert result.files_scanned > 100


def test_scan_is_cheap_enough_for_ci():
    # pure-ast analysis: the whole tree in well under CI-noticeable time
    import time

    t0 = time.perf_counter()
    scan_paths([os.path.join(REPO, p) for p in GATE_PATHS])
    assert time.perf_counter() - t0 < 30.0


@pytest.mark.slow
def test_module_entrypoint_exit_zero():
    # the exact command CI runs, exit-code contract included
    proc = subprocess.run(
        [sys.executable, "-m", "byzpy_tpu.analysis", *GATE_PATHS],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


@pytest.mark.slow
def test_module_entrypoint_fails_on_seeded_violation(tmp_path):
    # the CI leg must fail the build when a violation is introduced:
    # seed an env read into a real jitted fold and scan the copy
    src = open(
        os.path.join(REPO, "byzpy_tpu", "ops", "robust.py"),
        encoding="utf-8",
    ).read()
    needle = "@partial(jax.jit, donate_argnums=(0,))\n"
    assert needle in src
    idx = src.index(needle) + len(needle)
    rest = src[idx:]
    def_end = rest.index(":\n") + 2
    seeded = (
        src[:idx]
        + rest[:def_end]
        + "    import os; _seed = os.environ.get('SEEDED')\n"
        + rest[def_end:]
    )
    target = tmp_path / "robust_seeded.py"
    target.write_text(seeded, encoding="utf-8")
    proc = subprocess.run(
        [sys.executable, "-m", "byzpy_tpu.analysis", str(target)],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "TRACE-DISPATCH" in proc.stdout

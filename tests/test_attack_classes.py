import asyncio

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from byzpy_tpu.attacks import (
    EmpireAttack,
    GaussianAttack,
    InfAttack,
    LabelFlipAttack,
    LittleAttack,
    MimicAttack,
    SignFlipAttack,
)
from byzpy_tpu.engine.graph.operator import OpContext
from byzpy_tpu.models import ModelBundle


def grads(n=6, d=12, seed=0):
    r = np.random.default_rng(seed)
    return [jnp.asarray(r.normal(size=d).astype(np.float32)) for _ in range(n)]


def test_sign_flip_tree():
    base = {"w": jnp.ones((2, 2)), "b": jnp.ones(2)}
    out = SignFlipAttack().apply(base_grad=base)
    np.testing.assert_allclose(np.asarray(out["w"]), -np.ones((2, 2)))


def test_empire_and_flags():
    hs = grads()
    atk = EmpireAttack()
    assert atk.uses_honest_grads and not atk.uses_base_grad
    out = atk.apply(honest_grads=hs)
    want = -np.stack([np.asarray(g) for g in hs]).mean(0)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)


def test_little_shrinks_toward_mean():
    hs = grads(n=9, seed=2)
    out = np.asarray(LittleAttack(f=2).apply(honest_grads=hs))
    mu = np.stack([np.asarray(g) for g in hs]).mean(0)
    sig = np.stack([np.asarray(g) for g in hs]).std(0)
    # attack stays within a few sigma of the mean (that's the point)
    assert np.all(np.abs(out - mu) <= 3 * sig + 1e-6)


def test_gaussian_fresh_draws_and_reproducible():
    hs = grads()
    a1 = GaussianAttack(seed=7)
    v1 = np.asarray(a1.apply(honest_grads=hs))
    v2 = np.asarray(a1.apply(honest_grads=hs))
    assert not np.allclose(v1, v2)  # fresh draw per apply
    a2 = GaussianAttack(seed=7)
    np.testing.assert_array_equal(np.asarray(a2.apply(honest_grads=hs)), v1)


def test_inf_and_mimic():
    hs = grads()
    assert np.all(np.isposinf(np.asarray(InfAttack().apply(honest_grads=hs))))
    out = MimicAttack(epsilon=2).apply(honest_grads=hs)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(hs[2]))
    with pytest.raises(ValueError):
        MimicAttack(epsilon=99).apply(honest_grads=hs)


def test_label_flip_with_model_bundle():
    r = np.random.default_rng(3)
    params = {"w": jnp.zeros((4, 3))}
    bundle = ModelBundle(apply_fn=lambda p, x: x @ p["w"], params=params)
    x = jnp.asarray(r.normal(size=(6, 4)).astype(np.float32))
    y = jnp.asarray(np.array([0, 1, 2, 0, 1, 2]))
    atk = LabelFlipAttack(num_classes=3)
    g_mal = atk.apply(model=bundle, x=x, y=y)
    g_honest = bundle.grad(x, y)
    assert not np.allclose(np.asarray(g_mal["w"]), np.asarray(g_honest["w"]))
    # identity mapping == honest gradient
    ident = LabelFlipAttack(mapping=[0, 1, 2]).apply(model=bundle, x=x, y=y)
    np.testing.assert_allclose(
        np.asarray(ident["w"]), np.asarray(g_honest["w"]), rtol=1e-5, atol=1e-6
    )


def test_compute_collects_flagged_inputs():
    hs = grads()
    ctx = OpContext("atk")
    out = EmpireAttack().compute({"honest_grads": hs}, context=ctx)
    assert out.shape == hs[0].shape
    with pytest.raises(KeyError):
        EmpireAttack().compute({}, context=ctx)
    with pytest.raises(KeyError):
        SignFlipAttack().compute({"honest_grads": hs}, context=ctx)


@pytest.mark.parametrize(
    "attack,inputs_key",
    [
        (EmpireAttack(scale=-0.5), "honest_grads"),
        (LittleAttack(f=2), "honest_grads"),
        (MimicAttack(epsilon=1), "honest_grads"),
        (InfAttack(), "honest_grads"),
        (SignFlipAttack(scale=-2.0), "base_grad"),
    ],
    ids=lambda v: getattr(v, "name", "k"),
)
def test_attack_pool_fanout_matches_direct(attack, inputs_key):
    """Deterministic attacks parallelize over the pool (the reference's
    attack subtask mode, ref attacks/base.py:47-119) with results equal to
    the direct apply path."""
    from byzpy_tpu import run_operator
    from byzpy_tpu.engine.graph import ActorPool, ActorPoolConfig

    assert type(attack).supports_subtasks
    attack.chunk_size = 16  # force several feature chunks at d=61
    r = np.random.default_rng(0)
    gs = [jnp.asarray(r.normal(size=61).astype(np.float32)) for _ in range(7)]
    if inputs_key == "honest_grads":
        inputs = {"honest_grads": gs}
        direct = attack.apply(honest_grads=gs)
    else:
        inputs = {"base_grad": gs[0]}
        direct = attack.apply(base_grad=gs[0])

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=3)) as pool:
            return await run_operator(attack, inputs, pool=pool)

    pooled = asyncio.run(main())
    # chunked fan-out reorders f32 accumulations (little's per-chunk
    # mean/std); allow ulp-scale drift, nothing more
    np.testing.assert_allclose(
        np.asarray(pooled), np.asarray(direct), rtol=3e-7, atol=1e-7
    )


def test_gaussian_pool_fanout_distribution_and_freshness():
    """Gaussian fan-out draws fresh, correctly-distributed noise per call
    (the chunked draw legitimately differs from the direct draw)."""
    from byzpy_tpu import run_operator
    from byzpy_tpu.engine.graph import ActorPool, ActorPoolConfig

    attack = GaussianAttack(mu=0.5, sigma=2.0, seed=7)
    attack.chunk_size = 1024
    r = np.random.default_rng(1)
    gs = [jnp.asarray(r.normal(size=8192).astype(np.float32)) for _ in range(4)]
    inputs = {"honest_grads": gs}

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=3)) as pool:
            a = await run_operator(attack, inputs, pool=pool)
            b = await run_operator(attack, inputs, pool=pool)
            return a, b

    a, b = asyncio.run(main())
    a, b = np.asarray(a), np.asarray(b)
    assert a.shape == (8192,)
    assert not np.array_equal(a, b)  # key advances per fan-out
    assert abs(a.mean() - 0.5) < 0.15
    assert abs(a.std() - 2.0) < 0.15
    # chunk boundaries must not repeat noise (distinct fold_in per chunk)
    c0, c1 = a[:1024], a[1024:2048]
    assert not np.array_equal(c0, c1)


def test_label_flip_has_no_subtasks():
    """Parity: the reference's LabelFlip is the one attack without a
    subtask path (attacks/base.py:47-119)."""
    from byzpy_tpu.attacks import LabelFlipAttack

    assert not LabelFlipAttack.supports_subtasks

"""Autotuner tile-cache contract (ISSUE 2 satellite): corrupt or stale
cache entries degrade to the heuristic tile (never crash a dispatch),
cache hits skip the sweep, the env override wins, and every dispatch
decision resolves BEFORE trace time."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from byzpy_tpu.ops import pallas_kernels as pk
from byzpy_tpu.ops import robust
from byzpy_tpu.profiling import autotune, tilecache


@pytest.fixture
def cache_file(tmp_path, monkeypatch):
    path = str(tmp_path / "tiles.json")
    monkeypatch.setenv("BYZPY_TPU_TUNE_CACHE", path)
    return path


def test_cache_round_trip(cache_file):
    tilecache.store("selection", platform="cpu", n=64, d=65536, tile=4096,
                    ms=1.25)
    assert tilecache.lookup("selection", platform="cpu", n=64, d=65536) == 4096
    # persisted on disk, reloadable from a fresh read
    data = json.load(open(cache_file))
    assert data["selection:cpu:64x65536"]["tile"] == 4096
    assert data["selection:cpu:64x65536"]["ms"] == 1.25
    # distinct keys don't collide
    assert tilecache.lookup("selection", platform="cpu", n=64, d=1024) is None
    assert tilecache.lookup("meamed", platform="cpu", n=64, d=65536) is None


def test_corrupt_cache_degrades_to_heuristic(cache_file):
    with open(cache_file, "w") as fh:
        fh.write("{not json at all")
    assert tilecache.lookup("selection", platform="cpu", n=64, d=65536) is None
    assert tilecache.load_cache() == {}
    # dispatch still works end to end on a corrupt cache
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 256), jnp.float32)
    out = pk.selection_mean_stream_pallas(x[None], f=1, q=3, mode="krum")[0]
    assert out.shape == (256,)
    # and store() recovers the file
    tilecache.store("gram", platform="cpu", n=8, d=256, tile=128)
    assert tilecache.lookup("gram", platform="cpu", n=8, d=256) == 128


@pytest.mark.parametrize(
    "bad", [0, -128, 100, 1 << 20, "4096", 4096.0, None, True]
)
def test_stale_entry_values_are_ignored(cache_file, bad):
    with open(cache_file, "w") as fh:
        json.dump({"selection:cpu:64x65536": {"tile": bad}}, fh)
    assert tilecache.lookup("selection", platform="cpu", n=64, d=65536) is None
    assert not tilecache.valid_tile(bad)


def test_cache_hit_skips_sweep(cache_file, monkeypatch):
    tilecache.store("gram", platform=jax.default_backend(), n=8, d=256,
                    tile=256)
    ran = []
    monkeypatch.setattr(
        autotune, "_kernel_runner",
        lambda family: ran.append(family) or (lambda x, t: x),
    )
    row = autotune.sweep("gram", n=8, d=256)
    assert row["cached"] is True and row["tile"] == 256
    assert ran == []  # no kernel was ever invoked
    # force=True re-measures
    row = autotune.sweep("gram", n=8, d=256, force=True, repeat=1,
                         candidates=[128], verbose=False)
    assert row["cached"] is False


def test_env_override_beats_cache(cache_file, monkeypatch):
    tilecache.store("selection", platform=jax.default_backend(), n=8, d=512,
                    tile=512)
    assert pk._tuned_tile("selection", 8, 512) == 512
    monkeypatch.setenv("BYZPY_TPU_TILE_SELECTION", "256")
    assert pk._tuned_tile("selection", 8, 512) == 256
    # malformed env values fall through to the cache
    monkeypatch.setenv("BYZPY_TPU_TILE_SELECTION", "not-a-tile")
    assert pk._tuned_tile("selection", 8, 512) == 512
    monkeypatch.setenv("BYZPY_TPU_TILE_SELECTION", "100")  # not lane-aligned
    assert pk._tuned_tile("selection", 8, 512) == 512


def test_sweep_persists_winner(cache_file):
    row = autotune.sweep(
        "gram", n=8, d=256, candidates=[128, 256], repeat=1, verbose=False
    )
    assert row["cached"] is False
    assert row["tile"] in (128, 256)
    hit = tilecache.lookup(
        "gram", platform=jax.default_backend(), n=8, d=256
    )
    assert hit == row["tile"]
    entry = tilecache.load_cache()[
        tilecache.cache_key("gram", platform=jax.default_backend(), n=8, d=256)
    ]
    assert set(entry["candidates"]) == {"128", "256"}


def test_dispatch_decisions_resolve_before_trace(cache_file, monkeypatch):
    """The round-5 ADVICE pitfall: env-var dispatch knobs used to be read
    inside jitted functions, so flipping them after a shape had traced
    changed nothing. All knobs now resolve in the Python wrappers —
    flipping one between two calls of the SAME shape changes the very
    next dispatch."""
    calls = []
    real = pk.meamed_stream_pallas

    def spy(xs, **kw):
        calls.append(xs.shape)
        kw.setdefault("interpret", True)  # still off-chip in reality
        return real(xs, **kw)

    monkeypatch.setattr(
        "byzpy_tpu.ops.pallas_kernels.meamed_stream_pallas", spy
    )
    # pretend we're on chip so the floor (not the platform) is the gate:
    # the forced BYZPY_TPU_PALLAS=1 flag bypasses min_dim by design
    monkeypatch.setattr("byzpy_tpu.ops.pallas_kernels._on_tpu", lambda: True)
    x = jax.random.normal(jax.random.PRNGKey(1), (9, 512), jnp.float32)

    # floor above d: XLA path, kernel untouched
    monkeypatch.setenv("BYZPY_TPU_MEAMED_MIN_DIM", "100000")
    a = robust.mean_of_medians(x, f=2)
    assert calls == []
    # SAME shape, floor flipped below d: the kernel dispatches immediately
    monkeypatch.setenv("BYZPY_TPU_MEAMED_MIN_DIM", "128")
    b = robust.mean_of_medians(x, f=2)
    assert calls == [(1, 9, 512)]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                               atol=1e-6)


def test_tile_override_resolves_before_trace(cache_file, monkeypatch):
    """Same-shape calls honor a BYZPY_TPU_TILE_* flip (tile is a static
    argument of the inner jit, so a new value retraces rather than
    reusing the stale closure)."""
    seen = []
    real = pk._sorted_reduce_stream_call

    def spy(xs, **kw):
        seen.append(kw["tile"])
        return real(xs, **kw)

    monkeypatch.setattr(
        "byzpy_tpu.ops.pallas_kernels._sorted_reduce_stream_call", spy
    )
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 512), jnp.float32)
    pk.sorted_reduce_stream_pallas(x[None], mode="median")
    monkeypatch.setenv("BYZPY_TPU_TILE_SORTED_REDUCE", "128")
    pk.sorted_reduce_stream_pallas(x[None], mode="median")
    assert len(seen) == 2 and seen[1] == 128 and seen[0] != 128


def test_invalid_store_rejected(cache_file):
    with pytest.raises(ValueError):
        tilecache.store("gram", platform="cpu", n=8, d=256, tile=100)

"""BASELINE config #5 end-to-end: ResNet-50 + CenterClipping + Empire.

The north star's fifth config is "PS ResNet-50 ImageNet with
CenterClipping under Empire attack (v5e-128 pod)". The pod is a
deployment scale, but the PIPELINE — bf16 ResNet-50 gradients through a
centered-clipping robust aggregate with empire rows, fused into one
SPMD PS step over a mesh — is fully exercisable on the virtual CPU
mesh at reduced spatial/batch size. This pins that the config compiles,
steps, and stays finite (shape/dtype-only model tests live in
``test_models.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = [pytest.mark.slow, pytest.mark.heavy]  # ResNet-50 compile


def test_resnet50_centered_clipping_empire_ps_step():
    from functools import partial

    from byzpy_tpu.models.nets import ResNet50, make_bundle
    from byzpy_tpu.ops import attack_ops, robust
    from byzpy_tpu.parallel import PSStepConfig, jit_ps_train_step, node_mesh

    n, n_byz, batch, hw = 4, 1, 2, 32
    bundle = make_bundle(
        ResNet50(num_classes=10, small_input=False, dtype=jnp.bfloat16),
        (1, hw, hw, 3),
    )

    cfg = PSStepConfig(n_nodes=n, n_byzantine=n_byz, learning_rate=0.01)
    step, opt0 = jit_ps_train_step(
        bundle,
        partial(robust.centered_clipping, c_tau=10.0, M=3),
        cfg,
        attack=lambda honest, key: attack_ops.empire(honest),
        mesh=node_mesh(n),
        grad_dtype=jnp.bfloat16,  # the config's bf16 gradient pipeline
        donate=False,  # bundle.params is compared against afterwards
    )
    key = jax.random.PRNGKey(1)
    xs = jax.random.normal(key, (n, batch, hw, hw, 3), jnp.float32)
    ys = jax.random.randint(jax.random.PRNGKey(2), (n, batch), 0, 10)

    params2, opt, metrics = step(
        bundle.params, opt0, xs, ys, jax.random.PRNGKey(3)
    )
    assert np.isfinite(float(metrics["honest_loss"]))
    assert np.isfinite(float(metrics["agg_grad_norm"]))
    f_before = np.concatenate(
        [np.ravel(leaf) for leaf in jax.tree_util.tree_leaves(bundle.params)]
    )
    f_after = np.concatenate(
        [np.ravel(np.asarray(leaf, np.float32))
         for leaf in jax.tree_util.tree_leaves(params2)]
    )
    assert f_after.shape == f_before.shape
    assert not np.allclose(f_after, np.asarray(f_before, np.float32))
    assert all(
        bool(jnp.isfinite(leaf.astype(jnp.float32)).all())
        for leaf in jax.tree_util.tree_leaves(params2)
    )

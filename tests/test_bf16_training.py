"""bf16 gradients as a first-class robust-training mode (VERDICT r4 #9).

The 150k grads/sec headline is a bf16 kernel number; these tests pin the
TRAINING-path semantics around it: per-node gradients cast to bfloat16
before attack + robust aggregation, f32 master params/optimizer, and a
trajectory that stays close to the f32 one (robustness survives the
cast).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byzpy_tpu.models import ShardedDataset, mnist_mlp, synthetic_classification
from byzpy_tpu.ops import attack_ops, robust
from byzpy_tpu.parallel import PSStepConfig, jit_ps_train_step

N, B = 8, 16


@pytest.fixture(scope="module")
def setup():
    bundle = mnist_mlp(hidden=16)
    x, y = synthetic_classification(n_samples=N * B, seed=11)
    ds = ShardedDataset(x, y, n_nodes=N)
    xs, ys = ds.stacked_shards()
    return bundle, xs, ys


def _flat(params):
    return np.concatenate(
        [np.ravel(leaf) for leaf in jax.tree_util.tree_leaves(params)]
    )


def test_bf16_grad_step_keeps_f32_master_params(setup):
    bundle, xs, ys = setup
    cfg = PSStepConfig(n_nodes=N, n_byzantine=2)
    step, opt0 = jit_ps_train_step(
        bundle, lambda m: robust.trimmed_mean(m, f=2), cfg,
        attack=lambda honest, key: attack_ops.empire(honest),
        grad_dtype=jnp.bfloat16, donate=False,
    )
    params, opt, metrics = step(
        bundle.params, opt0, xs, ys, jax.random.PRNGKey(0)
    )
    # master params and the applied update stay f32 end to end
    for leaf in jax.tree_util.tree_leaves(params):
        assert leaf.dtype == jnp.float32, leaf.dtype
    assert np.isfinite(float(metrics["agg_grad_norm"]))
    assert not np.allclose(_flat(params), _flat(bundle.params))


def test_bf16_trajectory_tracks_f32_under_attack(setup):
    """5 rounds of trimmed-mean under empire: the bf16-gradient
    trajectory lands near the f32 one (bf16 has ~3 decimal digits; the
    robust statistics are medians/means over 64 values, so the update
    error stays at the rounding scale, not the attack scale)."""
    bundle, xs, ys = setup
    cfg = PSStepConfig(n_nodes=N, n_byzantine=2)

    def run(grad_dtype):
        step, opt0 = jit_ps_train_step(
            bundle, lambda m: robust.trimmed_mean(m, f=2), cfg,
            attack=lambda honest, key: attack_ops.empire(honest),
            grad_dtype=grad_dtype, donate=False,
        )
        params, opt = bundle.params, opt0
        for r in range(5):
            params, opt, _ = step(params, opt, xs, ys, jax.random.PRNGKey(r))
        return _flat(params)

    f32 = run(None)
    bf16 = run(jnp.bfloat16)
    # relative trajectory deviation bounded by bf16 rounding accumulation
    denom = np.maximum(np.abs(f32), 1e-3)
    assert np.max(np.abs(bf16 - f32) / denom) < 0.15, (
        np.max(np.abs(bf16 - f32) / denom)
    )


def test_robust_ops_bf16_in_bf16_out_f32_accumulation():
    """Aggregators keep bf16 payloads bf16 (half the HBM traffic) while
    reducing in f32: the bf16 result must match the f32 oracle to bf16
    resolution, far tighter than bf16-accumulation error would allow at
    n=64."""
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (64, 4096), jnp.float32)
    xb = x.astype(jnp.bfloat16)
    for fn in (
        lambda m: robust.trimmed_mean(m, f=8),
        robust.coordinate_median,
        lambda m: robust.multi_krum(m, f=8, q=12),
    ):
        out_b = fn(xb)
        assert out_b.dtype == jnp.bfloat16, out_b.dtype
        oracle = fn(x)
        np.testing.assert_allclose(
            np.asarray(out_b, np.float32), np.asarray(oracle),
            rtol=2e-2, atol=2e-2,
        )


def test_study_config_plumbs_grad_dtype():
    from byzpy_tpu.models.data import load_digits_dataset
    from byzpy_tpu.models.nets import digits_mlp
    from byzpy_tpu.utils.robust_study import StudyConfig, run_cell

    cfg = StudyConfig(rounds=2, eval_every=1, grad_dtype="bfloat16")
    cell = run_cell(
        lambda: digits_mlp(seed=0),
        load_digits_dataset(seed=0),
        "trimmed_mean", "sign_flip", cfg,
    )
    assert 0.0 <= cell.final_accuracy <= 1.0
    assert np.isfinite(cell.final_accuracy)

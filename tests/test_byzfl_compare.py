"""Coverage for ``benchmarks/byzfl_compare.py`` via a fake ``byzfl``.

The live-comparison harness is an optional-dependency shim (torch-based
ByzFL is not installed here), so its timing loop, provenance stamping,
label alignment, per-row error isolation, and clean-skip line are
exercised with stub ``byzfl``/``torch`` modules injected into
``sys.modules`` — no network, no torch.
"""

import importlib.util
import json
import os
import sys
import time
import types

import numpy as np
import pytest

BENCH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks", "byzfl_compare.py",
)
RESULTS_MD = os.path.join(os.path.dirname(BENCH), "RESULTS.md")


def _load_harness():
    spec = importlib.util.spec_from_file_location("_byzfl_compare", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fake_torch():
    torch = types.ModuleType("torch")

    class Generator:
        def __init__(self, device="cpu"):
            self.rng = np.random.default_rng(0)

        def manual_seed(self, seed):
            self.rng = np.random.default_rng(seed)
            return self

    def randn(dim, generator=None, dtype=None):
        rng = generator.rng if generator is not None else np.random.default_rng()
        return rng.normal(size=dim).astype(np.float32)

    torch.Generator = Generator
    torch.randn = randn
    torch.float32 = np.float32
    return torch


class _FakeOp:
    """Stands in for every ByzFL aggregator/pre-aggregator/attack."""

    def __init__(self, **kwargs):
        self.kwargs = kwargs

    def __call__(self, grads):
        return np.mean(np.stack(grads), axis=0)


def _fake_byzfl(missing=()):
    """Module tree matching the harness's import paths; class names in
    ``missing`` are omitted to exercise per-row error isolation."""
    byzfl = types.ModuleType("byzfl")
    aggs_pkg = types.ModuleType("byzfl.aggregators")
    attacks_pkg = types.ModuleType("byzfl.attacks")
    leaves = {
        "byzfl.aggregators.aggregators": [
            "MultiKrum", "TrMean", "Meamed", "MoNNA", "CAF",
            "CenteredClipping", "MDA", "SMEA",
        ],
        "byzfl.aggregators.preaggregators": [
            "NNM", "ARC", "Clipping", "Bucketing",
        ],
        "byzfl.attacks.attacks": [
            "ALittleIsEnough", "Gaussian", "Inf",
            "InnerProductManipulation", "Mimic",
        ],
    }
    mods = {"byzfl": byzfl, "byzfl.aggregators": aggs_pkg,
            "byzfl.attacks": attacks_pkg}
    for name, classes in leaves.items():
        mod = types.ModuleType(name)
        for cls in classes:
            if cls not in missing:
                setattr(mod, cls, _FakeOp)
        mods[name] = mod
    byzfl.aggregators = aggs_pkg
    byzfl.attacks = attacks_pkg
    aggs_pkg.aggregators = mods["byzfl.aggregators.aggregators"]
    aggs_pkg.preaggregators = mods["byzfl.aggregators.preaggregators"]
    attacks_pkg.attacks = mods["byzfl.attacks.attacks"]
    return mods


def test_clean_skip_line_without_byzfl(monkeypatch, tmp_path, capsys):
    harness = _load_harness()
    monkeypatch.setattr(harness, "HERE", str(tmp_path))
    monkeypatch.setitem(sys.modules, "byzfl", None)  # forces ImportError
    monkeypatch.setattr(sys, "argv", ["byzfl_compare.py"])
    assert harness.main() == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert line["status"] == "skipped"
    assert "byzfl" in line["reason"]
    assert not (tmp_path / "results").exists()  # nothing written on skip


def test_timing_loop_labels_and_provenance(monkeypatch, tmp_path, capsys):
    harness = _load_harness()
    monkeypatch.setattr(harness, "HERE", str(tmp_path))
    for name, mod in _fake_byzfl(missing=("SMEA",)).items():
        monkeypatch.setitem(sys.modules, name, mod)
    monkeypatch.setitem(sys.modules, "torch", _fake_torch())
    monkeypatch.setattr(sys, "argv", ["byzfl_compare.py", "--repeat", "2"])
    assert harness.main() == 0

    out_path = tmp_path / "results" / "byzfl_local.jsonl"
    assert out_path.exists()
    records = [json.loads(ln) for ln in out_path.read_text().splitlines()]
    by_label = {r["row"]: r for r in records}
    assert len(records) == len(harness.WORKLOADS)

    for label, module, cls, kwargs, n, dim in harness.WORKLOADS:
        rec = by_label[label]
        # provenance stamping: where the number came from and when
        assert rec["impl"] == f"{module}.{cls}"
        assert rec["n"] == n and rec["dim"] == dim
        assert rec["device"] == "cpu"
        assert "byzfl_compare.py" in rec["provenance"]
        assert "ts" in rec
        if cls == "SMEA":
            assert rec["status"] == "error"  # isolated, not fatal
            assert "AttributeError" in rec["error"]
        else:
            assert rec["status"] == "ok"
            assert rec["reps"] == 2
            assert rec["ms"] >= 0.0

    # the stdout stream mirrors the sink, plus the final done line
    lines = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()]
    assert lines[-1]["status"] == "done"
    assert lines[-1]["rows"] == len(harness.WORKLOADS)


def test_row_labels_align_with_results_md_grid():
    """Workload shapes must line up with the RESULTS.md grid rows the
    ByzFL column annotates (MDA/SMEA intentionally run reduced shapes —
    ByzFL times out at the grid size; see RESULTS.md)."""
    harness = _load_harness()
    results = open(RESULTS_MD).read()
    reduced = {"mda_18x2048_f6", "smea_12x1024_f3"}
    for label, _, _, _, n, dim in harness.WORKLOADS:
        if label in reduced:
            continue
        assert f"{n}×{dim:,}" in results, (
            f"{label}: shape {n}x{dim} has no RESULTS.md grid row"
        )


def test_rows_filter_selects_subset(monkeypatch, tmp_path, capsys):
    harness = _load_harness()
    monkeypatch.setattr(harness, "HERE", str(tmp_path))
    for name, mod in _fake_byzfl().items():
        monkeypatch.setitem(sys.modules, name, mod)
    monkeypatch.setitem(sys.modules, "torch", _fake_torch())
    monkeypatch.setattr(
        sys, "argv",
        ["byzfl_compare.py", "--rows", "cwtm_64x65536_f8", "--repeat", "1"],
    )
    assert harness.main() == 0
    records = [
        json.loads(ln)
        for ln in (tmp_path / "results" / "byzfl_local.jsonl")
        .read_text().splitlines()
    ]
    assert [r["row"] for r in records] == ["cwtm_64x65536_f8"]


def test_time_row_budget_timeout():
    harness = _load_harness()

    def slow(grads):
        time.sleep(0.05)

    rec = harness._time_row(slow, [], repeat=3, budget=0.01)
    assert rec["status"] == "timeout"
    assert rec["first_call_s"] >= 0.05
    quick = harness._time_row(lambda g: None, [], repeat=3, budget=5.0)
    assert quick["status"] == "ok" and quick["reps"] == 3

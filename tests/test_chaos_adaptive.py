"""Adaptive-adversary API: state transitions, replay purity, and
actor-mode vs fused-SPMD parity.

The satellite contract: an adaptive attack is a pure function of its
constructor arguments and observation sequence — SAME public
observations in, SAME adversarial submissions out, no matter which
fabric (actor-mode PS, fused-SPMD serving step, direct masked door)
produced the observations."""

import asyncio

import numpy as np
import pytest

from byzpy_tpu.attacks import (
    InfluenceAscentAttack,
    KrumEvasionAttack,
    PublicRoundState,
    StalenessAbuseAttack,
)
from byzpy_tpu.chaos import AttackSpec, ChaosHarness, Scenario
from byzpy_tpu.serving.staleness import StalenessPolicy

DIM = 16


def _state(r, agg, accepted=None, verdicts=None):
    return PublicRoundState(
        round_id=r,
        aggregate=np.asarray(agg, np.float32),
        accepted=accepted or {},
        verdicts=verdicts or {},
        server_round=r + 1,
    )


class TestInfluenceAscent:
    def test_scale_grows_while_influence_rises(self):
        atk = InfluenceAscentAttack(DIM, scale0=0.1, grow=2.0, shrink=0.5)
        s0 = float(atk.scale)
        atk.observe_round(_state(0, np.ones(DIM)))       # first obs: grow
        atk.observe_round(_state(1, 2.0 * np.ones(DIM)))  # improved: grow
        assert float(atk.scale) == pytest.approx(s0 * 4.0)

    def test_scale_backs_off_when_influence_drops(self):
        atk = InfluenceAscentAttack(DIM, scale0=0.1, grow=2.0, shrink=0.5)
        atk.observe_round(_state(0, np.ones(DIM)))
        atk.observe_round(_state(1, np.zeros(DIM)))  # regressed: shrink
        assert float(atk.scale) == pytest.approx(0.1 * 2.0 * 0.5)

    def test_submission_tracks_public_estimate(self):
        atk = InfluenceAscentAttack(DIM, scale0=0.5)
        first = atk.apply()
        np.testing.assert_allclose(first, 0.5 / np.sqrt(DIM), rtol=1e-5)
        atk.observe_round(_state(0, 3.0 * np.ones(DIM)))
        second = atk.apply()
        assert float(second.mean()) > 3.0  # estimate + push


class TestKrumEvasion:
    def test_bias_shrinks_on_exclusion_grows_on_selection(self):
        atk = KrumEvasionAttack(
            DIM, eps0=0.1, grow=2.0, shrink=0.25, client_id="byz"
        )
        atk.observe_round(_state(0, np.zeros(DIM), accepted={"byz": True}))
        assert float(atk.eps) == pytest.approx(0.2)
        atk.observe_round(_state(1, np.zeros(DIM), accepted={"byz": False}))
        assert float(atk.eps) == pytest.approx(0.05)

    def test_mimics_published_consensus(self):
        atk = KrumEvasionAttack(DIM, eps0=1e-4)
        agg = np.arange(DIM, dtype=np.float32)
        atk.observe_round(_state(0, agg))
        np.testing.assert_allclose(atk.apply(), agg, atol=1e-3)


class TestStalenessAbuse:
    def test_stamps_cutoff_and_cancels_discount(self):
        pol = StalenessPolicy(kind="exponential", gamma=0.5, cutoff=4)
        atk = StalenessAbuseAttack(DIM, staleness=pol, scale=1.0)
        # before the cutoff is reachable, the claimed δ tracks the
        # server round (a round-2 server can't take a round −2 gradient)
        assert atk.delta == 0 and float(atk.inflation) == 1.0
        atk.observe_round(
            PublicRoundState(
                round_id=1, aggregate=np.zeros(DIM), server_round=2
            )
        )
        assert atk.delta == 2 and float(atk.inflation) == pytest.approx(4.0)
        atk.observe_round(
            PublicRoundState(
                round_id=9, aggregate=np.zeros(DIM), server_round=10
            )
        )
        assert atk.delta == 4  # capped at the cutoff
        assert atk.next_round_stamp(10) == 6
        assert atk.next_round_stamp(2) == 0  # clamped at round 0
        assert float(atk.inflation) == pytest.approx(16.0)
        # inflation * discount(claimed δ) == 1: the fold-time cancellation
        assert float(atk.inflation) * pol.discount(4) == pytest.approx(1.0)

    def test_no_cutoff_means_fresh_submissions(self):
        atk = StalenessAbuseAttack(DIM, staleness=StalenessPolicy())
        assert atk.delta == 0 and float(atk.inflation) == 1.0

    def test_backs_off_after_rejection_verdict(self):
        atk = StalenessAbuseAttack(
            DIM,
            staleness=StalenessPolicy(kind="exponential", cutoff=2),
            backoff_rounds=2,
            client_id="byz",
        )
        assert atk.should_submit()
        atk.observe_round(
            _state(0, np.zeros(DIM), verdicts={"byz": "rejected_rate"})
        )
        assert not atk.should_submit()
        atk.observe_round(
            _state(1, np.zeros(DIM), verdicts={"byz": "accepted"})
        )
        atk.observe_round(
            _state(2, np.zeros(DIM), verdicts={"byz": "accepted"})
        )
        assert atk.should_submit()


class TestReplayPurity:
    """Same observation sequence ⇒ same submission sequence, bit for bit."""

    @pytest.mark.parametrize(
        "make",
        [
            lambda: InfluenceAscentAttack(DIM, seed=7, client_id="b"),
            lambda: KrumEvasionAttack(DIM, seed=7, client_id="b"),
            lambda: StalenessAbuseAttack(
                DIM,
                staleness=StalenessPolicy(kind="exponential", cutoff=3),
                seed=7,
                client_id="b",
            ),
        ],
    )
    def test_replay_reproduces_submissions(self, make):
        rng = np.random.default_rng(0)
        observations = [
            _state(
                r,
                rng.normal(size=DIM).astype(np.float32),
                accepted={"b": bool(r % 2)},
                verdicts={"b": "accepted" if r % 3 else "rejected_rate"},
            )
            for r in range(8)
        ]
        live, replay = make(), make()
        live_subs = []
        for obs in observations:
            live_subs.append(live.apply())
            live.observe_round(obs)
        for obs, expected in zip(observations, live_subs, strict=True):
            assert np.array_equal(replay.apply(), expected)
            replay.observe_round(obs)


class TestCrossFabricParity:
    """Actor-mode PS, fused-SPMD serving step, and the direct masked
    door produce the SAME observation feed on the same scenario, hence
    the SAME adversarial submissions — the PR's parity satellite."""

    def _scenario(self, engine):
        return Scenario(
            name=f"parity-{engine}",
            seed=17,
            n_clients=8,
            n_byzantine=2,
            dim=DIM,
            rounds=6,
            aggregator="trimmed_mean",
            aggregator_params={"f": 2},
            attack=AttackSpec(name="influence_ascent"),
            noise=0.0,
            engine=engine,
        )

    def test_actor_vs_spmd_submissions_identical(self):
        ra = ChaosHarness(self._scenario("actor")).run()
        rs = ChaosHarness(self._scenario("spmd")).run()
        assert len(ra.submissions) == len(rs.submissions) > 0
        for a, b in zip(ra.submissions, rs.submissions, strict=True):
            assert np.array_equal(a, b)

    def test_actor_vs_direct_submissions_identical(self):
        ra = ChaosHarness(self._scenario("actor")).run()
        rd = ChaosHarness(self._scenario("direct")).run()
        for a, b in zip(ra.submissions, rd.submissions, strict=True):
            assert np.array_equal(a, b)


class TestObservationChannel:
    def test_parameter_server_publishes_to_adaptive_nodes(self):
        """The actor-mode PS feeds observe_round on local byzantine
        nodes after every round — the production observation channel."""
        from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
        from byzpy_tpu.engine.parameter_server import ParameterServer

        attack = InfluenceAscentAttack(4, client_id="byz")

        class Honest:
            def honest_gradient_for_next_batch(self):
                return np.ones(4, np.float32)

            def apply_server_gradient(self, g):
                pass

        class Byz:
            def byzantine_gradient_for_next_batch(self, honest):
                return attack.apply()

            def apply_server_gradient(self, g):
                pass

            def observe_round(self, state):
                attack.observe_round(state)

        ps = ParameterServer(
            honest_nodes=[Honest(), Honest(), Honest()],
            byzantine_nodes=[Byz()],
            aggregator=CoordinateWiseTrimmedMean(f=1),
        )

        async def drive():
            for _ in range(3):
                await ps.round()

        asyncio.run(drive())
        assert len(attack.observations) == 3
        assert [o.round_id for o in attack.observations] == [0, 1, 2]

    def test_static_attack_observe_round_is_noop(self):
        from byzpy_tpu.attacks import SignFlipAttack

        atk = SignFlipAttack()
        atk.observe_round(_state(0, np.zeros(4)))  # must not raise


class TestAdaptiveAttackRowsBridge:
    def test_tiles_rows_for_fused_step(self):
        from byzpy_tpu.parallel.ps import adaptive_attack_rows

        atk = InfluenceAscentAttack(DIM, scale0=0.25)
        rows = np.asarray(adaptive_attack_rows(atk, 3))
        assert rows.shape == (3, DIM)
        assert np.array_equal(rows[0], rows[2])

    def test_rejects_bad_counts_and_missing_context(self):
        from byzpy_tpu.attacks import EmpireAttack
        from byzpy_tpu.parallel.ps import adaptive_attack_rows

        with pytest.raises(ValueError):
            adaptive_attack_rows(InfluenceAscentAttack(DIM), 0)
        with pytest.raises(ValueError, match="honest"):
            adaptive_attack_rows(EmpireAttack(scale=-1.1), 2)

"""The promoted fault drills: declarative scenarios reproduce the
``tests/test_multihost.py`` invariants in-process.

The subprocess originals stay as regression pins (nothing simulates a
real SIGKILL); these runs prove the *fault semantics* are captured in
replayable configs the chaos grid can sweep."""

import numpy as np
import pytest

from byzpy_tpu.chaos import DRILL_SCENARIOS, run_drill
from byzpy_tpu.chaos.scenario import Scenario


def test_all_four_drills_present():
    assert set(DRILL_SCENARIOS) == {
        "two_host_psum",
        "sigkill_midround",
        "byzantine_process",
        "heartbeat_excision",
    }


@pytest.mark.parametrize("name", sorted(DRILL_SCENARIOS))
def test_drill_invariant_holds(name):
    report, ok = run_drill(name)
    assert ok, report.summary()


def test_drills_are_replayable_configs():
    for name, scenario in DRILL_SCENARIOS.items():
        rebuilt = Scenario.from_dict(scenario.to_dict())
        assert rebuilt == scenario, name


def test_sigkill_drill_matches_original_consensus():
    """The original drill pins the survivors' trimmed mean at 1.5
    (targets 1.0/2.0 once the 9.0 host is dead) — the simulated twin
    converges to the same consensus."""
    report, ok = run_drill("sigkill_midround")
    assert ok
    np.testing.assert_allclose(report.final_params, 1.5, atol=0.05)


def test_heartbeat_drill_excludes_victim_from_cohorts():
    report, ok = run_drill("heartbeat_excision")
    assert ok
    victim = "c0003"
    assert any(e.who == victim for e in report.trace.of_kind("partition"))
    for e in report.trace.of_kind("arrive"):
        if e.who == victim:
            assert e.round_id < 3

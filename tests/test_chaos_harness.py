"""Chaos harness: determinism, fault injection, and engine contracts.

The headline contract is REPLAY: a scenario is one seed, and one seed
is one run — same event trace (digest equality), same adversarial
submissions, same verdicts. Everything the chaos grid pins in
``benchmarks/results/chaos_cpu.jsonl`` rests on this."""

import numpy as np
import pytest

from byzpy_tpu.chaos import (
    ArrivalModel,
    AttackSpec,
    ChaosHarness,
    CrashModel,
    FaultPlan,
    PartitionEvent,
    Scenario,
    StragglerModel,
)


def _scenario(**kwargs) -> Scenario:
    base = dict(
        name="t",
        seed=123,
        n_clients=8,
        n_byzantine=2,
        dim=16,
        rounds=6,
        aggregator="trimmed_mean",
        aggregator_params={"f": 2},
        attack=AttackSpec(name="influence_ascent"),
    )
    base.update(kwargs)
    return Scenario(**base)


def _run(s: Scenario):
    return ChaosHarness(s).run()


class TestDeterminism:
    def test_same_seed_same_trace_and_submissions(self):
        s = _scenario(
            faults=FaultPlan(
                stragglers=StragglerModel(kind="bimodal", tail_prob=0.3),
                crash=CrashModel(prob_per_round=0.1, restart_after_rounds=2),
                partitions=(PartitionEvent(start_round=2, end_round=4),),
            ),
            arrivals=ArrivalModel(kind="bernoulli", p=0.9),
        )
        r1, r2 = _run(s), _run(s)
        assert r1.trace.digest() == r2.trace.digest()
        assert len(r1.submissions) == len(r2.submissions) > 0
        for a, b in zip(r1.submissions, r2.submissions, strict=True):
            assert np.array_equal(a, b)
        assert r1.summary() == r2.summary()

    def test_different_seed_different_trace(self):
        s = _scenario(noise=0.1)
        assert (
            _run(s).trace.digest()
            != _run(s.with_(seed=124)).trace.digest()
        )

    def test_serving_engine_deterministic(self):
        s = _scenario(
            engine="serving",
            attack=AttackSpec(name="staleness_abuse", params={"cutoff": 3}),
            staleness_kind="exponential",
            staleness_cutoff=3,
        )
        r1, r2 = _run(s), _run(s)
        assert r1.trace.digest() == r2.trace.digest()
        assert r1.verdict_counts == r2.verdict_counts


class TestFaultInjection:
    def test_targeted_crash_removes_client(self):
        s = _scenario(
            n_byzantine=0,
            attack=AttackSpec(name="none"),
            faults=FaultPlan(
                crash=CrashModel(at_round=1, victim_indices=(0,))
            ),
            noise=0.0,
        )
        r = _run(s)
        crashes = r.trace.of_kind("crash")
        assert [e.who for e in crashes] == ["c0000"]
        # after the crash the victim never arrives again
        late_arrivals = [
            e for e in r.trace.of_kind("arrive")
            if e.round_id > 1 and e.who == "c0000"
        ]
        assert late_arrivals == []

    def test_crash_restart_cycle(self):
        s = _scenario(
            n_byzantine=0,
            attack=AttackSpec(name="none"),
            rounds=10,
            faults=FaultPlan(
                crash=CrashModel(
                    at_round=1, victim_indices=(2,), restart_after_rounds=3
                )
            ),
        )
        r = _run(s)
        restarts = r.trace.of_kind("restart")
        assert [e.who for e in restarts] == ["c0002"]
        assert restarts[0].round_id == 4
        assert any(
            e.who == "c0002" and e.round_id >= 4
            for e in r.trace.of_kind("arrive")
        )

    def test_partition_and_rejoin(self):
        s = _scenario(
            n_byzantine=0,
            attack=AttackSpec(name="none"),
            rounds=8,
            faults=FaultPlan(
                partitions=(
                    PartitionEvent(start_round=2, end_round=5, members=(1, 3)),
                )
            ),
        )
        r = _run(s)
        assert {e.who for e in r.trace.of_kind("partition")} == {
            "c0001", "c0003"
        }
        assert {e.who for e in r.trace.of_kind("rejoin")} == {
            "c0001", "c0003"
        }
        for e in r.trace.of_kind("arrive"):
            if e.who in ("c0001", "c0003"):
                assert not 2 <= e.round_id < 5

    def test_honestless_round_survives_context_hungry_attack(self):
        """A round whose honest set is emptied by crashes must not kill
        the run when the attack needs honest context — the byzantine
        client sits the round out (nothing to mimic) and the run
        continues on the restarts."""
        s = _scenario(
            n_clients=3,
            n_byzantine=1,
            aggregator_params={"f": 0},
            attack=AttackSpec(name="empire", params={"scale": -1.1}),
            rounds=6,
            faults=FaultPlan(
                crash=CrashModel(
                    at_round=1, victim_indices=(0, 1),
                    restart_after_rounds=2,
                )
            ),
        )
        r = _run(s)  # must not raise
        assert len(r.trace.of_kind("crash")) == 2
        assert len(r.trace.of_kind("restart")) == 2
        assert r.rounds_completed > 0

    def test_stragglers_miss_the_window(self):
        s = _scenario(
            n_byzantine=0,
            attack=AttackSpec(name="none"),
            faults=FaultPlan(
                stragglers=StragglerModel(
                    kind="bimodal", tail_prob=0.5, tail_s=1.0
                )
            ),
            window_s=0.1,
            rounds=10,
        )
        r = _run(s)
        straggles = r.trace.of_kind("straggle")
        assert straggles, "bimodal tail should miss the 0.1 s window"
        assert len(straggles) + len(r.trace.of_kind("arrive")) == 8 * 10


class TestEngines:
    def test_direct_vs_spmd_bit_parity(self):
        """The fused serving step closes rounds bit-identically to the
        host masked door on the same cohorts (PR-6 contract riding the
        chaos schedule)."""
        s = _scenario(noise=0.0)
        rd = _run(s.with_(engine="direct"))
        rs = _run(s.with_(engine="spmd"))
        assert rd.rounds_completed == rs.rounds_completed
        for a, b in zip(rd.submissions, rs.submissions, strict=True):
            assert np.array_equal(a, b)
        np.testing.assert_allclose(
            rd.final_params, rs.final_params, atol=1e-6
        )

    def test_spmd_rejects_unmasked_aggregator(self):
        # MDA is subset-enumeration: no masked program, so the fused
        # serving step cannot host it — direct falls back, spmd refuses
        s = _scenario(engine="spmd", aggregator="mda",
                      aggregator_params={"f": 1})
        with pytest.raises(ValueError, match="masked"):
            _run(s)

    def test_precision_int8_bounded_drift(self):
        s = _scenario(noise=0.0)
        off = _run(s)
        q = _run(s.with_(precision="int8"))
        assert off.rounds_completed == q.rounds_completed
        # int8 wire error is tiny relative to the honest signal
        np.testing.assert_allclose(
            off.final_params, q.final_params, atol=0.05
        )
        assert off.trace.digest() != "" and q.trace.digest() != ""

    def test_serving_engine_uses_real_admission(self):
        """Credit exhaustion surfaces as real rejected_rate acks from
        the production ledger, and rejected rows never aggregate."""
        s = _scenario(
            engine="serving",
            n_byzantine=0,
            attack=AttackSpec(name="none"),
            arrivals=ArrivalModel(kind="poisson", p=3.0),
            credit_rate_per_s=1.0,
            credit_burst=1.0,
            rounds=6,
        )
        r = _run(s)
        assert r.verdict_counts.get("rejected_rate", 0) > 0
        assert r.rounds_completed > 0

    def test_influence_zero_without_attack(self):
        r = _run(_scenario(n_byzantine=0, attack=AttackSpec(name="none")))
        assert r.influences == [0.0] * r.rounds_completed

    def test_summary_row_is_json_ready(self):
        import json

        row = _run(_scenario()).summary()
        assert json.loads(json.dumps(row)) == row
        assert row["trace_digest"]

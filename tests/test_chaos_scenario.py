"""Scenario schema: validation, registries, and JSON round-tripping.

The chaos grid's regression-wall property depends on configs being
exactly reconstructable from their committed JSON — a cell that cannot
be rerun from its row is not a regression pin."""

import json

import pytest

from byzpy_tpu.chaos import (
    ArrivalModel,
    AttackSpec,
    CrashModel,
    FaultPlan,
    PartitionEvent,
    Scenario,
    StragglerModel,
    build_aggregator,
    build_attack,
)
from byzpy_tpu.chaos.scenario import AGGREGATORS, ATTACKS


def _rich_scenario() -> Scenario:
    return Scenario(
        name="rich",
        seed=42,
        n_clients=10,
        n_byzantine=2,
        dim=32,
        rounds=7,
        aggregator="multi_krum",
        aggregator_params={"f": 2, "q": 3},
        attack=AttackSpec(name="krum_evasion", params={"eps0": 0.02}),
        faults=FaultPlan(
            stragglers=StragglerModel(kind="bimodal", tail_prob=0.3),
            crash=CrashModel(prob_per_round=0.05, restart_after_rounds=3),
            partitions=(
                PartitionEvent(start_round=2, end_round=5, fraction=0.2),
                PartitionEvent(start_round=5, end_round=6, members=(1, 3)),
            ),
        ),
        arrivals=ArrivalModel(kind="bernoulli", p=0.8),
        engine="direct",
        precision="int8",
        client_values=tuple(float(i) for i in range(10)),
        staleness_kind="exponential",
        staleness_cutoff=4,
    )


def test_roundtrip_through_json():
    s = _rich_scenario()
    rebuilt = Scenario.from_dict(json.loads(s.to_json()))
    assert rebuilt == s
    assert rebuilt.to_json() == s.to_json()


def test_with_derives_cells():
    s = _rich_scenario()
    cell = s.with_(aggregator="cge", aggregator_params={"f": 1}, name="cell")
    assert cell.aggregator == "cge" and cell.name == "cell"
    assert cell.faults == s.faults  # everything else carried over


@pytest.mark.parametrize(
    "kwargs",
    [
        {"n_clients": 0},
        {"n_byzantine": 5, "n_clients": 5},
        {"rounds": 0},
        {"engine": "warp"},
        {"precision": "fp4"},
        {"aggregator": "no_such_aggregator"},
        {"attack": AttackSpec(name="no_such_attack")},
        {"client_values": (1.0, 2.0)},  # wrong length
    ],
)
def test_invalid_configs_rejected(kwargs):
    base = dict(name="bad", n_clients=5)
    base.update(kwargs)
    with pytest.raises(ValueError):
        Scenario(**base)


def test_fault_model_validation():
    with pytest.raises(ValueError):
        StragglerModel(kind="cauchy")
    with pytest.raises(ValueError):
        CrashModel(prob_per_round=1.5)
    with pytest.raises(ValueError):
        CrashModel(at_round=3)  # victims missing
    with pytest.raises(ValueError):
        PartitionEvent(start_round=5, end_round=5)
    with pytest.raises(ValueError):
        ArrivalModel(kind="burst")


def test_registries_build_every_entry():
    for name in AGGREGATORS:
        s = Scenario(name="t", aggregator=name)
        agg = build_aggregator(s)
        assert hasattr(agg, "aggregate"), name
    for name in ATTACKS:
        s = Scenario(name="t", n_clients=4, n_byzantine=1,
                     attack=AttackSpec(name=name))
        attack = build_attack(s, seed=1, client_id="byz0001")
        if name == "none":
            assert attack is None
        else:
            assert hasattr(attack, "apply"), name


def test_adaptive_attacks_flagged():
    for name in ("influence_ascent", "krum_evasion", "staleness_abuse"):
        s = Scenario(name="t", n_clients=4, n_byzantine=1,
                     attack=AttackSpec(name=name))
        attack = build_attack(s, seed=1, client_id="b")
        assert attack.is_adaptive
    s = Scenario(name="t", n_clients=4, n_byzantine=1,
                 attack=AttackSpec(name="sign_flip"))
    assert not build_attack(s, seed=1, client_id="b").is_adaptive

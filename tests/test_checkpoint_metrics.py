"""Checkpoint/resume (sharded) + metrics logging.

These are survey-mandated additions (SURVEY §5) with no reference
equivalent: checkpoints must round-trip sharded pytrees (restore onto a
mesh re-shards), metrics must capture structured step series.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byzpy_tpu.parallel.mesh import node_mesh, replicated, sharding
from byzpy_tpu.utils.checkpoint import (
    CheckpointManager,
    restore_checkpoint,
    save_checkpoint,
)
from byzpy_tpu.utils.metrics import MetricsLogger, StepTimer


def test_checkpoint_roundtrip_plain(tmp_path):
    state = {
        "params": {"w": jnp.arange(8.0), "b": jnp.zeros((3,))},
        "round": jnp.asarray(7),
    }
    d = str(tmp_path / "ck")
    with CheckpointManager(d) as mgr:
        assert mgr.latest_step() is None
        with pytest.raises(FileNotFoundError):
            mgr.restore()
        mgr.save(3, state)
        mgr.save(5, state)
        assert mgr.latest_step() == 5
        assert mgr.all_steps() == [3, 5]
        out = mgr.restore()
    np.testing.assert_allclose(np.asarray(out["params"]["w"]), np.arange(8.0))
    assert int(out["round"]) == 7


def test_checkpoint_restores_sharded(tmp_path, devices):
    mesh = node_mesh(8)
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8), sharding(mesh, "nodes"))
    d = str(tmp_path / "ck")
    save_checkpoint(d, 1, {"x": x})

    # restore with a sharded target layout
    like = {"x": jax.ShapeDtypeStruct((8, 8), jnp.float32,
                                      sharding=sharding(mesh, "nodes"))}
    out = restore_checkpoint(d, like=like)
    assert out["x"].sharding.spec == sharding(mesh, "nodes").spec
    np.testing.assert_allclose(np.asarray(out["x"]), np.asarray(x))

    # restore replicated instead — resharding on load
    like_rep = {"x": jax.ShapeDtypeStruct((8, 8), jnp.float32,
                                          sharding=replicated(mesh))}
    out2 = restore_checkpoint(d, like=like_rep)
    assert out2["x"].sharding.is_fully_replicated
    np.testing.assert_allclose(np.asarray(out2["x"]), np.asarray(x))


def test_checkpoint_max_to_keep(tmp_path):
    d = str(tmp_path / "ck")
    with CheckpointManager(d, max_to_keep=2) as mgr:
        for s in (1, 2, 3, 4):
            mgr.save(s, {"v": jnp.asarray(float(s))})
        assert mgr.all_steps() == [3, 4]


def test_metrics_logger_history_and_sink(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    with MetricsLogger(path) as log:
        log.log(0, loss=jnp.asarray(2.5), acc=0.1)
        log.log(1, loss=1.5)
        log.log(2, loss=jnp.asarray(0.5), acc=0.9)
        assert log.series("loss") == [2.5, 1.5, 0.5]
        assert log.latest("acc") == 0.9
        s = log.summary()
        assert s["loss"]["min"] == 0.5 and s["loss"]["count"] == 3
        assert s["acc"]["last"] == 0.9
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 3 and lines[0]["loss"] == 2.5
    assert all("time" in l and "step" in l for l in lines)


def test_step_timer_blocks_on_device_work():
    t = StepTimer()
    x = jnp.ones((256, 256))
    t.start()
    y = x @ x
    dt = t.stop(y)
    assert dt > 0
    assert t.mean_s > 0 and t.median_s > 0
    with pytest.raises(RuntimeError):
        t.stop()


def test_checkpoint_restore_missing_step_raises(tmp_path):
    """Missing state surfaces as the typed CheckpointNotFoundError (with
    the directory in the message), not whatever orbax raises that week."""
    from byzpy_tpu.utils.checkpoint import CheckpointManager, CheckpointNotFoundError

    with CheckpointManager(str(tmp_path / "ck")) as mgr:
        assert mgr.latest_step() is None
        with pytest.raises(CheckpointNotFoundError, match="ck"):
            mgr.restore(41)


def test_checkpoint_restore_empty_dir_typed_error(tmp_path):
    from byzpy_tpu.utils.checkpoint import CheckpointManager, CheckpointNotFoundError

    with CheckpointManager(str(tmp_path / "empty")) as mgr:
        with pytest.raises(CheckpointNotFoundError, match="empty"):
            mgr.restore()  # latest on an empty directory


def test_checkpoint_restore_corrupt_step_typed_error(tmp_path):
    """A present-but-mangled step restores as CheckpointCorruptError
    (orbax's internal error chained as __cause__)."""
    import shutil

    from byzpy_tpu.utils.checkpoint import (
        CheckpointCorruptError,
        CheckpointManager,
    )

    d = tmp_path / "ck"
    with CheckpointManager(str(d)) as mgr:
        mgr.save(3, {"w": jnp.arange(4, dtype=jnp.float32)})
        # mangle the step's payload directory in place
        step_dir = d / "3"
        for sub in step_dir.rglob("*"):
            if sub.is_file():
                sub.write_bytes(b"not a checkpoint")
        shutil.rmtree(step_dir / "default", ignore_errors=True)
        with pytest.raises((CheckpointCorruptError, Exception)) as ei:
            mgr.restore(3)
        # whatever orbax hit, the surface must be one of the two typed
        # errors, never a bare orbax internal
        from byzpy_tpu.utils.checkpoint import CheckpointNotFoundError

        assert isinstance(
            ei.value, (CheckpointCorruptError, CheckpointNotFoundError)
        )


def test_checkpoint_like_template_controls_dtype(tmp_path):
    """Restoring with a `like` template must reproduce dtypes/shapes from
    the template (the re-shard-on-restore contract)."""
    from byzpy_tpu.utils.checkpoint import CheckpointManager

    state = {"w": jnp.arange(8, dtype=jnp.float32), "step": 3}
    with CheckpointManager(str(tmp_path / "ck")) as mgr:
        mgr.save(1, state)
        out = mgr.restore(like=state)
    assert out["w"].dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(8, dtype=np.float32))
    assert int(out["step"]) == 3


def test_checkpoint_all_steps_sorted(tmp_path):
    from byzpy_tpu.utils.checkpoint import CheckpointManager

    with CheckpointManager(str(tmp_path / "ck"), max_to_keep=5) as mgr:
        for s in (1, 3, 7):
            mgr.save(s, {"v": jnp.asarray(s)})
        assert mgr.all_steps() == [1, 3, 7]
        assert mgr.latest_step() == 7
        # orbax semantics: a save at an older step than the latest is
        # dropped by the manager's step tracking, not an error
        mgr.save(2, {"v": jnp.asarray(2)})
        assert mgr.latest_step() == 7

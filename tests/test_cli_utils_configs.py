"""CLI, training utils, and config setters.

Parity targets: ``byzpy/cli.py`` (version/doctor/list), ``byzpy/utils/
training.py`` (train_with_progress), ``byzpy/configs/actor.py`` (+ the
mesh analogue of configs/backend.py).
"""

import json

import pytest

from byzpy_tpu.cli import doctor_report, main
from byzpy_tpu.configs import (
    get_actor,
    get_default_mesh,
    set_actor,
    set_default_mesh,
    use_actor,
    use_mesh,
)
from byzpy_tpu.utils.training import train_with_progress
from byzpy_tpu.version import __version__


def test_cli_version(capsys):
    assert main(["version"]) == 0
    assert capsys.readouterr().out.strip() == __version__


def test_cli_doctor_json(capsys):
    assert main(["doctor", "--format", "json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["jax"]["ok"]
    assert report["device_count"] >= 8  # virtual CPU mesh from conftest
    assert all(d["platform"] == "cpu" for d in report["devices"])
    assert report["version"] == __version__


def test_cli_list_kinds(capsys):
    assert main(["list", "aggregators"]) == 0
    out = capsys.readouterr().out
    for expected in ("CoordinateWiseMedian", "MultiKrum", "GeometricMedian",
                     "CenteredClipping", "SMEA"):
        assert expected in out
    assert main(["list", "attacks"]) == 0
    out = capsys.readouterr().out
    assert "SignFlipAttack" in out and "LittleAttack" in out
    assert main(["list", "pre-aggregators"]) == 0
    out = capsys.readouterr().out
    assert "Bucketing" in out and "NearestNeighborMixing" in out


def test_cli_lint_matches_module_entrypoint(capsys):
    # `byzpy-tpu lint` must be the exact same gate as
    # `python -m byzpy_tpu.analysis`: same findings, same exit codes
    import os

    fixtures = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "fixtures", "analysis"
    )
    tp = os.path.join(fixtures, "donation_tp.py")
    fp = os.path.join(fixtures, "donation_fp.py")

    assert main(["lint", fp]) == 0
    capsys.readouterr()
    assert main(["lint", tp]) == 1
    via_cli = capsys.readouterr().out

    from byzpy_tpu.analysis import main as lint_main

    assert lint_main([tp]) == 1
    via_module = capsys.readouterr().out
    assert via_cli == via_module
    assert "DONATION" in via_cli

    assert main(["lint", "--list-rules"]) == 0
    listed = capsys.readouterr().out
    for rule in ("TRACE-DISPATCH", "DONATION", "AXIS-BINDING", "HOST-SYNC",
                 "ASYNC-BLOCKING", "PYTREE-REG", "UNUSED-IGNORE"):
        assert rule in listed


def test_doctor_report_probes_deps():
    report = doctor_report()
    assert report["flax"]["ok"] and report["optax"]["ok"]
    assert "native_shm_store" in report


def test_train_with_progress_runs_rounds_and_evals():
    class FakePS:
        def __init__(self):
            self.rounds = 0

        async def round(self):
            self.rounds += 1

    ps = FakePS()
    evals = []
    history = train_with_progress(
        ps, 25,
        eval_callback=lambda i: evals.append(i) or ps.rounds,
        eval_interval=10,
        progress=False,
    )
    assert ps.rounds == 25
    assert [i for i, _ in history] == [9, 19, 24]
    assert [r for _, r in history] == [10, 20, 25]


def test_actor_config_roundtrip():
    assert get_actor() == "thread"
    set_actor("process")
    try:
        assert get_actor() == "process"
        with use_actor("tpu"):
            assert get_actor() == "tpu"
        assert get_actor() == "process"
    finally:
        set_actor("thread")
    with pytest.raises(ValueError):
        set_actor("warp-drive")


def test_mesh_config_roundtrip(devices):
    assert get_default_mesh() is None
    mesh = get_default_mesh(create=True)
    assert mesh is not None and mesh.devices.size >= 8
    set_default_mesh(mesh)
    try:
        assert get_default_mesh() is mesh
    finally:
        set_default_mesh(None)
    with use_mesh(mesh):
        assert get_default_mesh() is mesh
    assert get_default_mesh() is None


def test_doctor_device_probe_times_out_instead_of_hanging(monkeypatch):
    """Platform plugins dialing a dead remote accelerator can block
    forever; doctor must degrade with a devices_error, not hang."""
    import time

    from byzpy_tpu import cli

    class StuckJax:
        __version__ = "test"

        @staticmethod
        def devices():
            time.sleep(60)

    monkeypatch.setenv("BYZPY_TPU_DOCTOR_TIMEOUT", "0.2")
    with pytest.raises(TimeoutError, match="did not initialize"):
        cli._devices_with_timeout(StuckJax)

    class ErrJax:
        @staticmethod
        def devices():
            raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        cli._devices_with_timeout(ErrJax)


def test_cli_bench_runs_and_reports(capsys):
    from byzpy_tpu.cli import main

    rc = main(["bench", "--nodes", "8", "--dim", "1024", "--repeat", "2"])
    assert rc == 0
    import json

    report = json.loads(capsys.readouterr().out)
    assert report["shape"] == [8, 1024]
    for op in ("coordinate_median", "trimmed_mean", "multi_krum",
               "geometric_median"):
        assert "ms" in report[op], report[op]
        assert report[op]["ms"] > 0


def test_cli_study_parser_and_short_run(capsys):
    """The study subcommand wires mean-vs-robust through the real study
    harness (tiny round count; the accuracy contracts live in
    tests/test_robust_learning.py)."""
    pytest.importorskip("sklearn")
    from byzpy_tpu.cli import main

    assert main(["study", "--rounds", "2", "--aggregator", "median"]) == 0
    out = capsys.readouterr().out
    assert "| aggregator | sign_flip |" in out
    assert "median" in out and "mean" in out


def test_cli_study_choices_match_study_zoo():
    """The CLI's literal choices (kept import-light) must track the study
    module's zoo names."""
    from byzpy_tpu.cli import build_parser
    from byzpy_tpu.utils.robust_study import STUDY_AGGREGATORS, STUDY_ATTACKS

    parser = build_parser()
    sub = next(
        a for a in parser._subparsers._group_actions
    ).choices["study"]
    by_dest = {a.dest: a for a in sub._actions}
    assert tuple(by_dest["aggregator"].choices) == STUDY_AGGREGATORS
    assert tuple(by_dest["attack"].choices) == STUDY_ATTACKS


def test_apply_env_platform_reasserts_env(monkeypatch):
    """The helper must push JAX_PLATFORMS through jax.config (plugin
    sitecustomizes override the env var at import time) and no-op
    cleanly when unset. The suite already runs on cpu, so re-asserting
    'cpu' is safe and observable."""
    from byzpy_tpu.utils.platform import apply_env_platform

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    assert apply_env_platform() is None

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert apply_env_platform() == "cpu"
    import jax

    assert jax.config.jax_platforms == "cpu"

"""Close-path paydown (ISSUE 19): arrival-time dedup staging,
incremental cross-Gram assembly, off-path finalize.

Contracts under test:

* **staged close parity** — a close whose frames were checked AND
  staged at arrival (``stage_partial``: dedup verdict + merge input
  parked on the reader thread) publishes the SAME bits as the barrier
  close, for every partial-fold aggregator × arrival orders × quorum
  and degraded closes, with the ``staged_closes``/``dedup_promoted``
  counters proving the fast path actually ran;
* **cross-Gram accounting** — a Multi-Krum close of k staged partials
  costs EXACTLY k·(k−1)/2 cross blocks and ZERO per-partial diagonal
  recomputes when every shard shipped extras (``gram_cross_blocks`` /
  ``partial_transforms`` pin it — the "no redundant extras recompute"
  acceptance);
* **0-ulp extras-verify** — ``combined_extras`` (the merge tree's
  incremental assembly) is BIT-equal to ``segmented_extras_reference``
  (the ``extras_policy='verify'`` recompute), and a single-ulp nudge
  anywhere in a combined frame's shipped Gram fails the check loudly;
* **epoch revalidation** — a verdict staged while an earlier round was
  still pending is revalidated after that round settles: duplicates
  staged as fresh flip to duplicates (``dedup_restaged``), the staged
  accumulator stands down, and no row folds twice;
* **SIGKILL drill** — staged-but-unsettled state is VOLATILE by
  design: after a shard dies mid-window and recovers from its WAL, the
  stale staging entries are discarded (id mismatch → classic rebuild)
  and the replayed rows fold exactly once (cross-WAL audit clean).
"""

import itertools
import os
import tempfile

import numpy as np
import pytest

from byzpy_tpu.serving import ShardedCoordinator, TenantConfig
from byzpy_tpu.serving.sharded import (
    PartialFold,
    audit_sharded_exactly_once,
    combine_partials,
    shard_for,
)
from byzpy_tpu.serving.staleness import StalenessPolicy
from byzpy_tpu.forensics.evidence import evidence_digest
from byzpy_tpu.resilience.durable import DurabilityConfig

from test_partial_fold import CASES

DIM = 16
TENANT = "m0"
CLIENTS = [f"c{i:04d}" for i in range(18)]

MAKERS = [c[0] for c in CASES]
IDS = [c[1] for c in CASES]


def _tenants(agg, **kw):
    kw.setdefault("min_cohort", 1)
    return [
        TenantConfig(
            name=TENANT,
            aggregator=agg,
            dim=DIM,
            cohort_cap=64,
            staleness=StalenessPolicy(
                kind="exponential", gamma=0.5, cutoff=8
            ),
            **kw,
        )
    ]


def _grads(seed=0):
    rng = np.random.default_rng(seed)
    return {
        c: rng.normal(size=DIM).astype(np.float32) for c in CLIENTS
    }


def _drained_partials(agg, k, seed=0, **co_kw):
    co = ShardedCoordinator(_tenants(agg), k, quorum=1, **co_kw)
    for c, g in _grads(seed).items():
        ok, reason = co.submit(TENANT, c, 0, g, seq=0)
        assert ok, (c, reason)
    partials = [co.shards[s].close_partial(TENANT) for s in range(k)]
    assert all(p is not None for p in partials)
    return co, partials


def _staged_close(co, arrival, missing=()):
    """The full close-path discipline: check + STAGE each frame the
    moment it 'lands', then the close consumes the prechecked results
    and promotes the staged verdicts/accumulator."""
    prechecked = {}
    for p in arrival:
        chk = co.check_partial(TENANT, p, inflight=True)
        prechecked[id(p)] = chk
        if chk[0]:
            assert co.stage_partial(TENANT, p, chk)
    return co.merge_partials(
        TENANT, list(arrival), missing=list(missing),
        prechecked=prechecked,
    )


# ---------------------------------------------------------------------------
# staged close: bit parity with the barrier twin, every family
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [2, 3, 4])
@pytest.mark.parametrize("make_agg", MAKERS, ids=IDS)
def test_staged_close_bit_identical(make_agg, k):
    co_ref, parts = _drained_partials(make_agg(), k, seed=21)
    full = co_ref.merge_partials(TENANT, parts)
    assert full is not None and full[0] == 0
    co_deg, parts_d = _drained_partials(make_agg(), k, seed=21)
    degraded = co_deg.merge_partials(
        TENANT, parts_d[:-1], missing=[k - 1]
    )
    assert degraded is not None
    for order in itertools.permutations(range(k)):
        co, p = _drained_partials(make_agg(), k, seed=21)
        res = _staged_close(co, [p[i] for i in order])
        assert res is not None and res[0] == 0
        np.testing.assert_array_equal(
            np.asarray(res[2]), np.asarray(full[2]), err_msg=str(order)
        )
        st = co.stats()["root"][TENANT]
        # the fast path actually ran: every verdict promoted from the
        # staging table, the close consumed the arrival accumulator
        assert st["dedup_staged"] == k, st
        assert st["dedup_promoted"] == k, st
        assert st["dedup_restaged"] == 0, st
        assert st["staged_closes"] == 1, st
        assert st["partials_inflight"] == 0, st
        # degraded close through the same door
        co2, p2 = _drained_partials(make_agg(), k, seed=21)
        arrival = [p2[i] for i in order if i != k - 1]
        res2 = _staged_close(co2, arrival, missing=[k - 1])
        assert res2 is not None
        np.testing.assert_array_equal(
            np.asarray(res2[2]), np.asarray(degraded[2]),
            err_msg=str(order),
        )
        assert co2.stats()["root"][TENANT]["staged_closes"] == 1


@pytest.mark.parametrize("k", [2, 3, 4])
def test_staged_close_gram_accounting(k):
    """k Multi-Krum partials with shipped extras: EXACTLY k·(k−1)/2
    cross blocks, zero diagonal recomputes — at the close and again
    through ``stats()`` (the runner/chaos counter-pin contract)."""
    from byzpy_tpu.aggregators import MultiKrum

    co, parts = _drained_partials(MultiKrum(f=2, q=3), k, seed=7)
    assert all(p.extras for p in parts)
    res = _staged_close(co, list(reversed(parts)))
    assert res is not None
    st = co.stats()["root"][TENANT]
    assert st["gram_cross_blocks"] == k * (k - 1) // 2, st
    assert st["partial_transforms"] == 0, st
    assert st["staged_closes"] == 1, st


def test_staged_close_with_forged_sibling_falls_back():
    """A forged frame staged alongside honest ones: the close excludes
    it, the staged accumulator stands down (id-set mismatch), and the
    result still equals the honest-only barrier twin."""
    from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean

    k = 3
    make = lambda: CoordinateWiseTrimmedMean(f=1)  # noqa: E731
    co_ref, parts_ref = _drained_partials(make(), k, seed=33)
    honest_only = co_ref.merge_partials(
        TENANT, parts_ref[1:], missing=[0]
    )
    assert honest_only is not None
    co, parts = _drained_partials(make(), k, seed=33)
    forged = PartialFold(
        tenant=parts[0].tenant, round_id=parts[0].round_id,
        shard=parts[0].shard,
        rows=np.asarray(parts[0].rows) * 3.0 + 1.0,
        clients=parts[0].clients, seqs=parts[0].seqs,
        wal_ids=parts[0].wal_ids, extras=parts[0].extras,
        digest=parts[0].digest,
        first_arrival_s=parts[0].first_arrival_s,
    )
    res = _staged_close(co, [forged, *parts[1:]], missing=[0])
    assert res is not None
    np.testing.assert_array_equal(
        np.asarray(res[2]), np.asarray(honest_only[2])
    )
    st = co.stats()["root"][TENANT]
    assert st["forged_partials"] == 1, st
    # the forged frame failed its arrival check, so only the honest
    # frames staged — but the accumulator covers all k-1 honest shards
    # and the close still consumed it
    assert st["dedup_staged"] == k - 1, st
    assert st["staged_closes"] == 1, st
    assert st["partials_inflight"] == 0, st


# ---------------------------------------------------------------------------
# 0-ulp extras-verify: incremental assembly == verifier recompute
# ---------------------------------------------------------------------------


def test_combined_extras_bit_equal_to_segmented_reference():
    """The block-contraction contract, pinned at 0 ulp: the merge
    tree's incremental cross-Gram assembly and the
    ``extras_policy='verify'`` reference recompute produce the SAME
    BITS — `np.array_equal`, not allclose. Any drift (a different
    contraction order, a transposed gemm, a dtype excursion) must fail
    this test loudly."""
    from byzpy_tpu.aggregators import MultiKrum

    agg = MultiKrum(f=2, q=3)
    _co, parts = _drained_partials(agg, 3, seed=9)
    combined = combine_partials(agg, parts)
    spans = combined.segment_spans()
    assert len(spans) == 3
    rows = np.asarray(combined.rows, np.float32)
    want = agg.segmented_extras_reference(rows, spans)
    assert set(want) == {"gram"} == set(combined.extras)
    assert np.array_equal(
        np.asarray(combined.extras["gram"]),
        np.asarray(want["gram"]),
    ), "combined_extras drifted from the verify reference (>0 ulp)"
    # the assembly really was incremental: shipped child diagonals
    # land verbatim in the combined Gram
    off = 0
    for p in parts:
        m = int(p.m)
        assert np.array_equal(
            np.asarray(combined.extras["gram"])[off:off + m, off:off + m],
            np.asarray(p.extras["gram"]),
        )
        off += m


def test_combined_extras_one_ulp_tamper_fails_verify():
    """One ulp of drift anywhere in a combined frame's shipped Gram is
    a forgery under ``extras_policy='verify'`` — exact equality is the
    contract, not matmul tolerance."""
    from byzpy_tpu.aggregators import MultiKrum

    agg = MultiKrum(f=2, q=3)
    co, parts = _drained_partials(
        agg, 3, seed=9, extras_policy="verify"
    )
    combined = combine_partials(agg, parts)
    ok, _ = co.check_partial(TENANT, combined)
    assert ok, "honest combined frame must pass the verify recompute"
    gram = np.asarray(combined.extras["gram"]).copy()
    # nudge one CROSS block entry by exactly one ulp
    i, j = 0, gram.shape[1] - 1
    gram[i, j] = np.nextafter(
        gram[i, j], np.float32(np.inf), dtype=np.float32
    )
    tampered = PartialFold(
        tenant=combined.tenant, round_id=combined.round_id,
        shard=combined.shard, rows=combined.rows,
        clients=combined.clients, seqs=combined.seqs,
        wal_ids=combined.wal_ids, extras={"gram": gram},
        digest=combined.digest,
        first_arrival_s=combined.first_arrival_s,
        segments=combined.segments,
    )
    ok2, _ = co.check_partial(TENANT, tampered)
    assert ok2 is False, "1-ulp Gram tamper must fail extras verify"


def test_staged_merge_extras_bit_equal_to_barrier():
    """The staged accumulator's merged Gram (cross blocks computed at
    arrival, placement at finish) is bit-equal to the one-shot
    ``fold_merge`` of the same shard-sorted partials."""
    from byzpy_tpu.aggregators import MultiKrum

    agg = MultiKrum(f=2, q=3)
    _co, parts = _drained_partials(agg, 4, seed=13)
    inputs = [
        {"rows": np.asarray(p.rows), "m": int(p.m), "extras": p.extras}
        for p in parts
    ]
    ref = agg.fold_merge(inputs)
    for order in itertools.permutations(range(4)):
        acc = agg.fold_merge_begin()
        for s in order:
            agg.fold_merge_add(acc, s, inputs[s])
        merged = agg.fold_merge_finish(acc)
        assert np.array_equal(
            np.asarray(merged["extras"]["gram"]),
            np.asarray(ref["extras"]["gram"]),
        ), f"arrival order {order} moved the merged Gram bits"
        ms = merged["merge_stats"]
        assert ms == {"cross_blocks": 6, "transforms": 0}, ms


# ---------------------------------------------------------------------------
# epoch revalidation: pipelined staging across a settle
# ---------------------------------------------------------------------------


def test_stale_staged_duplicate_revalidates_and_never_double_folds():
    """Round N+1's frame staged while round N pends, claiming pairs
    round N then folds: promotion revalidates the stale-epoch verdict,
    flips the rows to duplicates (``dedup_restaged``), stands the
    staged accumulator down, and the fold table never sees a pair
    twice."""
    from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean

    co, parts = _drained_partials(CoordinateWiseTrimmedMean(f=1), 1, seed=5)
    (p0,) = parts
    chk0 = co.check_partial(TENANT, p0, inflight=True)
    assert chk0[0] and co.stage_partial(TENANT, p0, chk0)
    # round 1's window, arriving EARLY (while round 0 pends): a frame
    # re-claiming round 0's exact (client, seq) pairs
    replay = PartialFold(
        tenant=p0.tenant, round_id=1, shard=0,
        rows=p0.rows, clients=p0.clients, seqs=p0.seqs,
        wal_ids=p0.wal_ids, extras=p0.extras,
        digest=evidence_digest(np.asarray(p0.rows)),
        first_arrival_s=p0.first_arrival_s,
    )
    chk1 = co.check_partial(TENANT, replay, inflight=True)
    assert chk1[0] and co.stage_partial(TENANT, replay, chk1)
    rt = co._roots[TENANT]
    epoch_before = rt.dedup_epoch
    # settle round 0: the staged pairs fold, the epoch advances
    res0 = co.merge_partials(
        TENANT, [p0], prechecked={id(p0): chk0}
    )
    assert res0 is not None and res0[0] == 0
    assert rt.dedup_epoch == epoch_before + 1
    assert rt.staged_closes == 1
    # close round 1: the staged verdict is epoch-stale and WRONG now —
    # revalidation flips every row to a duplicate, the close holds the
    # window open (nothing admissible), and nothing folds twice
    res1 = co.merge_partials(
        TENANT, [replay], prechecked={id(replay): chk1}
    )
    assert res1 is None
    assert rt.dedup_restaged == 1, "stale verdict must be invalidated"
    assert rt.staged_closes == 1, "poisoned accumulator must not close"
    assert co._partials_inflight == 0
    # the authority is intact: every pair folded exactly once
    for c, s in zip(p0.clients, p0.seqs, strict=True):
        assert rt.is_folded(c, s)
    assert rt.round_id == 1


def test_fresh_pairs_staged_across_settle_promote_cleanly():
    """The benign pipelined case: round N+1's frame carries FRESH
    pairs, staged while round N pends — after N settles the stale
    epoch revalidates to the SAME verdict, the entry refreshes, and
    round N+1 closes off the staged accumulator (no restage)."""
    from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean

    co, parts = _drained_partials(CoordinateWiseTrimmedMean(f=1), 1, seed=6)
    (p0,) = parts
    chk0 = co.check_partial(TENANT, p0, inflight=True)
    assert chk0[0] and co.stage_partial(TENANT, p0, chk0)
    rng = np.random.default_rng(66)
    rows1 = rng.normal(size=(len(CLIENTS), DIM)).astype(np.float32)
    nxt = PartialFold(
        tenant=p0.tenant, round_id=1, shard=0,
        rows=rows1, clients=p0.clients,
        seqs=[s + 1 for s in p0.seqs],
        wal_ids=p0.wal_ids, extras=None,
        digest=evidence_digest(rows1),
        first_arrival_s=p0.first_arrival_s,
    )
    chk1 = co.check_partial(TENANT, nxt, inflight=True)
    assert chk1[0] and co.stage_partial(TENANT, nxt, chk1)
    res0 = co.merge_partials(TENANT, [p0], prechecked={id(p0): chk0})
    assert res0 is not None
    res1 = co.merge_partials(
        TENANT, [nxt], prechecked={id(nxt): chk1}
    )
    assert res1 is not None and res1[0] == 1
    rt = co._roots[TENANT]
    assert rt.dedup_restaged == 0
    assert rt.dedup_promoted == 2
    assert rt.staged_closes == 2, "fresh-pair staging must survive settles"
    assert co._partials_inflight == 0


def test_duplicate_resubmission_acked_while_round_pends():
    """A client re-sending ``(client, seq)`` into the next window
    while the pair's round is staged-but-unsettled: the shard acks
    ``duplicate`` (exactly-once to the client) and neither the shard
    queue nor the root staging table grows."""
    from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean

    co, parts = _drained_partials(CoordinateWiseTrimmedMean(f=1), 2, seed=8)
    for p in parts:
        chk = co.check_partial(TENANT, p, inflight=True)
        assert chk[0] and co.stage_partial(TENANT, p, chk)
    rt = co._roots[TENANT]
    staged_before = rt.dedup_staged
    c = CLIENTS[0]
    ok, reason = co.submit(
        TENANT, c, 1, np.ones(DIM, np.float32), seq=0
    )
    assert (ok, reason) == (True, "duplicate")
    home = co.shards[shard_for(c, 2)]
    assert home.frontend.stats()[TENANT]["queue_depth"] == 0
    assert rt.dedup_staged == staged_before
    assert not rt.is_folded(c, 0), "ack must not touch the fold table"


# ---------------------------------------------------------------------------
# SIGKILL drill: staged-but-unsettled state is volatile by design
# ---------------------------------------------------------------------------


def test_sigkill_mid_stage_rebuilds_from_wal_exactly_once():
    """Shard dies AFTER its round-1 frame was checked + staged but
    BEFORE the round settled (no WAL round record): recovery replays
    the accepts as pending, the stale staging entries are discarded
    (fresh partial ids → classic rebuild), the rows fold exactly once,
    and the cross-WAL audit is clean."""
    from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean

    grads = _grads(44)
    with tempfile.TemporaryDirectory() as tmp:
        co = ShardedCoordinator(
            _tenants(CoordinateWiseTrimmedMean(f=1)), 2, quorum=1,
            durability=DurabilityConfig(directory=tmp),
        )
        for c, g in grads.items():
            ok, _ = co.submit(TENANT, c, 0, g, seq=0)
            assert ok
        parts = [co.shards[s].close_partial(TENANT) for s in range(2)]
        res0 = _staged_close(co, parts)
        assert res0 is not None
        # round 1: both shards drain + check + stage, then shard 1 is
        # SIGKILLed before the settle
        for c, g in grads.items():
            ok, _ = co.submit(TENANT, c, 1, g, seq=1)
            assert ok
        parts1 = [co.shards[s].close_partial(TENANT) for s in range(2)]
        for p in parts1:
            chk = co.check_partial(TENANT, p, inflight=True)
            assert chk[0] and co.stage_partial(TENANT, p, chk)
        rt = co._roots[TENANT]
        assert 1 in rt.staging and len(rt.staging[1]["entries"]) == 2
        co.kill_shard(1)
        # the frames' inflight slots are consumed by NO close (the
        # round never settles as staged) — release them as the async
        # straggler path would
        co._dec_inflight(2)
        shard1 = co.recover_shard(1)
        own = [c for c in CLIENTS if shard_for(c, 2) == 1]
        assert shard1.frontend.stats()[TENANT]["queue_depth"] == len(own)
        # next close: shard 0's replayed + shard 1's recovered rows
        # fold exactly once through the CLASSIC path (the stale staged
        # entries reference dead partial objects and must not match)
        co.shards[0].requeue(TENANT, 1)
        parts1b = [co.shards[s].close_partial(TENANT) for s in range(2)]
        assert all(p is not None for p in parts1b)
        prechecked = {}
        for p in parts1b:
            chk = co.check_partial(TENANT, p, inflight=True)
            prechecked[id(p)] = chk
            # staging is REFUSED: the dead frames' stale entries still
            # claim these shards, so the accumulator fast path stands
            # down and the close rebuilds classically
            assert co.stage_partial(TENANT, p, chk) is False
        res1 = co.merge_partials(TENANT, parts1b, prechecked=prechecked)
        assert res1 is not None and res1[0] == 1
        assert res1[1].shape[0] == len(CLIENTS)
        assert rt.dedup_restaged == 0
        assert not rt.staging, "settled rounds must prune their staging"
        audit = audit_sharded_exactly_once(tmp, TENANT, 2)
        assert audit["violations"] == []
        # accepted-then-lost is impossible: every accept is folded,
        # dropped-with-accounting, or pending — and both rounds' rows
        # folded exactly once
        assert audit["folded"] == 2 * len(CLIENTS)

"""Collective layer on the 8-device virtual CPU mesh.

The ring implementations must match the XLA primitives exactly (they ARE
the same math), and the host-level wrappers must accept sharded arrays.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from byzpy_tpu.parallel import collectives as coll
from byzpy_tpu.parallel.mesh import node_mesh, sharding


@pytest.fixture
def mesh(devices):
    return node_mesh(8)


def _node_sharded(mesh, key, shape):
    x = jax.random.normal(key, shape, jnp.float32)
    return jax.device_put(x, sharding(mesh, "nodes"))


def test_all_gather_and_reduce(mesh):
    x = _node_sharded(mesh, jax.random.PRNGKey(0), (8, 16))

    fn = coll.sharded_fn(
        mesh, "nodes",
        lambda s: coll.all_gather(s, "nodes"),
        in_spec=P("nodes"), out_spec=P(),
    )
    np.testing.assert_allclose(np.asarray(fn(x)), np.asarray(x), rtol=1e-6)

    total = coll.allreduce_sharded(mesh, x)
    np.testing.assert_allclose(
        np.asarray(total), np.asarray(x).sum(axis=0), rtol=1e-5
    )


def test_reduce_scatter_matches_psum_slice(mesh):
    x = _node_sharded(mesh, jax.random.PRNGKey(1), (8, 32))

    fn = coll.sharded_fn(
        mesh, "nodes",
        lambda s: coll.reduce_scatter_sum(s[0], "nodes", axis=0),
        in_spec=P("nodes"), out_spec=P("nodes"),
    )
    out = np.asarray(fn(x)).reshape(-1)  # each device keeps 32/8=4 elems
    oracle = np.asarray(x).sum(axis=0)
    np.testing.assert_allclose(out, oracle, rtol=1e-5)


def test_neighbor_shift_is_ring(mesh):
    x = _node_sharded(mesh, jax.random.PRNGKey(2), (8, 4))
    fn = coll.sharded_fn(
        mesh, "nodes", lambda s: coll.neighbor_shift(s, "nodes", offset=1)
    )
    out = np.asarray(fn(x))
    np.testing.assert_allclose(out, np.roll(np.asarray(x), 1, axis=0), rtol=1e-6)


def test_ring_all_reduce_matches_psum(mesh):
    for dim in (24, 37):  # divisible and ragged chunking
        x = _node_sharded(mesh, jax.random.PRNGKey(dim), (8, dim))
        ring = coll.sharded_fn(
            mesh, "nodes",
            lambda s: coll.ring_all_reduce_sum(s[0], "nodes")[None],
            in_spec=P("nodes"), out_spec=P("nodes"),
        )
        out = np.asarray(ring(x))
        oracle = np.asarray(x).sum(axis=0)
        for row in out:  # every device holds the full reduction
            np.testing.assert_allclose(row, oracle, rtol=1e-4, atol=1e-5)


def test_all_to_all_transposes_ownership(mesh):
    # each device holds (1, 8, k); all_to_all redistributes the second axis
    x = _node_sharded(mesh, jax.random.PRNGKey(5), (8, 8, 4))
    fn = coll.sharded_fn(
        mesh, "nodes",
        lambda s: coll.all_to_all(s[0], "nodes", split_axis=0, concat_axis=0)[None],
        in_spec=P("nodes"), out_spec=P("nodes"),
    )
    out = np.asarray(fn(x))
    np.testing.assert_allclose(out, np.swapaxes(np.asarray(x), 0, 1), rtol=1e-6)


def test_initialize_multihost_noop_single_process():
    assert coll.initialize_multihost() is False

"""Communication accounting: HLO collective parsing + wire-byte laws."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from byzpy_tpu.parallel.comms import (
    CollectiveOp,
    collective_traffic,
    collectives_in_hlo,
    compression_factor,
    scaling_model,
)


def test_parse_sync_and_async_collectives():
    hlo = """
HloModule m

ENTRY %main (p: f32[8,128]) -> f32[8,128] {
  %p = f32[8,128] parameter(0)
  %ar = f32[8,128]{1,0} all-reduce(%p), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add
  %ags = f32[8,128]{1,0} all-gather-start(%ar), replica_groups=[1,8]<=[8], dimensions={0}
  %agd = f32[8,128]{1,0} all-gather-done(%ags)
  ROOT %out = f32[8,128]{1,0} add(%ar, %agd)
}
"""
    ops = collectives_in_hlo(hlo, default_group=8)
    kinds = sorted(op.opcode for op in ops)
    # the -done twin must NOT double count
    assert kinds == ["all-gather", "all-reduce"], ops
    by = {op.opcode: op for op in ops}
    assert by["all-reduce"].group_size == 8
    assert by["all-gather"].group_size == 8
    assert by["all-reduce"].result_bytes == 8 * 128 * 4
    assert all(op.in_entry for op in ops)


def test_loop_body_collectives_flagged_not_totalled():
    hlo = """
HloModule m

%body (x: f32[64]) -> f32[64] {
  %x = f32[64] parameter(0)
  ROOT %cp = f32[64]{0} collective-permute(%x), source_target_pairs={{0,1},{1,0}}
}

ENTRY %main (p: f32[64]) -> f32[64] {
  %p = f32[64] parameter(0)
  ROOT %w = f32[64]{0} while(%p), condition=%cond, body=%body
}
"""
    ops = collectives_in_hlo(hlo, default_group=2)
    assert len(ops) == 1 and not ops[0].in_entry


def test_quantized_dtypes_counted_not_dropped():
    """Satellite of ISSUE 3: s8/u8/s16/u16/f8*/pred buffers must land in
    wire_bytes_per_device instead of silently vanishing from the traffic
    model — pinned with a hand-written int8 all-gather (the compressed
    fabric's dominant payload) plus fp8 and pred cousins."""
    hlo = """
HloModule m

ENTRY %main (p: s8[8,256]) -> s8[64,256] {
  %p = s8[8,256] parameter(0)
  %ag = s8[64,256]{1,0} all-gather(%p), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %f8 = f8e4m3[8,256]{1,0} all-gather(%p2), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %f8b = f8e5m2[8,256]{1,0} all-gather(%p3), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %msk = pred[8,256]{1,0} all-gather(%p4), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %s16 = s16[8,256]{1,0} all-gather(%p5), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %u16 = u16[8,256]{1,0} all-gather(%p6), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %u8 = u8[8,256]{1,0} all-gather(%p7), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  ROOT %out = s8[64,256]{1,0} copy(%ag)
}
"""
    ops = collectives_in_hlo(hlo, default_group=8)
    assert len(ops) == 7, ops
    by_bytes = {op.result_bytes for op in ops}
    # int8 gather result: 64*256*1 bytes; 1-byte cousins: 8*256; 2-byte: 8*256*2
    assert 64 * 256 in by_bytes
    assert 8 * 256 in by_bytes and 8 * 256 * 2 in by_bytes
    assert all(op.result_bytes > 0 for op in ops), "a dtype fell out of the table"
    int8_ag = next(op for op in ops if op.result_bytes == 64 * 256)
    assert int8_ag.wire_bytes_per_device == 64 * 256 * 7 // 8


def test_wire_byte_laws():
    assert CollectiveOp("all-gather", 1024, 8).wire_bytes_per_device == 1024 * 7 // 8
    assert CollectiveOp("all-reduce", 1024, 8).wire_bytes_per_device == 2 * 1024 * 7 // 8
    assert CollectiveOp("reduce-scatter", 128, 8).wire_bytes_per_device == 128 * 7
    assert CollectiveOp("all-to-all", 1024, 8).wire_bytes_per_device == 1024 * 7 // 8
    assert CollectiveOp("collective-permute", 1024, 8).wire_bytes_per_device == 1024
    # degenerate single-device group moves nothing (permute excepted)
    assert CollectiveOp("all-reduce", 1024, 1).wire_bytes_per_device == 0


def test_collective_traffic_measures_gradient_transpose(devices):
    mesh = Mesh(np.array(devices[:8]), ("nodes",))
    d = 4096

    @jax.jit
    def step(x):
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P("nodes", None)))
        y = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(None, "nodes")))
        return jnp.sum(y, axis=0)

    x = jnp.ones((8, d), jnp.float32)
    traffic = collective_traffic(step, x)
    # node->feature transpose must appear as an all-to-all moving ~(g-1)/g
    # of the (8, d) f32 matrix's per-device share
    assert traffic["per_opcode_bytes"].get("all-to-all", 0) > 0, traffic
    assert traffic["wire_bytes_per_device"] > 0


def test_scaling_model_efficiency_saturates():
    pts = scaling_model(
        flops_per_chip=1e9,
        wire_bytes_fn=lambda g: 2.0 * 1e6 * 4 * (g - 1) / g,
        chips=(8, 128),
    )
    # comm is ~constant in N: 128-chip efficiency within 3% of 8-chip
    assert abs(pts[0].efficiency - pts[1].efficiency) < 0.03
    assert 0.0 < pts[0].efficiency < 1.0


def test_scaling_model_predicts_compressed_fabrics():
    """The comm term scales by the compression factor: int8 at block 256
    moves (1 + 4/256)/4 of the f32 bytes, bf16 exactly half."""
    kwargs = dict(
        flops_per_chip=1e9,
        wire_bytes_fn=lambda g: 8e6 * (g - 1) / g,
        chips=(8,),
    )
    full = scaling_model(**kwargs)[0]
    i8 = scaling_model(precision="int8", quant_block=256, **kwargs)[0]
    bf = scaling_model(precision="bf16", **kwargs)[0]
    assert i8.comm_s == pytest.approx(full.comm_s * (1 + 4 / 256) / 4)
    assert bf.comm_s == pytest.approx(full.comm_s / 2)
    assert i8.efficiency > bf.efficiency > full.efficiency
    assert compression_factor("off") == 1.0
    with pytest.raises(ValueError):
        compression_factor("fp4")


def test_loop_body_collectives_reported_separately(devices):
    """ring_all_reduce_sum runs its collective-permutes inside fori_loop
    bodies; the accounting must flag them as per-iteration lower bounds
    instead of silently under-counting the per-invocation total."""
    from byzpy_tpu.parallel.collectives import ring_all_reduce_sum, sharded_fn

    mesh = Mesh(np.array(devices[:8]), ("r",))
    fn = sharded_fn(
        mesh, "r", lambda s: ring_all_reduce_sum(s, "r"),
        in_spec=P("r"), out_spec=P("r"),
    )
    x = jnp.ones((8, 256), jnp.float32)
    traffic = collective_traffic(fn, x)
    assert traffic["loop_body_bytes_per_iteration"] > 0, traffic

"""Critical-path attribution: tree reconstruction, blame accounting.

Contracts under test:

* **reconstruction** — complete events link into causal trees by their
  ``span``/``parent`` ids; orphans surface as roots, context-free
  events are skipped, round roots are found through wrapper spans;
* **attribution** — per-stage blame partitions the round makespan
  EXACTLY (sums to it); overlapping children (parallel shard legs)
  resolve to the dominating chain, so the slow shard gets the blame
  and the fast one gets none; an injected slow stage owns the round;
* **plumbing** — the CLI ``--critical-path`` section and the live
  tracer round-trip (record through real spans, attribute offline).
"""

import json

import pytest

from byzpy_tpu import observability as obs
from byzpy_tpu.observability import critical_path as cp
from byzpy_tpu.observability import tracing as obs_tracing


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    obs.disable()
    obs_tracing.tracer().clear()
    obs_tracing.adopt_context(None)
    yield
    obs.disable()
    obs_tracing.tracer().clear()
    obs_tracing.adopt_context(None)


def _ev(name, ts, dur, span, parent=None, **args):
    a = {"span": span, **args}
    if parent is not None:
        a["parent"] = parent
    return {"name": name, "ph": "X", "ts": ts, "dur": dur, "tid": 1, "args": a}


class TestForest:
    def test_links_children_and_surfaces_orphans(self):
        events = [
            _ev("root", 0, 100, "r"),
            _ev("child", 10, 20, "c", parent="r"),
            _ev("orphan", 50, 5, "o", parent="gone"),
            {"name": "instant", "ph": "i", "ts": 1, "tid": 1, "args": {}},
            _ev("ctxfree", 0, 1, None),  # span id None -> skipped
        ]
        roots = cp.build_forest(events)
        names = sorted(r.name for r in roots)
        assert names == ["orphan", "root"]
        (root,) = [r for r in roots if r.name == "root"]
        assert [c.name for c in root.children] == ["child"]

    def test_round_roots_found_through_wrappers_and_nested_once(self):
        events = [
            _ev("bench.wrapper", 0, 100, "w"),
            _ev("serving.sharded_round", 0, 90, "sr", parent="w", round=3),
            _ev("serving.round", 5, 10, "r", parent="sr", round=3),
        ]
        rounds = cp.round_roots(cp.build_forest(events))
        # the OUTER round root counts once; the nested serving.round
        # inside it is part of its tree, not a second round
        assert [r.name for r in rounds] == ["serving.sharded_round"]


class TestCriticalPath:
    def test_blame_partitions_makespan_exactly(self):
        events = [
            _ev("serving.round", 0, 100, "r", round=0, tenant="m0"),
            _ev("serving.cohort_close", 5, 10, "a", parent="r"),
            _ev("serving.fold", 20, 60, "b", parent="r"),
            _ev("serving.device_step", 30, 40, "c", parent="b"),
        ]
        (row,) = cp.blame_rounds(events)
        assert row["makespan_us"] == 100
        blame = {r["stage"]: r["blame_us"] for r in row["stages"]}
        # device_step owns its 40, fold its surrounding 20, the
        # cohort_close its 10, the round span the gaps (5+10+15)
        assert blame["serving.device_step"] == 40
        assert blame["serving.fold"] == 20
        assert blame["serving.cohort_close"] == 10
        assert blame["serving.round"] == 30
        assert sum(blame.values()) == pytest.approx(100)

    def test_parallel_legs_blame_the_dominating_chain(self):
        # two shard legs overlap in wall time under one round root: the
        # slow one (ends at 80) dominates; the fast one (ends at 30)
        # is off the critical path entirely
        events = [
            _ev("serving.sharded_round", 0, 100, "r", round=0),
            _ev("serving.shard_close", 0, 30, "s0", parent="r", shard=0),
            _ev("serving.shard_close", 0, 80, "s1", parent="r", shard=1),
            _ev("serving.fold_merge", 80, 20, "m", parent="r"),
        ]
        (row,) = cp.blame_rounds(events)
        blame = {
            (r["stage"], r["shard"]): r["blame_us"] for r in row["stages"]
        }
        assert blame[("serving.shard_close", 1)] == 80
        assert ("serving.shard_close", 0) not in blame
        assert blame[("serving.fold_merge", None)] == 20
        assert sum(blame.values()) == pytest.approx(100)

    def test_injected_slow_stage_is_attributed(self):
        fast = [
            _ev("serving.round", 0, 10, "r0", round=0),
            _ev("serving.fold", 1, 8, "f0", parent="r0"),
        ]
        slow = [
            _ev("serving.round", 100, 200, "r1", round=1),
            _ev("serving.fold", 101, 5, "f1", parent="r1"),
            _ev("serving.bucket_pad", 110, 180, "p1", parent="r1"),
        ]
        summary = cp.summarize(fast + slow)
        assert summary["max_blame_residual"] < 1e-9
        table = {
            (r["stage"], r["shard"]): r for r in summary["stages"]
        }
        # the injected slow stage dominates the aggregate blame
        top = summary["stages"][0]
        assert top["stage"] == "serving.bucket_pad"
        assert top["share"] > 0.8
        assert table[("serving.fold", None)]["rounds"] == 2

    def test_summarize_last_window(self):
        events = []
        for r in range(6):
            events += [
                _ev("serving.round", r * 100, 50, f"r{r}", round=r),
            ]
        summary = cp.summarize(events, last=2)
        assert [r["round"] for r in summary["rounds"]] == [4, 5]


class TestOverlappedAttribution:
    def test_exclusive_blame_sums_to_union_makespan(self):
        # round 1's ingest (span r1) starts while round 0's tail is
        # still closing: wall-clock [80, 100) is claimed by BOTH round
        # trees.  Exclusive blame must count it once.
        events = [
            _ev("serving.round", 0, 100, "r0", round=0, tenant="m0"),
            _ev("serving.fold", 60, 40, "f0", parent="r0"),
            _ev("serving.round", 80, 100, "r1", round=1, tenant="m0"),
            _ev("serving.fold", 140, 40, "f1", parent="r1"),
        ]
        summary = cp.summarize_overlapped(events)
        # union of [0,100) and [80,180) is 180, not 200
        assert summary["makespan_us"] == pytest.approx(180)
        assert summary["max_blame_residual"] < 1e-9
        assert summary["overlap_hidden_us"] == pytest.approx(20)
        assert summary["overlap_ratio"] == pytest.approx(1 - 180 / 200)
        # the hidden 20us belongs to round 1's segments (its head ran
        # under round 0's tail), visible in the per-round rows
        r1 = summary["rounds"][1]
        assert r1["overlap_hidden_us"] == pytest.approx(20)
        assert r1["exclusive_us"] == pytest.approx(100 - 20)

    def test_hidden_column_names_the_hidden_stage(self):
        # round 1's fold runs ENTIRELY under round 0's span: all of its
        # blame moves to the overlap_hidden_us column
        events = [
            _ev("serving.round", 0, 100, "r0", round=0),
            _ev("serving.round", 50, 100, "r1", round=1),
            _ev("serving.fold", 55, 40, "f1", parent="r1"),
        ]
        summary = cp.summarize_overlapped(events)
        table = {
            (r["stage"], r["shard"]): r for r in summary["stages"]
        }
        fold = table[("serving.fold", None)]
        assert fold["overlap_hidden_us"] == pytest.approx(40)
        assert fold["blame_us"] == pytest.approx(0)
        assert summary["max_blame_residual"] < 1e-9

    def test_reduces_to_sequential_summary_without_overlap(self):
        events = []
        for r in range(3):
            events += [
                _ev("serving.round", r * 200, 100, f"r{r}", round=r),
                _ev("serving.fold", r * 200 + 10, 50, f"f{r}",
                    parent=f"r{r}"),
            ]
        seq = cp.summarize(events)
        ovl = cp.summarize_overlapped(events)
        assert ovl["overlap_hidden_us"] == 0.0
        assert ovl["overlap_ratio"] == 0.0
        assert ovl["max_blame_residual"] < 1e-9
        seq_blame = {
            (r["stage"], r["shard"]): r["blame_us"]
            for r in seq["stages"]
        }
        ovl_blame = {
            (r["stage"], r["shard"]): r["blame_us"]
            for r in ovl["stages"]
        }
        assert seq_blame == ovl_blame

    def test_interval_clip_arithmetic(self):
        covered = []
        cp._add_interval(covered, 0.0, 10.0)
        cp._add_interval(covered, 20.0, 30.0)
        visible, hidden = cp._clip_to_uncovered(5.0, 25.0, covered)
        assert visible == [(10.0, 20.0)]
        assert hidden == pytest.approx(10.0)
        # merge across the gap
        cp._add_interval(covered, 8.0, 22.0)
        assert covered == [(0.0, 30.0)]


class TestLiveTracerRoundTrip:
    def test_recorded_spans_attribute_offline(self, tmp_path):
        import time

        obs.enable()
        with obs_tracing.span("serving.round", round=0, tenant="m0"):
            with obs_tracing.span("serving.fold"):
                time.sleep(0.002)
        path = str(tmp_path / "t.json")
        obs_tracing.tracer().export_chrome_trace(path)
        with open(path) as fh:
            events = json.load(fh)["traceEvents"]
        (row,) = cp.blame_rounds(events)
        blame = {r["stage"]: r["blame_us"] for r in row["stages"]}
        assert blame["serving.fold"] >= 2000  # the slept 2 ms
        assert sum(blame.values()) == pytest.approx(
            row["makespan_us"], rel=1e-6
        )

    def test_cli_critical_path_section(self, tmp_path, capsys):
        from byzpy_tpu.observability.__main__ import main

        obs.enable()
        for r in range(2):
            with obs_tracing.span("serving.round", round=r, tenant="m0"):
                with obs_tracing.span("serving.fold"):
                    pass
        path = str(tmp_path / "t.json")
        obs_tracing.tracer().export_chrome_trace(path)
        assert main([path, "--critical-path"]) == 0
        out = capsys.readouterr().out
        assert "critical-path blame" in out
        assert main([path, "--critical-path", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["critical_path"]["max_blame_residual"] < 1e-6
        assert len(doc["critical_path"]["rounds"]) == 2

"""Self-healing serving tier: durable round state, idempotent replays,
degraded mode, and the real SIGKILL drill.

The headline invariant, pinned here and by the chaos bench's recovery
lane: **a submission acked ``accepted`` is never lost and never folded
twice** — across duplicate wire replays (retry after a lost ack) and
across a SIGKILL of the frontend process mid-round.
"""

import asyncio

import numpy as np
import pytest

from byzpy_tpu.aggregators import CoordinateWiseMedian
from byzpy_tpu.resilience.breaker import BreakerPolicy
from byzpy_tpu.resilience.durable import DurabilityConfig
from byzpy_tpu.resilience.retry import RetryPolicy
from byzpy_tpu.serving import ServingClient, ServingFrontend, TenantConfig
from byzpy_tpu.serving.frontend import (
    DUPLICATE,
    REJECTED_QUARANTINED,
    _agg_digest,
)
from byzpy_tpu.utils.checkpoint import CheckpointNotFoundError

D = 16


def _grad(seed=0):
    return np.random.default_rng(seed).normal(size=D).astype(np.float32)


def _tenant(name="m0", **kw):
    defaults = dict(
        name=name,
        aggregator=CoordinateWiseMedian(),
        dim=D,
        window_s=0.02,
        cohort_cap=8,
        queue_capacity=32,
    )
    defaults.update(kw)
    return TenantConfig(**defaults)


def _dur(tmp_path, **kw):
    kw.setdefault("snapshot_every", 2)
    kw.setdefault("prune", False)
    return DurabilityConfig(directory=str(tmp_path / "dur"), **kw)


# ---------------------------------------------------------------------------
# idempotency (dedup layer)
# ---------------------------------------------------------------------------


def test_duplicate_seq_folds_exactly_once_bit_parity():
    """The acceptance contract at the dedup layer: replaying every frame
    twice changes NOTHING about the round aggregate (bit parity)."""

    def run(replay: bool):
        fe = ServingFrontend([_tenant()])
        for i in range(5):
            ok, reason = fe.submit("m0", f"c{i}", 0, _grad(i), seq=0)
            assert ok and reason == "accepted"
            if replay:
                ok, reason = fe.submit("m0", f"c{i}", 0, _grad(i), seq=0)
                assert ok and reason == DUPLICATE  # acked, not re-enqueued
        closed = fe.close_round_nowait("m0")
        assert closed is not None
        stats = fe.stats()["m0"]
        return _agg_digest(closed[2]), stats

    clean, s_clean = run(replay=False)
    replayed, s_replayed = run(replay=True)
    assert clean == replayed  # bit-for-bit: duplicates never folded
    assert s_clean["duplicates"] == 0
    assert s_replayed["duplicates"] == 5


def test_seq_monotonicity_is_per_client():
    fe = ServingFrontend([_tenant()])
    assert fe.submit("m0", "a", 0, _grad(1), seq=5) == (True, "accepted")
    # lower AND equal seqs for the same client are duplicates
    assert fe.submit("m0", "a", 0, _grad(2), seq=5)[1] == DUPLICATE
    assert fe.submit("m0", "a", 0, _grad(2), seq=3)[1] == DUPLICATE
    # a DIFFERENT client may reuse the number freely
    assert fe.submit("m0", "b", 0, _grad(3), seq=5) == (True, "accepted")
    # and the original client moves on with a higher seq
    assert fe.submit("m0", "a", 0, _grad(4), seq=6) == (True, "accepted")


def test_legacy_submissions_without_seq_never_dedupe():
    fe = ServingFrontend([_tenant()])
    for _ in range(3):
        assert fe.submit("m0", "a", 0, _grad(0)) == (True, "accepted")
    assert fe.stats()["m0"]["duplicates"] == 0
    assert fe.stats()["m0"]["outstanding"] == 3


# ---------------------------------------------------------------------------
# durable round state + recovery
# ---------------------------------------------------------------------------


def test_recover_restores_rounds_pending_and_dedup(tmp_path):
    dur = _dur(tmp_path)
    fe = ServingFrontend([_tenant()], durability=dur)
    # round 0 folds; then two accepted-but-unfolded submissions "die"
    # with the process (we simply abandon the object, as SIGKILL would)
    for i in range(4):
        assert fe.submit("m0", f"c{i}", 0, _grad(i), seq=10 + i)[0]
    closed = fe.close_round_nowait("m0")
    assert closed is not None and closed[0] == 0
    digest0 = _agg_digest(closed[2])
    assert fe.submit("m0", "c0", 1, _grad(50), seq=20)[0]
    assert fe.submit("m0", "c1", 1, _grad(51), seq=21)[0]

    fe2 = ServingFrontend.recover([_tenant()], dur)
    stats = fe2.stats()["m0"]
    assert stats["round_id"] == 1  # monotonic: resumes AFTER round 0
    assert stats["outstanding"] == 2  # the acked-unfolded pair survived
    assert stats["recovered_from"]["round_id"] == 1
    # stale replays of pre-kill frames dedupe against the recovered table
    assert fe2.submit("m0", "c0", 1, _grad(50), seq=20)[1] == DUPLICATE
    assert fe2.submit("m0", "c3", 1, _grad(3), seq=13)[1] == DUPLICATE
    # new traffic + close: the recovered pending folds exactly once
    closed = fe2.close_round_nowait("m0")
    assert closed is not None and closed[0] == 1
    assert sorted(closed[1].clients) == ["c0", "c1"]
    assert fe2.stats()["m0"]["outstanding"] == 0
    # the WAL recorded round 0's digest — continuity across the "kill"
    rec = fe2.recovered["m0"]
    assert rec.rounds == [(0, digest0)]


def test_recover_on_empty_directory_raises_typed_error(tmp_path):
    with pytest.raises(CheckpointNotFoundError, match="nothing to recover"):
        ServingFrontend.recover([_tenant()], _dur(tmp_path))


def test_constructor_on_fresh_directory_starts_clean(tmp_path):
    fe = ServingFrontend([_tenant()], durability=_dur(tmp_path))
    assert fe.recovered == {"m0": None}
    assert fe.stats()["m0"]["recovered_from"] is None


def test_snapshot_cadence_and_recovery_from_snapshot(tmp_path):
    dur = _dur(tmp_path, snapshot_every=2)
    fe = ServingFrontend([_tenant()], durability=dur)
    for r in range(5):
        for i in range(3):
            assert fe.submit("m0", f"c{i}", r, _grad(r * 10 + i))[0]
        assert fe.close_round_nowait("m0") is not None
    t = fe._tenants["m0"]
    assert t.durability.snaps.all_steps()  # the cadence actually fired
    fe2 = ServingFrontend.recover([_tenant()], dur)
    stats = fe2.stats()["m0"]
    assert stats["round_id"] == 5
    assert stats["recovered_from"]["snapshot"] is not None


def test_failed_round_drop_is_not_resurrected(tmp_path):
    """Crash-guarded rounds drop their cohort WITH accounting; recovery
    must not re-enqueue those rows as pending."""

    class Poison:
        def aggregate_masked(self, matrix, valid):
            raise RuntimeError("poisoned cohort")

        def validate_n(self, n):
            return None

    dur = _dur(tmp_path)
    fe = ServingFrontend([_tenant(aggregator=Poison())], durability=dur)
    assert fe.submit("m0", "a", 0, _grad(0), seq=0)[0]
    assert fe.close_round_nowait("m0") is None  # crash-guarded drop
    assert fe.stats()["m0"]["failed_rounds"] == 1
    fe2 = ServingFrontend.recover([_tenant(aggregator=Poison())], dur)
    assert fe2.stats()["m0"]["outstanding"] == 0  # dropped, not pending


# ---------------------------------------------------------------------------
# degraded mode (circuit breaker)
# ---------------------------------------------------------------------------


def test_breaker_quarantines_after_consecutive_failures_then_recovers():
    class Flaky:
        poisoned = True

        def aggregate_masked(self, matrix, valid):
            if self.poisoned:
                raise RuntimeError("boom")
            return np.asarray(matrix[np.asarray(valid)].mean(axis=0))

        def validate_n(self, n):
            return None

    t = [0.0]
    agg = Flaky()
    fe = ServingFrontend(
        [_tenant(aggregator=agg,
                 breaker=BreakerPolicy(threshold=2, cooldown_s=5.0))],
        clock=lambda: t[0],
    )
    # two consecutive failed rounds open the breaker; the second's drain
    # clears whatever is queued
    for r in range(2):
        assert fe.submit("m0", "a", 0, _grad(r))[0]
        assert fe.close_round_nowait("m0") is None
    stats = fe.stats()["m0"]
    assert stats["breaker"]["state"] == "open"
    assert stats["failed_rounds"] == 2
    # quarantined: explicit rejection, no crash loop, no silent acks
    ok, reason = fe.submit("m0", "a", 0, _grad(9))
    assert not ok and reason == REJECTED_QUARANTINED
    # cooldown elapses: half-open probe round is admitted and succeeds
    t[0] = 5.0
    agg.poisoned = False
    assert fe.submit("m0", "a", 0, _grad(10))[0]
    assert fe.close_round_nowait("m0") is not None
    assert fe.stats()["m0"]["breaker"]["state"] == "closed"
    assert fe.submit("m0", "a", 0, _grad(11))[0]


def test_breaker_open_drains_queue_with_accounting():
    class Poison:
        def aggregate_masked(self, matrix, valid):
            raise RuntimeError("boom")

        def validate_n(self, n):
            return None

    fe = ServingFrontend(
        [_tenant(aggregator=Poison(), cohort_cap=2,
                 breaker=BreakerPolicy(threshold=1, cooldown_s=60.0))]
    )
    # 4 accepted; the closer pops 2 (cohort_cap) and fails; the breaker
    # opens and the drain clears the 2 still queued
    for i in range(4):
        assert fe.submit("m0", f"c{i}", 0, _grad(i))[0]
    assert fe.close_round_nowait("m0") is None
    stats = fe.stats()["m0"]
    assert stats["quarantine_drops"] == 2
    assert stats["outstanding"] == 0  # nothing silently parked


# ---------------------------------------------------------------------------
# client: context manager + retry + wire idempotency
# ---------------------------------------------------------------------------


def test_serving_client_context_manager_closes_writer():
    async def run():
        fe = ServingFrontend([_tenant()])
        host, port = await fe.serve("127.0.0.1", 0)
        try:
            with pytest.raises(RuntimeError, match="mid-test"):
                async with ServingClient() as c:
                    await c.connect(host, port)
                    writer = c._writer
                    assert writer is not None
                    raise RuntimeError("mid-test")
            # __aexit__ closed the writer even though the body raised
            assert c._writer is None and writer.is_closing()
        finally:
            await fe.close()

    asyncio.run(run())


def test_serving_client_reconnects_and_dedupes_over_tcp(tmp_path):
    """Kill the TCP server between acks; the client's retry loop redials
    the restarted server and replays — the dedup layer + durable state
    keep folding exactly-once."""

    async def run():
        dur = _dur(tmp_path)
        fe = ServingFrontend([_tenant()], durability=dur)
        host, port = await fe.serve("127.0.0.1", 0)
        async with ServingClient(
            retry=RetryPolicy(max_attempts=6, base_s=0.01, cap_s=0.05,
                              deadline_s=10.0)
        ) as c:
            await c.connect(host, port)
            for i in range(3):
                ack = await c.submit("m0", f"c{i}", 0, _grad(i))
                assert ack["accepted"]
            await fe.close()  # the "crash": connection dies with it

            fe2 = ServingFrontend.recover([_tenant()], dur)
            host2, port2 = await fe2.serve("127.0.0.1", port)
            try:
                # same port: the client's next call rides its retry loop
                # through the dead connection onto the recovered server
                ack = await c.submit("m0", "c0", 0, _grad(0), seq=0)
                assert ack["accepted"] and ack["reason"] == DUPLICATE
                ack = await c.submit("m0", "c3", 0, _grad(3))
                assert ack["accepted"] and ack["reason"] == "accepted"
                assert c.reconnects >= 1
                r = await c.close_round(TENANT_NAME)
                assert r["closed"] == 0
                stats = (await c.stats("m0"))["stats"]
                assert stats["outstanding"] == 0
                assert stats["round_id"] == 1
            finally:
                await fe2.close()

    TENANT_NAME = "m0"
    asyncio.run(run())


def test_close_round_wire_door_requires_sync_mode():
    async def run():
        fe = ServingFrontend([_tenant()])
        await fe.start()
        host, port = await fe.serve("127.0.0.1", 0)
        try:
            async with ServingClient() as c:
                await c.connect(host, port)
                r = await c.close_round("m0")
                assert r["accepted"] is False
                assert "close_round_unavailable" in r["reason"]
        finally:
            await fe.close()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# the real SIGKILL drill (subprocess; one seeded cycle)
# ---------------------------------------------------------------------------


def test_sigkill_drill_zero_invariant_violations(tmp_path):
    """SIGKILL a real TCP frontend mid-round; recovery must preserve
    every acked submission exactly once with monotonic rounds and digest
    continuity (the full 20-seed sweep runs in the chaos bench)."""
    from byzpy_tpu.resilience.drill import run_kill_recover

    row = run_kill_recover(123, str(tmp_path / "drill"))
    assert row["violations"] == 0, row
    assert row["lost"] == 0 and row["double_folded"] == 0
    assert row["rounds_monotonic"] and row["digest_breaks"] == 0
    assert row["duplicates_absorbed"] == 5
    assert row["recovery_metric_exported"]


def test_wire_drop_lane_bit_parity():
    from byzpy_tpu.resilience.drill import run_wire_drop

    row = run_wire_drop(7)
    assert row["violations"] == 0, row
    assert row["bit_parity"] and row["duplicates_absorbed"] >= 1


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------


def test_wal_append_failure_refuses_ack_and_enqueues_nothing(tmp_path):
    """If the write-ahead append fails (disk full), the ack cannot be a
    durable promise: the submission is refused with an explicit reason,
    NOTHING is enqueued (no fold of an unlogged row), and a later retry
    under the same seq succeeds once the disk heals."""
    from byzpy_tpu.serving.frontend import REJECTED_UNDURABLE

    fe = ServingFrontend([_tenant()], durability=_dur(tmp_path))
    t = fe._tenants["m0"]
    real_append = t.durability.record_accept
    t.durability.record_accept = lambda *a, **k: (_ for _ in ()).throw(
        OSError("no space left on device")
    )
    ok, reason = fe.submit("m0", "a", 0, _grad(0), seq=5)
    assert not ok and reason == REJECTED_UNDURABLE
    assert t.queue.depth() == 0 and t.outstanding == 0  # nothing queued
    # the seq was NOT consumed: the healed retry is not a duplicate
    t.durability.record_accept = real_append
    assert fe.submit("m0", "a", 0, _grad(0), seq=5) == (True, "accepted")
    assert t.queue.depth() == 1


def test_failed_recover_leaves_no_trace_behind(tmp_path):
    """A recover() attempt on a fresh/wrong directory must not create
    artifacts that make a SECOND attempt silently 'recover' empty
    state — both attempts raise the typed error."""
    dur = _dur(tmp_path)
    with pytest.raises(CheckpointNotFoundError):
        ServingFrontend.recover([_tenant()], dur)
    with pytest.raises(CheckpointNotFoundError):
        ServingFrontend.recover([_tenant()], dur)  # still nothing there
    # and a real durable frontend afterwards starts genuinely fresh
    fe = ServingFrontend([_tenant()], durability=dur)
    assert fe.recovered == {"m0": None}


def test_close_round_never_resent_on_ambiguous_wire_death():
    """close_round is not idempotent: a connection that dies before the
    ack must raise, not reconnect-and-resend (two closed rounds)."""

    async def run():
        served = {"requests": 0}

        async def swallow(reader, writer):
            from byzpy_tpu.engine.actor import wire
            try:
                header = await reader.readexactly(wire._HEADER.size)
                (length,) = wire._HEADER.unpack(header)
                await reader.readexactly(length)
                served["requests"] += 1
            except Exception:
                pass
            writer.close()  # no reply: the ambiguous shape

        server = await asyncio.start_server(swallow, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        try:
            async with ServingClient(
                retry=RetryPolicy(max_attempts=5, base_s=0.01, cap_s=0.02,
                                  deadline_s=5.0)
            ) as c:
                await c.connect("127.0.0.1", port)
                with pytest.raises(RuntimeError, match="non-idempotent"):
                    await c.close_round("m0")
        finally:
            server.close()
            await server.wait_closed()
        assert served["requests"] == 1  # sent once, never replayed

    asyncio.run(run())

"""Elastic P2P: gossip training continues through a node death.

The PS analogue lives in ``ParameterServer(elastic=...)``; for the
decentralized fabric the policy loop is liveness-driven —
``HeartbeatMonitor.on_suspect -> DecentralizedPeerToPeer.remove_node`` —
after which the survivors gossip over the induced sub-topology with
shrunken expected-message counts.
"""

from __future__ import annotations

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from byzpy_tpu.aggregators import CoordinateWiseMedian
from byzpy_tpu.engine.node.context import InProcessContext
from byzpy_tpu.engine.node.liveness import HeartbeatMonitor
from byzpy_tpu.engine.peer_to_peer import HeartbeatPolicy, Topology
from byzpy_tpu.engine.peer_to_peer.nodes import HonestP2PWorker
from byzpy_tpu.engine.peer_to_peer.runner import DecentralizedPeerToPeer


class QuadWorker(HonestP2PWorker):
    def __init__(self, target, dim=6):
        self.target = jnp.full((dim,), float(target), jnp.float32)
        self.w = jnp.zeros((dim,), jnp.float32)

    def half_step(self, lr):
        self.w = self.w - lr * 2.0 * (self.w - self.target)
        return self.w

    def parameters(self):
        return self.w

    def apply_aggregate(self, vector):
        self.w = jnp.asarray(vector)


@pytest.fixture(autouse=True)
def clean_registry():
    InProcessContext._registry.clear()
    yield
    InProcessContext._registry.clear()


def test_remove_node_mid_training_rounds_continue():
    """Train, excise a node, keep training: the survivors' expected
    counts shrink with the induced topology and consensus proceeds
    without the removed peer."""
    async def run():
        workers = [QuadWorker(t) for t in (0.0, 1.0, 2.0, 9.0)]
        p2p = DecentralizedPeerToPeer(
            workers, [], aggregator=CoordinateWiseMedian(),
            topology=Topology.complete(4), learning_rate=0.3,
        )
        async with p2p:
            for _ in range(3):
                await p2p.run_round_async()
            assert p2p._honest_expected(0) == 3
            await p2p.remove_node(3)  # the outlier-target peer leaves
            assert p2p.honest_indices == [0, 1, 2]
            assert p2p._honest_expected(0) == 2
            for _ in range(30):
                await p2p.run_round_async()
            # consensus over the survivors' targets (median of 0, 1, 2),
            # no longer dragged by the removed node's target 9
            for i in (0, 1, 2):
                np.testing.assert_allclose(
                    np.asarray(workers[i].w), 1.0, atol=0.1
                )
            assert p2p.rounds_completed == 33
    asyncio.run(run())


def test_remove_node_guards():
    async def run():
        workers = [QuadWorker(t) for t in (0.0, 1.0)]
        p2p = DecentralizedPeerToPeer(
            workers, [], aggregator=CoordinateWiseMedian(),
            topology=Topology.complete(2), learning_rate=0.3,
        )
        async with p2p:
            with pytest.raises(KeyError):
                await p2p.remove_node(7)
            await p2p.remove_node(1)
            with pytest.raises(ValueError, match="last honest node"):
                await p2p.remove_node(0)
    asyncio.run(run())


def test_remove_node_rejects_unbounded_gossip_timeout():
    """gossip_timeout=None would make removal wait forever on an
    in-flight round's dead-peer gossip (advisor r4) — refused up front."""
    async def run():
        workers = [QuadWorker(t) for t in (0.0, 1.0, 2.0)]
        p2p = DecentralizedPeerToPeer(
            workers, [], aggregator=CoordinateWiseMedian(),
            topology=Topology.complete(3), learning_rate=0.3,
            gossip_timeout=None,
        )
        async with p2p:
            with pytest.raises(ValueError, match="finite gossip_timeout"):
                await p2p.remove_node(2)
    asyncio.run(run())


def test_heartbeat_drives_removal_end_to_end():
    """The full policy loop: a peer DIES (shutdown, no goodbye), the
    observer's heartbeat monitor suspects it, on_suspect excises it from
    the runner, and training rounds keep completing."""
    async def run():
        workers = [QuadWorker(t) for t in (0.0, 1.0, 2.0, 9.0)]
        p2p = DecentralizedPeerToPeer(
            workers, [], aggregator=CoordinateWiseMedian(),
            topology=Topology.complete(4), learning_rate=0.3,
        )
        async with p2p:
            await p2p.run_round_async()
            removed = asyncio.Event()
            victim_gi = 3
            victim_id = p2p.node_ids[victim_gi]

            def on_suspect(peer_id):
                assert peer_id == victim_id

                async def act():
                    await p2p.remove_node(victim_gi)
                    removed.set()
                asyncio.get_running_loop().create_task(act())

            for gi, node in p2p.nodes.items():
                if gi != 0:
                    HeartbeatMonitor.install_responder(node)
            mon = HeartbeatMonitor(
                p2p.nodes[0], interval=0.05, max_missed=3,
                on_suspect=on_suspect,
            )
            await mon.start()
            try:
                # wait for the monitor to see everyone, then kill the peer
                for _ in range(100):
                    if len(mon.alive()) == 3:
                        break
                    await asyncio.sleep(0.05)
                await p2p.nodes[victim_gi].shutdown()
                await asyncio.wait_for(removed.wait(), timeout=10.0)
                for _ in range(20):
                    await p2p.run_round_async()
                for i in (0, 1, 2):
                    np.testing.assert_allclose(
                        np.asarray(workers[i].w), 1.0, atol=0.15
                    )
            finally:
                await mon.stop()
    asyncio.run(run())


def test_heartbeat_policy_excises_dead_peer_without_wiring():
    """The shipped default policy (VERDICT r4 #7): construct with
    ``elastic=HeartbeatPolicy(...)`` and a dead peer is excised with NO
    test-side monitor/responder/callback wiring at all."""
    async def run():
        workers = [QuadWorker(t) for t in (0.0, 1.0, 2.0, 9.0)]
        p2p = DecentralizedPeerToPeer(
            workers, [], aggregator=CoordinateWiseMedian(),
            topology=Topology.complete(4), learning_rate=0.3,
            elastic=HeartbeatPolicy(interval=0.05, max_missed=3),
        )
        async with p2p:
            await p2p.run_round_async()
            victim_id = p2p.node_ids[3]
            await p2p.nodes[3].shutdown()  # dies, no goodbye
            for _ in range(200):
                if (victim_id, "removed") in p2p.elastic_events:
                    break
                await asyncio.sleep(0.05)
            assert (victim_id, "removed") in p2p.elastic_events
            assert p2p.honest_indices == [0, 1, 2]
            for _ in range(20):
                await p2p.run_round_async()
            for i in (0, 1, 2):
                np.testing.assert_allclose(
                    np.asarray(workers[i].w), 1.0, atol=0.15
                )
    asyncio.run(run())


def test_heartbeat_policy_requires_finite_gossip_timeout():
    with pytest.raises(ValueError, match="finite gossip_timeout"):
        DecentralizedPeerToPeer(
            [QuadWorker(0.0), QuadWorker(1.0)], [],
            aggregator=CoordinateWiseMedian(),
            topology=Topology.complete(2), gossip_timeout=None,
            elastic=HeartbeatPolicy(),
        )


def test_resetup_after_removal_uses_shrunken_fabric():
    """shutdown() then re-enter: the fabric must come back up with only
    the survivors (review finding: re-setup used to iterate the full
    original topology and KeyError on the popped worker)."""
    async def run():
        workers = [QuadWorker(t) for t in (0.0, 1.0, 2.0, 9.0)]
        p2p = DecentralizedPeerToPeer(
            workers, [], aggregator=CoordinateWiseMedian(),
            topology=Topology.complete(4), learning_rate=0.3,
        )
        async with p2p:
            await p2p.run_round_async()
            await p2p.remove_node(3)
        # re-enter on the shrunken fabric
        async with p2p:
            assert sorted(p2p.nodes) == [0, 1, 2]
            assert p2p._honest_expected(0) == 2
            for _ in range(20):
                await p2p.run_round_async()
            for i in (0, 1, 2):
                np.testing.assert_allclose(
                    np.asarray(workers[i].w), 1.0, atol=0.15
                )
    asyncio.run(run())


def test_remove_node_serializes_with_inflight_round():
    """A round already in flight completes against the OLD membership
    (the lock delays the removal); the next round sees the new one."""
    async def run():
        workers = [QuadWorker(t) for t in (0.0, 1.0, 2.0, 9.0)]
        p2p = DecentralizedPeerToPeer(
            workers, [], aggregator=CoordinateWiseMedian(),
            topology=Topology.complete(4), learning_rate=0.3,
        )
        async with p2p:
            round_task = asyncio.create_task(p2p.run_round_async())
            await asyncio.sleep(0)  # let the round take the lock
            await p2p.remove_node(3)
            out = await round_task  # must not have raced the removal
            assert sorted(out) in ([0, 1, 2], [0, 1, 2, 3])
            out = await p2p.run_round_async()
            assert sorted(out) == [0, 1, 2]
    asyncio.run(run())

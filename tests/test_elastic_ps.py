"""Elastic parameter-server rounds (crash/omission fault tolerance).

The reference's PS round fails outright when any node raises
(``byzpy/engine/parameter_server/ps.py:103-144``); with an
``ElasticPolicy`` a failure costs the node its slot, suspects are
probed for re-admission, and ``min_quorum`` guards the aggregator's
f-of-n assumption.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean, MultiKrum
from byzpy_tpu.engine.parameter_server import (
    ElasticPolicy,
    ParameterServer,
    QuorumLostError,
)


class Node:
    def __init__(self, value, d=64):
        self.value = float(value)
        self.d = d
        self.applied = []

    def honest_gradient_for_next_batch(self):
        return [np.full(self.d, self.value, np.float32)]

    def apply_server_gradient(self, g):
        self.applied.append(g)


class CrashingNode(Node):
    """Fails for ``fail_rounds`` calls, then recovers."""

    def __init__(self, value, fail_rounds=10**9, **kw):
        super().__init__(value, **kw)
        self.fail_rounds = fail_rounds
        self.calls = 0

    def honest_gradient_for_next_batch(self):
        self.calls += 1
        if self.calls <= self.fail_rounds:
            raise ConnectionError("node down")
        return super().honest_gradient_for_next_batch()


class HangingNode(Node):
    async def honest_gradient_for_next_batch(self):
        await asyncio.sleep(30.0)
        return [np.full(self.d, self.value, np.float32)]


class HangingSyncNode(Node):
    """A *plain sync* node that hangs — no awaitable for the loop to
    time out; only the to_thread dispatch in ``call_node`` lets
    ``call_timeout`` fire (the hang previously blocked the event loop
    itself)."""

    def honest_gradient_for_next_batch(self):
        import time

        time.sleep(5.0)
        return [np.full(self.d, self.value, np.float32)]


class ApplyFailsNode(Node):
    def apply_server_gradient(self, g):
        raise RuntimeError("disk full")


def run(coro):
    return asyncio.run(coro)


def test_default_semantics_unchanged_failure_raises():
    ps = ParameterServer(
        honest_nodes=[Node(1.0), CrashingNode(2.0)],
        aggregator=CoordinateWiseTrimmedMean(f=0),
    )
    with pytest.raises(ConnectionError):
        run(ps.round())


def test_crash_excludes_node_and_round_succeeds():
    nodes = [Node(v) for v in (1.0, 2.0, 3.0)] + [CrashingNode(100.0)]
    ps = ParameterServer(
        honest_nodes=nodes,
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=2),
    )
    out = run(ps.round())
    # mean of the three alive values; the crasher contributed nothing
    np.testing.assert_allclose(np.asarray(out[0]), np.full(64, 2.0), rtol=1e-6)
    assert "honest:3" in ps.elastic_state.suspects
    assert ps.rounds_completed == 1
    # the suspect got no apply fan-out; alive nodes did
    assert nodes[3].applied == []
    assert len(nodes[0].applied) == 1


def test_recovery_readmits_node():
    flaky = CrashingNode(4.0, fail_rounds=2)
    nodes = [Node(v) for v in (1.0, 2.0, 3.0)] + [flaky]
    ps = ParameterServer(
        honest_nodes=nodes,
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=2, readmit_every=1),
    )
    run(ps.round())  # fails, suspected
    run(ps.round())  # probe fails again
    assert "honest:3" in ps.elastic_state.suspects
    out = run(ps.round())  # probe succeeds -> readmitted, contributes
    assert "honest:3" not in ps.elastic_state.suspects
    np.testing.assert_allclose(np.asarray(out[0]), np.full(64, 2.5), rtol=1e-6)
    kinds = [k for _, nid, k in ps.elastic_state.events if nid == "honest:3"]
    assert "suspected" in kinds and "readmitted" in kinds


def test_readmit_every_zero_never_probes():
    flaky = CrashingNode(4.0, fail_rounds=1)
    ps = ParameterServer(
        honest_nodes=[Node(1.0), flaky],
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=1, readmit_every=0),
    )
    run(ps.round())
    run(ps.round())
    run(ps.round())
    assert "honest:1" in ps.elastic_state.suspects
    assert flaky.calls == 1  # never probed again


def test_quorum_lost_raises():
    ps = ParameterServer(
        honest_nodes=[Node(1.0), CrashingNode(2.0), CrashingNode(3.0)],
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=2),
    )
    with pytest.raises(QuorumLostError, match="min_quorum=2"):
        run(ps.round())
    assert ps.rounds_completed == 0


def test_min_quorum_validated_against_node_count():
    with pytest.raises(ValueError, match="min_quorum"):
        ParameterServer(
            honest_nodes=[Node(1.0)],
            aggregator=CoordinateWiseTrimmedMean(f=0),
            elastic=ElasticPolicy(min_quorum=2),
        )


def test_call_timeout_excludes_hanging_node():
    nodes = [Node(1.0), Node(3.0), HangingNode(100.0)]
    ps = ParameterServer(
        honest_nodes=nodes,
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=2, call_timeout=0.2),
    )
    out = run(ps.round())
    np.testing.assert_allclose(np.asarray(out[0]), np.full(64, 2.0), rtol=1e-6)
    assert "honest:2" in ps.elastic_state.suspects


def test_call_timeout_excludes_hanging_sync_node():
    """call_timeout must interrupt plain sync nodes too (advisor r4):
    the hung call runs in a worker thread, the round completes without
    it well before the node's 5 s sleep ends."""
    import time

    nodes = [Node(1.0), Node(3.0), HangingSyncNode(100.0)]
    ps = ParameterServer(
        honest_nodes=nodes,
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=2, call_timeout=0.2),
    )
    t0 = time.monotonic()
    out = run(ps.round())
    assert time.monotonic() - t0 < 4.0
    np.testing.assert_allclose(np.asarray(out[0]), np.full(64, 2.0), rtol=1e-6)
    assert "honest:2" in ps.elastic_state.suspects


def test_apply_failure_tolerated_and_suspected():
    nodes = [Node(1.0), Node(3.0), ApplyFailsNode(2.0)]
    ps = ParameterServer(
        honest_nodes=nodes,
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=1),
    )
    out = run(ps.round())  # round result stands despite the apply failure
    np.testing.assert_allclose(np.asarray(out[0]), np.full(64, 2.0), rtol=1e-6)
    assert "honest:2" in ps.elastic_state.suspects


def test_byzantine_crash_is_tolerated():
    class ByzCrash:
        def byzantine_gradient_for_next_batch(self, honest):
            raise OSError("gone")

        def apply_server_gradient(self, g):
            pass

    ps = ParameterServer(
        honest_nodes=[Node(v) for v in (1.0, 2.0, 3.0, 4.0)],
        byzantine_nodes=[ByzCrash()],
        aggregator=MultiKrum(f=1, q=2),
        elastic=ElasticPolicy(min_quorum=3),
    )
    out = run(ps.round())
    assert np.isfinite(np.asarray(out[0])).all()
    assert "byzantine:0" in ps.elastic_state.suspects


def test_external_suspects_skipped_without_probe():
    flagged = Node(100.0)
    ps = ParameterServer(
        honest_nodes=[Node(1.0), Node(3.0), flagged],
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(
            min_quorum=1, external_suspects=lambda: ["honest:2"]
        ),
    )
    out = run(ps.round())
    np.testing.assert_allclose(np.asarray(out[0]), np.full(64, 2.0), rtol=1e-6)
    kinds = [k for _, nid, k in ps.elastic_state.events if nid == "honest:2"]
    assert kinds == ["skipped_external"]
    # the flagged node is out of the apply fan-out too — delivering the
    # update to a node the fabric knows is dead would hang the round
    assert flagged.applied == []


def test_hanging_external_suspect_does_not_block_round():
    """A dead node flagged externally must not cost the round anything —
    not even the call_timeout (here: no timeout is set at all, so any
    contact with the hung node would block forever)."""
    class HungEverywhere(Node):
        async def honest_gradient_for_next_batch(self):
            await asyncio.sleep(30.0)

        async def apply_server_gradient(self, g):
            await asyncio.sleep(30.0)

    ps = ParameterServer(
        honest_nodes=[Node(1.0), Node(3.0), HungEverywhere(9.0)],
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(
            min_quorum=1, external_suspects=lambda: ["honest:2"]
        ),
    )
    async def bounded():
        return await asyncio.wait_for(ps.round(), timeout=5.0)
    out = run(bounded())
    np.testing.assert_allclose(np.asarray(out[0]), np.full(64, 2.0), rtol=1e-6)


def test_events_ring_is_bounded():
    from byzpy_tpu.engine.parameter_server.elastic import MAX_EVENTS

    ps = ParameterServer(
        honest_nodes=[Node(1.0), CrashingNode(2.0)],
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=1),
    )
    for _ in range(60):
        run(ps.round())
    assert len(ps.elastic_state.events) <= MAX_EVENTS
    assert ps.elastic_state.events.maxlen == MAX_EVENTS


def test_elastic_training_converges_through_crashes():
    """10-round run where one node dies at round 3 and recovers at round
    6: every round still aggregates, and the suspect set ends empty."""
    class Intermittent(Node):
        def __init__(self, value):
            super().__init__(value)
            self.round_no = 0

        def honest_gradient_for_next_batch(self):
            self.round_no += 1
            if 3 <= self.round_no <= 5:
                raise ConnectionError("flaky link")
            return super().honest_gradient_for_next_batch()

    nodes = [Node(v) for v in (1.0, 2.0)] + [Intermittent(3.0)]
    ps = ParameterServer(
        honest_nodes=nodes,
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=2),
    )
    for _ in range(10):
        out = run(ps.round())
        assert np.isfinite(np.asarray(out[0])).all()
    assert ps.rounds_completed == 10
    assert ps.elastic_state.suspects == {}


def test_timed_out_sync_node_is_never_reentered_concurrently():
    """A timed-out sync call keeps running in its daemon thread; the next
    round's re-admission probe must NOT dispatch a second thread into the
    same (non-thread-safe) node object — it fails fast with NodeBusyError
    and the node stays suspected until the zombie call drains."""
    import threading
    import time

    class StallingNode(Node):
        def __init__(self, value):
            super().__init__(value)
            self.concurrent = 0
            self.max_concurrent = 0
            self._lock = threading.Lock()

        def honest_gradient_for_next_batch(self):
            with self._lock:
                self.concurrent += 1
                self.max_concurrent = max(self.max_concurrent, self.concurrent)
            try:
                time.sleep(1.5)
                return [np.full(self.d, self.value, np.float32)]
            finally:
                with self._lock:
                    self.concurrent -= 1

    stalling = StallingNode(100.0)
    ps = ParameterServer(
        honest_nodes=[Node(1.0), Node(3.0), stalling],
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=2, call_timeout=0.2,
                              readmit_every=1),
    )

    async def rounds():
        for _ in range(4):  # probes re-hit the stalling node every round
            out = await ps.round()
            np.testing.assert_allclose(
                np.asarray(out[0]), np.full(64, 2.0), rtol=1e-6
            )

    run(rounds())
    assert "honest:2" in ps.elastic_state.suspects
    assert stalling.max_concurrent == 1, stalling.max_concurrent


# ---------------------------------------------------------------------------
# readmission with param resync (ElasticPolicy.resync)
# ---------------------------------------------------------------------------


class ResyncNode(Node):
    """Records the authoritative state pushed on re-admission."""

    def __init__(self, value, fail_rounds=0, **kw):
        super().__init__(value, **kw)
        self.fail_rounds = fail_rounds
        self.calls = 0
        self.resyncs = []

    def honest_gradient_for_next_batch(self):
        self.calls += 1
        if self.calls <= self.fail_rounds:
            raise ConnectionError("node down")
        return super().honest_gradient_for_next_batch()

    def resync_params(self, state):
        self.resyncs.append(state)


def test_readmit_resyncs_params_before_first_counted_gradient():
    """A restarted worker receives the authoritative state BEFORE its
    gradient re-enters the aggregate; the suspicion record clears and
    the event stream shows resync -> readmitted."""
    flaky = ResyncNode(4.0, fail_rounds=1)
    nodes = [Node(v) for v in (1.0, 2.0, 3.0)] + [flaky]
    authoritative = {"params": np.full(4, 7.0, np.float32), "round": 0}
    ps = ParameterServer(
        honest_nodes=nodes,
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(
            min_quorum=2, resync=lambda: authoritative
        ),
    )
    run(ps.round())  # flaky crashes -> suspected
    assert "honest:3" in ps.elastic_state.suspects
    assert flaky.resyncs == []
    run(ps.round())  # probe: resync first, then the gradient counts
    assert "honest:3" not in ps.elastic_state.suspects
    assert len(flaky.resyncs) == 1
    assert flaky.resyncs[0] is authoritative
    kinds = [
        kind for _, nid, kind in ps.elastic_state.events if nid == "honest:3"
    ]
    assert "resync" in kinds and "readmitted" in kinds
    assert kinds.index("resync") < kinds.index("readmitted")


def test_readmit_without_resync_hook_keeps_old_path():
    flaky = ResyncNode(4.0, fail_rounds=1)
    nodes = [Node(v) for v in (1.0, 2.0, 3.0)] + [flaky]
    ps = ParameterServer(
        honest_nodes=nodes,
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=2),
    )
    run(ps.round())
    run(ps.round())
    assert "honest:3" not in ps.elastic_state.suspects  # readmitted
    assert flaky.resyncs == []  # never pushed without the hook


def test_failed_resync_keeps_node_suspected():
    class ResyncRefuses(ResyncNode):
        def resync_params(self, state):
            raise ConnectionError("still rebooting")

    flaky = ResyncRefuses(4.0, fail_rounds=1)
    nodes = [Node(v) for v in (1.0, 2.0, 3.0)] + [flaky]
    ps = ParameterServer(
        honest_nodes=nodes,
        aggregator=CoordinateWiseTrimmedMean(f=0),
        elastic=ElasticPolicy(min_quorum=2, resync=lambda: {"p": 1}),
    )
    run(ps.round())
    out = run(ps.round())  # resync fails -> node stays out this round
    assert "honest:3" in ps.elastic_state.suspects
    np.testing.assert_allclose(np.asarray(out[0]), np.full(64, 2.0), rtol=1e-6)


def test_elastic_state_readmit_is_idempotent_and_eventful():
    from byzpy_tpu.engine.parameter_server.elastic import ElasticState

    state = ElasticState()
    state.fail(0, "honest:1", ConnectionError("down"))
    assert "honest:1" in state.suspects
    state.readmit(1, "honest:1")
    assert "honest:1" not in state.suspects
    assert (1, "honest:1", "readmitted") in list(state.events)
    before = len(state.events)
    state.readmit(2, "honest:1")  # second readmit: no-op, no event spam
    assert len(state.events) == before

"""Elastic PS against a REAL dying remote node (loopback TCP actor).

``tests/test_elastic_ps.py`` exercises the policy with in-process fakes;
this is the failure mode elasticity exists for: a node lives in a
:class:`RemoteActorServer` across a socket, the server dies mid-training,
and the round must survive on the local survivors with the remote node
suspected — where the default (reference-semantics) path fails the
round outright.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
from byzpy_tpu.engine.actor.backends.remote import RemoteActorServer
from byzpy_tpu.engine.node.actors import HonestNodeActor
from byzpy_tpu.engine.node.base import HonestNode
from byzpy_tpu.engine.parameter_server import ElasticPolicy, ParameterServer

D = 32


class LocalNode:
    def __init__(self, value):
        self.value = float(value)

    def honest_gradient_for_next_batch(self):
        return [np.full(D, self.value, np.float32)]

    def apply_server_gradient(self, g):
        self.applied = g


class RemoteNode(HonestNode):
    """Lives inside the RemoteActorServer process-side backend."""

    def __init__(self, value):
        self.value = float(value)

    def next_batch(self):
        return None, None

    def honest_gradient(self, x, y):
        return [np.full(D, self.value, np.float32)]

    def apply_server_gradient(self, g):
        self.applied = g


def test_remote_node_death_survived_and_suspected():
    asyncio.run(_run_survival())


async def _run_survival():
    server = RemoteActorServer("127.0.0.1", 0)
    await server.start()
    try:
        remote = await HonestNodeActor.spawn(
            RemoteNode, 3.0, backend=f"tcp://127.0.0.1:{server.port}"
        )
        nodes = [LocalNode(1.0), LocalNode(2.0), remote]
        ps = ParameterServer(
            honest_nodes=nodes,
            aggregator=CoordinateWiseTrimmedMean(f=0),
            elastic=ElasticPolicy(min_quorum=2, call_timeout=5.0),
        )
        out = await ps.round()
        np.testing.assert_allclose(
            np.asarray(out[0]), np.full(D, 2.0), rtol=1e-6
        )
        assert ps.elastic_state.suspects == {}

        # the remote host dies between rounds
        await server.close()
        out = await ps.round()
        # survivors carry the round; the dead remote is suspected
        np.testing.assert_allclose(
            np.asarray(out[0]), np.full(D, 1.5), rtol=1e-6
        )
        assert "honest:2" in ps.elastic_state.suspects
        assert ps.rounds_completed == 2
        await remote.close()

        # ... and stays out without failing subsequent rounds either
        out = await ps.round()
        np.testing.assert_allclose(
            np.asarray(out[0]), np.full(D, 1.5), rtol=1e-6
        )
    finally:
        await server.close()


def test_remote_node_death_fails_default_round():
    asyncio.run(_run_default_fails())


async def _run_default_fails():
    """Reference semantics without the policy: the same dead remote node
    fails the whole round."""
    server = RemoteActorServer("127.0.0.1", 0)
    await server.start()
    try:
        remote = await HonestNodeActor.spawn(
            RemoteNode, 3.0, backend=f"tcp://127.0.0.1:{server.port}"
        )
        ps = ParameterServer(
            honest_nodes=[LocalNode(1.0), remote],
            aggregator=CoordinateWiseTrimmedMean(f=0),
        )
        await ps.round()
        await server.close()
        # a hang would surface as TimeoutError — that is a different
        # failure (round neither succeeded nor failed), so only accept
        # a genuine error from the dead connection
        try:
            await asyncio.wait_for(ps.round(), timeout=10.0)
        except asyncio.TimeoutError:
            raise AssertionError("round hung instead of failing fast") from None
        except Exception:
            pass  # expected: the dead remote fails the round
        else:
            raise AssertionError("round succeeded against a dead remote")
        await remote.close()
    finally:
        await server.close()

"""Examples tree smoke tests.

Full example runs take minutes on this 1-core host, so the default suite
only (a) compiles every example for syntax/import-level rot and (b)
executes the one sub-second demo end-to-end. Set
``BYZPY_TPU_RUN_EXAMPLE_TESTS=1`` to also execute the heavier training
examples with tiny round counts (what CI's nightly lane would do).
"""

import os
import pathlib
import py_compile
import subprocess
import sys

import pytest

pytestmark = pytest.mark.heavy  # opt-in lane: see pyproject addopts

EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"
ENV = {
    **os.environ,
    "BYZPY_TPU_PLATFORM": "cpu",
    "JAX_PLATFORMS": "cpu",
    "PS_ROUNDS": "2",
    "P2P_ROUNDS": "2",
    "ROUNDS": "2",
    "SEQ_LEN": "64",
    # keep the ResNet gossip example inside the smoke budget: the real
    # ResNet-18 (filters=64) compile alone runs past 900 s on this
    # 1-core host
    "P2P_STEPS": "2",
    "P2P_FILTERS": "8",
    "P2P_BATCH": "8",
}


def _all_example_files():
    return sorted(EXAMPLES.rglob("*.py"))


def test_every_example_compiles():
    files = _all_example_files()
    assert len(files) >= 10  # the tree documented in examples/README.md
    for f in files:
        py_compile.compile(str(f), doraise=True)


def test_actor_demo_runs():
    out = subprocess.run(
        [sys.executable, str(EXAMPLES / "actor_demo.py")],
        env=ENV, capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "add(2)" in out.stdout


@pytest.mark.skipif(
    not os.environ.get("BYZPY_TPU_RUN_EXAMPLE_TESTS"),
    reason="heavy example runs are opt-in (BYZPY_TPU_RUN_EXAMPLE_TESTS=1)",
)
@pytest.mark.parametrize(
    "rel",
    [
        "long_context_lm.py",
        "ps/thread_mnist.py",
        "ps/spmd_mnist.py",
        "ps/real_data_robust.py",
        "ps/elastic_crash_recovery.py",
        "p2p/elastic_gossip.py",
        "p2p/gossip_mnist.py",
        "p2p/real_data_gossip.py",
        "p2p/resnet_cifar_gossip.py",
        "distributed/two_host_psum.py",
    ],
)
def test_training_example_runs(rel):
    out = subprocess.run(
        [sys.executable, str(EXAMPLES / rel)],
        env=ENV, capture_output=True, text=True, timeout=900,
    )
    assert out.returncode == 0, out.stderr[-2000:]

"""OperatorExecutor unit depth (ref intent: byzpy engine executor tests):
graph caching, bare-vs-mapping inputs, missing-input errors, pool
ownership semantics on close, and reuse across runs.
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from byzpy_tpu.engine.graph import ActorPoolConfig
from byzpy_tpu.engine.graph.executor import OperatorExecutor, run_operator
from byzpy_tpu.engine.graph.operator import OpContext, Operator


class _SumOp(Operator):
    name = "sum-op"
    input_key = "values"

    def compute(self, inputs, *, context: OpContext):
        return jnp.sum(jnp.stack(list(inputs["values"])), axis=0)


class _NoKeyOp(Operator):
    name = "no-key-op"
    input_key = None

    def compute(self, inputs, *, context: OpContext):
        return inputs["a"] + inputs["b"]


def test_bare_input_uses_operator_input_key():
    out = asyncio.run(run_operator(_SumOp(), [jnp.ones(3), jnp.ones(3)]))
    np.testing.assert_array_equal(np.asarray(out), np.full(3, 2.0))


def test_mapping_input_and_no_input_key_error():
    out = asyncio.run(run_operator(_NoKeyOp(), {"a": 1.0, "b": 2.0}))
    assert float(out) == 3.0
    with pytest.raises(ValueError, match="input_key"):
        asyncio.run(run_operator(_NoKeyOp(), 1.0))


def test_executor_reuse_caches_graph():
    ex = OperatorExecutor(_SumOp())
    try:
        out1 = asyncio.run(ex.run([jnp.ones(2)]))
        assert len(ex._graph_cache) == 1
        out2 = asyncio.run(ex.run([jnp.ones(2) * 3]))
        assert len(ex._graph_cache) == 1  # same input-name set -> one graph
        np.testing.assert_array_equal(np.asarray(out1), np.ones(2))
        np.testing.assert_array_equal(np.asarray(out2), np.full(2, 3.0))
        # a different input-name set builds (and caches) a second graph
        ex2 = OperatorExecutor(_NoKeyOp())
        asyncio.run(ex2.run({"a": 1.0, "b": 2.0}))
        asyncio.run(ex2.run({"b": 5.0, "a": 1.0}))  # order-insensitive key
        assert len(ex2._graph_cache) == 1
    finally:
        asyncio.run(ex.close())


def test_executor_owns_pool_only_from_config():
    async def main():
        ex = OperatorExecutor(
            _SumOp(), pool_config=ActorPoolConfig(backend="thread", count=1)
        )
        assert ex._owns_pool
        out = await ex.run([jnp.ones(2), jnp.ones(2)])
        assert ex._pool is not None
        await ex.close()
        assert ex._pool is None  # owned pool torn down
        return out

    out = asyncio.run(main())
    np.testing.assert_array_equal(np.asarray(out), np.full(2, 2.0))


def test_executor_borrowed_pool_not_closed():
    from byzpy_tpu.engine.graph import ActorPool

    async def main():
        pool = ActorPool(ActorPoolConfig(backend="thread", count=1))
        await pool.start()
        try:
            ex = OperatorExecutor(_SumOp(), pool=pool)
            assert not ex._owns_pool
            await ex.run([jnp.ones(2)])
            await ex.close()
            # borrowed pool must still be usable
            ex2 = OperatorExecutor(_SumOp(), pool=pool)
            out = await ex2.run([jnp.ones(2) * 4])
            await ex2.close()
            return out
        finally:
            await pool.close()

    out = asyncio.run(main())
    np.testing.assert_array_equal(np.asarray(out), np.full(2, 4.0))


def test_missing_graph_input_raises():
    with pytest.raises(KeyError):
        asyncio.run(run_operator(_SumOp(), {"wrong_key": [jnp.ones(2)]}))

"""Forensics plane: evidence extraction, trust ledger, quarantine,
digest pins, WAL audit, compile-cache observability.

The two load-bearing contracts:

* **bit-effect-free** — round aggregates (serving) and chaos grid
  digests are IDENTICAL with forensics enabled vs disabled (the plane
  is a pure observer on data the round already produced);
* **auditable** — every exclusion/flag/quarantine is reconstructable
  from the WAL by ``python -m byzpy_tpu.forensics``.
"""

from __future__ import annotations

import asyncio
import os

import numpy as np
import pytest

from byzpy_tpu.aggregators import (
    CenteredClipping,
    ComparativeGradientElimination,
    CoordinateWiseMedian,
    CoordinateWiseTrimmedMean,
    GeometricMedian,
    MoNNA,
    MultiKrum,
)
from byzpy_tpu.forensics import (
    DetectorConfig,
    ForensicsConfig,
    ForensicsPlane,
    RoundEvidence,
    SubmissionEvidence,
    TrustLedger,
    TrustPolicy,
    audit,
)
from byzpy_tpu.forensics.evidence import instant_flags, row_features


def _cohort(n=12, d=16, seed=0):
    rng = np.random.default_rng(seed)
    matrix = rng.normal(1.0, 0.3, (n, d)).astype(np.float32)
    valid = np.ones((n,), bool)
    return matrix, valid


# ---------------------------------------------------------------------------
# aggregator round_evidence views
# ---------------------------------------------------------------------------


class TestRoundEvidenceViews:
    def test_selection_kinds_and_keep_counts(self):
        matrix, valid = _cohort()
        cases = [
            (MultiKrum(f=2, q=4), "krum_distance", 4),
            (ComparativeGradientElimination(f=2), "norm", 10),
            (MoNNA(f=2), "reference_distance", 10),
        ]
        for agg, kind, kept in cases:
            view = agg.round_evidence(matrix, valid)
            assert view["kind"] == kind
            assert view["keep"].sum() == kept
            assert np.isfinite(view["scores"][valid]).all()

    def test_selection_mask_delegates_to_evidence(self):
        # one schema, two producers: chaos influence's selection view IS
        # the evidence view's keep mask
        from byzpy_tpu.chaos.influence import selection_mask

        matrix, valid = _cohort()
        valid[9:] = False
        for agg in (MultiKrum(f=2, q=3), ComparativeGradientElimination(f=2),
                    MoNNA(f=2)):
            view = agg.round_evidence(matrix, valid)
            mask = selection_mask(agg, matrix, valid)
            np.testing.assert_array_equal(mask, view["keep"])
            assert not mask[~valid].any()
        assert selection_mask(CoordinateWiseMedian(), matrix, valid) is None

    def test_trimmed_mean_clip_fractions(self):
        matrix, valid = _cohort()
        matrix[0] = 100.0  # every coordinate of row 0 lands in the top-f
        view = CoordinateWiseTrimmedMean(f=2).round_evidence(matrix, valid)
        assert view["kind"] == "trim_fraction"
        assert view["keep"] is None
        assert view["scores"][0] == pytest.approx(1.0)
        # honest rows are clipped on roughly 2f/m of coordinates
        assert view["scores"][1:12].mean() < 0.6

    def test_center_seeking_views_need_aggregate(self):
        matrix, valid = _cohort()
        agg_vec = matrix.mean(axis=0)
        for agg in (GeometricMedian(), CenteredClipping(c_tau=2.0)):
            assert agg.round_evidence(matrix, valid) is None
            view = agg.round_evidence(matrix, valid, aggregate=agg_vec)
            assert view["keep"] is None
            assert np.isfinite(view["scores"][valid]).all()

    def test_inadmissible_and_empty_return_none(self):
        matrix, valid = _cohort()
        assert MultiKrum(f=2, q=4).round_evidence(
            matrix, np.zeros_like(valid)
        ) is None
        small = np.zeros_like(valid)
        small[:3] = True  # m=3 rejected by f=2 (needs f < m-1)
        assert MultiKrum(f=2, q=4).round_evidence(matrix, small) is None

    def test_padded_positions(self):
        matrix, valid = _cohort()
        valid[3] = valid[7] = False
        view = ComparativeGradientElimination(f=1).round_evidence(matrix, valid)
        assert np.isnan(view["scores"][3]) and np.isnan(view["scores"][7])
        assert not view["keep"][3] and not view["keep"][7]


# ---------------------------------------------------------------------------
# detectors
# ---------------------------------------------------------------------------


class TestDetectors:
    def test_staleness_inflation_fires_pre_discount(self):
        matrix, valid = _cohort()
        weights = np.ones((12,), np.float32)
        weights[0] = 0.0625  # δ=4 at γ=0.5
        matrix[0] *= 16.0  # pre-inflated to cancel the discount
        feats = row_features(matrix, valid, matrix[1:].mean(0), weights=weights)
        flags = instant_flags(feats, DetectorConfig())
        assert "staleness_inflation" in flags[0]
        assert all("staleness_inflation" not in f for f in flags[1:])

    def test_fresh_inflated_row_is_not_staleness(self):
        matrix, valid = _cohort()
        matrix[0] *= 16.0  # big but FRESH: norm_outlier's job, not staleness
        feats = row_features(matrix, valid, matrix[1:].mean(0))
        flags = instant_flags(feats, DetectorConfig())
        assert "staleness_inflation" not in flags[0]
        assert "norm_outlier" in flags[0]

    def test_sign_anomaly_needs_coherence(self):
        matrix, valid = _cohort()
        agg = matrix.mean(axis=0)
        matrix[0] = -4.0 * matrix[0]
        feats = row_features(matrix, valid, agg)
        assert "sign_anomaly" in instant_flags(feats, DetectorConfig())[0]
        # incoherent cohort (half the clients legitimately disagree):
        # the detector disarms rather than flag honest dissent
        split = matrix.copy()
        split[6:] *= -1.0
        feats2 = row_features(split, valid, agg)
        assert all(
            "sign_anomaly" not in f
            for f in instant_flags(feats2, DetectorConfig())
        )

    def test_clean_cohort_no_flags(self):
        matrix, valid = _cohort()
        feats = row_features(matrix, valid, matrix.mean(0))
        assert all(not f for f in instant_flags(feats, DetectorConfig()))

    def test_echo_needs_persistence(self):
        plane = ForensicsPlane("t", ForensicsConfig())
        matrix, valid = _cohort()
        clients = [f"c{i}" for i in range(11)] + ["byz0"]
        agg = matrix[:11].mean(axis=0)
        flagged_rounds = []
        for r in range(4):
            matrix2 = matrix.copy()
            if r > 0:
                matrix2[11] = agg  # byz0 echoes the previous broadcast
            ev = plane.observe_round(r, matrix2, valid, clients, agg)
            if "echo" in dict(ev.flag_counts):
                flagged_rounds.append(r)
        # round 1 is the first echo (streak 1 < echo_rounds=2); flag
        # fires from round 2 on
        assert flagged_rounds == [2, 3]

    def test_selection_verdict_scores_discounted_matrix(self):
        # the serving fold aggregates matrix * weights: the evidence
        # verdict must match what the aggregator ACTUALLY selected. A
        # staleness abuser pre-inflates by 1/discount so its DISCOUNTED
        # row is cohort-central — scoring the raw matrix would claim it
        # was de-selected in exactly the rounds it folded in.
        from byzpy_tpu.chaos.influence import selection_mask

        rng = np.random.default_rng(0)
        matrix = rng.normal(1.0, 0.1, (12, 16)).astype(np.float32)
        weights = np.ones((12,), np.float32)
        weights[11] = 0.0625
        matrix[11] = matrix[:11].mean(0) / weights[11]  # discounts to central
        valid = np.ones((12,), bool)
        agg = MultiKrum(f=2, q=4)
        actual_keep = selection_mask(agg, matrix * weights[:, None], valid)
        assert actual_keep[11]  # the fold really selects the abuser
        plane = ForensicsPlane("t", ForensicsConfig())
        clients = [f"c{i}" for i in range(11)] + ["byz0"]
        ev = plane.observe_round(
            0, matrix, valid, clients, matrix[:11].mean(0),
            aggregator=agg, weights=weights,
        )
        by_slot = {r.slot: r for r in ev.records}
        assert by_slot[11].selected is True
        for slot in range(12):
            assert by_slot[slot].selected == bool(actual_keep[slot])
        # the pre-discount FEATURES still expose the abuse
        assert "staleness_inflation" in by_slot[11].flags

    def test_streaks_reset_across_absent_rounds(self):
        # an intermittent client stale on each APPEARANCE must not
        # accumulate a "consecutive rounds" streak across gaps
        plane = ForensicsPlane(
            "t", ForensicsConfig(trust=TrustPolicy(alpha=0.01))
        )
        matrix, valid = _cohort(n=6)
        clients = [f"c{i}" for i in range(5)] + ["slow"]
        weights = np.ones((6,), np.float32)
        weights[5] = 0.5
        pinned = []
        for r in (0, 1, 5, 9, 13, 17):  # 2 consecutive, then gapped
            ev = plane.observe_round(
                r, matrix, valid, clients, matrix[:5].mean(0), weights=weights
            )
            if "staleness_pinned" in dict(ev.flag_counts):
                pinned.append(r)
        assert pinned == []  # never 4 CONSECUTIVE rounds

    def test_staleness_pinned_streak(self):
        plane = ForensicsPlane("t", ForensicsConfig())
        matrix, valid = _cohort()
        clients = [f"c{i}" for i in range(11)] + ["byz0"]
        weights = np.ones((12,), np.float32)
        weights[11] = 0.5  # byz0 stale every round (NOT inflated)
        first = None
        for r in range(6):
            ev = plane.observe_round(
                r, matrix, valid, clients, matrix[:11].mean(0), weights=weights
            )
            if "staleness_pinned" in dict(ev.flag_counts) and first is None:
                first = r
        assert first == 3  # streak reaches pinned_rounds=4 on the 4th round


# ---------------------------------------------------------------------------
# trust ledger
# ---------------------------------------------------------------------------


class TestTrustLedger:
    def test_lru_bound(self):
        ledger = TrustLedger(TrustPolicy(max_tracked_clients=8))
        for i in range(32):
            ledger.observe(f"c{i}", 0, selected=True, flags=())
        assert len(ledger._clients) == 8
        assert ledger.evicted == 24
        # an evicted client restarts at initial trust
        assert ledger.score("c0") == TrustPolicy().initial

    def test_ewma_direction(self):
        ledger = TrustLedger(TrustPolicy(alpha=0.5))
        up = ledger.observe("good", 0, selected=True, flags=())
        down = ledger.observe("bad", 0, selected=None, flags=("norm_outlier",))
        assert up > TrustPolicy().initial > down
        mild = ledger.observe("meh", 0, selected=False, flags=())
        assert down < mild < up

    def test_quarantine_readmit_state_machine(self):
        policy = TrustPolicy(alpha=0.5, readmit_after_rounds=3)
        ledger = TrustLedger(policy)
        r = 0
        while not ledger.is_quarantined("byz"):
            ledger.observe("byz", r, selected=False, flags=("echo",))
            r += 1
        entered = ledger.quarantined()["byz"]
        assert ledger.quarantines_total == 1
        # quarantined: admission refused until the cooldown elapses
        assert not ledger.allows("byz", entered + 1)
        assert not ledger.allows("byz", entered + 2)
        # readmission on probation trust
        assert ledger.allows("byz", entered + 3)
        assert ledger.readmits_total == 1
        assert ledger.score("byz") == policy.probation_trust
        assert not ledger.is_quarantined("byz")
        # probation: one more bad streak re-quarantines quickly
        rr = entered + 3
        while not ledger.is_quarantined("byz"):
            ledger.observe("byz", rr, selected=False, flags=("echo",))
            rr += 1
        assert ledger.quarantines_total == 2

    def test_observe_only_mode_never_pins_quarantine_state(self):
        # quarantine can only be LIFTED via allows(), which the default
        # (quarantine=False) plane never consults: entering the state
        # there would pin the client as "quarantined" in gauges and the
        # audit trail forever while gating nothing
        plane = ForensicsPlane(
            "t", ForensicsConfig(trust=TrustPolicy(alpha=0.5), quarantine=False)
        )
        matrix, valid = _cohort()
        clients = [f"c{i}" for i in range(11)] + ["byz0"]
        weights = np.ones((12,), np.float32)
        weights[11] = 0.0625
        bad = matrix.copy()
        bad[11] = 16.0 * bad[11]  # flagged every round -> trust sinks
        for r in range(8):
            ev = plane.observe_round(
                r, bad, valid, clients, matrix[:11].mean(0), weights=weights
            )
        assert plane.ledger.score("byz0") < 0.2  # trust DID collapse
        assert not plane.ledger.quarantined()  # but no un-liftable state
        assert not any(
            t_["event"] == "quarantine" for t_ in plane.pop_transitions()
        )
        assert "low_trust" in dict(ev.flag_counts)  # still fully flagged

    def test_prepare_apply_equals_observe_round(self):
        # the async scheduler splits the plane call (prepare on the fold
        # executor, apply on the loop): must be the same computation
        matrix, valid = _cohort()
        clients = [f"c{i}" for i in range(11)] + ["byz0"]
        weights = np.ones((12,), np.float32)
        weights[11] = 0.5
        matrix[11] *= 8.0
        agg = MultiKrum(f=2, q=4)
        one = ForensicsPlane("a", ForensicsConfig())
        two = ForensicsPlane("b", ForensicsConfig())
        for r in range(3):
            ev1 = one.observe_round(
                r, matrix, valid, clients, matrix[:11].mean(0),
                aggregator=agg, weights=weights, bucket=16,
            )
            ev2 = two.apply(
                two.prepare(
                    r, matrix, valid, clients, matrix[:11].mean(0),
                    aggregator=agg, weights=weights, bucket=16,
                )
            )
            assert ev1.to_wire() == {**ev2.to_wire(), "tenant": "a"}

    def test_rate_scale(self):
        policy = TrustPolicy(alpha=0.5)
        ledger = TrustLedger(policy)
        assert ledger.rate_scale("unseen") == 1.0
        ledger.observe("good", 0, selected=True, flags=())
        assert ledger.rate_scale("good") == 1.0  # above initial: exact 1.0
        for r in range(16):
            ledger.observe("bad", r, selected=None, flags=("echo",))
        assert 0.05 <= ledger.rate_scale("bad") < 0.2

    def test_trust_weighted_refill_arithmetic(self):
        from byzpy_tpu.serving.credits import CreditPolicy, TokenBucket

        policy = CreditPolicy(rate_per_s=10.0, burst=5.0)
        full = TokenBucket(policy, 0.0)
        slow = TokenBucket(policy, 0.0)
        for b in (full, slow):
            for _ in range(5):
                assert b.try_consume(0.0)
        # refill over 0.2 s: full rate earns 2 tokens, half rate 1
        assert full.try_consume(0.2) and full.try_consume(0.2)
        assert not full.try_consume(0.2)
        assert slow.try_consume(0.2, rate_scale=0.5)
        assert not slow.try_consume(0.2, rate_scale=0.5)


# ---------------------------------------------------------------------------
# schema round-trip
# ---------------------------------------------------------------------------


class TestSchema:
    def test_wire_roundtrip(self):
        rec = SubmissionEvidence(
            client="c1", slot=3, norm=2.5, norm_z=0.7, cos_to_agg=0.99,
            echo_ratio=1.2, weight=0.5, delta=1, inflation=1.1,
            score=4.25, selected=False, flags=("echo",), trust=0.4,
        )
        ev = RoundEvidence(
            tenant="m0", round_id=7, m=1, bucket=2, agg_digest="ab" * 8,
            score_kind="krum_distance", records=(rec,),
            flag_counts={"echo": 1},
        )
        back = RoundEvidence.from_wire(ev.to_wire())
        assert back.round_id == 7 and back.score_kind == "krum_distance"
        assert back.records[0].client == "c1"
        assert back.records[0].selected is False
        assert back.records[0].flags == ("echo",)
        assert back.excluded_clients == ("c1",)
        assert back.flagged_clients == ("c1",)


# ---------------------------------------------------------------------------
# digest pins: forensics on/off is bit-identical
# ---------------------------------------------------------------------------


def _drive_frontend(forensics_cfg):
    from byzpy_tpu.serving import ServingFrontend, TenantConfig

    fe = ServingFrontend(
        [
            TenantConfig(
                name="m0",
                aggregator=MultiKrum(f=2, q=4),
                dim=8,
                forensics=forensics_cfg,
            )
        ]
    )
    rng = np.random.default_rng(7)
    aggs = []
    for r in range(5):
        for i in range(9):
            ok, reason = fe.submit(
                "m0", f"c{i}", r, rng.normal(1.0, 0.2, 8).astype(np.float32)
            )
            assert ok, reason
        closed = fe.close_round_nowait("m0")
        assert closed is not None
        aggs.append(np.asarray(closed[2], np.float32))
    return aggs


class TestDigestPins:
    def test_serving_aggregates_bit_identical(self):
        without = _drive_frontend(None)
        with_f = _drive_frontend(ForensicsConfig())
        for a, b in zip(without, with_f, strict=True):
            np.testing.assert_array_equal(a, b)

    def test_chaos_digest_bit_identical(self):
        from byzpy_tpu.chaos import AttackSpec, ChaosHarness, Scenario

        cell = Scenario(
            name="pin",
            seed=11,
            n_clients=10,
            n_byzantine=2,
            dim=16,
            rounds=6,
            aggregator="multi_krum",
            aggregator_params={"f": 2, "q": 3},
            attack=AttackSpec(
                name="influence_ascent", params={"grow": 1.8, "scale0": 0.1}
            ),
        )
        plain = ChaosHarness(cell).run()
        forensic = ChaosHarness(cell, forensics=ForensicsConfig()).run()
        assert plain.trace.digest() == forensic.trace.digest()
        assert plain.final_error == forensic.final_error
        assert not plain.evidence and len(forensic.evidence) == 6

    def test_chaos_serving_engine_digest_bit_identical(self):
        from byzpy_tpu.chaos import AttackSpec, ChaosHarness, Scenario

        cell = Scenario(
            name="pin-serving",
            seed=11,
            n_clients=10,
            n_byzantine=2,
            dim=16,
            rounds=6,
            engine="serving",
            aggregator="trimmed_mean",
            aggregator_params={"f": 2},
            attack=AttackSpec(
                name="staleness_abuse",
                params={"kind": "exponential", "gamma": 0.5, "cutoff": 3},
            ),
            staleness_kind="exponential",
            staleness_gamma=0.5,
            staleness_cutoff=3,
        )
        plain = ChaosHarness(cell).run()
        forensic = ChaosHarness(cell, forensics=ForensicsConfig()).run()
        assert plain.trace.digest() == forensic.trace.digest()
        assert plain.final_error == forensic.final_error


# ---------------------------------------------------------------------------
# serving integration: quarantine acks, WAL audit, CLI
# ---------------------------------------------------------------------------


def _abused_frontend(tmp_path, *, quarantine=True):
    from byzpy_tpu.serving import (
        DurabilityConfig,
        ServingFrontend,
        StalenessPolicy,
        TenantConfig,
    )

    fe = ServingFrontend(
        [
            TenantConfig(
                name="m0",
                aggregator=CoordinateWiseTrimmedMean(f=1),
                dim=8,
                staleness=StalenessPolicy(
                    kind="exponential", gamma=0.5, cutoff=4
                ),
                forensics=ForensicsConfig(
                    trust=TrustPolicy(alpha=0.5, readmit_after_rounds=4),
                    quarantine=quarantine,
                ),
            )
        ],
        durability=DurabilityConfig(directory=str(tmp_path), prune=False),
    )
    rng = np.random.default_rng(3)
    untrusted = 0
    for r in range(8):
        for i in range(6):
            ok, reason = fe.submit(
                "m0", f"c{i}", r, rng.normal(1.0, 0.1, 8).astype(np.float32)
            )
            assert ok, reason
        inflated = (16.0 * rng.normal(1.0, 0.1, 8)).astype(np.float32)
        ok, reason = fe.submit("m0", "byz0", max(0, r - 4), inflated)
        if reason == "rejected_untrusted":
            untrusted += 1
        assert fe.close_round_nowait("m0") is not None
    return fe, untrusted


class TestServingIntegration:
    def test_quarantine_rejects_and_accounts(self, tmp_path):
        fe, untrusted = _abused_frontend(tmp_path)
        stats = fe.stats()["m0"]
        assert untrusted > 0
        assert stats["forensics"]["rejected_untrusted"] == untrusted
        assert stats["ledger"]["totals"]["rejected_untrusted"] == untrusted
        assert stats["forensics"]["trust"]["quarantines_total"] >= 1
        asyncio.run(fe.close())

    def test_wal_audit_reconstructs_exclusion_evidence(self, tmp_path):
        fe, _ = _abused_frontend(tmp_path)
        asyncio.run(fe.close())
        report = audit.wal_timeline(os.path.join(str(tmp_path), "m0"))
        assert report["evidence_rounds"] > 0
        assert not report["digest_mismatches"]
        byz = report["clients"]["byz0"]
        assert byz["flags"]  # flagged with named detectors
        assert "staleness_inflation" in byz["flags"]
        assert byz["last_trust"] is not None and byz["last_trust"] < 0.3
        assert any(
            t["event"] == "quarantine" and t["client"] == "byz0"
            for t in report["transitions"]
        )
        # honest clients folded and stayed unflagged
        assert report["clients"]["c0"]["folded_rounds"]
        assert not report["clients"]["c0"]["flags"]

    def test_cli_report_and_replay(self, tmp_path, capsys):
        from byzpy_tpu.forensics.__main__ import main as fmain

        fe, _ = _abused_frontend(tmp_path)
        asyncio.run(fe.close())
        rc = fmain(["report", "--wal", str(tmp_path), "--tenant", "m0"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "byz0" in out and "staleness_inflation" in out
        # auto-discovery without --tenant
        assert fmain(["report", "--wal", str(tmp_path), "--json"]) == 0

    def test_cli_flags_digest_mismatch(self, tmp_path, capsys):
        from byzpy_tpu.forensics.__main__ import main as fmain
        from byzpy_tpu.resilience.durable import DurabilityConfig, TenantDurability

        d = TenantDurability(DurabilityConfig(directory=str(tmp_path)), "m0")
        d.record_round(0, (0,), "aa" * 8, 1)
        ev = RoundEvidence(
            tenant="m0", round_id=0, m=1, bucket=2, agg_digest="bb" * 8,
            score_kind="", records=(), flag_counts={},
        )
        d.record_evidence(0, ev.to_wire())
        d.close()
        rc = fmain(["report", "--wal", str(tmp_path), "--tenant", "m0"])
        capsys.readouterr()
        assert rc == 1  # tampered/buggy evidence is itself surfaced

    def test_cli_clean_error_on_bad_paths(self, tmp_path, capsys):
        from byzpy_tpu.forensics.__main__ import main as fmain

        rc = fmain(["report", "--wal", str(tmp_path), "--tenant", "typo"])
        err = capsys.readouterr().err
        assert rc == 2 and "no such tenant" in err  # not a traceback
        rc = fmain(["report", "--wal", str(tmp_path / "missing")])
        assert rc == 2
        rc = fmain(["replay", "--trace", str(tmp_path / "missing.jsonl")])
        assert rc == 2

    def test_failed_evidence_append_requeues_transitions(self, tmp_path):
        # "WAL-recorded, never silent": a transition popped for
        # persistence must survive an append failure and retry on the
        # next round's close
        fe, _ = _abused_frontend(tmp_path)
        t = fe._tenants["m0"]
        plane = t.forensics
        plane._transitions.append(
            {"event": "quarantine", "client": "ghost", "round": 99}
        )
        real_append = t.durability.record_evidence

        def flaky(round_id, payload):
            raise OSError("disk full")

        t.durability.record_evidence = flaky
        errors_before = fe.callback_errors
        from byzpy_tpu.serving.cohort import build_cohort
        from byzpy_tpu.serving.queue import Submission

        subs = [
            Submission(client="c0", round_submitted=t.round_id,
                       gradient=np.ones(8, np.float32), arrived_s=0.0)
            for _ in range(3)
        ]
        cohort = build_cohort(subs, t.round_id, t.ladder, t.cfg.staleness)
        fe._observe_forensics(t, cohort, np.ones(8, np.float32), subs)
        assert fe.callback_errors == errors_before + 1
        assert {"event": "quarantine", "client": "ghost", "round": 99} in (
            plane._transitions
        )  # re-queued, not lost
        t.durability.record_evidence = real_append
        fe._observe_forensics(t, cohort, np.ones(8, np.float32), subs)
        assert not plane._transitions  # retried and persisted
        asyncio.run(fe.close())

    def test_selection_mask_skips_scoreless_aggregators(self):
        # selection_mask must not pay trimmed mean's O(m·d·log m) clip
        # pass only to discard it: non-selecting aggregators
        # short-circuit before round_evidence is even called
        from byzpy_tpu.chaos.influence import selection_mask

        matrix, valid = _cohort()
        agg = CoordinateWiseTrimmedMean(f=2)

        def boom(*a, **k):  # pragma: no cover — must not run
            raise AssertionError("round_evidence should not be called")

        agg.round_evidence = boom
        assert selection_mask(agg, matrix, valid) is None

    def test_trace_replay_timeline(self, tmp_path):
        from byzpy_tpu.chaos import AttackSpec, ChaosHarness, Scenario

        cell = Scenario(
            name="replay",
            seed=5,
            n_clients=10,
            n_byzantine=2,
            dim=16,
            rounds=6,
            aggregator="multi_krum",
            aggregator_params={"f": 2, "q": 3},
            attack=AttackSpec(name="outlier", params={"scale": 50.0}),
        )
        report = ChaosHarness(cell).run()
        path = str(tmp_path / "trace.jsonl")
        report.trace.to_jsonl(path)
        timeline = audit.trace_timeline(path)
        assert timeline["exclusions_by_round"]  # outliers excluded by Krum
        assert any(
            c.startswith("byz") and e["excluded_rounds"]
            for c, e in timeline["clients"].items()
        )

    def test_recovery_ignores_evidence_records(self, tmp_path):
        # EVIDENCE WAL records carry no round state: a recovery replay
        # over a forensics-bearing WAL must reconstruct the same rounds
        from byzpy_tpu.resilience.durable import DurabilityConfig, TenantDurability

        fe, _ = _abused_frontend(tmp_path)
        rounds_before = fe.round_of("m0")
        asyncio.run(fe.close())
        rec = TenantDurability(
            DurabilityConfig(directory=str(tmp_path), prune=False), "m0"
        ).recovered
        assert rec is not None
        assert rec.round_id == rounds_before


# ---------------------------------------------------------------------------
# metrics / flight recorder / compile cache
# ---------------------------------------------------------------------------


class TestObservabilitySurfaces:
    def test_forensics_metrics_in_prometheus_text(self, tmp_path):
        from byzpy_tpu.observability import metrics as obs_metrics

        fe, _ = _abused_frontend(tmp_path)
        asyncio.run(fe.close())
        text = obs_metrics.registry().prometheus_text()
        for family in (
            "byzpy_anomaly_flags_total",
            "byzpy_trust_score",
            "byzpy_client_excluded_total",
            "byzpy_quarantined_clients",
            "byzpy_client_quarantines_total",
        ):
            assert family in text
        assert 'detector="staleness_inflation"' in text

    def test_flight_dump_carries_recent_evidence(self):
        from byzpy_tpu.observability.recorder import FlightRecorder

        plane = ForensicsPlane("ftest", ForensicsConfig(recent_rounds=4))
        matrix, valid = _cohort()
        clients = [f"c{i}" for i in range(12)]
        for r in range(6):
            plane.observe_round(r, matrix, valid, clients, matrix.mean(0))
        dump = FlightRecorder().record()
        assert "ftest" in dump["forensics"]
        rounds = [e["round"] for e in dump["forensics"]["ftest"]]
        assert rounds == [2, 3, 4, 5]  # bounded to recent_rounds

    def test_jitstats_counts_growth_only(self):
        from byzpy_tpu.observability import jitstats, metrics as obs_metrics

        site = "test.site.a"
        assert jitstats.note_cache_size(site, 1) == 1
        assert jitstats.note_cache_size(site, 1) == 0
        assert jitstats.note_cache_size(site, 3) == 2
        assert jitstats.note_cache_size(site, 2) == 0  # cache cleared: no negative
        assert jitstats.note_cache_size(site, None) == 0
        assert jitstats.compiles_seen(site) == 3
        counter = obs_metrics.registry().counter(
            "byzpy_jit_compiles_total", labels={"site": site}
        )
        assert counter.value == 3

    def test_serving_recompile_warning(self, caplog):
        import logging

        from byzpy_tpu.observability import metrics as obs_metrics
        from byzpy_tpu.serving import ServingFrontend, TenantConfig

        fe = ServingFrontend(
            [
                TenantConfig(
                    name="warn0",
                    aggregator=CoordinateWiseTrimmedMean(f=1),
                    dim=4,
                )
            ]
        )
        t = fe._tenants["warn0"]

        class _FakeJit:
            def __init__(self, n):
                self.n = n

            def _cache_size(self):
                return self.n

        expected = len(t.ladder.sizes)
        t.executor.aggregator._masked_jit_cache = _FakeJit(expected)
        with caplog.at_level(logging.WARNING, logger="byzpy_tpu.serving"):
            fe._note_compiles(t)  # at the ladder bound: no warning
            assert not caplog.records
            t.executor.aggregator._masked_jit_cache = _FakeJit(expected + 1)
            fe._note_compiles(t)  # one past the ladder: warn once
            fe._note_compiles(t)  # same size again: no repeat
        warnings = [r for r in caplog.records if "jit cache" in r.message]
        assert len(warnings) == 1
        counter = obs_metrics.registry().counter(
            "byzpy_serving_recompile_warnings_total",
            labels={"tenant": "warn0"},
        )
        assert counter.value == 1

    def test_serving_compile_site_counts(self, tmp_path, monkeypatch):
        from byzpy_tpu.observability import jitstats

        # the bucket-ladder door (escape hatch): since PR 11 the default
        # path is the ragged dispatcher, whose own compile site is
        # pinned in tests/test_ragged.py — this pin keeps the masked-
        # aggregate site honest for ladder-served tenants
        monkeypatch.setenv("BYZPY_TPU_RAGGED", "0")
        fe, _ = _abused_frontend(tmp_path)
        asyncio.run(fe.close())
        # the masked-aggregate cache was observed (one bucket compiled)
        assert jitstats.compiles_seen("serving.masked_aggregate:m0") >= 1

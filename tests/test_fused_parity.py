"""Parity pins for the roofline-driven hot-path surgery (ISSUE 2):

* ``sort_rows`` (the int32-key XLA sort serving every coordinate-wise
  fallback) matches ``jnp.sort``'s value ordering including non-finite
  values (bit-level divergence on signed zeros only, as documented);
* the conditional-mask selection fallback (``_selection_mean_xla``)
  matches the reference ``ranked_mean`` path for finite AND adversarial
  inputs across dtypes;
* the fused from-Gram Pallas pass matches the unfused
  ``multi_krum_from_gram`` (documented tolerance — score sums reduce in
  a different order), including through the streaming fold;
* the ``BYZPY_TPU_MATMUL_DTYPE=bf16`` Gram policy stays within bf16
  tolerance of the exact f32 path and resolves per call.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from byzpy_tpu.aggregators import MultiKrum
from byzpy_tpu.ops import pallas_kernels as pk
from byzpy_tpu.ops import robust


def _rand(n, d, dtype=jnp.float32, seed=0, scale=1.0):
    return (jax.random.normal(jax.random.PRNGKey(seed), (n, d)) * scale).astype(dtype)


# ---------------------------------------------------------------------------
# sort_rows == jnp.sort, bit for bit
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.float16])
def test_sort_rows_matches_jnp_sort(dtype):
    x = _rand(13, 999, dtype, seed=1, scale=10.0)
    np.testing.assert_array_equal(
        np.asarray(robust.sort_rows(x)), np.asarray(jnp.sort(x, axis=0))
    )


def test_sort_rows_nonfinite_and_signed_zero_order():
    x = np.random.default_rng(0).normal(size=(11, 64)).astype(np.float32)
    x[0, :8] = np.nan
    x[1, :8] = np.inf
    x[2, :8] = -np.inf
    x[3, :16] = 0.0
    x[4, :16] = -0.0
    xj = jnp.asarray(x)
    got = np.asarray(robust.sort_rows(xj))
    want = np.asarray(jnp.sort(xj, axis=0))
    # value equality (assert_array_equal would distinguish -0.0/+0.0)
    np.testing.assert_allclose(got, want, rtol=0, atol=0, equal_nan=True)
    # signed zeros: VALUES match (0.0 == -0.0); the key path orders
    # -0.0 strictly before +0.0 where the stable jnp.sort preserves
    # input order — the same documented bit-level-only divergence as
    # sort_columns. Pin the key path's order per column.
    for c in range(16):
        zero_rows = np.flatnonzero(got[:, c] == 0.0)
        assert zero_rows.size == 2
        assert np.signbit(got[zero_rows[0], c])
        assert not np.signbit(got[zero_rows[1], c])


def test_sort_rows_int_dtype_passthrough():
    x = jnp.asarray(np.random.default_rng(1).integers(-50, 50, (9, 33)),
                    jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(robust.sort_rows(x)), np.asarray(jnp.sort(x, axis=0))
    )


def test_coordinate_median_matches_jnp_median_fallback():
    for seed, poison in ((0, False), (1, True)):
        x = np.array(_rand(10, 257, seed=seed, scale=100.0))
        if poison:
            x[3, 5] = np.nan
            x[:, 6] = np.inf
        xj = jnp.asarray(x)
        np.testing.assert_array_equal(
            np.asarray(robust.coordinate_median(xj)),
            np.asarray(jnp.median(xj, axis=0)),
        )


# ---------------------------------------------------------------------------
# Conditional-mask selection == reference ranked_mean
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_multi_krum_fallback_matches_reference(dtype):
    x = _rand(23, 700, dtype, seed=2)
    got = robust.multi_krum(x, f=4, q=6)
    want = robust.ranked_mean(x, robust.krum_scores(x, f=4), 6)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2 if dtype == jnp.bfloat16 else 1e-6, atol=1e-6,
    )


@pytest.mark.parametrize("poison", ["nan", "inf", "overflow"])
def test_selection_fallbacks_route_adversarial_rows_to_masked_path(poison):
    x = np.array(_rand(17, 300, seed=3))
    val = {"nan": np.nan, "inf": np.inf, "overflow": 1e30}[poison]
    x[5] = val
    xj = jnp.asarray(x)
    got = np.asarray(robust.multi_krum(xj, f=3, q=4))
    want = np.asarray(robust.ranked_mean(xj, robust.krum_scores(xj, f=3), 4))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert np.isfinite(got).all()  # the bad row was never selected
    for fn, ref_scores in (
        (lambda a: robust.cge(a, f=3), lambda a: jnp.sum(a * a, axis=1)),
        (lambda a: robust.monna(a, f=3),
         lambda a: jnp.sum((a - a[0][None, :]) ** 2, axis=1)),
    ):
        got = np.asarray(fn(xj))
        want = np.asarray(robust.ranked_mean(xj, ref_scores(xj), 17 - 3))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6,
                                   equal_nan=True)


# ---------------------------------------------------------------------------
# Fused from-Gram pass vs the unfused finalize, incl. the streaming fold
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_from_gram_kernel_matches_unfused(dtype):
    x = _rand(16, 384, dtype, seed=4)
    gram = robust.gram_matrix(x)
    got = pk.selection_mean_from_gram_pallas(
        x, gram, f=2, q=5, mode="krum", interpret=True
    )
    want = robust.multi_krum_from_gram(x, gram, f=2, q=5)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2 if dtype == jnp.bfloat16 else 1e-5, atol=1e-6,
    )
    # ... and both equal the from-scratch multi_krum on the same matrix
    direct = robust.multi_krum(x, f=2, q=5)
    np.testing.assert_allclose(
        np.asarray(want, np.float32), np.asarray(direct, np.float32),
        rtol=2e-2 if dtype == jnp.bfloat16 else 1e-5, atol=1e-6,
    )


def test_from_gram_kernel_nan_scores_rank_last():
    x = np.array(_rand(12, 256, seed=5))
    x[2] = np.nan
    xj = jnp.asarray(x)
    gram = robust.gram_matrix(xj)
    got = np.asarray(pk.selection_mean_from_gram_pallas(
        xj, gram, f=2, q=4, mode="krum", interpret=True
    ))
    want = np.asarray(robust.multi_krum_from_gram(xj, gram, f=2, q=4))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert np.isfinite(got).all()


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_streaming_fold_matches_barrier_across_dtypes(dtype):
    """The donated-buffer Gram fold reproduces the barrier aggregate for
    any arrival order (documented float tolerance: the per-arrival
    matvec accumulates in arrival order)."""
    n, d = 11, 193
    rng = np.random.default_rng(7)
    grads = [
        jnp.asarray(rng.normal(size=d), jnp.float32).astype(dtype)
        for _ in range(n)
    ]
    agg = MultiKrum(f=2, q=3)
    ref = np.asarray(agg.aggregate(list(grads)), np.float32)
    for order in ([*range(n)], [*reversed(range(n))], [5, 0, 9, 2, 7, 1, 10, 4, 8, 3, 6]):
        state = agg.fold_init(n)
        for i in order:
            agg.fold(state, i, grads[i])
        out = np.asarray(agg.fold_finalize(state), np.float32)
        np.testing.assert_allclose(
            out, ref, rtol=2e-2 if dtype == jnp.bfloat16 else 1e-5,
            atol=1e-6,
        )


def test_streaming_fold_partial_round():
    """Elastic partial rounds gather the arrived subset in canonical
    order — same result as the barrier over the arrived gradients."""
    n, d = 9, 120
    rng = np.random.default_rng(8)
    grads = [jnp.asarray(rng.normal(size=d), jnp.float32) for _ in range(n)]
    agg = MultiKrum(f=1, q=3)
    arrived = [7, 1, 4, 2, 8, 0]
    state = agg.fold_init(n)
    for i in arrived:
        agg.fold(state, i, grads[i])
    out = np.asarray(agg.fold_finalize(state))
    ref = np.asarray(agg.aggregate([grads[i] for i in sorted(arrived)]))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_fold_guards_slot_errors():
    agg = MultiKrum(f=1, q=2)
    state = agg.fold_init(4)
    g = jnp.ones((8,), jnp.float32)
    agg.fold(state, 1, g)
    with pytest.raises(ValueError, match="folded twice"):
        agg.fold(state, 1, g)
    with pytest.raises(IndexError):
        agg.fold(state, 4, g)
    with pytest.raises(ValueError, match="same length"):
        agg.fold(state, 2, jnp.ones((9,), jnp.float32))


# ---------------------------------------------------------------------------
# bf16 Gram policy
# ---------------------------------------------------------------------------


def test_matmul_dtype_policy_resolves_per_call(monkeypatch):
    x = _rand(10, 512, seed=9)
    exact = np.asarray(robust.gram_matrix(x))
    monkeypatch.setenv("BYZPY_TPU_MATMUL_DTYPE", "bf16")
    approx = np.asarray(robust.gram_matrix(x))
    assert approx.dtype == np.float32  # f32 accumulator survives
    # bf16 input rounding perturbs each product by ~2^-8 relative to the
    # OPERAND norms, not the (possibly tiny) entry value — tolerance is
    # therefore absolute, scaled by the diagonal magnitude
    tol = 2e-2 * float(np.abs(np.diagonal(exact)).mean())
    np.testing.assert_allclose(approx, exact, atol=tol)
    assert not np.array_equal(approx, exact)  # the cast really happened
    monkeypatch.delenv("BYZPY_TPU_MATMUL_DTYPE")
    np.testing.assert_array_equal(np.asarray(robust.gram_matrix(x)), exact)
    # bf16 inputs are unaffected by the policy (already narrow)
    xb = x.astype(jnp.bfloat16)
    monkeypatch.setenv("BYZPY_TPU_MATMUL_DTYPE", "bf16")
    assert pk.matmul_input_dtype(xb.dtype) is None


def test_bf16_policy_multi_krum_parity(monkeypatch):
    x = _rand(16, 640, seed=10)
    exact = np.asarray(robust.multi_krum(x, f=3, q=5))
    monkeypatch.setenv("BYZPY_TPU_MATMUL_DTYPE", "bf16")
    approx = np.asarray(robust.multi_krum(x, f=3, q=5))
    # scores shift by ~2^-8 relative; on generic (tie-free) data the
    # selection is identical, so the aggregate matches to bf16 tolerance
    np.testing.assert_allclose(approx, exact, rtol=2e-2, atol=1e-2)

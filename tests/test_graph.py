"""Graph structure, operator dispatch, schedulers, lazy builder, session."""

import asyncio

import pytest

from byzpy_tpu.engine.graph import (
    ActorPool,
    ActorPoolConfig,
    CallableOp,
    ComputationGraph,
    ExecutionSession,
    GraphBuilder,
    GraphInput,
    GraphNode,
    MessageAwareNodeScheduler,
    MessageSource,
    NodeScheduler,
    OpContext,
    Operator,
    ParallelScheduler,
    RemoteCallableOp,
    SubTask,
    run_operator,
    select_adaptive_chunk_size,
)


class AddOp(Operator):
    name = "add"

    def __init__(self, amount):
        self.amount = amount

    def compute(self, inputs, *, context):
        return inputs["value"] + self.amount


class SumSubtasksOp(Operator):
    """Fan out one subtask per item, reduce by summing."""

    name = "sum-subtasks"
    supports_subtasks = True

    def compute(self, inputs, *, context):
        return sum(inputs["items"])

    def create_subtasks(self, inputs, *, context):
        for i, item in enumerate(inputs["items"]):
            yield SubTask(fn=lambda x: x * 10, args=(item,), name=f"st{i}")

    def reduce_subtasks(self, partials, inputs, *, context):
        return sum(partials)


# ---------------------------------------------------------------------------
# graph structure
# ---------------------------------------------------------------------------


def test_topo_order_and_outputs():
    g = ComputationGraph(
        [
            GraphNode("c", AddOp(1), {"value": "b"}),
            GraphNode("a", AddOp(1), {"value": GraphInput("x")}),
            GraphNode("b", AddOp(1), {"value": "a"}),
        ]
    )
    order = [n.name for n in g.nodes_in_order()]
    assert order.index("a") < order.index("b") < order.index("c")
    assert g.outputs == ["c"]  # last topo node is the default output
    assert g.required_inputs() == {"x"}


def test_cycle_detection_and_duplicates():
    with pytest.raises(ValueError, match="cycle"):
        ComputationGraph(
            [
                GraphNode("a", AddOp(1), {"value": "b"}),
                GraphNode("b", AddOp(1), {"value": "a"}),
            ]
        )
    with pytest.raises(ValueError, match="duplicate"):
        ComputationGraph([GraphNode("a", AddOp(1)), GraphNode("a", AddOp(2))])


def test_unknown_reference_caught():
    g = ComputationGraph([GraphNode("a", AddOp(1), {"value": "ghost"})])
    with pytest.raises(ValueError, match="ghost"):
        g.required_inputs()


# ---------------------------------------------------------------------------
# schedulers
# ---------------------------------------------------------------------------


def test_node_scheduler_chain():
    g = ComputationGraph(
        [
            GraphNode("a", AddOp(1), {"value": GraphInput("x")}),
            GraphNode("b", AddOp(10), {"value": "a"}),
        ],
        outputs=["a", "b"],
    )
    out = asyncio.run(NodeScheduler(g).run({"x": 5}))
    assert out == {"a": 6, "b": 16}


def test_node_scheduler_missing_input():
    g = ComputationGraph([GraphNode("a", AddOp(1), {"value": GraphInput("x")})])
    with pytest.raises(KeyError, match="x"):
        asyncio.run(NodeScheduler(g).run({}))


def test_parallel_scheduler_diamond():
    order = []

    def track(name, delay):
        async def fn(value):
            order.append(f"{name}+")
            await asyncio.sleep(delay)
            order.append(f"{name}-")
            return value + 1

        return fn

    g = ComputationGraph(
        [
            GraphNode("src", CallableOp(track("src", 0.0)), {"value": GraphInput("x")}),
            GraphNode("l", CallableOp(track("l", 0.05)), {"value": "src"}),
            GraphNode("r", CallableOp(track("r", 0.05)), {"value": "src"}),
            GraphNode(
                "join",
                CallableOp(lambda l, r: l + r),
                {"l": "l", "r": "r"},
            ),
        ]
    )
    out = asyncio.run(ParallelScheduler(g).run({"x": 0}))
    assert out == {"join": 4}
    # l and r must have overlapped (parallel execution)
    assert order.index("r+") < order.index("l-")


def test_message_aware_scheduler():
    async def main():
        g = ComputationGraph(
            [
                GraphNode(
                    "a",
                    AddOp(1),
                    {"value": MessageSource(message_type="grad", field="v")},
                )
            ]
        )
        sched = MessageAwareNodeScheduler(g)
        run = asyncio.ensure_future(sched.run({}))
        await asyncio.sleep(0.02)
        await sched.deliver_message("grad", {"v": 41})
        out = await run
        assert out == {"a": 42}
        # cached messages are consumed FIFO by later waits
        await sched.deliver_message("grad", {"v": 1})
        await sched.deliver_message("grad", {"v": 2})
        assert (await sched.wait_for_message("grad"))["v"] == 1
        assert sched.pending_message_count("grad") == 1
        with pytest.raises(TimeoutError):
            await sched.wait_for_message("never", timeout=0.01)

    asyncio.run(main())


# ---------------------------------------------------------------------------
# pool + subtasks
# ---------------------------------------------------------------------------


def test_pool_subtask_fanout_thread_backend():
    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=3)) as pool:
            op = SumSubtasksOp()
            result = await op.run(
                {"items": list(range(8))}, context=OpContext("n"), pool=pool
            )
            assert result == sum(i * 10 for i in range(8))

    asyncio.run(main())


def test_pool_retry_and_affinity():
    attempts = {"n": 0}

    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise RuntimeError("transient")
        return "ok"

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            out = await pool.run_subtask(SubTask(fn=flaky, max_retries=3))
            assert out == "ok"
            assert attempts["n"] == 3
            # exhausted retries raise the last error
            with pytest.raises(ZeroDivisionError):
                await pool.run_subtask(SubTask(fn=lambda: 1 / 0, max_retries=1))
            # affinity for a capability nobody has falls back to any worker
            assert await pool.run_subtask(SubTask(fn=lambda: 7, affinity="tpu")) == 7

    asyncio.run(main())


def test_pool_channel():
    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            chan = await pool.open_channel("bus")
            names = pool.worker_names
            await chan.send(names[0], names[1], {"hello": 1})
            msg = await chan.recv(names[1])
            assert msg == {"sender": names[0], "payload": {"hello": 1}}

    asyncio.run(main())


def test_run_operator_front_door():
    assert asyncio.run(run_operator(AddOp(5), {"value": 1})) == 6
    # bare value + explicit input key
    assert asyncio.run(run_operator(AddOp(5), 2, input_key="value")) == 7


def test_remote_callable_op_runs_on_pool():
    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            op = RemoteCallableOp(lambda value: value * 3)
            out = await op.run({"value": 4}, context=OpContext("n"), pool=pool)
            assert out == 12

    asyncio.run(main())


# ---------------------------------------------------------------------------
# lazy builder + session
# ---------------------------------------------------------------------------


def test_lazy_builder_chain():
    b = GraphBuilder()
    out = (
        b.input("x")
        .apply(AddOp(1), input_key="value", name="inc")
        .apply(AddOp(10), input_key="value")
    )
    g = b.build(out)
    results = asyncio.run(NodeScheduler(g).run({"x": 0}))
    assert list(results.values()) == [11]


def test_session_caches_intermediates():
    calls = {"n": 0}

    class CountingOp(Operator):
        name = "counting"

        def compute(self, inputs, *, context):
            calls["n"] += 1
            return inputs["value"] * 2

    async def main():
        g = ComputationGraph(
            [
                GraphNode("a", CountingOp(), {"value": GraphInput("x")}),
                GraphNode("b", AddOp(1), {"value": "a"}),
            ],
            outputs=["b"],
        )
        session = ExecutionSession()
        out1 = await session.execute(g, {"x": 3})
        assert out1 == {"b": 7}
        assert calls["n"] == 1
        # second execution: 'a' (and 'b') served from cache
        out2 = await session.execute(g, {"x": 3})
        assert out2 == {"b": 7}
        assert calls["n"] == 1
        session.invalidate(["a", "b"])
        await session.execute(g, {"x": 5})
        assert calls["n"] == 2
        # async future API
        session.invalidate()
        fut = session.execute_async(g, {"x": 1})
        assert not fut.done()
        res = await fut.result()
        assert res == {"b": 3}
        assert fut.done()

    asyncio.run(main())


def test_chunking_heuristic():
    # small pool: keep configured
    assert select_adaptive_chunk_size(1000, 100, pool_size=1) == 100
    # big pool: shrink to keep >=4 chunks/worker, capped at 8x shrink
    c = select_adaptive_chunk_size(1000, 800, pool_size=8)
    assert c <= 800 and c >= 100
    assert select_adaptive_chunk_size(0, 64) == 64

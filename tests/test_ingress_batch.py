"""Batched ingress bit-parity: the PR-16 wire-rate front door.

Pins the tentpole contract: draining a connection's queued frames into
ONE ``serve_frames`` batch (vectorized decode, amortized HMAC,
quantized rows kept compressed into the ragged fold) is bit-identical
to serving the same frames one at a time — identical acks, identical
round aggregates, identical pre-decode inflation forensics — across
every wire precision, with hostile frames (tampered / oversized /
duplicate-seq) interleaved mid-batch."""

import asyncio

import numpy as np
import pytest

from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
from byzpy_tpu.engine.actor import wire
from byzpy_tpu.serving import ServingFrontend, TenantConfig
from byzpy_tpu.serving.frontend import serve_frame

D = 4096  # above WIRE_QUANT_MIN_SIZE so blockwise modes engage

PRECISIONS = ("off", "bf16", "int8", "fp8", "s4")


def _frontend(**kw):
    cfg = dict(
        name="m0", dim=D, aggregator=CoordinateWiseTrimmedMean(f=1),
        cohort_cap=16, window_s=0.01,
    )
    cfg.update(kw)
    return ServingFrontend([TenantConfig(**cfg)])


def _frames(n=6, *, dup_at=None, seed=0):
    """n submit frame bodies (length prefixes stripped); ``dup_at``
    re-sends frame 0's (client, seq) key mid-batch."""
    rng = np.random.default_rng(seed)
    bodies = []
    for i in range(n):
        client, seq = f"c{i}", 0
        if dup_at is not None and i == dup_at:
            client, seq = "c0", 0  # replayed idempotency key
        bodies.append(wire.encode({
            "kind": "submit", "tenant": "m0", "client": client,
            "round": 0, "gradient": rng.normal(size=D).astype(np.float32),
            "seq": seq,
        })[4:])
    return bodies


@pytest.mark.parametrize("precision", PRECISIONS)
def test_batched_matches_per_frame_bitwise(precision, monkeypatch):
    monkeypatch.setenv("BYZPY_TPU_WIRE_PRECISION", precision)
    bodies = _frames(dup_at=3)

    fe_b = _frontend()
    replies, served, err = fe_b.serve_frames(bodies)
    assert err is None and served == len(bodies)
    acks_b = [wire.decode(r[4:]) for r in replies]

    fe_p = _frontend()
    acks_p = [wire.decode(serve_frame(fe_p, b)[4:]) for b in bodies]

    assert acks_b == acks_p
    assert acks_b[3]["reason"] == "duplicate"  # mid-batch dedup held
    assert [a["accepted"] for a in acks_b] == [True] * len(bodies)

    closed_b = fe_b.close_round_nowait("m0")
    closed_p = fe_p.close_round_nowait("m0")
    assert closed_b is not None and closed_p is not None
    vb, vp = np.asarray(closed_b[2]), np.asarray(closed_p[2])
    assert vb.tobytes() == vp.tobytes()  # aggregates byte-identical
    # pre-decode inflation forensics identical, frame for frame
    assert closed_b[1].wire_inflations == closed_p[1].wire_inflations
    if precision in wire.BLOCKWISE_WIRE_MODES:
        assert all(r is not None for r in closed_b[1].wire_inflations)
    assert (
        fe_b.stats()["m0"]["ledger"]["totals"]
        == fe_p.stats()["m0"]["ledger"]["totals"]
    )
    assert fe_b.ingress_max_batch == len(bodies)
    assert (
        fe_b._tenants["m0"].ingress_bytes
        == fe_p._tenants["m0"].ingress_bytes
    )


@pytest.mark.parametrize("precision", ("off", "s4"))
def test_tampered_frame_mid_batch_truncates_at_parity(
    precision, monkeypatch
):
    monkeypatch.setenv("BYZPY_TPU_WIRE_PRECISION", precision)
    monkeypatch.setenv("BYZPY_TPU_WIRE_KEY", "batch-parity-key")
    bodies = _frames(5)
    bad = bytearray(bodies[2])
    bad[-1] ^= 0xFF  # flip a payload byte under the HMAC
    bodies[2] = bytes(bad)

    fe_b = _frontend()
    replies, served, err = fe_b.serve_frames(bodies)
    # frames BEFORE the tampered one served; it and everything after
    # did not — exactly where the per-frame door dropped the peer
    assert served == 2 and err is not None
    assert fe_b.bad_frames == 1
    acks_b = [wire.decode(r[4:]) for r in replies]

    fe_p = _frontend()
    acks_p = []
    for i, b in enumerate(bodies):
        if i == 2:
            with pytest.raises(Exception):
                serve_frame(fe_p, b)
            break
        acks_p.append(wire.decode(serve_frame(fe_p, b)[4:]))
    assert acks_b == acks_p
    assert fe_p.bad_frames == 1
    assert (
        fe_b.stats()["m0"]["ledger"]["totals"]
        == fe_p.stats()["m0"]["ledger"]["totals"]
    )


def test_hostile_interleave_over_tcp(monkeypatch):
    """One connection, one write: [good, dup-seq, oversized junk,
    good, tampered, good]. The batched read loop serves every frame up
    to the tampered one (resyncing past the oversized frame), then
    drops the peer — acks in arrival order, both framing faults
    counted."""
    monkeypatch.setenv("BYZPY_TPU_WIRE_PRECISION", "s4")
    monkeypatch.setenv("BYZPY_TPU_WIRE_KEY", "batch-parity-key")
    monkeypatch.setattr(wire, "MAX_FRAME", 1 << 16)
    bodies = _frames(4, dup_at=1)
    tampered = bytearray(bodies[3])
    tampered[-1] ^= 0xFF
    junk_len = wire.MAX_FRAME + 64

    async def run():
        fe = _frontend()
        await fe.start()
        host, port = await fe.serve()
        reader, writer = await asyncio.open_connection(host, port)
        writer.write(
            wire._HEADER.pack(len(bodies[0])) + bodies[0]
            + wire._HEADER.pack(len(bodies[1])) + bodies[1]
            + wire._HEADER.pack(junk_len) + b"\xee" * junk_len
            + wire._HEADER.pack(len(bodies[2])) + bodies[2]
            + wire._HEADER.pack(len(tampered)) + bytes(tampered)
        )
        writer.write_eof()
        await writer.drain()
        data = await reader.read()
        writer.close()
        await fe.close()
        return data, fe

    data, fe = asyncio.run(run())
    acks = []
    while data:
        (ln,) = wire._HEADER.unpack(data[:4])
        acks.append(wire.decode(data[4:4 + ln]))
        data = data[4 + ln:]
    assert [a["reason"] for a in acks] == [
        "accepted", "duplicate", "accepted"
    ]
    assert fe.bad_frames == 2  # oversized + tampered
    assert fe.stats()["m0"]["ledger"]["totals"]["accepted"] == 2


def test_torn_frame_at_eof_counts_bad_frame():
    async def run():
        fe = _frontend()
        await fe.start()
        host, port = await fe.serve()
        reader, writer = await asyncio.open_connection(host, port)
        body = _frames(1)[0]
        writer.write(wire._HEADER.pack(len(body)) + body[: len(body) // 2])
        writer.write_eof()
        await writer.drain()
        data = await reader.read()
        writer.close()
        await fe.close()
        return data, fe.bad_frames

    data, bad = asyncio.run(run())
    assert data == b"" and bad == 1


# ---------------------------------------------------------------------------
# device-side dequantization fused into the ragged fold
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ("int8", "fp8", "s4"))
def test_fused_dequant_kernel_matches_xla_fallback(mode):
    from byzpy_tpu.ops.pallas_kernels import ragged_segment_sum_dequant_pallas
    from byzpy_tpu.ops.ragged import flat_dequantize

    rng = np.random.default_rng(5)
    n, d, block = 12, 1024, 256
    rows = [rng.normal(size=d).astype(np.float32) for _ in range(n)]
    enc = [wire._np_blockwise_encode(r, block, mode) for r in rows]
    codes = np.stack([e[0] for e in enc])
    scales = np.stack([e[1] for e in enc])
    seg = np.asarray(
        [0] * 5 + [1] * 4 + [2] * 3, np.int32
    )
    weights = np.zeros((3, n), np.float32)
    for i, s in enumerate(seg):
        weights[s, i] = 1.0 if i % 3 else 0.5

    fused = np.asarray(ragged_segment_sum_dequant_pallas(
        codes, scales, weights, mode=mode, block=block, d=d
    ))
    flat = np.asarray(flat_dequantize(
        codes, scales, mode=mode, block=block, d=d
    ))
    ref = np.einsum("cn,nd->cd", weights, flat)
    np.testing.assert_allclose(fused, ref, rtol=1e-6, atol=1e-6)
    # XLA dequant mirror is bit-identical to the wire codec's numpy one
    host = wire.decode_rows_np(
        codes, scales, mode=mode, block=block, d=d
    )
    assert flat.tobytes() == host.tobytes()


def test_quantized_round_keeps_rows_compressed(monkeypatch):
    """The batched quantized path never materializes host f32 rows:
    the cohort reaches the fold (and leaves it) as codes + scales, the
    executor records a quantized dispatch, and the lowered program's
    parameters show the codes entering the device AS wire bytes."""
    monkeypatch.setenv("BYZPY_TPU_WIRE_PRECISION", "int8")
    import jax

    fe = _frontend()
    replies, served, err = fe.serve_frames(_frames(6))
    assert err is None and served == 6
    closed = fe.close_round_nowait("m0")
    assert closed is not None
    cohort = closed[1]
    assert cohort.quantized
    assert cohort.dense is None  # no consumer forced a host decode
    ex = fe._ragged.executor_for("m0")
    assert ex is not None and ex.quantized_dispatches == 1
    jitted = ex._jitted_q[("int8", cohort.qblock)]
    ncodes = cohort.qcodes.shape[1]
    nb = cohort.qscales.shape[1]
    hlo = jitted.lower(
        jax.ShapeDtypeStruct((ex.rows, ncodes), np.int8),
        jax.ShapeDtypeStruct((ex.rows, nb), np.float32),
        jax.ShapeDtypeStruct((ex.rows,), np.int32),
        jax.ShapeDtypeStruct((ex.max_cohorts,), np.int32),
        jax.ShapeDtypeStruct((ex.max_cohorts,), np.int32),
        jax.ShapeDtypeStruct((ex.rows,), np.float32),
    ).as_text()
    main = next(
        line for line in hlo.splitlines() if "func.func public @main" in line
    )
    # int8 wire codes are a program INPUT...
    assert f"tensor<{ex.rows}x{ncodes}xi8>" in main
    # ...and the f32 flat batch exists only INSIDE the program (on
    # device), never as a host-side argument
    assert f"tensor<{ex.rows}x{D}xf32>" not in main


@pytest.mark.parametrize("precision", ("int8", "s4"))
def test_pallas_fused_round_matches_xla_round(precision, monkeypatch):
    """With the Pallas ragged fold enabled, the fused dequant kernel
    (codes travel into the MXU tile) produces the same round aggregate
    as the XLA dequant-then-fold program — interpret mode on CPU is
    the same contraction the TPU kernel runs."""
    monkeypatch.setenv("BYZPY_TPU_WIRE_PRECISION", precision)
    bodies = _frames(6)

    monkeypatch.setenv("BYZPY_TPU_RAGGED_PALLAS", "1")
    fe_k = _frontend()
    _, served, err = fe_k.serve_frames(bodies)
    assert err is None and served == 6
    vec_k = np.asarray(fe_k.close_round_nowait("m0")[2])

    monkeypatch.delenv("BYZPY_TPU_RAGGED_PALLAS")
    fe_x = _frontend()
    fe_x.serve_frames(bodies)
    vec_x = np.asarray(fe_x.close_round_nowait("m0")[2])
    np.testing.assert_allclose(vec_k, vec_x, rtol=1e-6, atol=1e-6)


def test_mixed_spec_round_falls_back_dense(monkeypatch):
    """A round mixing wire-quantized and in-process dense submissions
    cannot stack codes — it falls back to the dense cohort layout,
    decoding admitted rows bit-identically to a per-frame ingress."""
    monkeypatch.setenv("BYZPY_TPU_WIRE_PRECISION", "int8")
    fe = _frontend()
    replies, served, err = fe.serve_frames(_frames(4))
    assert err is None and served == 4
    rng = np.random.default_rng(9)
    for i in range(2):
        ok, reason = fe.submit(
            "m0", f"p{i}", 0, rng.normal(size=D).astype(np.float32)
        )
        assert ok, reason
    closed = fe.close_round_nowait("m0")
    assert closed is not None
    cohort = closed[1]
    assert not cohort.quantized and cohort.dense is not None
    assert np.isfinite(np.asarray(closed[2])).all()

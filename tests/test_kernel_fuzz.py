"""Seeded fuzz sweep: every fused Pallas kernel vs its XLA oracle.

Randomized (but deterministic) shapes, hyper-parameters, dtypes, and
non-finite injection patterns — the structured unit tests pin known edge
cases; this sweep hunts the unknown ones. Interpret mode on CPU, same
code paths as the chip (tests/conftest.py pins the platform).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.heavy  # opt-in lane: see pyproject addopts

from byzpy_tpu.ops import robust
from byzpy_tpu.ops.pallas_kernels import (
    nnm_stream_pallas,
    selection_mean_stream_pallas,
    sorted_reduce_stream_pallas,
)

N_CASES = 12


def _random_case(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(5, 33))
    d = int(rng.integers(130, 900))
    x = rng.normal(size=(n, d)).astype(np.float32) * 10.0 ** float(rng.integers(-2, 3))
    # sprinkle non-finite rows/entries in ~half the cases
    if rng.random() < 0.5:
        for _ in range(int(rng.integers(1, 3))):
            r = int(rng.integers(0, n))
            val = rng.choice([np.inf, -np.inf, np.nan])
            if rng.random() < 0.5:
                x[r] = val  # whole row
            else:
                x[r, :: int(rng.integers(2, 7))] = val
    return n, d, x


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_selection_mean_krum(seed):
    n, d, x = _random_case(1000 + seed)
    rng = np.random.default_rng(seed)
    f = int(rng.integers(0, max(1, (n - 1) // 2)))
    q = int(rng.integers(1, n - f + 1))
    xa = jnp.asarray(x)
    got = selection_mean_stream_pallas(
        xa[None], f=f, q=q, mode="krum", tile=128, interpret=True
    )[0]
    want = robust.ranked_mean(xa, robust.krum_scores(xa, f=f), q)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5, equal_nan=True
    )


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_sorted_reduce(seed):
    n, d, x = _random_case(2000 + seed)
    xa = jnp.asarray(x)
    got = sorted_reduce_stream_pallas(
        xa[None], mode="median", tile=128, interpret=True
    )[0]
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(jnp.median(xa, axis=0))
    )
    f = int(np.random.default_rng(seed).integers(0, (n - 1) // 2 + 1))
    if 2 * f < n:
        got = sorted_reduce_stream_pallas(
            xa[None], mode="trimmed", f=f, tile=128, interpret=True
        )[0]
        s = jnp.sort(xa, axis=0)
        want = jnp.mean(s[f : n - f], axis=0)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6,
            equal_nan=True,
        )


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_nnm(seed):
    n, d, x = _random_case(3000 + seed)
    rng = np.random.default_rng(seed)
    f = int(rng.integers(0, n))
    xa = jnp.asarray(x)
    got = np.asarray(nnm_stream_pallas(xa[None], f=f, tile=128, interpret=True)[0])
    # oracle: the (fixed) XLA path — identical non-finite semantics
    import os

    prev = os.environ.get("BYZPY_TPU_PALLAS")
    os.environ["BYZPY_TPU_PALLAS"] = "0"
    try:
        from byzpy_tpu.ops import preagg

        want = np.asarray(preagg.nnm(xa, f=f))
    finally:
        if prev is None:
            os.environ.pop("BYZPY_TPU_PALLAS", None)
        else:
            os.environ["BYZPY_TPU_PALLAS"] = prev
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5, equal_nan=True)


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_bf16_selection(seed):
    n, d, x = _random_case(4000 + seed)
    rng = np.random.default_rng(seed)
    f = int(rng.integers(0, max(1, (n - 1) // 2)))
    q = int(rng.integers(1, n - f + 1))
    xb = jnp.asarray(x).astype(jnp.bfloat16)
    got = selection_mean_stream_pallas(
        xb[None], f=f, q=q, mode="krum", tile=128, interpret=True
    )[0]
    want = robust.ranked_mean(xb, robust.krum_scores(xb, f=f), q)
    assert got.dtype == jnp.bfloat16
    g32 = np.asarray(got, np.float32)
    w32 = np.asarray(want, np.float32)
    both_nan = np.isnan(g32) & np.isnan(w32)
    scale = float(np.nanmax(np.abs(w32[~both_nan]))) if (~both_nan).any() else 1.0
    # bf16 scores can flip near-tie selections between the two paths;
    # any legitimate q-subset mean stays within the honest spread
    assert np.allclose(
        g32[~both_nan], w32[~both_nan], rtol=0.15, atol=0.15 * max(scale, 1e-6)
    ) or not np.isfinite(scale)


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_weighted_center_step(seed):
    from byzpy_tpu.ops.pallas_kernels import weighted_center_step_pallas

    n, d, x = _random_case(5000 + seed)
    xa = jnp.asarray(x)
    z = jnp.median(xa, axis=0)
    got = weighted_center_step_pallas(xa, z, mode="weiszfeld", tile=128,
                                      interpret=True)
    diff = xa - z[None, :]
    dist = jnp.sqrt(jnp.sum(diff * diff, axis=1))
    w = 1.0 / jnp.maximum(dist, 1e-12)
    want = jnp.sum(w[:, None] * xa, axis=0) / jnp.sum(w)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4, equal_nan=True
    )
    tau = float(np.random.default_rng(seed).uniform(0.5, 3.0))
    got = weighted_center_step_pallas(xa, z, mode="clip", c_tau=tau, tile=128,
                                      interpret=True)
    scale = jnp.minimum(1.0, tau / jnp.maximum(dist, 1e-12))
    want = z + jnp.mean(diff * scale[:, None], axis=0)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4, equal_nan=True
    )


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_mda_matches_bruteforce(seed):
    """MDA's branch-and-bound + greedy-peel incumbent vs exhaustive
    enumeration on small instances (diameter ties broken identically:
    first subset in combination order)."""
    import itertools

    from byzpy_tpu.aggregators import MinimumDiameterAveraging

    rng = np.random.default_rng(7000 + seed)
    n = int(rng.integers(6, 11))
    f = int(rng.integers(1, (n - 1) // 2 + 1))
    m = n - f
    x = rng.normal(size=(n, 12)).astype(np.float32)
    grads = [jnp.asarray(r) for r in x]
    got = np.asarray(MinimumDiameterAveraging(f=f).aggregate(grads))
    # oracle uses the implementation's own metric (f32 Gram-trick
    # distances): a direct-difference f64 metric can crown a different
    # winner on near-ties, which is a float-representation disagreement,
    # not an algorithmic one
    gram = x @ x.T
    nrm = np.diagonal(gram)
    d2 = np.maximum(nrm[:, None] + nrm[None, :] - 2.0 * gram, 0.0)
    combos = list(itertools.combinations(range(n), m))
    diams = np.array([d2[np.ix_(np.array(c), np.array(c))].max() for c in combos])
    best_diam = diams.min()
    # the branch-and-bound may return ANY minimum-diameter subset (ties
    # are not broken by enumeration order); accept every tied winner
    winners = [
        x[list(c)].mean(0)
        for c, dm in zip(combos, diams, strict=True)
        if dm <= best_diam * (1 + 1e-6) + 1e-9
    ]
    assert any(
        np.allclose(got, w, rtol=1e-4, atol=1e-5) for w in winners
    ), (best_diam, len(winners))


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_random_dag_schedulers_agree(seed):
    """Property: ParallelScheduler and the sequential NodeScheduler give
    identical results on random DAGs of arithmetic ops."""
    import asyncio

    from byzpy_tpu.engine.graph.graph import (
        ComputationGraph,
        GraphInput,
        GraphNode,
    )
    from byzpy_tpu.engine.graph.ops import CallableOp
    from byzpy_tpu.engine.graph.parallel_scheduler import ParallelScheduler
    from byzpy_tpu.engine.graph.scheduler import NodeScheduler

    rng = np.random.default_rng(8000 + seed)
    n_nodes = int(rng.integers(3, 9))
    nodes = []
    names = []
    for i in range(n_nodes):
        # each node consumes the graph input and up to 2 earlier nodes
        deps = {"x": GraphInput("x")}
        if names:
            for j, nm in enumerate(
                rng.choice(names, size=min(len(names), int(rng.integers(0, 3))),
                           replace=False)
            ):
                deps[f"d{j}"] = str(nm)
        coefs = rng.normal(size=len(deps))

        def fn(_coefs=coefs, **kw):
            vals = [kw[k] for k in sorted(kw)]
            return sum(float(c) * v for c, v in zip(_coefs, vals, strict=True))

        name = f"n{i}"
        nodes.append(GraphNode(name=name, op=CallableOp(fn), inputs=deps))
        names.append(name)
    graph = ComputationGraph(nodes)
    inputs = {"x": jnp.asarray(rng.normal(size=(16,)).astype(np.float32))}
    seq = asyncio.run(NodeScheduler(graph).run(inputs))
    par = asyncio.run(ParallelScheduler(ComputationGraph(nodes)).run(inputs))
    for k in seq:
        np.testing.assert_allclose(
            np.asarray(seq[k]), np.asarray(par[k]), rtol=1e-6, atol=1e-6
        )


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_caf_downweights_outliers(seed):
    """Property: with f large outliers, CAF's output stays near the
    honest mean (closer than the naive mean is) and finite."""
    rng = np.random.default_rng(9000 + seed)
    n = int(rng.integers(10, 20))
    f = max(1, n // 5)
    d = int(rng.integers(16, 64))
    honest = rng.normal(size=(n - f, d)).astype(np.float32)
    outliers = (rng.normal(size=(f, d)) * 100 + 500).astype(np.float32)
    x = np.concatenate([honest, outliers])
    out = np.asarray(robust.caf(jnp.asarray(x), f=f))
    assert np.isfinite(out).all()
    honest_mean = honest.mean(0)
    naive_mean = x.mean(0)
    assert np.linalg.norm(out - honest_mean) < 0.5 * np.linalg.norm(
        naive_mean - honest_mean
    )


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_meamed_window_vs_gather_oracle(seed, monkeypatch):
    """The single-phase window kernel AND the XLA window path vs the
    gather-rule oracle (shared with test_pallas_kernels), under random
    shapes/f and non-finite injection — whole-inf rows can drive the
    median itself to ±inf, the regime the round-5 review found broken.
    Non-finite outputs must match exactly (kind AND sign)."""
    from test_pallas_kernels import _meamed_oracle

    from byzpy_tpu.ops.pallas_kernels import meamed_stream_pallas

    n, d, x = _random_case(7000 + seed)
    rng = np.random.default_rng(seed)
    f = int(rng.integers(0, n))
    want = _meamed_oracle(x, f)
    xa = jnp.asarray(x)
    got_kernel = np.asarray(
        meamed_stream_pallas(xa[None], f=f, tile=128, interpret=True)[0]
    )
    monkeypatch.setenv("BYZPY_TPU_PALLAS", "0")
    got_xla = np.asarray(robust.mean_of_medians(xa, f=f))
    for got, label in ((got_kernel, "kernel"), (got_xla, "xla")):
        np.testing.assert_allclose(
            got, want, rtol=1e-4, atol=1e-5, equal_nan=True,
            err_msg=f"{label} n={n} f={f} seed={seed}",
        )

"""Deep L2 coverage: ParallelScheduler, Operator dispatch modes, windowed
subtask execution, and the message machinery.

Mirrors the intent of the reference suites
``engine/graph/tests/test_parallel_scheduler.py`` (concurrency caps,
failure propagation, shared subtask budget), ``test_operator.py``
(dispatch-mode selection, windowed refill ordering, semaphore
release-on-failure, affinity), ``test_message_trigger_op.py`` and
``test_scheduler_message.py`` (trigger ops, waiter/cache discipline).
"""

import asyncio

import pytest

from byzpy_tpu.engine.graph import (
    ActorPool,
    ActorPoolConfig,
    ComputationGraph,
    GraphInput,
    GraphNode,
)
from byzpy_tpu.engine.graph.operator import (
    MessageTriggerOp,
    OpContext,
    Operator,
    run_subtasks_windowed,
)
from byzpy_tpu.engine.graph.parallel_scheduler import ParallelScheduler
from byzpy_tpu.engine.graph.scheduler import (
    MessageAwareNodeScheduler,
    MessageSource,
    NodeScheduler,
)
from byzpy_tpu.engine.graph.subtask import SubTask


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


class SleepOp(Operator):
    """Records entry/exit so tests can assert overlap and ordering."""

    def __init__(self, name, delay=0.05, log=None, result=None):
        self.name = name
        self.delay = delay
        self.log = log if log is not None else []
        self.result = result if result is not None else name

    async def compute(self, inputs, *, context):
        self.log.append(("start", self.name))
        await asyncio.sleep(self.delay)
        self.log.append(("end", self.name))
        return self.result


class GaugeOp(Operator):
    """Tracks the peak number of concurrently-running instances."""

    running = 0
    peak = 0

    def __init__(self, name, delay=0.05):
        self.name = name
        self.delay = delay

    async def compute(self, inputs, *, context):
        cls = GaugeOp
        cls.running += 1
        cls.peak = max(cls.peak, cls.running)
        try:
            await asyncio.sleep(self.delay)
        finally:
            cls.running -= 1
        return self.name


class FailOp(Operator):
    name = "fail"

    async def compute(self, inputs, *, context):
        raise RuntimeError("node exploded")


def graph_of(*nodes, outputs=None):
    return ComputationGraph(list(nodes), outputs=outputs)


# ---------------------------------------------------------------------------
# ParallelScheduler
# ---------------------------------------------------------------------------


def test_parallel_independent_branches_overlap():
    """Two independent branches must interleave (start/start before any
    end), unlike the sequential NodeScheduler."""
    log = []
    g = graph_of(
        GraphNode("a", SleepOp("a", 0.05, log), {}),
        GraphNode("b", SleepOp("b", 0.05, log), {}),
        outputs=["a", "b"],
    )
    asyncio.run(ParallelScheduler(g).run({}))
    starts = [i for i, (kind, _) in enumerate(log) if kind == "start"]
    first_end = min(i for i, (kind, _) in enumerate(log) if kind == "end")
    assert max(starts) < first_end, log  # both started before either ended


def test_sequential_scheduler_does_not_overlap():
    log = []
    g = graph_of(
        GraphNode("a", SleepOp("a", 0.02, log), {}),
        GraphNode("b", SleepOp("b", 0.02, log), {}),
        outputs=["a", "b"],
    )
    asyncio.run(NodeScheduler(g).run({}))
    assert log == [("start", "a"), ("end", "a"), ("start", "b"), ("end", "b")]


def test_parallel_max_concurrent_nodes_cap():
    GaugeOp.running = GaugeOp.peak = 0
    g = graph_of(
        *(GraphNode(f"n{i}", GaugeOp(f"n{i}", 0.02), {}) for i in range(6)),
        outputs=[f"n{i}" for i in range(6)],
    )
    asyncio.run(ParallelScheduler(g, max_concurrent_nodes=2).run({}))
    assert GaugeOp.peak <= 2, GaugeOp.peak


def test_parallel_unbounded_runs_all_at_once():
    GaugeOp.running = GaugeOp.peak = 0
    g = graph_of(
        *(GraphNode(f"n{i}", GaugeOp(f"n{i}", 0.03), {}) for i in range(5)),
        outputs=[f"n{i}" for i in range(5)],
    )
    asyncio.run(ParallelScheduler(g).run({}))
    assert GaugeOp.peak == 5


def test_parallel_dependency_ordering():
    """A strict chain on the parallel scheduler still executes in order."""
    log = []

    class PassThrough(SleepOp):
        async def compute(self, inputs, *, context):
            await super().compute(inputs, context=context)
            return inputs.get("x", 0) + 1

    g = graph_of(
        GraphNode("a", PassThrough("a", 0.01, log), {"x": GraphInput("seed")}),
        GraphNode("b", PassThrough("b", 0.01, log), {"x": "a"}),
        GraphNode("c", PassThrough("c", 0.01, log), {"x": "b"}),
        outputs=["c"],
    )
    out = asyncio.run(ParallelScheduler(g).run({"seed": 10}))
    assert out == {"c": 13}
    assert log == [
        ("start", "a"), ("end", "a"),
        ("start", "b"), ("end", "b"),
        ("start", "c"), ("end", "c"),
    ]


def test_parallel_wide_diamond_values():
    def make(fn_name, f):
        class Op(Operator):
            name = fn_name

            async def compute(self, inputs, *, context):
                return f(**inputs)

        return Op()

    g = graph_of(
        GraphNode("src", make("src", lambda x: x * 2), {"x": GraphInput("x")}),
        GraphNode("l", make("l", lambda v: v + 1), {"v": "src"}),
        GraphNode("r", make("r", lambda v: v + 2), {"v": "src"}),
        GraphNode("join", make("join", lambda a, b: (a, b)), {"a": "l", "b": "r"}),
        outputs=["join"],
    )
    assert asyncio.run(ParallelScheduler(g).run({"x": 5})) == {"join": (11, 12)}


def test_parallel_node_failure_propagates():
    g = graph_of(
        GraphNode("ok", SleepOp("ok", 0.01), {}),
        GraphNode("bad", FailOp(), {}),
        outputs=["ok", "bad"],
    )
    with pytest.raises(RuntimeError, match="node exploded"):
        asyncio.run(ParallelScheduler(g).run({}))


def test_parallel_failure_does_not_hang_downstream():
    """A consumer of a failed node must not deadlock the run."""
    g = graph_of(
        GraphNode("bad", FailOp(), {}),
        GraphNode("after", SleepOp("after", 0.01), {"x": "bad"}),
        outputs=["after"],
    )
    with pytest.raises(RuntimeError, match="node exploded"):
        asyncio.run(asyncio.wait_for(ParallelScheduler(g).run({}), timeout=5))


def test_parallel_missing_app_input_raises_keyerror():
    g = graph_of(
        GraphNode("a", SleepOp("a", 0.0), {"x": GraphInput("missing")}),
        outputs=["a"],
    )
    with pytest.raises(KeyError, match="missing"):
        asyncio.run(ParallelScheduler(g).run({}))


def test_parallel_unknown_string_source_raises():
    g = graph_of(
        GraphNode("a", SleepOp("a", 0.0), {"x": "nonexistent"}),
        outputs=["a"],
    )
    with pytest.raises(KeyError, match="nonexistent"):
        asyncio.run(ParallelScheduler(g).run({}))


def test_parallel_string_source_falls_back_to_inputs():
    """A string source that is not a node name resolves from the input
    mapping (how sessions feed cached upstream values)."""

    class Echo(Operator):
        name = "echo"

        async def compute(self, inputs, *, context):
            return inputs["x"]

    g = graph_of(GraphNode("a", Echo(), {"x": "warm"}), outputs=["a"])
    out = asyncio.run(ParallelScheduler(g).run({"warm": 42}))
    assert out == {"a": 42}


def test_parallel_message_source_rejected():
    g = graph_of(
        GraphNode("a", SleepOp("a", 0.0), {"x": MessageSource("gradient")}),
        outputs=["a"],
    )
    with pytest.raises(RuntimeError, match="MessageAware"):
        asyncio.run(ParallelScheduler(g).run({}))


def test_parallel_only_outputs_returned():
    g = graph_of(
        GraphNode("a", SleepOp("a", 0.0, result=1), {}),
        GraphNode("b", SleepOp("b", 0.0, result=2), {"x": "a"}),
        outputs=["b"],
    )
    assert asyncio.run(ParallelScheduler(g).run({})) == {"b": 2}


def test_parallel_shared_subtask_budget_across_operators():
    """max_pending_subtasks bounds in-flight subtasks ACROSS concurrently
    running operators via the shared semaphore."""
    state = {"running": 0, "peak": 0}

    class Fanner(Operator):
        supports_subtasks = True
        max_subtasks_inflight = 0  # per-op unbounded; shared budget only

        def __init__(self, name):
            self.name = name

        def create_subtasks(self, inputs, *, context):
            async def unit():
                state["running"] += 1
                state["peak"] = max(state["peak"], state["running"])
                await asyncio.sleep(0.01)
                state["running"] -= 1
                return 1

            for i in range(6):
                yield SubTask(fn=unit, name=f"{self.name}-{i}")

        def reduce_subtasks(self, partials, inputs, *, context):
            return sum(partials)

        async def compute(self, inputs, *, context):
            return 0

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            g = graph_of(
                GraphNode("f1", Fanner("f1"), {}),
                GraphNode("f2", Fanner("f2"), {}),
                outputs=["f1", "f2"],
            )
            return await ParallelScheduler(
                g, pool=pool, max_pending_subtasks=3
            ).run({})

    out = asyncio.run(main())
    assert out == {"f1": 6, "f2": 6}
    assert state["peak"] <= 3, state["peak"]


# ---------------------------------------------------------------------------
# Operator dispatch modes + windowed runner
# ---------------------------------------------------------------------------


class RecordingOp(Operator):
    """Operator that records which execution path ran."""

    supports_subtasks = True
    name = "recording"

    def __init__(self):
        self.paths = []

    async def compute(self, inputs, *, context):
        self.paths.append("compute")
        return "compute"

    def create_subtasks(self, inputs, *, context):
        self.paths.append("create")
        for i in range(3):
            yield SubTask(fn=lambda i=i: i, name=f"st{i}")

    def reduce_subtasks(self, partials, inputs, *, context):
        self.paths.append("reduce")
        return partials


def _run_op(op, pool=None):
    async def main():
        return await op.run({}, context=OpContext("n"), pool=pool)

    return asyncio.run(main())


def test_operator_plain_compute_without_pool():
    op = RecordingOp()
    assert _run_op(op) == "compute"
    assert op.paths == ["compute"]


def test_operator_subtasks_need_multiworker_pool():
    async def main():
        op = RecordingOp()
        async with ActorPool(ActorPoolConfig(backend="thread", count=1)) as pool:
            out = await op.run({}, context=OpContext("n"), pool=pool)
        return op.paths, out

    paths, out = asyncio.run(main())
    assert paths == ["compute"] and out == "compute"  # 1 worker -> no fan-out

    async def main2():
        op = RecordingOp()
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            out = await op.run({}, context=OpContext("n"), pool=pool)
        return op.paths, out

    paths, out = asyncio.run(main2())
    assert paths == ["create", "reduce"] and out == [0, 1, 2]


def test_operator_empty_subtasks_falls_back_to_compute():
    class EmptyFan(RecordingOp):
        def create_subtasks(self, inputs, *, context):
            self.paths.append("create")
            return []

    async def main():
        op = EmptyFan()
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            out = await op.run({}, context=OpContext("n"), pool=pool)
        return op.paths, out

    paths, out = asyncio.run(main())
    assert paths == ["create", "compute"] and out == "compute"


def test_windowed_results_in_submission_order():
    """Later-submitted subtasks may finish first; results must still come
    back in submission order."""

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=4)) as pool:
            async def unit(i):
                await asyncio.sleep(0.03 if i % 2 == 0 else 0.0)
                return i

            sts = [SubTask(fn=unit, args=(i,), name=f"s{i}") for i in range(8)]
            return await run_subtasks_windowed(pool, sts, limit=4)

    assert asyncio.run(main()) == list(range(8))


def test_windowed_limit_bounds_inflight():
    state = {"running": 0, "peak": 0}

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=8)) as pool:
            async def unit():
                state["running"] += 1
                state["peak"] = max(state["peak"], state["running"])
                await asyncio.sleep(0.01)
                state["running"] -= 1
                return 1

            sts = [SubTask(fn=unit, name=f"s{i}") for i in range(12)]
            return await run_subtasks_windowed(pool, sts, limit=3)

    assert sum(asyncio.run(main())) == 12
    assert state["peak"] <= 3, state["peak"]


def test_windowed_failure_cancels_and_releases_semaphore():
    """A failing subtask raises, and the shared semaphore is fully
    released so a following operator can still use its budget."""

    async def main():
        sem = asyncio.Semaphore(2)
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            async def boom():
                raise ValueError("subtask failed")

            sts = [SubTask(fn=boom, name=f"s{i}") for i in range(4)]
            with pytest.raises(ValueError, match="subtask failed"):
                await run_subtasks_windowed(pool, sts, limit=2, semaphore=sem)

            # budget fully restored: both permits immediately acquirable
            await asyncio.wait_for(sem.acquire(), 1)
            await asyncio.wait_for(sem.acquire(), 1)
            sem.release()
            sem.release()

            async def ok():
                return "fine"

            out = await run_subtasks_windowed(
                pool, [SubTask(fn=ok, name="ok")], limit=2, semaphore=sem
            )
            return out

    assert asyncio.run(main()) == ["fine"]


def test_windowed_subtask_retry_budget():
    attempts = {"n": 0}

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            def flaky():
                attempts["n"] += 1
                if attempts["n"] < 3:
                    raise OSError("transient")
                return "recovered"

            st = SubTask(fn=flaky, name="flaky", max_retries=2)
            return await run_subtasks_windowed(pool, [st], limit=1)

    assert asyncio.run(main()) == ["recovered"]
    assert attempts["n"] == 3


def test_operator_affinity_metadata_round_robin():
    """worker_affinities metadata assigns affinities round-robin to
    subtasks that lack one."""
    seen = []

    class AffOp(Operator):
        supports_subtasks = True
        name = "aff"

        def create_subtasks(self, inputs, *, context):
            for i in range(4):
                yield SubTask(fn=lambda i=i: i, name=f"s{i}")

        def reduce_subtasks(self, partials, inputs, *, context):
            return partials

        async def compute(self, inputs, *, context):
            return None

    class SpyPool:
        size = 2

        async def run_subtask(self, st):
            seen.append(st.affinity)
            return 0

    async def main():
        op = AffOp()
        ctx = OpContext("n", metadata={"worker_affinities": ["w0", "w1"]})
        return await op.run({}, context=ctx, pool=SpyPool())

    asyncio.run(main())
    assert seen == ["w0", "w1", "w0", "w1"]


# ---------------------------------------------------------------------------
# Message machinery
# ---------------------------------------------------------------------------


def _msg_graph(op):
    return graph_of(GraphNode("trigger", op, {}), outputs=["trigger"])


def test_message_trigger_returns_full_message():
    sched = MessageAwareNodeScheduler(_msg_graph(MessageTriggerOp("gradient")))

    async def main():
        await sched.deliver_message("gradient", {"vector": [1, 2], "round": 7})
        return await sched.run({})

    out = asyncio.run(main())
    assert out["trigger"] == {"vector": [1, 2], "round": 7}


def test_message_trigger_field_extraction():
    sched = MessageAwareNodeScheduler(
        _msg_graph(MessageTriggerOp("gradient", field="vector"))
    )

    async def main():
        await sched.deliver_message("gradient", {"vector": [3, 4]})
        return await sched.run({})

    assert asyncio.run(main())["trigger"] == [3, 4]


def test_message_trigger_timeout():
    sched = MessageAwareNodeScheduler(
        _msg_graph(MessageTriggerOp("never", timeout=0.05))
    )
    with pytest.raises(TimeoutError, match="never"):
        asyncio.run(sched.run({}))


def test_message_trigger_requires_message_aware_scheduler():
    sched = NodeScheduler(_msg_graph(MessageTriggerOp("gradient")))
    with pytest.raises(RuntimeError, match="wait_for_message"):
        asyncio.run(sched.run({}))


def test_wait_before_deliver_wakes_waiter():
    g = _msg_graph(MessageTriggerOp("late"))
    sched = MessageAwareNodeScheduler(g)

    async def main():
        run = asyncio.ensure_future(sched.run({}))
        await asyncio.sleep(0.02)  # run() is now parked on the waiter
        await sched.deliver_message("late", "payload")
        return await run

    assert asyncio.run(main())["trigger"] == "payload"


def test_multiple_waiters_fifo():
    sched = MessageAwareNodeScheduler(_msg_graph(MessageTriggerOp("t")))

    async def main():
        w1 = asyncio.ensure_future(sched.wait_for_message("t"))
        await asyncio.sleep(0)
        w2 = asyncio.ensure_future(sched.wait_for_message("t"))
        await asyncio.sleep(0)
        await sched.deliver_message("t", "first")
        await sched.deliver_message("t", "second")
        return await w1, await w2

    assert asyncio.run(main()) == ("first", "second")


def test_message_cache_bounded_drops_oldest():
    sched = MessageAwareNodeScheduler(
        _msg_graph(MessageTriggerOp("t")), max_cached_per_type=3
    )

    async def main():
        for i in range(5):
            await sched.deliver_message("t", i)
        assert sched.pending_message_count("t") == 3
        return [await sched.wait_for_message("t") for _ in range(3)]

    assert asyncio.run(main()) == [2, 3, 4]  # 0 and 1 dropped


def test_message_source_graph_input():
    class Echo(Operator):
        name = "echo"

        async def compute(self, inputs, *, context):
            return inputs["v"]

    g = graph_of(
        GraphNode("n", Echo(), {"v": MessageSource("grad", field="x")}),
        outputs=["n"],
    )
    sched = MessageAwareNodeScheduler(g)

    async def main():
        await sched.deliver_message("grad", {"x": 99})
        return await sched.run({})

    assert asyncio.run(main())["n"] == 99


def test_swap_graph_reuses_inbox():
    """Swapping graphs preserves cached messages (decentralized nodes swap
    per-pipeline graphs into one scheduler)."""
    sched = MessageAwareNodeScheduler(_msg_graph(MessageTriggerOp("a")))

    async def main():
        await sched.deliver_message("b", "kept")
        sched.swap_graph(_msg_graph(MessageTriggerOp("b")))
        return await sched.run({})

    assert asyncio.run(main())["trigger"] == "kept"

"""Deep L2 coverage: ExecutionSession/ExecutionFuture, ActorPool
acquisition/affinity/channels, OperatorExecutor, and the lazy builder.

Mirrors the intent of the reference suites
``engine/graph/tests/test_session.py`` (cache pruning, futures,
cancellation), ``test_pool.py`` (affinity under contention, rotation,
waiters), ``test_executor.py`` / ``test_run_operator.py`` and
``test_lazy.py``.
"""

import asyncio

import pytest

from byzpy_tpu.engine.graph import (
    ActorPool,
    ActorPoolConfig,
    ComputationGraph,
    GraphBuilder,
    GraphInput,
    GraphNode,
)
from byzpy_tpu.engine.graph.executor import OperatorExecutor, run_operator
from byzpy_tpu.engine.graph.operator import OpContext, Operator
from byzpy_tpu.engine.graph.ops import CallableOp, RemoteCallableOp
from byzpy_tpu.engine.graph.session import ExecutionSession
from byzpy_tpu.engine.graph.subtask import SubTask


class CountingOp(Operator):
    """Counts compute() invocations — the probe for cache behavior."""

    def __init__(self, name, fn=None):
        self.name = name
        self.calls = 0
        self.fn = fn or (lambda **kw: name)

    async def compute(self, inputs, *, context):
        self.calls += 1
        return self.fn(**inputs)


def chain_graph(a, b):
    return ComputationGraph(
        [
            GraphNode("a", a, {"x": GraphInput("x")}),
            GraphNode("b", b, {"x": "a"}),
        ],
        outputs=["b"],
    )


# ---------------------------------------------------------------------------
# ExecutionSession
# ---------------------------------------------------------------------------


def test_session_skips_cached_nodes_on_rerun():
    a = CountingOp("a", lambda x: x + 1)
    b = CountingOp("b", lambda x: x * 10)
    g = chain_graph(a, b)
    s = ExecutionSession()

    async def main():
        r1 = await s.execute(g, {"x": 1})
        r2 = await s.execute(g, {"x": 999})  # fully cached: input ignored
        return r1, r2

    r1, r2 = asyncio.run(main())
    assert r1 == {"b": 20} and r2 == {"b": 20}
    assert a.calls == 1 and b.calls == 1
    assert set(s.cached_nodes) == {"a", "b"}


def test_session_partial_invalidate_reruns_only_downstream_consumer():
    a = CountingOp("a", lambda x: x + 1)
    b = CountingOp("b", lambda x: x * 10)
    g = chain_graph(a, b)
    s = ExecutionSession()

    async def main():
        await s.execute(g, {"x": 1})
        s.invalidate(["b"])
        return await s.execute(g, {"x": 1})

    out = asyncio.run(main())
    assert out == {"b": 20}
    assert a.calls == 1  # cached upstream fed the re-run
    assert b.calls == 2


def test_session_full_invalidate_and_use_cache_false():
    a = CountingOp("a", lambda x: x + 1)
    b = CountingOp("b", lambda x: x * 10)
    g = chain_graph(a, b)
    s = ExecutionSession()

    async def main():
        await s.execute(g, {"x": 1})
        s.invalidate()
        await s.execute(g, {"x": 2})
        await s.execute(g, {"x": 3}, use_cache=False)

    asyncio.run(main())
    assert a.calls == 3 and b.calls == 3


def test_session_seed_feeds_downstream():
    a = CountingOp("a", lambda x: x + 1)
    b = CountingOp("b", lambda x: x * 10)
    g = chain_graph(a, b)
    s = ExecutionSession()
    s.seed("a", 7)

    async def main():
        return await s.execute(g, {"x": 1})

    assert asyncio.run(main()) == {"b": 70}
    assert a.calls == 0 and b.calls == 1


def test_session_cache_shared_across_graphs():
    """A node cached from one graph serves a different graph that contains
    a node of the same name."""
    a = CountingOp("a", lambda x: x + 1)
    s = ExecutionSession()
    g1 = ComputationGraph([GraphNode("a", a, {"x": GraphInput("x")})])

    b = CountingOp("b", lambda x: -x)
    g2 = chain_graph(CountingOp("unused"), b)  # has its own "a" node

    async def main():
        await s.execute(g1, {"x": 4})
        return await s.execute(g2, {"x": 0})

    assert asyncio.run(main()) == {"b": -5}
    assert a.calls == 1


def test_future_done_wait_result():
    a = CountingOp("a", lambda x: x + 1)
    g = ComputationGraph([GraphNode("a", a, {"x": GraphInput("x")})])
    s = ExecutionSession()

    async def main():
        fut = s.execute_async(g, {"x": 1})
        assert not fut.done()
        assert await fut.wait(timeout=5)
        assert fut.done()
        return await fut.result()

    assert asyncio.run(main()) == {"a": 2}


def test_future_wait_timeout_returns_false():
    async def slow_fn(**kw):
        await asyncio.sleep(0.2)
        return 1

    g = ComputationGraph([GraphNode("slow", CallableOp(slow_fn, name="slow"), {})])
    s = ExecutionSession()

    async def main():
        fut = s.execute_async(g)
        early = await fut.wait(timeout=0.01)
        late = await fut.wait(timeout=5)
        return early, late

    assert asyncio.run(main()) == (False, True)


def test_future_cancel():
    async def never(**kw):
        await asyncio.sleep(30)

    g = ComputationGraph([GraphNode("n", CallableOp(never, name="never"), {})])
    s = ExecutionSession()

    async def main():
        fut = s.execute_async(g)
        await asyncio.sleep(0.01)
        assert fut.cancel()
        assert await fut.wait(timeout=5)
        with pytest.raises(asyncio.CancelledError):
            await fut.result()

    asyncio.run(main())


def test_future_failure_surfaced_by_result_not_wait():
    def boom(**kw):
        raise ValueError("graph failed")

    g = ComputationGraph([GraphNode("n", CallableOp(boom, name="boom"), {})])
    s = ExecutionSession()

    async def main():
        fut = s.execute_async(g)
        assert await fut.wait(timeout=5)  # wait() swallows the failure
        with pytest.raises(ValueError, match="graph failed"):
            await fut.result()

    asyncio.run(main())


# ---------------------------------------------------------------------------
# ActorPool
# ---------------------------------------------------------------------------


def test_pool_affinity_under_contention_routes_to_capable_worker():
    """With one 'fast' worker and several plain ones, fast-affinity
    subtasks must only ever run on the fast worker even under load."""
    ran_on = []

    async def main():
        cfgs = [
            ActorPoolConfig(backend="thread", count=1, capabilities=["cpu", "fast"], name="fastw"),
            ActorPoolConfig(backend="thread", count=3, name="slow"),
        ]
        async with ActorPool(cfgs) as pool:
            # discover which worker runs each subtask via a name probe the
            # subtask fn receives through kwargs
            async def unit(tag):
                await asyncio.sleep(0.005)
                return tag

            sts = [
                SubTask(fn=unit, args=(i,), name=f"s{i}", affinity="fast")
                for i in range(6)
            ]
            # run alongside background load with no affinity
            bg = [SubTask(fn=unit, args=(100 + i,), name=f"bg{i}") for i in range(6)]
            results = await asyncio.gather(
                *(pool.run_subtask(st) for st in sts + bg)
            )
            caps = pool.worker_capabilities
            fast_workers = [n for n, c in caps.items() if "fast" in c]
            return results, fast_workers

    results, fast_workers = asyncio.run(main())
    assert sorted(results) == [0, 1, 2, 3, 4, 5, 100, 101, 102, 103, 104, 105]
    assert len(fast_workers) == 1


def test_pool_unsatisfiable_affinity_falls_back_to_any_worker():
    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            st = SubTask(fn=lambda: "done", name="s", affinity="gpu")
            return await pool.run_subtask(st)

    assert asyncio.run(main()) == "done"


def test_pool_acquire_blocks_until_release():
    """With one worker, a second subtask must wait for the first."""
    order = []

    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=1)) as pool:
            async def unit(tag, delay):
                order.append(("start", tag))
                await asyncio.sleep(delay)
                order.append(("end", tag))
                return tag

            t1 = asyncio.ensure_future(
                pool.run_subtask(SubTask(fn=unit, args=("a", 0.05), name="a"))
            )
            await asyncio.sleep(0.01)
            t2 = asyncio.ensure_future(
                pool.run_subtask(SubTask(fn=unit, args=("b", 0.0), name="b"))
            )
            await asyncio.gather(t1, t2)

    asyncio.run(main())
    assert order == [("start", "a"), ("end", "a"), ("start", "b"), ("end", "b")]


def test_pool_not_started_raises():
    pool = ActorPool(ActorPoolConfig(backend="thread", count=1))

    async def main():
        await pool.run_subtask(SubTask(fn=lambda: 1, name="s"))

    with pytest.raises(RuntimeError, match="not started"):
        asyncio.run(main())


def test_pool_close_cancels_pending_waiters():
    """A subtask parked on the waiter queue is cancelled (not left hanging)
    when the pool closes while every worker is held."""

    async def main():
        pool = ActorPool(ActorPoolConfig(backend="thread", count=1))
        await pool.start()
        held = await pool._acquire(None)  # pin the only worker
        assert held is not None
        waiter = asyncio.ensure_future(
            pool.run_subtask(SubTask(fn=lambda: 2, name="waiting"))
        )
        await asyncio.sleep(0.01)  # waiter is now queued
        await pool.close()
        with pytest.raises(asyncio.CancelledError):
            await waiter

    asyncio.run(main())


def test_pool_run_many_and_channel_broadcast():
    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=3)) as pool:
            outs = await pool.run_many(
                [SubTask(fn=lambda i=i: i * i, name=f"s{i}") for i in range(5)]
            )
            chan = await pool.open_channel("gossip")
            await chan.broadcast(None, {"round": 1})
            received = [await chan.recv(n) for n in pool.worker_names]
            return outs, received

    outs, received = asyncio.run(main())
    assert outs == [0, 1, 4, 9, 16]
    assert all(m["payload"] == {"round": 1} for m in received)


def test_pool_worker_lookup_and_capabilities():
    async def main():
        cfgs = [
            ActorPoolConfig(backend="thread", count=1, name="named"),
        ]
        async with ActorPool(cfgs) as pool:
            name = pool.worker_names[0]
            assert pool.worker(name) is not None
            assert pool.has_capability("cpu")
            assert not pool.has_capability("tpu")
            with pytest.raises(KeyError):
                pool.worker("nope")

    asyncio.run(main())


# ---------------------------------------------------------------------------
# OperatorExecutor / run_operator
# ---------------------------------------------------------------------------


class KeyedOp(Operator):
    input_key = "things"
    name = "keyed"

    async def compute(self, inputs, *, context):
        return sum(inputs["things"])


def test_executor_bare_value_uses_input_key():
    assert asyncio.run(run_operator(KeyedOp(), [1, 2, 3])) == 6


def test_executor_mapping_passthrough():
    assert asyncio.run(run_operator(KeyedOp(), {"things": [4, 5]})) == 9


def test_executor_bare_value_without_key_raises():
    class NoKey(Operator):
        name = "nokey"

        async def compute(self, inputs, *, context):
            return 0

    with pytest.raises(ValueError, match="no input_key"):
        asyncio.run(run_operator(NoKey(), [1]))


def test_executor_explicit_input_key_override():
    class Wants(Operator):
        name = "wants"

        async def compute(self, inputs, *, context):
            return inputs["custom"]

    assert asyncio.run(run_operator(Wants(), "v", input_key="custom")) == "v"


def test_executor_owns_pool_lifecycle():
    async def main():
        ex = OperatorExecutor(
            KeyedOp(), pool_config=ActorPoolConfig(backend="thread", count=2)
        )
        out = await ex.run([1, 2])
        pool = ex._pool
        assert pool is not None and pool._started
        await ex.close()
        return out, pool._started

    out, started_after = asyncio.run(main())
    assert out == 3 and started_after is False


def test_executor_borrowed_pool_not_closed():
    async def main():
        async with ActorPool(ActorPoolConfig(backend="thread", count=2)) as pool:
            ex = OperatorExecutor(KeyedOp(), pool=pool)
            await ex.run([1, 2])
            await ex.close()
            return pool._started

    assert asyncio.run(main()) is True


def test_remote_callable_op_inline_without_pool():
    op = RemoteCallableOp(lambda x: x * 2, name="dbl")
    assert asyncio.run(run_operator(op, {"x": 21})) == 42


# ---------------------------------------------------------------------------
# Lazy builder
# ---------------------------------------------------------------------------


def test_lazy_builder_unique_names_and_explicit_name():
    b = GraphBuilder()
    src = b.input("xs")
    n1 = src.apply(CallableOp(lambda xs: sum(xs), name="agg"), input_key="xs")
    n2 = n1.apply(CallableOp(lambda v: v + 1, name="agg"), input_key="v")
    n3 = n2.apply(CallableOp(lambda v: v * 2, name="final"), input_key="v", name="out")
    g = b.build(n3)
    assert n3.source == "out"
    assert len(set(g.nodes)) == 3

    async def main():
        from byzpy_tpu.engine.graph.scheduler import NodeScheduler

        return await NodeScheduler(g).run({"xs": [1, 2, 3]})

    assert asyncio.run(main()) == {"out": 14}


def test_lazy_builder_multi_output():
    b = GraphBuilder()
    src = b.input("x")
    left = src.apply(CallableOp(lambda x: x + 1, name="l"), input_key="x")
    right = src.apply(CallableOp(lambda x: x - 1, name="r"), input_key="x")
    g = b.build([left, right])
    assert set(g.outputs) == {"l", "r"}


def test_lazy_builder_extra_inputs_lazynode_and_graphinput():
    b = GraphBuilder()
    x = b.input("x")
    base = x.apply(CallableOp(lambda x: x * 2, name="base"), input_key="x")
    join = base.apply(
        CallableOp(lambda v, other, k: (v, other, k), name="join"),
        input_key="v",
        extra_inputs={"other": x.apply(CallableOp(lambda x: -x, name="neg"), input_key="x"),
                      "k": b.input("x").source},
    )
    g = b.build(join)

    async def main():
        from byzpy_tpu.engine.graph.scheduler import NodeScheduler

        return await NodeScheduler(g).run({"x": 3})

    assert asyncio.run(main())["join"] == (6, -3, 3)


def test_lazy_builder_raw_input_output_rejected():
    b = GraphBuilder()
    x = b.input("x")
    x.apply(CallableOp(lambda x: x, name="id"), input_key="x")
    with pytest.raises(ValueError, match="raw inputs"):
        b.build(x)


def test_lazy_builder_empty_rejected():
    with pytest.raises(ValueError, match="nothing to build"):
        GraphBuilder().build()


def test_lazy_builder_missing_input_key_rejected():
    class NoKey(Operator):
        name = "nokey"

        async def compute(self, inputs, *, context):
            return 0

    b = GraphBuilder()
    with pytest.raises(ValueError, match="input_key"):
        b.input("x").apply(NoKey())

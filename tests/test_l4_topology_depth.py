"""Deep L4 coverage: topology-constrained messaging, replies, multicast,
failure tolerance, message-driven training rounds, autonomous nodes.

Mirrors the intent of the reference's
``engine/node/tests/test_topology_integration.py`` (949 LoC): whole
decentralized clusters inside one event loop via InProcessContext.
"""

import asyncio

import numpy as np
import pytest

from byzpy_tpu.engine.graph.graph import ComputationGraph, GraphInput, GraphNode
from byzpy_tpu.engine.graph.ops import CallableOp
from byzpy_tpu.engine.graph.scheduler import MessageSource
from byzpy_tpu.engine.peer_to_peer import Topology

# cluster construction + registry cleanup come from conftest fixtures
# (make_cluster / _clear_node_registries), shared with test_node_layer


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------


def test_ring_message_travels_the_cycle(make_cluster):
    """A token forwarded by each node's handler must traverse the full
    ring back to the origin in neighbor order."""

    async def main():
        n = 5
        cluster = make_cluster(n, Topology.ring(n, 1))
        path = []
        done = asyncio.Event()

        async with cluster:
            for nid, node in cluster.nodes.items():
                async def handler(msg, node=node, nid=nid):
                    path.append(nid)
                    if msg.payload["origin"] == nid:
                        done.set()
                        return
                    await node.broadcast_message("token", msg.payload)

                node.register_handler("token", handler)

            await cluster.node("node-0").broadcast_message(
                "token", {"origin": "node-0"}
            )
            await asyncio.wait_for(done.wait(), 5)
        return path

    path = run(main())
    assert path == ["node-1", "node-2", "node-3", "node-4", "node-0"]


def test_ring_k2_reaches_two_neighbors(make_cluster):
    async def main():
        n = 5
        cluster = make_cluster(n, Topology.ring(n, 2))
        got = []
        async with cluster:
            for nid, node in cluster.nodes.items():
                async def handler(msg, nid=nid):
                    got.append(nid)

                node.register_handler("ping", handler)
            await cluster.node("node-0").broadcast_message("ping", None)
            await asyncio.sleep(0.05)
        return sorted(got)

    assert run(main()) == ["node-1", "node-2"]


# ---------------------------------------------------------------------------
# direct / reply / multicast routing
# ---------------------------------------------------------------------------


def test_reply_ignores_topology_direction(make_cluster):
    """Replies route back along the reverse edge even when the forward
    topology forbids it (ref router reply semantics)."""

    async def main():
        # edges only 0 -> 1: node-1 cannot SEND to node-0, but may REPLY
        topo = Topology.from_edges(2, [(0, 1)])
        cluster = make_cluster(2, topo)
        answered = asyncio.Event()
        answer = {}

        illegal_send_error = {}

        async with cluster:
            n0, n1 = cluster.node("node-0"), cluster.node("node-1")

            async def on_ask(msg, node=n1):
                # record instead of pytest.raises: handler exceptions are
                # swallowed by handle_incoming_message, which would turn a
                # failed assertion into an opaque 5s timeout
                try:
                    await node.send_message("node-0", "ask", "illegal")
                    illegal_send_error["exc"] = None
                except ValueError as exc:
                    illegal_send_error["exc"] = exc
                await node.reply_message(msg.sender, "ans", msg.payload * 2)

            async def on_ans(msg):
                answer["v"] = msg.payload
                answered.set()

            n1.register_handler("ask", on_ask)
            n0.register_handler("ans", on_ans)
            await n0.send_message("node-1", "ask", 21)
            await asyncio.wait_for(answered.wait(), 5)
        return answer["v"], illegal_send_error["exc"]

    value, illegal_exc = run(main())
    assert value == 42
    assert isinstance(illegal_exc, ValueError)  # forward edge 1->0 forbidden


def test_multicast_subset_only(make_cluster):
    async def main():
        cluster = make_cluster(5)
        got = []
        async with cluster:
            for nid, node in cluster.nodes.items():
                async def handler(msg, nid=nid):
                    got.append(nid)

                node.register_handler("m", handler)
            await cluster.node("node-0").multicast_message(
                ["node-2", "node-4"], "m", None
            )
            await asyncio.sleep(0.05)
        return sorted(got)

    assert run(main()) == ["node-2", "node-4"]


def test_broadcast_tolerates_dead_neighbor(make_cluster):
    """A shut-down neighbor must not break delivery to the rest
    (ref router.py:155-186 failure tolerance)."""

    async def main():
        cluster = make_cluster(4)
        got = []
        async with cluster:
            for nid, node in cluster.nodes.items():
                async def handler(msg, nid=nid):
                    got.append(nid)

                node.register_handler("g", handler)
            await cluster.node("node-2").shutdown()
            delivered = await cluster.node("node-0").broadcast_message("g", 1)
            await asyncio.sleep(0.05)
            return sorted(got), delivered

    got, delivered = run(main())
    assert got == ["node-1", "node-3"]
    assert sorted(delivered) == ["node-1", "node-3"]  # reached-ids contract


# ---------------------------------------------------------------------------
# message-driven pipelines (mini decentralized training round)
# ---------------------------------------------------------------------------


def _avg_pipeline():
    """own vector + one received gradient message -> average. The message
    input resolves to the full Message envelope; the op unwraps payload."""

    def combine(own, received):
        return (np.asarray(own) + np.asarray(received.payload["vector"])) / 2

    return ComputationGraph([
        GraphNode(
            "combine",
            CallableOp(combine, name="combine"),
            {"own": GraphInput("own"),
             "received": MessageSource("gradient")},
        )
    ])


def test_pipeline_blocks_on_message_then_combines(make_cluster):
    async def main():
        cluster = make_cluster(2)
        async with cluster:
            a, b = cluster.node("node-0"), cluster.node("node-1")
            a.register_pipeline("avg", _avg_pipeline())

            run_task = asyncio.ensure_future(
                a.execute_pipeline("avg", {"own": [2.0, 4.0]})
            )
            await asyncio.sleep(0.05)
            assert not run_task.done()  # parked on the gradient message
            await b.send_message("node-0", "gradient", {"vector": [4.0, 8.0]})
            out = await asyncio.wait_for(run_task, 5)
            return out["combine"]

    np.testing.assert_allclose(run(main()), [3.0, 6.0])


def test_decentralized_average_round_converges(make_cluster):
    """One gossip round of pairwise averaging on a complete graph moves
    every node's value toward the global mean."""

    async def main():
        n = 4
        values = {f"node-{i}": float(i) for i in range(n)}
        cluster = make_cluster(n)
        async with cluster:
            # every node caches received values via a handler
            received = {nid: [] for nid in values}
            for nid, node in cluster.nodes.items():
                async def handler(msg, nid=nid):
                    received[nid].append(msg.payload)

                node.register_handler("value", handler)
            # broadcast, then each node averages what it saw
            for nid, node in cluster.nodes.items():
                await node.broadcast_message("value", values[nid])
            await asyncio.sleep(0.1)
            new = {
                nid: (values[nid] + sum(received[nid])) / (1 + len(received[nid]))
                for nid in values
            }
            return new

    new = run(main())
    for v in new.values():
        assert v == pytest.approx(1.5)  # global mean of 0..3


def test_autonomous_rounds_counter(make_cluster):
    """start_autonomous_task drives rounds without external ticks and
    stops cleanly at shutdown."""

    async def main():
        cluster = make_cluster(2)
        counts = {"node-0": 0, "node-1": 0}
        async with cluster:
            for nid, node in cluster.nodes.items():
                async def round_loop(node, nid=nid):
                    while True:
                        counts[nid] += 1
                        await asyncio.sleep(0.01)

                node.start_autonomous_task(round_loop)
            await asyncio.sleep(0.2)
        return dict(counts)

    counts = run(main())
    assert all(c >= 3 for c in counts.values()), counts


def test_concurrent_pipelines_share_one_scheduler(make_cluster):
    """Two in-flight executions of different pipelines on one node must
    not corrupt each other (the node swaps graphs per execution)."""

    async def main():
        cluster = make_cluster(1, Topology.complete(1))
        node = cluster.node("node-0")

        async def slow(x):
            await asyncio.sleep(0.05)
            return x * 10

        node.register_pipeline("slow", ComputationGraph([
            GraphNode("out", CallableOp(slow, name="slow"), {"x": GraphInput("x")})
        ]))
        node.register_pipeline("fast", ComputationGraph([
            GraphNode("out", CallableOp(lambda x: x + 1, name="fast"),
                      {"x": GraphInput("x")})
        ]))
        async with cluster:
            t1 = asyncio.ensure_future(node.execute_pipeline("slow", {"x": 3}))
            t2 = asyncio.ensure_future(node.execute_pipeline("fast", {"x": 3}))
            r1, r2 = await asyncio.gather(t1, t2)
            return r1["out"], r2["out"]

    assert run(main()) == (30, 4)

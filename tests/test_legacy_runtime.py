"""Prototype-lineage runtime: mailbox transports + step runners.

Parity targets: ``byzpy/engine/transport/`` (local + tcp_simple mailboxes),
``byzpy/engine/node_runner.py`` (process step loop), ``node_cluster.py``,
``engine/parameter_server/runner.py`` (prototype PS) — exercised the way
the reference's ``engine/tests`` do (loopback sockets, real subprocesses).
"""

import queue

import numpy as np
import pytest

from byzpy_tpu.engine.legacy import (
    LocalMailbox,
    NodeCluster,
    NodeRunner,
    StepParameterServer,
    TcpMailbox,
)


@pytest.fixture(autouse=True)
def clean_local_registry():
    LocalMailbox.clear_registry()
    yield
    LocalMailbox.clear_registry()


def test_local_mailbox_roundtrip():
    a, b = LocalMailbox("a"), LocalMailbox("b")
    a.send("b", {"v": 1})
    sender, payload = b.recv(timeout=1)
    assert sender == "a" and payload == {"v": 1}
    with pytest.raises(ConnectionError):
        a.send("ghost", None)
    with pytest.raises(queue.Empty):
        a.recv(timeout=0.05)
    b.close()
    a.close()


def test_tcp_mailbox_loopback():
    a = TcpMailbox("a")
    b = TcpMailbox("b")
    a.add_peer("b", (b.host, b.port))
    b.add_peer("a", (a.host, a.port))
    try:
        a.send("b", np.arange(4))
        sender, payload = b.recv(timeout=5)
        assert sender == "a"
        np.testing.assert_array_equal(payload, np.arange(4))
        b.send("a", "pong")
        assert a.recv(timeout=5) == ("b", "pong")
    finally:
        a.close()
        b.close()


class CountNode:
    """Step-protocol node: step() returns a gradient toward `target`."""

    def __init__(self, target):
        self.target = float(target)
        self.w = 0.0
        self.messages = []

    def step(self, payload=None):
        return 2.0 * (self.w - self.target)

    def apply_update(self, update):
        self.w -= 0.25 * update

    def get_w(self):
        return self.w

    def handle_message(self, message):
        self.messages.append(message)

    def message_count(self):
        return len(self.messages)


def test_node_runner_step_call_deliver():
    runner = NodeRunner(lambda: CountNode(2.0))
    runner.start()
    try:
        g = runner.step()
        assert g == -4.0
        runner.call("apply_update", g)
        assert runner.call("get_w") == 1.0
        runner.deliver({"hello": 1})
        for _ in range(100):
            if runner.call("message_count") == 1:
                break
        assert runner.call("message_count") == 1
        with pytest.raises(RuntimeError):
            runner.call("missing_method")
    finally:
        runner.stop()
    with pytest.raises(ConnectionError):
        runner.step()


def test_step_parameter_server_round():
    cluster = NodeCluster()
    for i, t in enumerate((1.0, 1.0, 4.0)):
        cluster.add(f"n{i}", NodeRunner(lambda t=t: CountNode(t)))
    with cluster:
        ps = StepParameterServer(
            cluster, lambda grads: float(np.median(grads))
        )
        for _ in range(25):
            ps.round()
        ws = [cluster.runner(n).call("get_w") for n in cluster.names]
    # median aggregation drives every node to the majority target
    np.testing.assert_allclose(ws, 1.0, atol=0.05)
    assert ps.rounds_completed == 25

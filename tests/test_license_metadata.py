"""Declared license metadata must match the committed LICENSE text.

ADVICE r5 flagged an Apache-2.0/MIT flip across rounds; this pins the
two sources of truth together so a future edit to either one fails
loudly instead of shipping contradictory licensing."""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: canonical first-line fingerprints of the license texts we could ship
_FINGERPRINTS = {
    "Apache-2.0": "Apache License",
    "MIT": "MIT License",
    "BSD-3-Clause": "BSD 3-Clause License",
}


def _declared_license() -> str:
    with open(os.path.join(REPO, "pyproject.toml")) as fh:
        text = fh.read()
    # tomllib only exists on >=3.11 and the floor is 3.10: the license
    # line is simple enough to pin textually
    m = re.search(r'^license\s*=\s*\{\s*text\s*=\s*"([^"]+)"', text, re.M)
    if m is None:
        m = re.search(r'^license\s*=\s*"([^"]+)"', text, re.M)
    assert m is not None, "pyproject.toml declares no license"
    return m.group(1)


def test_pyproject_license_matches_license_file():
    declared = _declared_license()
    assert declared in _FINGERPRINTS, (
        f"unrecognized declared license {declared!r} — extend the "
        f"fingerprint table if this is intentional"
    )
    with open(os.path.join(REPO, "LICENSE")) as fh:
        head = fh.read(2048)
    assert _FINGERPRINTS[declared] in head, (
        f"pyproject.toml declares {declared} but LICENSE does not open "
        f"with {_FINGERPRINTS[declared]!r}"
    )
    # and no OTHER known license text is what's actually committed
    for spdx, fingerprint in _FINGERPRINTS.items():
        if spdx != declared:
            assert fingerprint not in head, (
                f"LICENSE looks like {spdx} but pyproject declares {declared}"
            )

"""Heartbeat failure detection over the decentralized message fabric."""

import asyncio

import pytest

from byzpy_tpu.engine.node import HeartbeatMonitor
from byzpy_tpu.engine.peer_to_peer import Topology


async def _wait_until(pred, timeout=6.0, step=0.05):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(step)
    return False


def test_heartbeat_all_alive(make_cluster):
    async def run():
        cluster = make_cluster(3)
        await cluster.start_all()
        monitors = [
            HeartbeatMonitor(node, interval=0.05, max_missed=3)
            for node in cluster.nodes.values()
        ]
        try:
            for m in monitors:
                await m.start()
            ok = await _wait_until(
                lambda: all(len(m.alive()) == 2 for m in monitors)
            )
            assert ok, [m.alive() for m in monitors]
            assert all(m.suspects() == [] for m in monitors)
        finally:
            for m in monitors:
                await m.stop()
            await cluster.shutdown_all()

    asyncio.run(run())


def test_heartbeat_detects_dead_peer_and_recovery(make_cluster):
    async def run():
        cluster = make_cluster(3, topology=Topology.complete(3))
        await cluster.start_all()
        nodes = list(cluster.nodes.values())
        observer = nodes[0]
        victim = nodes[2]
        events = []
        for passive in nodes[1:]:
            HeartbeatMonitor.install_responder(passive)
        mon = HeartbeatMonitor(
            observer, interval=0.05, max_missed=3,
            on_suspect=lambda p: events.append(("suspect", p)),
            on_recover=lambda p: events.append(("recover", p)),
        )
        await mon.start()
        try:
            ok = await _wait_until(lambda: len(mon.alive()) == 2)
            assert ok, mon.alive()

            # kill the victim: its context leaves the in-process registry,
            # so pings go undelivered from now on
            await victim.shutdown()
            ok = await _wait_until(lambda: victim.node_id in mon.suspects())
            assert ok, (mon.suspects(), mon.peers)
            assert ("suspect", victim.node_id) in events
            # exactly one suspect transition (no flapping)
            assert events.count(("suspect", victim.node_id)) == 1
            assert nodes[1].node_id not in mon.suspects()
        finally:
            await mon.stop()
            await cluster.shutdown_all()

    asyncio.run(run())


def test_heartbeat_rejects_bad_config(make_cluster):
    async def run():
        cluster = make_cluster(2)
        await cluster.start_all()
        node = next(iter(cluster.nodes.values()))
        try:
            with pytest.raises(ValueError, match="max_missed"):
                HeartbeatMonitor(node, max_missed=0)
        finally:
            await cluster.shutdown_all()

    asyncio.run(run())


def test_suspect_callback_drives_topology_rebind(make_cluster):
    """The intended policy loop: a suspect transition shrinks the live
    nodes' topology, after which broadcasts no longer target the dead
    peer and keep flowing among survivors."""
    async def run():
        cluster = make_cluster(4, topology=Topology.complete(4))
        await cluster.start_all()
        nodes = list(cluster.nodes.values())
        observer, victim = nodes[0], nodes[3]
        survivors = nodes[:3]
        for passive in nodes[1:]:
            HeartbeatMonitor.install_responder(passive)

        rebound = asyncio.Event()
        suspected = []

        def on_suspect(peer):
            # record, don't assert: _fire swallows callback exceptions, so
            # an in-callback assert would surface only as a timeout
            suspected.append(peer)
            ids = {i: n.node_id for i, n in enumerate(survivors)}
            topo = Topology.complete(3)
            for n in survivors:
                n.bind_topology(topo, ids)
            rebound.set()

        mon = HeartbeatMonitor(
            observer, interval=0.05, max_missed=3, on_suspect=on_suspect
        )
        await mon.start()
        try:
            ok = await _wait_until(lambda: len(mon.alive()) == 3)
            assert ok, mon.alive()
            await victim.shutdown()
            ok = await _wait_until(rebound.is_set)
            assert ok, "suspect callback never fired"
            assert suspected == [victim.node_id], suspected

            got = []

            async def collect(m):
                got.append(m.payload)

            survivors[1].register_handler("payload", collect)
            delivered = await observer.broadcast_message("payload", 42)
            assert victim.node_id not in delivered
            ok = await _wait_until(lambda: got == [42])
            assert ok, got
        finally:
            await mon.stop()
            await cluster.shutdown_all()

    asyncio.run(run())


def test_startup_grace_shields_never_ponged_peer(make_cluster):
    """A peer that has never answered (e.g. a subprocess still importing
    jax) is not suspected inside startup_grace; one that HAS answered is
    still caught at max_missed * interval after it dies."""
    async def run():
        cluster = make_cluster(3)
        await cluster.start_all()
        nodes = list(cluster.nodes.values())
        observer, mute, responsive = nodes
        # 'mute' never responds: strip its ping handler after install by
        # monitoring from observer only — responders are installed by the
        # monitor on its own node; the others have none yet, so only
        # 'responsive' gets one explicitly.
        HeartbeatMonitor.install_responder(responsive)
        mon = HeartbeatMonitor(
            observer, interval=0.05, max_missed=3, startup_grace=2.0
        )
        await mon.start()
        try:
            ok = await _wait_until(lambda: responsive.node_id in mon.alive())
            assert ok, mon.alive()
            # well past max_missed * interval, still inside the grace:
            # the never-ponged peer is NOT suspect
            await asyncio.sleep(0.5)
            assert mon.suspects() == [], mon.suspects()
            # after the grace expires it is suspected like any dead peer
            ok = await _wait_until(lambda: mon.suspects() == [mute.node_id])
            assert ok, mon.suspects()
        finally:
            await mon.stop()
            await cluster.shutdown_all()

    asyncio.run(run())

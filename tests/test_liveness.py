"""Heartbeat failure detection over the decentralized message fabric."""

import asyncio

import pytest

from byzpy_tpu.engine.node import HeartbeatMonitor
from byzpy_tpu.engine.peer_to_peer import Topology


async def _wait_until(pred, timeout=6.0, step=0.05):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(step)
    return False


def test_heartbeat_all_alive(make_cluster):
    async def run():
        cluster = make_cluster(3)
        await cluster.start_all()
        monitors = [
            HeartbeatMonitor(node, interval=0.05, max_missed=3)
            for node in cluster.nodes.values()
        ]
        try:
            for m in monitors:
                await m.start()
            ok = await _wait_until(
                lambda: all(len(m.alive()) == 2 for m in monitors)
            )
            assert ok, [m.alive() for m in monitors]
            assert all(m.suspects() == [] for m in monitors)
        finally:
            for m in monitors:
                await m.stop()
            await cluster.shutdown_all()

    asyncio.run(run())


def test_heartbeat_detects_dead_peer_and_recovery(make_cluster):
    async def run():
        cluster = make_cluster(3, topology=Topology.complete(3))
        await cluster.start_all()
        nodes = list(cluster.nodes.values())
        observer = nodes[0]
        victim = nodes[2]
        events = []
        for passive in nodes[1:]:
            HeartbeatMonitor.install_responder(passive)
        mon = HeartbeatMonitor(
            observer, interval=0.05, max_missed=3,
            on_suspect=lambda p: events.append(("suspect", p)),
            on_recover=lambda p: events.append(("recover", p)),
        )
        await mon.start()
        try:
            ok = await _wait_until(lambda: len(mon.alive()) == 2)
            assert ok, mon.alive()

            # kill the victim: its context leaves the in-process registry,
            # so pings go undelivered from now on
            await victim.shutdown()
            ok = await _wait_until(lambda: victim.node_id in mon.suspects())
            assert ok, (mon.suspects(), mon.peers)
            assert ("suspect", victim.node_id) in events
            # exactly one suspect transition (no flapping)
            assert events.count(("suspect", victim.node_id)) == 1
            assert nodes[1].node_id not in mon.suspects()
        finally:
            await mon.stop()
            await cluster.shutdown_all()

    asyncio.run(run())


def test_heartbeat_rejects_bad_config(make_cluster):
    async def run():
        cluster = make_cluster(2)
        await cluster.start_all()
        node = next(iter(cluster.nodes.values()))
        try:
            with pytest.raises(ValueError, match="max_missed"):
                HeartbeatMonitor(node, max_missed=0)
        finally:
            await cluster.shutdown_all()

    asyncio.run(run())


def test_suspect_callback_drives_topology_rebind(make_cluster):
    """The intended policy loop: a suspect transition shrinks the live
    nodes' topology, after which broadcasts no longer target the dead
    peer and keep flowing among survivors."""
    async def run():
        cluster = make_cluster(4, topology=Topology.complete(4))
        await cluster.start_all()
        nodes = list(cluster.nodes.values())
        observer, victim = nodes[0], nodes[3]
        survivors = nodes[:3]
        for passive in nodes[1:]:
            HeartbeatMonitor.install_responder(passive)

        rebound = asyncio.Event()
        suspected = []

        def on_suspect(peer):
            # record, don't assert: _fire swallows callback exceptions, so
            # an in-callback assert would surface only as a timeout
            suspected.append(peer)
            ids = {i: n.node_id for i, n in enumerate(survivors)}
            topo = Topology.complete(3)
            for n in survivors:
                n.bind_topology(topo, ids)
            rebound.set()

        mon = HeartbeatMonitor(
            observer, interval=0.05, max_missed=3, on_suspect=on_suspect
        )
        await mon.start()
        try:
            ok = await _wait_until(lambda: len(mon.alive()) == 3)
            assert ok, mon.alive()
            await victim.shutdown()
            ok = await _wait_until(rebound.is_set)
            assert ok, "suspect callback never fired"
            assert suspected == [victim.node_id], suspected

            got = []

            async def collect(m):
                got.append(m.payload)

            survivors[1].register_handler("payload", collect)
            delivered = await observer.broadcast_message("payload", 42)
            assert victim.node_id not in delivered
            ok = await _wait_until(lambda: got == [42])
            assert ok, got
        finally:
            await mon.stop()
            await cluster.shutdown_all()

    asyncio.run(run())


def test_startup_grace_shields_never_ponged_peer(make_cluster):
    """A peer that has never answered (e.g. a subprocess still importing
    jax) is not suspected inside startup_grace; one that HAS answered is
    still caught at max_missed * interval after it dies."""
    async def run():
        cluster = make_cluster(3)
        await cluster.start_all()
        nodes = list(cluster.nodes.values())
        observer, mute, responsive = nodes
        # 'mute' never responds: strip its ping handler after install by
        # monitoring from observer only — responders are installed by the
        # monitor on its own node; the others have none yet, so only
        # 'responsive' gets one explicitly.
        HeartbeatMonitor.install_responder(responsive)
        mon = HeartbeatMonitor(
            observer, interval=0.05, max_missed=3, startup_grace=2.0
        )
        await mon.start()
        try:
            ok = await _wait_until(lambda: responsive.node_id in mon.alive())
            assert ok, mon.alive()
            # well past max_missed * interval, still inside the grace:
            # the never-ponged peer is NOT suspect
            await asyncio.sleep(0.5)
            assert mon.suspects() == [], mon.suspects()
            # after the grace expires it is suspected like any dead peer
            ok = await _wait_until(lambda: mon.suspects() == [mute.node_id])
            assert ok, mon.suspects()
        finally:
            await mon.stop()
            await cluster.shutdown_all()

    asyncio.run(run())


def test_heartbeat_partition_then_rejoin(make_cluster):
    """A PARTITIONED peer (sends to it fail, process alive) must be
    suspected like a dead one, and must RECOVER — one pong resets the
    miss streak — when the partition heals. Previously only the
    permanent-death path had direct coverage."""
    async def run():
        cluster = make_cluster(3, topology=Topology.complete(3))
        await cluster.start_all()
        nodes = list(cluster.nodes.values())
        observer, victim = nodes[0], nodes[2]
        for passive in nodes[1:]:
            HeartbeatMonitor.install_responder(passive)

        partitioned = {"on": False}
        real_send = observer.send_message

        async def flaky_send(peer, kind, payload):
            if partitioned["on"] and peer == victim.node_id:
                raise ConnectionError("partitioned link")
            return await real_send(peer, kind, payload)

        observer.send_message = flaky_send
        events = []
        mon = HeartbeatMonitor(
            observer, interval=0.05, max_missed=3,
            on_suspect=lambda p: events.append(("suspect", p)),
            on_recover=lambda p: events.append(("recover", p)),
        )
        await mon.start()
        try:
            ok = await _wait_until(lambda: len(mon.alive()) == 2)
            assert ok, mon.alive()

            partitioned["on"] = True  # drop the link, keep the process
            ok = await _wait_until(lambda: victim.node_id in mon.suspects())
            assert ok, (mon.suspects(), mon.peers)
            assert ("suspect", victim.node_id) in events
            assert nodes[1].node_id not in mon.suspects()  # isolation

            partitioned["on"] = False  # heal: one pong must recover it
            ok = await _wait_until(lambda: victim.node_id in mon.alive())
            assert ok, (mon.alive(), mon.suspects())
            assert ("recover", victim.node_id) in events
            # exactly one suspect + one recover edge: no flapping
            assert events.count(("suspect", victim.node_id)) == 1
            assert events.count(("recover", victim.node_id)) == 1
        finally:
            await mon.stop()
            await cluster.shutdown_all()

    asyncio.run(run())


def test_node_liveness_probe_suspects_and_recovers():
    """The actor-PS generalization: the same suspicion rules over direct
    node calls (no message plane), bridged into ElasticPolicy."""
    from byzpy_tpu.resilience.heartbeat import NodeLivenessProbe

    class ProbedNode:
        def __init__(self):
            self.down = False

        def ping(self):
            if self.down:
                raise ConnectionError("dead")
            return True

    async def run():
        nodes = [("honest:0", ProbedNode()), ("honest:1", ProbedNode())]
        events = []
        probe = NodeLivenessProbe(
            nodes, interval=0.03, max_missed=3,
            on_suspect=lambda p: events.append(("suspect", p)),
            on_recover=lambda p: events.append(("recover", p)),
        )
        await probe.start()
        try:
            ok = await _wait_until(lambda: probe.alive() == ["honest:0", "honest:1"])
            assert ok, probe.alive()
            nodes[1][1].down = True  # crash
            ok = await _wait_until(lambda: probe.suspects() == ["honest:1"])
            assert ok, probe.suspects()
            # the bridge the elastic PS consumes
            assert probe.suspects() == ["honest:1"]
            nodes[1][1].down = False  # restart
            ok = await _wait_until(lambda: probe.suspects() == [])
            assert ok, probe.suspects()
            assert ("suspect", "honest:1") in events
            assert ("recover", "honest:1") in events
        finally:
            await probe.stop()

    asyncio.run(run())


def test_node_liveness_probe_tolerates_nodes_without_ping():
    """Plain local objects without a probe method are in-process —
    reachable by construction, never suspected."""
    from byzpy_tpu.resilience.heartbeat import NodeLivenessProbe

    class Legacy:
        pass

    async def run():
        probe = NodeLivenessProbe(
            [("honest:0", Legacy())], interval=0.03, max_missed=2
        )
        await probe.start()
        try:
            ok = await _wait_until(lambda: probe.alive() == ["honest:0"])
            assert ok, (probe.alive(), probe.suspects())
            await asyncio.sleep(0.2)
            assert probe.suspects() == []
        finally:
            await probe.stop()

    asyncio.run(run())


def test_liveness_tracker_pure_state_machine():
    """The extracted core both monitors share: consecutive-miss
    suspicion, one-reply recovery, startup grace for never-repliers."""
    from byzpy_tpu.engine.node.liveness import LivenessTracker

    events = []
    tr = LivenessTracker(
        max_missed=2, startup_grace=10.0,
        on_suspect=lambda p: events.append(("suspect", p)),
        on_recover=lambda p: events.append(("recover", p)),
    )
    tr.start_clock(0.0)
    tr.ensure("a")
    tr.ensure("b")
    tr.record_reply("a")  # a has replied once; b never has
    for t in (1.0, 2.0, 3.0):
        tr.mark_pending("a")
        tr.mark_pending("b")
        tr.account_pending(t)
    # a crossed max_missed; b is shielded by startup grace
    assert tr.suspects() == ["a"]
    assert ("suspect", "a") in events
    # grace expires: b's unanswered probes start counting
    for t in (11.0, 12.0, 13.0):
        tr.mark_pending("b")
        tr.account_pending(t)
    assert "b" in tr.suspects()
    # one reply resets everything and fires recovery exactly once
    tr.record_reply("a")
    assert tr.alive() == ["a"]
    assert events.count(("recover", "a")) == 1

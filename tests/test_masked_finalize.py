"""Masked/ragged finalize parity: a cohort of m rows padded into bucket n
must match the exact size-m aggregate BIT-FOR-BIT (f32).

The serving tier's correctness contract (ISSUE 6): ``fold_finalize_masked``
/ ``aggregate_masked`` evaluate a fold declared for bucket size ``n`` with
an actual cohort of ``m <= n`` valid rows through one compiled program per
bucket (``m`` traced), and the result is indistinguishable from running
``aggregate`` on the unpadded rows. Aggregators without a masked matrix
program (CAF, MDA, SMEA) route through the exact-subset fallback — parity
is trivially bit-level there too, which is exactly the point of the
fallback.

Staleness-discount semantics are pinned here as well: ``discount(0)`` is
EXACTLY 1.0 and a weight-1.0 cohort is bit-identical to an undiscounted
one.
"""

import random

import numpy as np
import pytest

from byzpy_tpu.aggregators import (
    CAF,
    CenteredClipping,
    ComparativeGradientElimination,
    CoordinateWiseMedian,
    CoordinateWiseTrimmedMean,
    GeometricMedian,
    Krum,
    MeanOfMedians,
    MinimumDiameterAveraging,
    MoNNA,
    MultiKrum,
    SMEA,
)
from byzpy_tpu.serving.buckets import BucketLadder
from byzpy_tpu.serving.cohort import CohortAggregator, build_cohort
from byzpy_tpu.serving.queue import Submission
from byzpy_tpu.serving.staleness import StalenessPolicy

N = 8
D = 193

# (factory, has_masked_program): every aggregator participates — the
# masked set runs the bucket-shaped program, the rest prove the exact
# fallback. Hyperparameters chosen so the satellite's m grid
# {1, n/2, n-1, n} is mostly admissible; inadmissible (agg, m) pairs
# must raise on BOTH paths.
CASES = [
    (lambda: CoordinateWiseMedian(), True),
    (lambda: CoordinateWiseTrimmedMean(f=0), True),
    (lambda: CoordinateWiseTrimmedMean(f=1), True),
    (lambda: MeanOfMedians(f=0), True),
    (lambda: MeanOfMedians(f=2), True),
    (lambda: MultiKrum(f=1, q=2), True),
    (lambda: Krum(f=1), True),
    (lambda: ComparativeGradientElimination(f=0), True),
    (lambda: ComparativeGradientElimination(f=1), True),
    (lambda: MoNNA(f=1), True),
    (lambda: GeometricMedian(), True),
    (lambda: CenteredClipping(c_tau=1.0), True),
    (lambda: CAF(f=1), False),
    (lambda: MinimumDiameterAveraging(f=1), False),
    (lambda: SMEA(f=1), False),
]
IDS = [
    "median", "trimmed-f0", "trimmed-f1", "meamed-f0", "meamed-f2",
    "multikrum", "krum", "cge-f0", "cge-f1", "monna", "geomed", "clip",
    "caf", "mda", "smea",
]


def _grads(n=N, d=D, seed=0):
    rng = np.random.default_rng(seed)
    return [
        (rng.normal(size=d) * s).astype(np.float32)
        for s in rng.uniform(0.1, 50.0, n)
    ]


def _admissible(agg, m):
    try:
        agg.validate_n(m)
        return True
    except ValueError:
        return False


@pytest.mark.parametrize("make_agg,has_masked", CASES, ids=IDS)
@pytest.mark.parametrize("m", [1, N // 2, N - 1, N])
def test_masked_fold_matches_unpadded_aggregate_bitwise(
    make_agg, has_masked, m
):
    agg = make_agg()
    assert agg.supports_masked_finalize == has_masked
    grads = _grads()
    if not _admissible(agg, m):
        state = agg.fold_init(N)
        for i in range(m):
            agg.fold(state, i, grads[i])
        with pytest.raises(ValueError):
            agg.fold_finalize_masked(state)
        return
    ref = np.asarray(agg.aggregate(grads[:m]))
    state = agg.fold_init(N)
    for i in range(m):
        agg.fold(state, i, grads[i])
    out = np.asarray(agg.fold_finalize_masked(state))
    np.testing.assert_array_equal(out, ref, err_msg=f"{agg.name} m={m}")


@pytest.mark.parametrize("make_agg,has_masked", CASES, ids=IDS)
def test_masked_fold_arrival_order_and_scattered_slots(make_agg, has_masked):
    """Masked finalize is arrival-order independent and handles
    non-prefix slot occupancy (elastic cohorts): the result equals the
    unpadded aggregate of the occupied slots in CANONICAL slot order."""
    agg = make_agg()
    grads = _grads(seed=3)
    slots = [0, 2, 3, 6, 7]  # scattered occupancy, m=5
    if not _admissible(agg, len(slots)):
        pytest.skip("hyperparameters inadmissible at m=5")
    ref = np.asarray(agg.aggregate([grads[s] for s in slots]))
    for trial in range(3):
        order = list(slots)
        random.Random(trial).shuffle(order)
        state = agg.fold_init(N)
        for s in order:
            agg.fold(state, s, grads[s])
        out = np.asarray(agg.fold_finalize_masked(state))
        np.testing.assert_array_equal(out, ref, err_msg=f"{agg.name}")


@pytest.mark.parametrize(
    "make_agg",
    [
        lambda: CoordinateWiseTrimmedMean(f=2),
        lambda: MultiKrum(f=2, q=3),
        lambda: ComparativeGradientElimination(f=2),
        lambda: CenteredClipping(c_tau=1.0),
    ],
    ids=["trimmed", "multikrum", "cge", "clip"],
)
def test_masked_parity_holds_at_large_buckets(make_agg):
    """The einsum-contraction reductions stay bit-stable under zero
    padding at bench-scale buckets (where plain jnp.sum re-associates
    and drifts ~1e-7) — the load-bearing property of the masked
    recipe."""
    agg = make_agg()
    n = 64
    grads = _grads(n=n, d=257, seed=5)
    for m in (21, 40, 63, 64):
        ref = np.asarray(agg.aggregate(grads[:m]))
        matrix = np.zeros((n, 257), np.float32)
        matrix[:m] = np.stack(grads[:m])
        valid = np.zeros(n, bool)
        valid[:m] = True
        out = np.asarray(agg.aggregate_masked(matrix, valid))
        np.testing.assert_array_equal(out, ref, err_msg=f"{agg.name} m={m}")


def test_aggregate_masked_matches_fold_finalize_masked():
    """The batch door and the streaming fold share one program: same
    bits, same jit cache."""
    agg = MultiKrum(f=1, q=2)
    grads = _grads(seed=7)
    m = 6
    state = agg.fold_init(N)
    for i in range(m):
        agg.fold(state, i, grads[i])
    via_fold = np.asarray(agg.fold_finalize_masked(state))
    matrix = np.zeros((N, D), np.float32)
    matrix[:m] = np.stack(grads[:m])
    valid = np.zeros(N, bool)
    valid[:m] = True
    via_batch = np.asarray(agg.aggregate_masked(matrix, valid))
    np.testing.assert_array_equal(via_fold, via_batch)


def test_masked_jit_cache_one_entry_per_bucket():
    """The whole point of bucketing: aggregating many distinct cohort
    sizes compiles once per BUCKET shape, not once per size."""
    agg = CoordinateWiseTrimmedMean(f=1)
    rng = np.random.default_rng(11)
    for bucket in (8, 16):
        for m in range(4, bucket + 1):
            matrix = np.zeros((bucket, 64), np.float32)
            matrix[:m] = rng.normal(size=(m, 64)).astype(np.float32)
            valid = np.zeros(bucket, bool)
            valid[:m] = True
            agg.aggregate_masked(matrix, valid)
    assert agg._masked_jitted()._cache_size() == 2


def test_nonfinite_cohort_falls_back_to_exact_path():
    """A NaN/inf gradient sorts differently against mask padding, so
    non-finite cohorts must route to the exact subset path — and still
    match the unpadded aggregate bit-for-bit (NaN placement included)."""
    for make_agg in (
        lambda: CoordinateWiseMedian(),
        lambda: CoordinateWiseTrimmedMean(f=1),
        lambda: MultiKrum(f=1, q=2),
    ):
        agg = make_agg()
        grads = _grads(seed=13)
        grads[1] = grads[1].copy()
        grads[1][::7] = np.inf
        grads[2] = grads[2].copy()
        grads[2][3] = np.nan
        m = 6
        ref = np.asarray(agg.aggregate(grads[:m]))
        state = agg.fold_init(N)
        for i in range(m):
            agg.fold(state, i, grads[i])
        out = np.asarray(agg.fold_finalize_masked(state))
        np.testing.assert_array_equal(out, ref, err_msg=agg.name)


def test_masked_finalize_before_any_fold_raises():
    agg = CoordinateWiseMedian()
    state = agg.fold_init(N)
    with pytest.raises(ValueError):
        agg.fold_finalize_masked(state)


def test_aggregate_masked_all_false_mask_raises():
    # validate_n is a no-op for f=0 aggregators (median), and the masked
    # program's (m-1)//2 gather would wrap to a +inf padding row on m=0
    # — must be an error, never a silently-garbage aggregate
    agg = CoordinateWiseMedian()
    with pytest.raises(ValueError):
        agg.aggregate_masked(np.zeros((4, 3), np.float32), np.zeros(4, bool))


# ---------------------------------------------------------------------------
# staleness-discount semantics
# ---------------------------------------------------------------------------


def test_staleness_discount_zero_delta_is_exact_identity():
    for kind in ("none", "exponential", "polynomial"):
        pol = StalenessPolicy(kind=kind, gamma=0.3, alpha=2.0)
        assert pol.discount(0) == 1.0
        assert pol.discount(-1) == 1.0  # client ahead of server: fresh


def test_staleness_discount_values():
    exp = StalenessPolicy(kind="exponential", gamma=0.5)
    assert exp.discount(1) == 0.5 and exp.discount(3) == 0.125
    poly = StalenessPolicy(kind="polynomial", alpha=1.0)
    assert poly.discount(1) == 0.5 and poly.discount(3) == 0.25
    none = StalenessPolicy()
    assert none.discount(100) == 1.0
    cut = StalenessPolicy(cutoff=2)
    assert cut.admits(2) and not cut.admits(3)


def _cohort(grads, rounds_submitted, server_round, staleness, cap=8):
    subs = [
        Submission(client=f"c{i}", round_submitted=r, gradient=g,
                   arrived_s=float(i))
        for i, (g, r) in enumerate(zip(grads, rounds_submitted, strict=True))
    ]
    return build_cohort(
        subs, server_round, BucketLadder(cap), staleness
    )


def test_fresh_cohort_bit_identical_through_staleness_machinery():
    """δ=0 for every row ⇒ the staleness-aware path produces the same
    bits as the policy-free aggregate (weights exactly 1.0)."""
    agg = CoordinateWiseTrimmedMean(f=1)
    grads = _grads(seed=17)[:5]
    pol = StalenessPolicy(kind="exponential", gamma=0.25)
    cohort = _cohort(grads, [4] * 5, 4, pol)
    assert (cohort.weights[: cohort.m] == 1.0).all()
    out = np.asarray(CohortAggregator(agg).aggregate(cohort))
    ref = np.asarray(agg.aggregate(grads))
    np.testing.assert_array_equal(out, ref)


def test_stale_rows_are_discounted_before_aggregation():
    """A round-k gradient folded into round k+δ is scaled by
    discount(δ) — verified against the hand-scaled unpadded aggregate."""
    agg = CoordinateWiseTrimmedMean(f=0)
    grads = _grads(seed=19)[:4]
    pol = StalenessPolicy(kind="exponential", gamma=0.5)
    # server at round 6; submissions from rounds 6, 5, 4, 6 -> δ 0,1,2,0
    cohort = _cohort(grads, [6, 5, 4, 6], 6, pol)
    np.testing.assert_array_equal(
        cohort.weights[:4], np.float32([1.0, 0.5, 0.25, 1.0])
    )
    out = np.asarray(CohortAggregator(agg).aggregate(cohort))
    scaled = [
        grads[0], grads[1] * np.float32(0.5),
        grads[2] * np.float32(0.25), grads[3],
    ]
    ref = np.asarray(agg.aggregate(scaled))
    np.testing.assert_array_equal(out, ref)

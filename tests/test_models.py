"""Model zoo: shapes, gradients, and bundle plumbing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byzpy_tpu.models import (
    MLP,
    ResNet18,
    SmallCNN,
    cifar_resnet18,
    make_bundle,
    mnist_cnn,
    mnist_mlp,
    sample_batch,
    synthetic_classification,
    ShardedDataset,
)
from byzpy_tpu.utils.trees import tree_size


def test_mlp_forward_shape():
    b = mnist_mlp()
    x = jnp.zeros((4, 28, 28, 1))
    assert b.apply_fn(b.params, x).shape == (4, 10)


def test_small_cnn_forward_and_grad():
    b = mnist_cnn()
    x, y = synthetic_classification(n_samples=8)
    logits = b.apply_fn(b.params, x[:4])
    assert logits.shape == (4, 10)
    g = b.grad(x[:4], y[:4])
    assert jax.tree_util.tree_structure(g) == jax.tree_util.tree_structure(b.params)
    assert tree_size(g) == tree_size(b.params)


def test_resnet18_cifar_forward():
    b = cifar_resnet18()
    x = jnp.zeros((2, 32, 32, 3))
    assert b.apply_fn(b.params, x).shape == (2, 10)


def test_bundle_loss_decreases_with_sgd():
    b = mnist_mlp(hidden=32)
    x, y = synthetic_classification(n_samples=256, seed=3)
    loss0 = float(b.loss(x, y))
    params = b.params
    for _ in range(20):
        g = jax.grad(b.loss_fn)(params, x, y)
        params = jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, params, g)
    assert float(b.loss_fn(params, x, y)) < loss0


def test_sharded_dataset_slices():
    x, y = synthetic_classification(n_samples=64)
    ds = ShardedDataset(x, y, n_nodes=8)
    assert ds.shard_size == 8
    xs, ys = ds.stacked_shards()
    assert xs.shape == (8, 8, 28, 28, 1)
    x0, y0 = ds.node_slice(0)
    np.testing.assert_array_equal(np.asarray(x0), np.asarray(xs[0]))


def test_sample_batch_jit_safe():
    x, y = synthetic_classification(n_samples=32)
    key = jax.random.PRNGKey(0)
    bx, by = jax.jit(lambda k: sample_batch(x, y, k, 16))(key)
    assert bx.shape == (16, 28, 28, 1)
    assert by.shape == (16,)

"""Model zoo: shapes, gradients, and bundle plumbing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byzpy_tpu.models import (
    MLP,
    ResNet18,
    SmallCNN,
    cifar_resnet18,
    make_bundle,
    mnist_cnn,
    mnist_mlp,
    sample_batch,
    synthetic_classification,
    ShardedDataset,
)
from byzpy_tpu.utils.trees import tree_size


def test_mlp_forward_shape():
    b = mnist_mlp()
    x = jnp.zeros((4, 28, 28, 1))
    assert b.apply_fn(b.params, x).shape == (4, 10)


def test_small_cnn_forward_and_grad():
    b = mnist_cnn()
    x, y = synthetic_classification(n_samples=8)
    logits = b.apply_fn(b.params, x[:4])
    assert logits.shape == (4, 10)
    g = b.grad(x[:4], y[:4])
    assert jax.tree_util.tree_structure(g) == jax.tree_util.tree_structure(b.params)
    assert tree_size(g) == tree_size(b.params)


@pytest.mark.heavy  # ~30s XLA compile on 1-core CPU
def test_resnet18_cifar_forward():
    b = cifar_resnet18()
    x = jnp.zeros((2, 32, 32, 3))
    assert b.apply_fn(b.params, x).shape == (2, 10)


def test_bundle_loss_decreases_with_sgd():
    b = mnist_mlp(hidden=32)
    x, y = synthetic_classification(n_samples=256, seed=3)
    loss0 = float(b.loss(x, y))
    params = b.params
    for _ in range(20):
        g = jax.grad(b.loss_fn)(params, x, y)
        params = jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, params, g)
    assert float(b.loss_fn(params, x, y)) < loss0


def test_sharded_dataset_slices():
    x, y = synthetic_classification(n_samples=64)
    ds = ShardedDataset(x, y, n_nodes=8)
    assert ds.shard_size == 8
    xs, ys = ds.stacked_shards()
    assert xs.shape == (8, 8, 28, 28, 1)
    x0, y0 = ds.node_slice(0)
    np.testing.assert_array_equal(np.asarray(x0), np.asarray(xs[0]))


def test_sample_batch_jit_safe():
    x, y = synthetic_classification(n_samples=32)
    key = jax.random.PRNGKey(0)
    bx, by = jax.jit(lambda k: sample_batch(x, y, k, 16))(key)
    assert bx.shape == (16, 28, 28, 1)
    assert by.shape == (16,)


@pytest.mark.heavy  # ~30s XLA compile on 1-core CPU
def test_resnet50_imagenet_shape_and_dtype():
    """ResNet-50 bottleneck path at ImageNet shape, bf16 compute with f32
    logits (the BASELINE config-#5 model)."""
    from byzpy_tpu.models.nets import ResNet50

    model = ResNet50(num_classes=1000, small_input=False, dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    logits = model.apply(params, jnp.zeros((2, 64, 64, 3)))
    assert logits.shape == (2, 1000)
    assert logits.dtype == jnp.float32  # classifier head upcasts


@pytest.mark.heavy  # ~30s XLA compile on 1-core CPU
def test_resnet_grads_flow_through_batchnorm_free_path():
    """The training path must produce finite grads for every parameter
    (catches dead branches / stop_gradient mistakes in the blocks)."""
    from byzpy_tpu.models.nets import ResNet18

    model = ResNet18(num_classes=10)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y = jnp.asarray([1, 3])

    def loss(p):
        import optax

        return optax.softmax_cross_entropy_with_integer_labels(
            model.apply(p, x), y
        ).mean()

    g = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert leaves and all(bool(jnp.isfinite(leaf).all()) for leaf in leaves)
    assert any(float(jnp.max(jnp.abs(leaf))) > 0 for leaf in leaves)


def test_bundle_num_params_and_flatten_roundtrip():
    from byzpy_tpu.models.nets import mnist_mlp
    from byzpy_tpu.utils.trees import stack_gradients

    bundle = mnist_mlp(seed=0, hidden=16)
    flat, unravel = stack_gradients([bundle.params])
    back = unravel(flat[0])
    for a, b in zip(
        jax.tree_util.tree_leaves(bundle.params), jax.tree_util.tree_leaves(back),
        strict=True,
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_idx_parser_roundtrip(tmp_path):
    """load_mnist_idx reads the real MNIST wire format: write valid IDX
    files (gzip images + raw labels) and get the exact tensors back."""
    import gzip
    import struct

    from byzpy_tpu.models.data import load_mnist_idx

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(5, 28, 28), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(5,), dtype=np.uint8)

    img_hdr = struct.pack(">BBBBIII", 0, 0, 0x08, 3, 5, 28, 28)
    with gzip.open(tmp_path / "train-images-idx3-ubyte.gz", "wb") as fh:
        fh.write(img_hdr + images.tobytes())
    lbl_hdr = struct.pack(">BBBBI", 0, 0, 0x08, 1, 5)
    (tmp_path / "train-labels-idx1-ubyte").write_bytes(lbl_hdr + labels.tobytes())

    x, y = load_mnist_idx(str(tmp_path), split="train")
    assert x.shape == (5, 28, 28, 1) and x.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(x)[..., 0], images / 255.0, atol=1e-7)
    np.testing.assert_array_equal(np.asarray(y), labels.astype(np.int32))


def test_idx_parser_rejects_garbage(tmp_path):
    from byzpy_tpu.models.data import _idx_read

    p = tmp_path / "bad"
    p.write_bytes(b"\x12\x34junk")
    with pytest.raises(ValueError, match="not an IDX file"):
        _idx_read(str(p))
    # truncated payload must be caught, not silently reshaped
    import struct

    q = tmp_path / "short"
    q.write_bytes(struct.pack(">BBBBII", 0, 0, 0x08, 2, 4, 4) + b"\x00" * 7)
    with pytest.raises(ValueError, match="payload"):
        _idx_read(str(q))


def test_load_mnist_idx_missing_files_message(tmp_path):
    from byzpy_tpu.models.data import load_mnist_idx

    with pytest.raises(FileNotFoundError, match="train-images"):
        load_mnist_idx(str(tmp_path))

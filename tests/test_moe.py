"""Expert-parallel MoE: routing properties + sharded-vs-dense exactness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from byzpy_tpu.parallel.collectives import sharded_fn
from byzpy_tpu.parallel.moe import MoEFFN, moe_ffn, top1_dispatch


def weights(d=16, e=8, h=32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    gate_w = jax.random.normal(ks[0], (d, e), jnp.float32) * 0.5
    w_in = jax.random.normal(ks[1], (e, d, h), jnp.float32) * 0.2
    w_out = jax.random.normal(ks[2], (e, h, d), jnp.float32) * 0.2
    return gate_w, w_in, w_out


def test_top1_dispatch_properties():
    logits = jax.random.normal(jax.random.PRNGKey(1), (32, 4))
    dispatch, combine = top1_dispatch(logits, 4, capacity=16)
    d = np.asarray(dispatch)
    # each token occupies at most one (expert, slot) cell
    assert d.sum(axis=(1, 2)).max() == 1.0
    # each (expert, slot) cell holds at most one token
    assert d.sum(axis=0).max() <= 1.0
    # combine = dispatch scaled by the top-1 gate probability
    probs = np.asarray(jax.nn.softmax(logits, -1)).max(axis=1)
    got_gate = np.asarray(combine).sum(axis=(1, 2))
    kept = d.sum(axis=(1, 2)) > 0
    np.testing.assert_allclose(got_gate[kept], probs[kept], rtol=1e-5)


def test_top1_dispatch_capacity_drops():
    # all tokens to expert 0: capacity 4 keeps exactly the first 4
    logits = jnp.zeros((10, 3)).at[:, 0].set(10.0)
    dispatch, _ = top1_dispatch(logits, 3, capacity=4)
    d = np.asarray(dispatch)
    assert d[:4, 0].sum() == 4.0
    assert d[4:].sum() == 0.0  # dropped


def test_moe_dense_forward_shape_and_drop_zeroing():
    gate_w, w_in, w_out = weights()
    x = jax.random.normal(jax.random.PRNGKey(2), (24, 16))
    out = moe_ffn(x, gate_w, w_in, w_out, capacity_factor=0.25)
    assert out.shape == x.shape
    # tiny capacity: some tokens must be dropped -> exact zero rows
    assert (np.abs(np.asarray(out)).sum(axis=1) == 0.0).any()


@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_moe_expert_parallel_matches_dense(devices, n_shards):
    """Sharded experts + two all_to_alls == all-experts-local, when
    capacity is big enough that neither path drops (slot order then
    cannot matter)."""
    e = 8
    gate_w, w_in, w_out = weights(e=e)
    t = 64
    x = jax.random.normal(jax.random.PRNGKey(3), (t, 16))
    dense = moe_ffn(x, gate_w, w_in, w_out, capacity_factor=float(e))

    mesh = Mesh(np.array(devices[:n_shards]), ("ep",))

    def local(xs, gw, wi, wo):
        return moe_ffn(xs, gw, wi, wo, "ep", capacity_factor=float(e))

    fn = sharded_fn(
        mesh, "ep", local,
        in_spec=(P("ep"), P(), P("ep"), P("ep")),
        out_spec=P("ep"),
    )
    got = fn(x, gate_w, w_in, w_out)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(dense), rtol=2e-4, atol=2e-5
    )


def test_moe_flax_module_trains(devices):
    """Single-device module: gradient flows through router and experts."""
    model = MoEFFN(n_experts=4, hidden=32)
    x = jax.random.normal(jax.random.PRNGKey(4), (16, 8))
    params = model.init(jax.random.PRNGKey(5), x)

    def loss(p):
        return jnp.mean((model.apply(p, x) - 1.0) ** 2)

    g = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(bool(jnp.isfinite(leaf).all()) for leaf in leaves)
    assert any(float(jnp.abs(leaf).max()) > 0 for leaf in leaves)


def test_moe_expert_parallel_init_distinct_experts(devices):
    """Round-4 review regression: under expert parallelism the module RNG
    is replicated across the axis; init must fold in the device index so
    the E experts stay distinct instead of collapsing to E/p copies."""
    p = 2
    mesh = Mesh(np.array(devices[:p]), ("ep",))
    model = MoEFFN(n_experts=4, hidden=8, axis_name="ep")
    x = jax.random.normal(jax.random.PRNGKey(6), (8, 4))

    def local_init(xs):
        return model.init(jax.random.PRNGKey(7), xs)

    fn = sharded_fn(mesh, "ep", local_init, in_spec=P("ep"), out_spec=P("ep"))
    params = fn(x)
    w_in = np.asarray(params["params"]["w_in"])  # gathered (4, 4, 8)
    assert w_in.shape[0] == 4
    assert not np.allclose(w_in[:2], w_in[2:]), "experts collapsed to copies"

"""Multi-host bring-up: ``initialize_multihost`` exercised for real.

Spawns two worker processes that initialize the JAX distributed runtime
against a local coordinator, build one global 2-device mesh, and run a
cross-process psum (``examples/distributed/two_host_psum.py`` is the
worker). This is the only public entry point that cannot be covered by
the in-process 8-device mesh — the reference's analogue is its TCP
server/client integration tests (SURVEY §4 "subprocess integration").
"""

import os
import re
import socket
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.heavy]

EXAMPLE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples", "distributed", "two_host_psum.py",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_psum_over_distributed_runtime():
    proc = subprocess.run(
        [sys.executable, EXAMPLE, "--port", str(_free_port())],
        capture_output=True,
        text=True,
        timeout=420,
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    assert out.count("cross-host psum OK") == 2, out
    # device count per process varies with XLA_FLAGS (the suite's conftest
    # exposes 8 virtual CPU devices); the invariant is global == 2 x local
    m = re.search(r"global devices=(\d+) local=(\d+)", out)
    assert m and int(m.group(1)) == 2 * int(m.group(2)), out


# -- fault drills: death + recovery on the cross-process path ---------------
#
# The reference's multi-backend paranoia (its per-backend copies of the
# decentralized suites, e.g. node/tests/test_decentralized_process.py)
# is matched here with drills against REAL OS-process deaths: a SIGKILLed
# actor host mid-round, a byzantine peer living in a child process, and a
# heartbeat-policy excision of a killed subprocess peer.

import asyncio
import signal
import time

import numpy as np


def _spawn_drill_server():
    """Start tests/remote_drill_server.py in its own OS process; return
    (Popen, port)."""
    import select

    helper = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "remote_drill_server.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.Popen(
        [sys.executable, helper], stdout=subprocess.PIPE, text=True, env=env,
    )
    deadline = time.monotonic() + 120
    line = ""
    while time.monotonic() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [], 1.0)
        if ready:
            line = proc.stdout.readline()
            break
        if proc.poll() is not None:
            break
    if not line.startswith("PORT "):
        proc.kill()
        raise RuntimeError(f"drill server failed to start (got {line!r})")
    return proc, int(line.split()[1])


def test_elastic_ps_survives_sigkilled_host_process_midround():
    """A node's host process is SIGKILLed while its gradient call is IN
    FLIGHT: the elastic round completes on the survivors and the dead
    host is suspected; later rounds keep flowing without it."""
    from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
    from byzpy_tpu.engine.node.actors import HonestNodeActor
    from byzpy_tpu.engine.parameter_server import ElasticPolicy, ParameterServer
    from remote_drill_server import D, SlowRemoteNode

    class LocalNode:
        def __init__(self, value):
            self.value = float(value)

        def honest_gradient_for_next_batch(self):
            return [np.full(D, self.value, np.float32)]

        def apply_server_gradient(self, g):
            pass

    async def drill():
        proc, port = _spawn_drill_server()
        try:
            remote = await HonestNodeActor.spawn(
                SlowRemoteNode, 9.0, 3.0,
                backend=f"tcp://127.0.0.1:{port}",
            )
            ps = ParameterServer(
                honest_nodes=[LocalNode(1.0), LocalNode(2.0), remote],
                aggregator=CoordinateWiseTrimmedMean(f=0),
                elastic=ElasticPolicy(min_quorum=2, call_timeout=20.0),
            )
            round_task = asyncio.create_task(ps.round())
            await asyncio.sleep(1.0)  # remote is inside its 3 s gradient
            proc.send_signal(signal.SIGKILL)  # host dies mid-round
            out = await asyncio.wait_for(round_task, timeout=60.0)
            np.testing.assert_allclose(
                np.asarray(out[0]), np.full(D, 1.5), rtol=1e-6
            )
            assert "honest:2" in ps.elastic_state.suspects
            # the fabric keeps training without the dead host
            out = await asyncio.wait_for(ps.round(), timeout=60.0)
            np.testing.assert_allclose(
                np.asarray(out[0]), np.full(D, 1.5), rtol=1e-6
            )
            assert ps.rounds_completed == 2
        finally:
            proc.kill()

    asyncio.run(drill())


class _DrillWorker:
    """Quadratic-descent gossip worker (picklable for subprocess peers)."""

    def __init__(self, target, dim=4):
        import jax.numpy as jnp

        self.target = jnp.full((dim,), float(target), jnp.float32)
        self.w = jnp.zeros((dim,), jnp.float32)

    def half_step(self, lr):
        self.w = self.w - lr * 2.0 * (self.w - self.target)
        return self.w

    def parameters(self):
        return self.w

    def apply_aggregate(self, vector):
        import jax.numpy as jnp

        self.w = jnp.asarray(vector)


def _byz_outlier(honest_vectors):
    import jax.numpy as jnp

    return jnp.full((4,), 1e3, jnp.float32)


def test_gossip_with_byzantine_process():
    """A byzantine peer living in a CHILD OS PROCESS (its attack pipeline
    installed child-side via the configure hook): robust consensus among
    the in-process honest peers must hold against the subprocess's
    outlier vectors."""
    from byzpy_tpu.aggregators import CoordinateWiseMedian
    from byzpy_tpu.engine.node.context import InProcessContext
    from byzpy_tpu.engine.node.process_context import ProcessContext
    from byzpy_tpu.engine.peer_to_peer import Topology
    from byzpy_tpu.engine.peer_to_peer.nodes import FunctionP2PWorker
    from byzpy_tpu.engine.peer_to_peer.runner import DecentralizedPeerToPeer

    InProcessContext._registry.clear()
    ProcessContext.clear_registry()
    workers = [_DrillWorker(t) for t in (0.0, 1.0, 2.0)]
    byz = [FunctionP2PWorker(_byz_outlier)]

    def ctx_factory(nid):
        return (
            ProcessContext(nid) if nid == "node-3" else InProcessContext(nid)
        )

    p2p = DecentralizedPeerToPeer(
        workers, byz,
        aggregator=CoordinateWiseMedian(),
        topology=Topology.complete(4),
        learning_rate=0.3,
        context_factory=ctx_factory,
        gossip_timeout=120.0,
    )

    async def drill():
        async with p2p:
            for _ in range(8):
                await p2p.run_round_async()

    asyncio.run(drill())
    # each honest node medians 4 vectors (an even count: its own + three
    # in-neighbors, one byzantine) — the middle pair averages the honest
    # 1.0/2.0 targets, so consensus sits at 1.5, UNDRAGGED by the
    # subprocess's 1e3 outlier (mean aggregation would sit near 250)
    for i in (0, 1, 2):
        np.testing.assert_allclose(np.asarray(workers[i].w), 1.5, atol=0.3)


def test_heartbeat_policy_excises_sigkilled_process_peer():
    """Full DCN-path drill of the shipped elastic policy: an honest peer
    lives in a child OS process, the process is SIGKILLed mid-training,
    the heartbeat monitor suspects it (no pongs from a dead process), the
    policy excises it, and gossip continues among the survivors."""
    from byzpy_tpu.aggregators import CoordinateWiseMedian
    from byzpy_tpu.engine.node.context import InProcessContext
    from byzpy_tpu.engine.node.process_context import ProcessContext
    from byzpy_tpu.engine.peer_to_peer import HeartbeatPolicy, Topology
    from byzpy_tpu.engine.peer_to_peer.runner import DecentralizedPeerToPeer

    InProcessContext._registry.clear()
    ProcessContext.clear_registry()
    workers = [_DrillWorker(t) for t in (0.0, 1.0, 2.0, 9.0)]

    def ctx_factory(nid):
        return (
            ProcessContext(nid) if nid == "node-3" else InProcessContext(nid)
        )

    p2p = DecentralizedPeerToPeer(
        workers, [],
        aggregator=CoordinateWiseMedian(),
        topology=Topology.complete(4),
        learning_rate=0.3,
        context_factory=ctx_factory,
        gossip_timeout=60.0,
        # a subprocess peer's event loop stalls for seconds at a time
        # while jax traces/compiles its pipelines — give the detector
        # enough misses that only a real death (no pongs ever again)
        # trips it, not a compile pause
        elastic=HeartbeatPolicy(interval=1.0, max_missed=12),
    )

    async def drill():
        async with p2p:
            for _ in range(3):
                await p2p.run_round_async()
            assert p2p.honest_indices == [0, 1, 2, 3], p2p.elastic_events
            victim_id = p2p.node_ids[3]
            # SIGKILL the subprocess peer — no goodbye, no queue drain
            p2p.nodes[3].context._proc.kill()
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if (victim_id, "removed") in p2p.elastic_events:
                    break
                await asyncio.sleep(0.1)
            assert (victim_id, "removed") in p2p.elastic_events, (
                p2p.elastic_events
            )
            assert p2p.honest_indices == [0, 1, 2]
            for _ in range(12):
                await p2p.run_round_async()

    asyncio.run(drill())
    for i in (0, 1, 2):
        np.testing.assert_allclose(np.asarray(workers[i].w), 1.0, atol=0.3)

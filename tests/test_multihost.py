"""Multi-host bring-up: ``initialize_multihost`` exercised for real.

Spawns two worker processes that initialize the JAX distributed runtime
against a local coordinator, build one global 2-device mesh, and run a
cross-process psum (``examples/distributed/two_host_psum.py`` is the
worker). This is the only public entry point that cannot be covered by
the in-process 8-device mesh — the reference's analogue is its TCP
server/client integration tests (SURVEY §4 "subprocess integration").
"""

import os
import re
import socket
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.heavy]

EXAMPLE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples", "distributed", "two_host_psum.py",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_psum_over_distributed_runtime():
    proc = subprocess.run(
        [sys.executable, EXAMPLE, "--port", str(_free_port())],
        capture_output=True,
        text=True,
        timeout=420,
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    assert out.count("cross-host psum OK") == 2, out
    # device count per process varies with XLA_FLAGS (the suite's conftest
    # exposes 8 virtual CPU devices); the invariant is global == 2 x local
    m = re.search(r"global devices=(\d+) local=(\d+)", out)
    assert m and int(m.group(1)) == 2 * int(m.group(2)), out

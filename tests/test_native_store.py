"""Native shm tensor store + cross-process payload wrapping.

Parity targets: ``byzpy/engine/storage/shared_store.py`` (register/open/
cleanup of named tensors) and ``byzpy/engine/actor/ipc.py`` (payload
wrap/unwrap around process hops). The store here is a C library (POSIX
shm via ctypes) with a pure-Python fallback.
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from byzpy_tpu.engine.actor.ipc import (
    cleanup_handles,
    unwrap_payload,
    wrap_payload,
)
from byzpy_tpu.engine.storage import native_store


def test_native_library_builds():
    """The image has a C toolchain, so the native path must be live (the
    fallback exists for toolchain-less installs)."""
    assert native_store.available()


def test_register_open_cleanup_roundtrip():
    arr = np.arange(1024, dtype=np.float32).reshape(32, 32)
    handle = native_store.register_tensor(arr)
    assert handle.shape == (32, 32) and handle.np_dtype == np.float32
    assert handle.nbytes == arr.nbytes
    view = native_store.open_tensor(handle)
    np.testing.assert_array_equal(view, arr)
    # shm is shared: writes through one mapping are visible via another
    view[0, 0] = 123.0
    view2 = native_store.open_tensor(handle)
    assert view2[0, 0] == 123.0
    native_store.cleanup_tensor(handle)
    with pytest.raises(OSError):
        native_store.open_tensor(handle)


def test_wrap_payload_thresholds_and_structure():
    big = np.ones((64 * 1024,), dtype=np.float32)  # 256 KiB
    small = np.ones((4,), dtype=np.float32)
    payload = {"g": [big, small], "meta": ("x", 1)}
    wrapped, handles = wrap_payload(payload)
    try:
        assert len(handles) == 1  # only the big array moved to shm
        assert isinstance(wrapped["g"][0], tuple)
        assert isinstance(wrapped["g"][1], np.ndarray)
        out = unwrap_payload(wrapped, copy=True, close=True)
        np.testing.assert_array_equal(out["g"][0], big)
        np.testing.assert_array_equal(out["g"][1], small)
        assert out["meta"] == ("x", 1)
    finally:
        cleanup_handles(handles)


def test_unwrap_close_requires_copy():
    with pytest.raises(ValueError):
        unwrap_payload({}, copy=False, close=True)


def test_unwrap_tolerates_array_first_tuples():
    """A 2-tuple whose first element is an ndarray must not trip the shm-tag
    check (ambiguous array truth value)."""
    payload = (np.ones(4, np.float32), "x")
    out = unwrap_payload(payload)
    np.testing.assert_array_equal(out[0], payload[0])
    assert out[1] == "x"


def test_wrap_descends_into_dataclass_envelopes():
    """Message-style dataclass payloads go through the shm path like dicts."""
    from byzpy_tpu.engine.node.context import Message

    big = np.full((64 * 1024,), 3.0, dtype=np.float32)
    msg = Message("grad", "n0", big, {"round": 1})
    wrapped, handles = wrap_payload(msg)
    try:
        assert len(handles) == 1
        assert isinstance(wrapped.payload, tuple)  # shm marker
        out = unwrap_payload(wrapped, copy=True, close=True)
        np.testing.assert_array_equal(out.payload, big)
        assert out.metadata == {"round": 1}
    finally:
        cleanup_handles(handles)


def test_coordinate_ops_int_and_1d_inputs(monkeypatch):
    """Dispatch must not break non-2D or integer inputs (the jnp paths
    handled both before the Pallas fork existed)."""
    from byzpy_tpu.ops import robust

    monkeypatch.setenv("BYZPY_TPU_PALLAS", "1")
    v = jnp.arange(9.0)
    np.testing.assert_allclose(float(robust.trimmed_mean(v[:, None] * jnp.ones((1, 4)), f=2)[0]), 4.0)
    ints = jnp.arange(12, dtype=jnp.int32).reshape(3, 4)
    np.testing.assert_allclose(np.asarray(robust.coordinate_median(ints)),
                               np.median(np.asarray(ints), axis=0))
    # int sort through the network directly (iinfo padding)
    from byzpy_tpu.ops.pallas_kernels import sort_columns

    out = sort_columns(jnp.asarray([[3, 1], [2, 5], [9, 0]], jnp.int32), interpret=True)
    np.testing.assert_array_equal(np.asarray(out), [[2, 0], [3, 1], [9, 5]])


def test_wrap_preserves_namedtuples():
    from collections import namedtuple

    Point = namedtuple("Point", "x y")
    big = np.ones((64 * 1024,), dtype=np.float32)
    wrapped, handles = wrap_payload(Point(x=big, y=1))
    try:
        assert isinstance(wrapped, Point) and wrapped.y == 1
        out = unwrap_payload(wrapped, copy=True, close=True)
        assert isinstance(out, Point)
        np.testing.assert_array_equal(out.x, big)
    finally:
        cleanup_handles(handles)


def test_structured_dtype_roundtrip():
    dt = np.dtype([("a", "<f4"), ("b", "<i4")])
    arr = np.zeros(32 * 1024, dtype=dt)
    arr["a"] = 1.5
    arr["b"] = 7
    handle = native_store.register_tensor(arr)
    try:
        view = native_store.open_tensor(handle)
        assert view.dtype == dt
        assert view["b"][0] == 7 and view["a"][-1] == 1.5
    finally:
        native_store.cleanup_tensor(handle)


def test_object_dtype_rejected():
    with pytest.raises(TypeError):
        native_store.register_tensor(np.array([object()], dtype=object))


def test_process_actor_large_payload_via_shm():
    """A process actor call with a multi-MB array arrives intact (riding
    the shm path, not the pipe)."""
    from byzpy_tpu.engine.actor.backends.process import ProcessActorBackend
    from byzpy_tpu.engine.actor.base import spawn_actor

    class Echo:
        def stats(self, arr):
            return float(arr.sum()), arr.shape, float(arr[-1, -1])

    async def go():
        backend = ProcessActorBackend()
        ref = await spawn_actor(backend, Echo)
        big = np.full((1024, 1024), 2.0, dtype=np.float32)  # 4 MiB
        big[-1, -1] = 7.0
        total, shape, corner = await ref.stats(big)
        assert shape == (1024, 1024)
        assert corner == 7.0
        assert total == pytest.approx(2.0 * (1024 * 1024 - 1) + 7.0)
        await backend.close()

    asyncio.run(go())


def test_open_tensor_rejects_stale_oversized_handle():
    """A handle claiming more bytes than the segment holds must raise
    instead of handing out a view whose tail pages SIGBUS on first touch."""
    arr = np.arange(16, dtype=np.float32)
    handle = native_store.register_tensor(arr)
    try:
        stale = native_store.SharedTensorHandle(
            handle.name, (1024, 1024), handle.dtype
        )
        with pytest.raises(ValueError, match="stale or mismatched"):
            native_store.open_tensor(stale)
        # the honest handle still opens fine afterwards
        view = native_store.open_tensor(handle)
        np.testing.assert_array_equal(np.asarray(view), arr)
        del view
        native_store.close_tensor(handle)
    finally:
        native_store.cleanup_tensor(handle)

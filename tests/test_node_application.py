"""NodeApplication registry + Distributed node wrappers.

Parity targets: ``byzpy/engine/node/application.py`` (reserved pipeline
names, pool lifecycle, metadata) and ``byzpy/engine/node/distributed.py``
(auto-registered gradient/aggregate pipelines, __init_subclass__ rewiring
of byzantine_gradient through a pool pipeline).
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from byzpy_tpu.aggregators import CoordinateWiseMedian, CoordinateWiseTrimmedMean
from byzpy_tpu.attacks import EmpireAttack
from byzpy_tpu.engine.graph.graph import ComputationGraph, GraphInput, GraphNode
from byzpy_tpu.engine.graph.ops import CallableOp
from byzpy_tpu.engine.graph.pool import ActorPoolConfig
from byzpy_tpu.engine.node.application import (
    ByzantineNodeApplication,
    HonestNodeApplication,
    NodeApplication,
)
from byzpy_tpu.engine.node.distributed import (
    DistributedByzantineNode,
    DistributedHonestNode,
)
from byzpy_tpu.engine.parameter_server import ParameterServer


def _one_node_graph(fn, name="op", **inputs):
    return ComputationGraph([
        GraphNode(name=name, op=CallableOp(fn),
                  inputs={k: GraphInput(v) for k, v in inputs.items()})
    ])


def test_application_pipeline_registry_and_run():
    app = NodeApplication()
    app.register_pipeline(
        "double", _one_node_graph(lambda v: 2 * v, name="double", v="v"),
        metadata={"kind": "test"},
    )
    assert app.pipeline_names() == ["double"]
    assert app.pipeline_metadata("double") == {"kind": "test"}
    out = asyncio.run(app.run_pipeline("double", {"v": 21}))
    assert out["double"] == 42
    with pytest.raises(ValueError):
        app.register_pipeline("double", _one_node_graph(lambda v: v, v="v"))
    with pytest.raises(KeyError):
        asyncio.run(app.run_pipeline("missing"))


def test_reserved_names_guarded():
    app = HonestNodeApplication()
    with pytest.raises(ValueError):
        app.register_pipeline(
            "aggregate", _one_node_graph(lambda v: v, v="v")
        )
    app.register_aggregation(CoordinateWiseMedian())
    grads = [jnp.full((4,), v) for v in (1.0, 2.0, 9.0)]
    agg = asyncio.run(app.aggregate(grads))
    np.testing.assert_allclose(np.asarray(agg), 2.0)


def test_byzantine_application_attack_pipeline():
    app = ByzantineNodeApplication()
    app.register_attack(EmpireAttack(scale=-1.0))

    async def go():
        return await app.attack(honest_grads=[jnp.ones((3,)), 3 * jnp.ones((3,))])

    out = asyncio.run(go())
    np.testing.assert_allclose(np.asarray(out), -2.0)


class GradNode(DistributedHonestNode):
    def __init__(self, target, **kw):
        super().__init__(**kw)
        self.target = jnp.full((6,), float(target))
        self.w = jnp.zeros((6,))

    def next_batch(self):
        return None, None

    def honest_gradient(self, x, y):
        return 2.0 * (self.w - self.target)

    def apply_server_gradient(self, g):
        self.w = self.w - 0.25 * jnp.asarray(g)


class ScaledEmpire(DistributedByzantineNode):
    def next_batch(self):
        return None, None

    def apply_server_gradient(self, g):
        pass

    def byzantine_gradient(self, honest_gradients):
        stacked = jnp.stack([jnp.asarray(g) for g in honest_gradients])
        return -4.0 * jnp.mean(stacked, axis=0)


def test_distributed_honest_node_pipelines():
    async def go():
        node = GradNode(
            3.0,
            aggregator=CoordinateWiseMedian(),
            pool_config=ActorPoolConfig(backend="thread", count=2),
        )
        g = await node.honest_gradient_for_next_batch()
        np.testing.assert_allclose(np.asarray(g), -6.0)
        agg = await node.aggregate([jnp.ones((4,)), 5 * jnp.ones((4,)), jnp.ones((4,))])
        np.testing.assert_allclose(np.asarray(agg), 1.0)
        await node.close()

    asyncio.run(go())


def test_distributed_byzantine_rewiring():
    async def go():
        node = ScaledEmpire()
        out = await node.byzantine_gradient([jnp.ones((3,)), jnp.ones((3,))])
        np.testing.assert_allclose(np.asarray(out), -4.0)
        await node.close()

    asyncio.run(go())


def test_distributed_byzantine_requires_override():
    class NoOverride(DistributedByzantineNode):
        def next_batch(self):
            return None, None

        def apply_server_gradient(self, g):
            pass

    with pytest.raises(TypeError):
        NoOverride()


def test_distributed_honest_node_process_pool_sees_fresh_state():
    """Gradient subtasks on a process pool must re-pickle the node every
    round (cache_fn=False) so workers see post-update parameters — the
    stale-blob failure mode this guards against returned the round-1
    gradient forever."""

    async def go():
        node = GradNode(
            2.0,
            pool_config=ActorPoolConfig(backend="process", count=1),
        )
        try:
            g1 = await node.honest_gradient_for_next_batch()
            np.testing.assert_allclose(np.asarray(g1), -4.0)
            node.apply_server_gradient(g1)  # w: 0 -> 1
            g2 = await node.honest_gradient_for_next_batch()
            np.testing.assert_allclose(np.asarray(g2), -2.0)
        finally:
            await node.close()

    asyncio.run(go())


def test_distributed_nodes_in_parameter_server():
    async def go():
        honest = [GradNode(1.0) for _ in range(4)]
        byz = [ScaledEmpire()]
        ps = ParameterServer(
            honest, byz, aggregator=CoordinateWiseTrimmedMean(f=1)
        )
        for _ in range(25):
            await ps.round()
        for n in honest:
            np.testing.assert_allclose(np.asarray(n.w), 1.0, atol=5e-2)
        for n in honest + byz:
            await n.close()

    asyncio.run(go())

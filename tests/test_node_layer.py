"""Node layer: DecentralizedNode + contexts + router + cluster.

Mirrors the reference's in-process cluster test strategy
(ref: ``byzpy/engine/node/tests/test_topology_integration.py``).
"""

import asyncio

import numpy as np
import pytest

from byzpy_tpu.engine.graph.graph import ComputationGraph, GraphInput, GraphNode
from byzpy_tpu.engine.graph.ops import CallableOp
from byzpy_tpu.engine.node import (
    DecentralizedCluster,
    DecentralizedNode,
    InProcessContext,
    ProcessContext,
)
from byzpy_tpu.engine.peer_to_peer import Topology


# registry cleanup: conftest's autouse _clear_node_registries fixture


def _make_cluster(n, topology=None):
    topo = topology or Topology.complete(n)
    cluster = DecentralizedCluster(topo)
    for i in range(n):
        nid = f"node-{i}"
        cluster.add_node(DecentralizedNode(nid, InProcessContext(nid)))
    return cluster


def test_cluster_broadcast_and_handlers():
    async def run():
        cluster = _make_cluster(4)
        received = {f"node-{i}": [] for i in range(4)}

        async with cluster:
            for nid, node in cluster.nodes.items():
                async def handler(msg, nid=nid):
                    received[nid].append((msg.sender, msg.payload))
                node.register_handler("gossip", handler)

            await cluster.node("node-0").broadcast_message("gossip", 42)
            await asyncio.sleep(0.05)

        for i in range(1, 4):
            assert received[f"node-{i}"] == [("node-0", 42)]
        assert received["node-0"] == []  # no self-loop in complete topology

    asyncio.run(run())


def test_ring_topology_restricts_direct_sends():
    async def run():
        topo = Topology.ring(4, 1)
        cluster = _make_cluster(4, topo)
        async with cluster:
            n0 = cluster.node("node-0")
            await n0.send_message("node-1", "ping", "hi")  # edge exists
            with pytest.raises(ValueError, match="forbids"):
                await n0.send_message("node-2", "ping", "hi")  # no edge
            # replies skip the topology check
            await cluster.node("node-1").reply_message("node-0", "pong", "yo")
            msg = await n0.wait_for_message("pong", timeout=2)
            assert msg.payload == "yo"

    asyncio.run(run())


def test_wait_for_message_and_pipeline():
    async def run():
        topo = Topology.complete(2)
        cluster = _make_cluster(2, topo)
        async with cluster:
            a, b = cluster.node("node-0"), cluster.node("node-1")
            graph = ComputationGraph(
                nodes=[
                    GraphNode(
                        name="double",
                        op=CallableOp(lambda v: v * 2),
                        inputs={"v": GraphInput("v")},
                    )
                ]
            )
            a.register_pipeline("double", graph)
            out = await a.execute_pipeline("double", {"v": 21})
            assert out["double"] == 42

            # message triggers across nodes
            waiter = asyncio.ensure_future(a.wait_for_message("grad", timeout=2))
            await b.send_message("node-0", "grad", np.ones(3))
            msg = await waiter
            assert msg.sender == "node-1"
            np.testing.assert_array_equal(msg.payload, np.ones(3))

    asyncio.run(run())


def test_autonomous_task_and_shutdown():
    async def run():
        cluster = _make_cluster(2)
        ticks = []

        async def autonomous(node):
            while True:
                ticks.append(node.node_id)
                await asyncio.sleep(0.01)

        async with cluster:
            cluster.node("node-0").start_autonomous_task(autonomous)
            await asyncio.sleep(0.05)
        assert len(ticks) >= 2  # ran, then got cancelled by shutdown

    asyncio.run(run())


def test_unknown_pipeline_raises():
    async def run():
        cluster = _make_cluster(2)
        async with cluster:
            with pytest.raises(KeyError, match="no pipeline"):
                await cluster.node("node-0").execute_pipeline("nope")

    asyncio.run(run())


def _configure_child(node):
    """Picklable child-node config: a pipeline + an echo handler."""
    from byzpy_tpu.engine.graph.graph import ComputationGraph, GraphInput, GraphNode
    from byzpy_tpu.engine.graph.ops import CallableOp

    graph = ComputationGraph(
        nodes=[
            GraphNode(
                name="square",
                op=CallableOp(lambda v: v * v),
                inputs={"v": GraphInput("v")},
            )
        ]
    )
    node.register_pipeline("square", graph)

    async def echo(msg):
        await node.reply_message(msg.sender, "echo", msg.payload)

    node.register_handler("ping", echo)


@pytest.mark.slow
def test_process_context_pipeline_and_messaging():
    async def run():
        topo = Topology.complete(2)
        cluster = DecentralizedCluster(topo)
        parent = DecentralizedNode("parent", InProcessContext("parent"))
        child = DecentralizedNode(
            "child", ProcessContext("child", _configure_child)
        )
        cluster.add_node(parent)
        cluster.add_node(child)
        async with cluster:
            out = await child.execute_pipeline("square", {"v": 7})
            assert out["square"] == 49
            await parent.send_message("child", "ping", 123)
            msg = await parent.wait_for_message("echo", timeout=10)
            assert msg.payload == 123
            assert msg.sender == "child"

    asyncio.run(run())

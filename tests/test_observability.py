"""Observability core: spans, metrics registry, exporters, recorder, CLI.

The three contracts under test:

* **disabled is free** — ``span()`` with telemetry off returns one
  shared no-op object (no allocation) and records nothing;
* **formats are real** — the chrome-trace export opens as chrome-trace
  JSON, the Prometheus text endpoint renders exposition format 0.0.4
  (cumulative buckets, ``_sum``/``_count``), JSONL round-trips;
* **the shared percentile rule is the seed-era rule** — the stats
  views (overlap, serving) delegate to ``percentile_of_sorted`` and
  their outputs must be bit-identical to the formulas they replaced.
"""

import json

import numpy as np
import pytest

from byzpy_tpu import observability as obs
from byzpy_tpu.observability import metrics as obs_metrics
from byzpy_tpu.observability import tracing as obs_tracing
from byzpy_tpu.observability.recorder import FlightRecorder


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    """Every test starts disabled with a clean tracer ring."""
    obs.disable()
    obs_tracing.tracer().clear()
    obs_tracing.adopt_context(None)
    yield
    obs.disable()
    obs_tracing.tracer().clear()
    obs_tracing.adopt_context(None)


# ---------------------------------------------------------------------------
# spans / tracer
# ---------------------------------------------------------------------------


class TestSpans:
    def test_disabled_span_is_shared_noop(self):
        s1 = obs_tracing.span("a", round=1)
        s2 = obs_tracing.span("b")
        assert s1 is s2 is obs_tracing.NULL_SPAN
        assert obs_tracing.device_span("c") is obs_tracing.NULL_SPAN
        with s1:
            s1.set(x=1)  # no-op, must not raise
        obs_tracing.instant("d")
        assert obs_tracing.tracer().events() == []

    def test_span_records_complete_events_with_args(self):
        obs.enable()
        with obs_tracing.span("outer", track="test:track", round=7):
            with obs_tracing.span("inner") as sp:
                sp.set(m=3)
        events = obs_tracing.tracer().events()
        names = [ev["name"] for ev in events]
        assert names == ["inner", "outer"]  # closed in LIFO order
        inner, outer = events
        assert inner["ph"] == outer["ph"] == "X"
        assert inner["args"]["m"] == 3
        assert outer["args"]["round"] == 7
        assert inner["dur"] <= outer["dur"]

    def test_span_exception_path_records_error_and_propagates(self):
        obs.enable()
        with pytest.raises(ValueError):
            with obs_tracing.span("boom"):
                raise ValueError("x")
        (ev,) = obs_tracing.tracer().events()
        assert ev["args"]["error"] == "ValueError"

    def test_instant_events(self):
        obs.enable()
        obs_tracing.instant("tick", track="chaos", who="c1")
        (ev,) = obs_tracing.tracer().events()
        assert ev["ph"] == "i" and ev["args"]["who"] == "c1"

    def test_ring_is_bounded_and_counts_drops(self):
        tr = obs_tracing.Tracer(capacity=8)
        for i in range(20):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.events()) == 8
        assert tr.dropped == 12

    def test_chrome_trace_export(self, tmp_path):
        obs.enable()
        with obs_tracing.span("stage", track="tenant:m0", round=1):
            pass
        path = str(tmp_path / "trace.json")
        n = obs_tracing.tracer().export_chrome_trace(path)
        with open(path) as fh:
            doc = json.load(fh)
        assert n == len(doc["traceEvents"]) == 2  # metadata + span
        meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert meta[0]["args"]["name"] == "tenant:m0"
        span = [e for e in doc["traceEvents"] if e["ph"] == "X"][0]
        assert span["tid"] == meta[0]["tid"]
        assert {"ts", "dur", "pid"} <= set(span)

    def test_device_span_records_host_span(self):
        obs.enable()
        with obs_tracing.device_span("fold", m=4):
            pass
        (ev,) = obs_tracing.tracer().events()
        assert ev["name"] == "fold" and ev["args"]["m"] == 4


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_nested_spans_link_parent_child(self):
        obs.enable()
        with obs_tracing.span("outer") as outer:
            assert obs_tracing.current_context() == outer.context
            with obs_tracing.span("inner"):
                pass
            with obs_tracing.span("inner2"):
                pass
        assert obs_tracing.current_context() is None
        inner, inner2, outer_ev = obs_tracing.tracer().events()
        assert outer_ev["args"].get("parent") is None
        # siblings share the trace, carry distinct span ids, and both
        # point at the outer span
        assert inner["args"]["trace"] == outer_ev["args"]["trace"]
        assert inner2["args"]["trace"] == outer_ev["args"]["trace"]
        assert inner["args"]["span"] != inner2["args"]["span"]
        assert inner["args"]["parent"] == outer_ev["args"]["span"]
        assert inner2["args"]["parent"] == outer_ev["args"]["span"]

    def test_separate_roots_get_separate_traces(self):
        obs.enable()
        with obs_tracing.span("a"):
            pass
        with obs_tracing.span("b"):
            pass
        a, b = obs_tracing.tracer().events()
        assert a["args"]["trace"] != b["args"]["trace"]

    def test_context_scope_reparents_and_restores(self):
        obs.enable()
        remote = ("trace-x", "span-x")
        with obs_tracing.span("local") as local:
            with obs_tracing.context_scope(remote):
                with obs_tracing.span("child"):
                    pass
            with obs_tracing.span("sibling"):
                pass
        events = {ev["name"]: ev for ev in obs_tracing.tracer().events()}
        assert events["child"]["args"]["trace"] == "trace-x"
        assert events["child"]["args"]["parent"] == "span-x"
        # the scope restored the local context on exit
        assert events["sibling"]["args"]["parent"] == local.span_id

    def test_carry_context_crosses_executor_threads(self):
        from concurrent.futures import ThreadPoolExecutor

        obs.enable()
        with ThreadPoolExecutor(1) as pool:
            with obs_tracing.span("round") as round_span:

                def stage():
                    with obs_tracing.span("stage"):
                        pass

                pool.submit(obs_tracing.carry_context(stage)).result()
                # and WITHOUT carry: the stage orphans to its own trace
                pool.submit(stage).result()
        events = [
            ev for ev in obs_tracing.tracer().events()
            if ev["name"] == "stage"
        ]
        carried, bare = events
        assert carried["args"]["parent"] == round_span.span_id
        assert bare["args"].get("parent") is None
        assert bare["args"]["trace"] != round_span.trace_id

    def test_adopt_context_sets_position_and_survives_garbage(self):
        obs.enable()
        obs_tracing.adopt_context(("t1", "s1"))
        assert obs_tracing.current_context() == ("t1", "s1")
        obs_tracing.adopt_context("garbage-not-a-pair-of-two")  # ignored
        assert obs_tracing.current_context() == ("t1", "s1")
        obs_tracing.adopt_context(("t2", "s2"))
        with obs_tracing.span("child"):
            pass
        (ev,) = obs_tracing.tracer().events()
        assert ev["args"]["trace"] == "t2" and ev["args"]["parent"] == "s2"
        # None clears the position (also the fixtures' hygiene hook)
        obs_tracing.adopt_context(None)
        assert obs_tracing.current_context() is None

    def test_instant_links_into_enclosing_span(self):
        obs.enable()
        with obs_tracing.span("round") as r:
            obs_tracing.instant("slo.breach", burn=2.0)
        instant_ev = [
            ev for ev in obs_tracing.tracer().events() if ev["ph"] == "i"
        ][0]
        assert instant_ev["args"]["trace"] == r.trace_id
        assert instant_ev["args"]["parent"] == r.span_id

    def test_disabled_context_is_one_flag_check(self):
        assert obs_tracing.wire_context() is None
        assert obs_tracing.current_context() is None
        # disabled spans never touch the contextvar
        with obs_tracing.span("x"):
            assert obs_tracing.current_context() is None

    def test_chrome_trace_emits_cross_track_flow_events(self):
        obs.enable()
        with obs_tracing.span("root", track="root"):
            with obs_tracing.span("leg", track="shard:0"):
                pass
            with obs_tracing.span("same-track"):  # inherits root's? no:
                # default track = calling thread -> different tid than
                # the named root track, so this ALSO flows
                pass
        doc = obs_tracing.tracer().chrome_trace()
        flows = [e for e in doc["traceEvents"] if e.get("cat") == "flow"]
        starts = [e for e in flows if e["ph"] == "s"]
        ends = [e for e in flows if e["ph"] == "f"]
        assert len(starts) == len(ends) == 2
        assert {e["id"] for e in starts} == {e["id"] for e in ends}
        # flow binds the parent's track to the child's
        root_ev = [
            e for e in doc["traceEvents"]
            if e.get("ph") == "X" and e["name"] == "root"
        ][0]
        leg_ev = [
            e for e in doc["traceEvents"]
            if e.get("ph") == "X" and e["name"] == "leg"
        ][0]
        flow_pair = [
            (s, f) for s in starts for f in ends if s["id"] == f["id"]
            and f["tid"] == leg_ev["tid"]
        ]
        assert flow_pair and flow_pair[0][0]["tid"] == root_ev["tid"]

    def test_wire_frames_stamp_and_restore_context(self):
        from byzpy_tpu.engine.actor import wire

        obs.enable()
        with obs_tracing.span("client.submit") as submit:
            frame = wire.encode({"kind": "submit", "tenant": "m0"})
            ctx = submit.context
        with obs_tracing.context_scope(None):
            decoded = wire.decode(frame[4:])
            # the stamp is popped: consumers see what they were sent
            assert wire.TRACE_CTX_KEY not in decoded
            # ...and restored: the next span is the sender's child
            assert obs_tracing.current_context() == ctx
            with obs_tracing.span("serving.admission"):
                pass
        admission = obs_tracing.tracer().events()[-1]
        assert admission["args"]["parent"] == ctx[1]
        assert admission["args"]["trace"] == ctx[0]

    def test_unstamped_frames_leave_local_context_alone(self):
        from byzpy_tpu.engine.actor import wire

        frame = wire.encode({"kind": "submit"})  # disabled: no stamp
        obs.enable()
        with obs_tracing.span("local") as local:
            wire.decode(frame[4:])
            assert obs_tracing.current_context() == local.context

    def test_disabled_wire_bytes_identical(self):
        from byzpy_tpu.engine.actor import wire

        payload = {"kind": "submit", "tenant": "m0", "x": 1}
        off = wire.encode(payload)
        obs.enable()
        with obs_tracing.context_scope(None):
            on_no_span = wire.encode(payload)
        assert off == on_no_span


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


class TestMetrics:
    def test_counter_gauge_basics(self):
        reg = obs_metrics.MetricsRegistry()
        c = reg.counter("byzpy_t_total", "help", {"tenant": "a"})
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        g = reg.gauge("byzpy_t_depth")
        g.set(5)
        g.dec()
        assert g.value == 4

    def test_registry_get_or_create_identity_and_type_conflict(self):
        reg = obs_metrics.MetricsRegistry()
        a = reg.counter("byzpy_x_total", labels={"k": "v"})
        b = reg.counter("byzpy_x_total", labels={"k": "v"})
        assert a is b
        c = reg.counter("byzpy_x_total", labels={"k": "w"})
        assert c is not a
        with pytest.raises(ValueError):
            reg.gauge("byzpy_x_total")
        with pytest.raises(ValueError):
            reg.counter("not a name!")

    def test_histogram_buckets_and_percentiles(self):
        h = obs_metrics.Histogram("h", buckets=(1.0, 2.0, 4.0, 8.0))
        for v in (0.5, 1.5, 1.6, 3.0, 100.0):
            h.observe(v)
        assert h.count == 5
        assert h.sum == pytest.approx(106.6)
        assert h.counts == [1, 2, 1, 0, 1]  # last bin = overflow
        # p50 (rank 2) lands in the (1, 2] bucket
        assert 1.0 <= h.percentile(50) <= 2.0
        # p100 lands in overflow — clamped to the top finite edge
        assert h.percentile(100) == 8.0
        assert obs_metrics.Histogram("e").percentile(50) == 0.0

    def test_prometheus_text_format(self):
        reg = obs_metrics.MetricsRegistry()
        reg.counter("byzpy_a_total", "things", {"tenant": "x"}).inc(3)
        reg.gauge("byzpy_b", "level").set(2.5)
        h = reg.histogram("byzpy_c_seconds", "lat", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = reg.prometheus_text()
        lines = text.strip().split("\n")
        assert "# TYPE byzpy_a_total counter" in lines
        assert 'byzpy_a_total{tenant="x"} 3' in lines
        assert "# HELP byzpy_b level" in lines
        assert "byzpy_b 2.5" in lines
        # histogram: cumulative buckets + +Inf + sum/count
        assert 'byzpy_c_seconds_bucket{le="0.1"} 1' in lines
        assert 'byzpy_c_seconds_bucket{le="1"} 1' in lines
        assert 'byzpy_c_seconds_bucket{le="+Inf"} 2' in lines
        assert "byzpy_c_seconds_count 2" in lines
        assert any(line.startswith("byzpy_c_seconds_sum 5.05") for line in lines)
        # one TYPE header per family
        assert sum(1 for line in lines if line.startswith("# TYPE")) == 3

    def test_jsonl_roundtrip(self, tmp_path):
        reg = obs_metrics.MetricsRegistry()
        reg.counter("byzpy_j_total", labels={"t": "a"}).inc(7)
        h = reg.histogram("byzpy_j_seconds", buckets=(1.0,))
        h.observe(0.5)
        h.observe(2.0)
        path = str(tmp_path / "m.jsonl")
        assert reg.to_jsonl(path) == 2
        recs = {r["name"]: r for r in obs_metrics.iter_jsonl(path)}
        assert recs["byzpy_j_total"]["value"] == 7
        assert recs["byzpy_j_total"]["labels"] == {"t": "a"}
        assert recs["byzpy_j_seconds"]["count"] == 2
        assert recs["byzpy_j_seconds"]["overflow"] == 1

    def test_percentile_of_sorted_matches_seed_formulas(self):
        rng = np.random.default_rng(0)
        for n in (1, 2, 5, 33, 100):
            vals = sorted(rng.normal(size=n).tolist())
            for pct in (0, 10, 50, 90, 99, 100):
                # the pre-telemetry RoundOverlapStats.lag_percentile rule
                rank = max(0, min(n - 1, int(round(pct / 100.0 * (n - 1)))))
                assert obs_metrics.percentile_of_sorted(vals, pct) == vals[rank]
                # the pre-telemetry RoundStats.latency_percentiles_s rule
                top = n - 1
                assert (
                    obs_metrics.percentile_of_sorted(vals, pct)
                    == vals[min(top, int(round((pct / 100.0) * top)))]
                )
        assert obs_metrics.percentile_of_sorted([], 50) == 0.0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def _rounds(self, n):
        for r in range(n):
            with obs_tracing.span("serving.round", round=r):
                # stage spans ALSO carry a round arg — only the
                # round-lifecycle span may count as a window boundary,
                # or a 3-round window would shrink to one round
                with obs_tracing.span("serving.bucket_pad", round=r):
                    pass
                with obs_tracing.span("serving.fold"):
                    pass

    def test_dump_keeps_last_n_rounds(self, tmp_path):
        obs.enable()
        self._rounds(10)
        fr = FlightRecorder(last_rounds=3)
        dump = fr.dump(str(tmp_path / "dump.json"), reason="test")
        rounds = {
            ev["args"]["round"]
            for ev in dump["events"]
            if ev["name"] == "serving.round"
        }
        assert rounds == {7, 8, 9}
        # the retained rounds come with ALL their stage spans
        pads = {
            ev["args"]["round"]
            for ev in dump["events"]
            if ev["name"] == "serving.bucket_pad"
        }
        assert pads == {7, 8, 9}
        assert dump["reason"] == "test"
        assert isinstance(dump["metrics"], dict)
        with open(tmp_path / "dump.json") as fh:
            assert json.load(fh)["kind"] == "byzpy_tpu.flight_recorder"

    def test_crash_hook_dumps_and_uninstalls(self, tmp_path):
        import sys

        obs.enable()
        self._rounds(2)
        path = str(tmp_path / "crash.json")
        fr = FlightRecorder(last_rounds=8)
        prev = sys.excepthook
        fr.install(path)
        try:
            assert sys.excepthook is not prev
            # simulate an unhandled exception reaching the hook chain
            # (the chained previous hook prints the traceback to stderr)
            try:
                raise RuntimeError("boom")
            except RuntimeError:
                sys.excepthook(*sys.exc_info())
        finally:
            fr.uninstall()
        assert sys.excepthook is prev
        with open(path) as fh:
            dump = json.load(fh)
        assert dump["reason"] == "excepthook:RuntimeError"
        assert len(dump["events"]) > 0


# ---------------------------------------------------------------------------
# CLI summarizer
# ---------------------------------------------------------------------------


class TestCli:
    def _trace_file(self, tmp_path):
        obs.enable()
        for r in range(3):
            with obs_tracing.span(
                "serving.round", track="tenant:m0", round=r, tenant="m0"
            ):
                with obs_tracing.span("serving.fold", m=4):
                    pass
        path = str(tmp_path / "t.json")
        obs_tracing.tracer().export_chrome_trace(path)
        return path

    def test_summarize_text(self, tmp_path, capsys):
        from byzpy_tpu.observability.__main__ import main

        assert main([self._trace_file(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "serving.round" in out and "serving.fold" in out
        assert "per-stage latency breakdown" in out
        assert "slow rounds" in out

    def test_summarize_json_structure(self, tmp_path, capsys):
        from byzpy_tpu.observability.__main__ import main

        assert main([self._trace_file(tmp_path), "--json", "--top", "2"]) == 0
        doc = json.loads(capsys.readouterr().out)
        stages = {s["stage"] for s in doc["stages"]}
        assert stages == {"serving.round", "serving.fold"}
        assert len(doc["slow_rounds"]) == 2
        assert doc["slow_rounds"][0]["tenant"] == "m0"
        for s in doc["stages"]:
            assert s["count"] == 3
            assert s["p99_ms"] >= s["p50_ms"] >= 0

    def test_summarize_flight_dump(self, tmp_path, capsys):
        from byzpy_tpu.observability.__main__ import main

        obs.enable()
        with obs_tracing.span("serving.round", round=0):
            pass
        path = str(tmp_path / "d.json")
        FlightRecorder().dump(path)
        assert main([path]) == 0
        assert "serving.round" in capsys.readouterr().out

    def test_wire_residual_section(self, tmp_path, capsys):
        from byzpy_tpu.observability.__main__ import main
        from byzpy_tpu.parallel.comms import serving_ingress_bytes

        reg = obs_metrics.MetricsRegistry()
        law = serving_ingress_bytes(512, precision="off", signed=False)
        reg.counter(
            "byzpy_serving_ingress_bytes_total", labels={"tenant": "m0"}
        ).inc(10 * law)
        reg.counter(
            "byzpy_serving_submit_frames_total", labels={"tenant": "m0"}
        ).inc(10)
        reg.gauge("byzpy_serving_tenant_dim", labels={"tenant": "m0"}).set(512)
        reg.gauge(
            "byzpy_wire_info", labels={"precision": "off", "signed": "0"}
        ).set(1)
        mpath = str(tmp_path / "m.jsonl")
        reg.to_jsonl(mpath)
        trace = self._trace_file(tmp_path)
        assert main([trace, "--metrics", mpath, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        (row,) = doc["wire_residuals"]
        assert row["tenant"] == "m0" and row["frames"] == 10
        assert row["residual"] == pytest.approx(0.0, abs=1e-6)


# ---------------------------------------------------------------------------
# stats views on the shared machinery
# ---------------------------------------------------------------------------


class TestStatsViews:
    def test_overlap_stats_thin_view(self):
        from byzpy_tpu.engine.overlap import RoundOverlapStats

        stats = RoundOverlapStats(mode="stream")
        lags = [0.5, 0.1, 0.9, 0.3]
        for v in lags:
            stats.observe_lag(v)
        assert stats.ingest_lags_s == lags  # raw per-round samples kept
        s = sorted(lags)
        for pct in (0, 50, 99, 100):
            rank = max(0, min(3, int(round(pct / 100.0 * 3))))
            assert stats.lag_percentile(pct) == s[rank]

    def test_overlap_stats_publish_into_registry_when_enabled(self):
        from byzpy_tpu.engine.overlap import RoundOverlapStats

        hist = obs_metrics.registry().histogram(
            "byzpy_overlap_ingest_lag_seconds"
        )
        before = hist.count
        stats = RoundOverlapStats()
        stats.observe_lag(0.01)  # disabled: list only
        assert hist.count == before
        obs.enable()
        stats.observe_lag(0.02)
        assert hist.count == before + 1

    def test_round_stats_percentiles_unchanged(self):
        from byzpy_tpu.serving.credits import RoundStats

        rs = RoundStats()
        rng = np.random.default_rng(1)
        for v in rng.uniform(0, 1, size=57):
            rs.record(float(v), 4)
        data = sorted(rs.latencies_s)
        top = len(data) - 1
        p50, p99 = rs.latency_percentiles_s(50, 99)
        assert p50 == data[min(top, int(round(0.50 * top)))]
        assert p99 == data[min(top, int(round(0.99 * top)))]
        assert RoundStats().latency_percentiles_s(50, 99) == (0.0, 0.0)


# ---------------------------------------------------------------------------
# compat ports (utils.metrics shim)
# ---------------------------------------------------------------------------


class TestCompat:
    def test_metrics_logger_publishes_gauges(self):
        from byzpy_tpu.observability.compat import MetricsLogger

        with MetricsLogger() as log:
            log.log(0, loss=2.5, note="text")
            log.log(1, loss=1.25)
        g = obs_metrics.registry().gauge("byzpy_logged_loss")
        assert g.value == 1.25
        assert log.series("loss") == [2.5, 1.25]

    def test_step_timer_feeds_histogram(self):
        from byzpy_tpu.observability.compat import StepTimer

        h = obs_metrics.registry().histogram("byzpy_step_seconds")
        before = h.count
        t = StepTimer()
        t.start()
        assert t.stop() >= 0.0
        assert h.count == before + 1

    def test_utils_metrics_shim_warns_and_reexports(self):
        import importlib
        import sys
        import warnings

        sys.modules.pop("byzpy_tpu.utils.metrics", None)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            mod = importlib.import_module("byzpy_tpu.utils.metrics")
        assert any(
            issubclass(w.category, DeprecationWarning) for w in caught
        )
        from byzpy_tpu.observability.compat import MetricsLogger

        assert mod.MetricsLogger is MetricsLogger

"""docs/observability.md ↔ observability/catalog.py parity.

The catalog is the machine-readable single source of truth the
byzlint ``METRIC-CONTRACT`` rule checks code against; the docs tables
are its human rendering. This test parses every metric and span row
out of the markdown and pins BOTH directions: a docs row naming an
uncatalogued instrument is drift, and a catalogued instrument with no
docs row is an undocumented instrument. Metric types must match
cell-for-cell (one name, one type).
"""

from __future__ import annotations

import os
import re

from byzpy_tpu.observability import catalog

DOCS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs",
    "observability.md",
)

_TYPES = ("counter", "gauge", "histogram")


def _doc_tables():
    """Parse the markdown tables: ``(metrics, metric_prefixes, spans,
    span_prefixes)``. Metric rows may carry several backticked names
    per cell with one shared type or a slash-separated type per name;
    ``<...>`` placeholders declare prefix families."""
    with open(DOCS, encoding="utf-8") as fh:
        text = fh.read()
    metrics, metric_prefixes = {}, set()
    spans, span_prefixes = set(), set()
    for line in text.splitlines():
        if not line.startswith("| `"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        names = re.findall(r"`([a-zA-Z0-9_.<>]+)`", cells[0])
        if not names:
            continue
        types = [t.strip() for t in cells[1].split("/")] if len(cells) > 1 else []
        if all(t in _TYPES for t in types) and types:
            # a metric row: one shared type, or one type per name
            assert len(types) in (1, len(names)), f"ragged metric row: {line}"
            for i, name in enumerate(names):
                t = types[i] if len(types) == len(names) else types[0]
                if "<" in name:
                    metric_prefixes.add(name.split("<", 1)[0])
                else:
                    assert metrics.get(name, t) == t, (
                        f"{name} documented under two types"
                    )
                    metrics[name] = t
            continue
        for name in names:
            # span rows: dotted labels only (skip config/code lookalikes)
            if "." not in name or name.startswith("byzpy_"):
                continue
            if "<" in name:
                span_prefixes.add(name.split("<", 1)[0])
            else:
                spans.add(name)
    return metrics, metric_prefixes, spans, span_prefixes


def test_catalog_is_well_formed():
    assert catalog.METRICS, "empty metric catalog"
    assert catalog.SPANS, "empty span catalog"
    for name, mtype in catalog.METRICS.items():
        assert name.startswith("byzpy_"), name
        assert mtype in _TYPES, (name, mtype)
    for prefix in catalog.METRIC_PREFIXES:
        assert prefix.startswith("byzpy_"), prefix


def test_docs_metric_tables_match_catalog_both_ways():
    metrics, prefixes, _spans, _sp = _doc_tables()
    assert metrics, "no metric rows parsed from docs/observability.md"
    mismatched = {
        n: (t, catalog.METRICS.get(n))
        for n, t in metrics.items()
        if catalog.METRICS.get(n) != t
    }
    assert not mismatched, f"docs rows drifting from catalog: {mismatched}"
    undocumented = sorted(set(catalog.METRICS) - set(metrics))
    assert not undocumented, f"catalogued but not in docs: {undocumented}"
    assert prefixes == set(catalog.METRIC_PREFIXES)


def test_docs_span_table_matches_catalog_both_ways():
    _m, _p, spans, span_prefixes = _doc_tables()
    assert spans, "no span rows parsed from docs/observability.md"
    unknown = sorted(spans - set(catalog.SPANS))
    assert not unknown, f"docs span rows drifting from catalog: {unknown}"
    undocumented = sorted(set(catalog.SPANS) - spans)
    assert not undocumented, f"catalogued but not in docs: {undocumented}"
    assert span_prefixes == set(catalog.SPAN_PREFIXES)

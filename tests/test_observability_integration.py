"""Observability wired through the real fabrics.

* a serving round closed through the production path emits the full
  lifecycle span taxonomy and publishes the tenant's registry metrics;
* the TCP ingress answers an HTTP GET with a Prometheus scrape of the
  registry (and wire frames still work on the same port);
* the actor-mode ParameterServer emits round/gather/aggregate/broadcast
  spans and round metrics;
* chaos digests are BIT-IDENTICAL with telemetry on or off (the
  regression pin for the EventTrace mirror);
* the overhead budget: the disabled path costs one flag check (no
  allocation), and enabled telemetry projects to <5% of a serving
  round's latency.
"""

import asyncio
import json
import time

import numpy as np
import pytest

from byzpy_tpu import observability as obs
from byzpy_tpu.observability import metrics as obs_metrics
from byzpy_tpu.observability import tracing as obs_tracing

#: Every stage the ISSUE's acceptance criterion names for one serving
#: round recorded end-to-end (ingress decode is TCP-only, asserted in
#: the socket test below).
LIFECYCLE_SPANS = {
    "serving.admission",
    "serving.round",
    "serving.cohort_close",
    "serving.bucket_pad",
    "serving.fold",
    "serving.device_step",
    "serving.broadcast",
}


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    obs.disable()
    obs_tracing.tracer().clear()
    obs_tracing.adopt_context(None)
    yield
    obs.disable()
    obs_tracing.tracer().clear()
    obs_tracing.adopt_context(None)


def _frontend(dim=32, name="m0", min_bucket=2):
    from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
    from byzpy_tpu.serving import ServingFrontend, TenantConfig

    return ServingFrontend(
        [
            TenantConfig(
                name=name,
                aggregator=CoordinateWiseTrimmedMean(f=1),
                dim=dim,
                window_s=0.01,
                cohort_cap=16,
                min_bucket=min_bucket,
            )
        ]
    )


def _submit_round(fe, dim=32, m=4, tenant="m0", round_id=None):
    rid = fe.round_of(tenant) if round_id is None else round_id
    rng = np.random.default_rng(0)
    for i in range(m):
        req = {
            "kind": "submit",
            "tenant": tenant,
            "client": f"c{i}",
            "round": rid,
            "gradient": rng.normal(size=dim).astype(np.float32),
        }
        reply = fe.handle_request(req)
        assert reply["accepted"], reply
    closed = fe.close_round_nowait(tenant)
    assert closed is not None
    return closed


class TestServingLifecycle:
    def test_round_emits_every_lifecycle_span(self):
        obs.enable()
        fe = _frontend()
        _submit_round(fe)
        names = {ev["name"] for ev in obs_tracing.tracer().events()}
        assert LIFECYCLE_SPANS <= names, LIFECYCLE_SPANS - names
        # round span carries tenant/round/m and rides the tenant track
        rounds = [
            ev
            for ev in obs_tracing.tracer().events()
            if ev["name"] == "serving.round"
        ]
        assert rounds[0]["args"]["tenant"] == "m0"
        assert rounds[0]["args"]["round"] == 0
        assert rounds[0]["args"]["m"] == 4
        # the executor-thread stages are attributed to the tenant too
        for stage in ("serving.fold", "serving.device_step",
                      "serving.bucket_pad"):
            (ev,) = [
                e for e in obs_tracing.tracer().events()
                if e["name"] == stage
            ]
            assert ev["args"]["tenant"] == "m0", stage

    def test_round_publishes_registry_metrics(self):
        obs.enable()
        reg = obs_metrics.registry()
        acc = reg.counter(
            "byzpy_serving_submissions_total",
            labels={"tenant": "m1", "outcome": "accepted"},
        )
        fe = _frontend(name="m1")
        before = acc.value
        _submit_round(fe, tenant="m1")
        assert acc.value == before + 4
        rounds = reg.counter("byzpy_serving_rounds_total", labels={"tenant": "m1"})
        assert rounds.value >= 1
        lat = reg.histogram(
            "byzpy_serving_round_latency_seconds", labels={"tenant": "m1"}
        )
        assert lat.count >= 1
        cohort = reg.histogram(
            "byzpy_serving_cohort_size", labels={"tenant": "m1"},
            buckets=obs_metrics.SIZE_BUCKETS,
        )
        assert cohort.count >= 1
        dim = reg.gauge("byzpy_serving_tenant_dim", labels={"tenant": "m1"})
        assert dim.value == 32

    def test_disabled_round_records_nothing(self):
        fe = _frontend(name="m2")
        _submit_round(fe, tenant="m2")
        assert obs_tracing.tracer().events() == []

    def test_stats_dict_unchanged_by_telemetry(self):
        # the back-compat stats() shim must not depend on the flag
        fe_off = _frontend(name="m3")
        _submit_round(fe_off, tenant="m3")
        off = fe_off.stats()["m3"]
        obs.enable()
        fe_on = _frontend(name="m4")
        _submit_round(fe_on, tenant="m4")
        on = fe_on.stats()["m4"]
        for key in ("rounds", "round_id", "mean_cohort", "failed_rounds",
                    "outstanding", "queue_depth", "min_cohort"):
            assert off[key] == on[key], key


@pytest.mark.slow
class TestPrometheusIngress:
    def test_http_scrape_and_wire_frames_share_the_port(self):
        async def run():
            from byzpy_tpu.serving.frontend import ServingClient

            obs.enable()
            fe = _frontend(name="m5", dim=64)
            host, port = await fe.serve()
            # 1) wire submissions over TCP (counts ingress bytes/frames)
            client = ServingClient()
            await client.connect(host, port)
            for i in range(4):
                ack = await client.submit(
                    "m5", f"c{i}", 0, np.ones(64, np.float32)
                )
                assert ack["accepted"], ack
            await client.close()
            fe.close_round_nowait("m5")
            # 2) HTTP scrape on the SAME port
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            raw = await reader.read(-1)
            writer.close()
            await fe.close()
            return raw, fe

        raw, fe = asyncio.run(run())
        head, _, body = raw.partition(b"\r\n\r\n")
        assert head.startswith(b"HTTP/1.0 200 OK")
        assert b"text/plain; version=0.0.4" in head
        text = body.decode()
        assert "# TYPE byzpy_serving_submissions_total counter" in text
        assert 'byzpy_serving_rounds_total{tenant="m5"}' in text
        assert "byzpy_serving_round_latency_seconds_bucket" in text
        assert 'byzpy_wire_info{precision="off",signed="0"} 1' in text
        # ingress accounting followed the submit frames
        reg = obs_metrics.registry()
        frames = reg.counter(
            "byzpy_serving_submit_frames_total", labels={"tenant": "m5"}
        )
        nbytes = reg.counter(
            "byzpy_serving_ingress_bytes_total", labels={"tenant": "m5"}
        )
        assert frames.value >= 4
        assert nbytes.value == fe._tenants["m5"].ingress_bytes
        # the TCP path adds the ingress decode span to the lifecycle
        names = {ev["name"] for ev in obs_tracing.tracer().events()}
        assert "serving.ingress.decode" in names
        # ...and the cross-process linkage holds on the REAL socket
        # path: every admission span is the client submit span's child
        # (the decode span's exit must not wipe the adopted context)
        events = obs_tracing.tracer().events()
        submit_ids = {
            ev["args"]["span"]
            for ev in events
            if ev["name"] == "serving.client.submit"
        }
        admissions = [
            ev for ev in events if ev["name"] == "serving.admission"
        ]
        assert len(submit_ids) >= 4 and len(admissions) >= 4
        for ev in admissions:
            assert ev["args"].get("parent") in submit_ids, ev["args"]

    def test_scrape_does_not_count_as_bad_frame(self):
        async def run():
            fe = _frontend(name="m6")
            host, port = await fe.serve()
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(b"GET / HTTP/1.0\r\n\r\n")
            await writer.drain()
            raw = await reader.read(-1)
            writer.close()
            await fe.close()
            return raw, fe.bad_frames

        raw, bad = asyncio.run(run())
        assert raw.startswith(b"HTTP/1.0 200 OK")
        assert bad == 0


class TestActorPS:
    def test_round_spans_and_metrics(self):
        from byzpy_tpu.aggregators import CoordinateWiseMedian
        from byzpy_tpu.engine.parameter_server import ParameterServer

        class _Node:
            def __init__(self, v):
                self.v = np.full(8, v, np.float32)

            def honest_gradient_for_next_batch(self):
                return self.v

            def apply_server_gradient(self, g):
                pass

        async def run():
            obs.enable()
            ps = ParameterServer(
                honest_nodes=[_Node(1.0), _Node(2.0), _Node(3.0)],
                aggregator=CoordinateWiseMedian(),
            )
            return await ps.round()

        agg = asyncio.run(run())
        np.testing.assert_allclose(np.asarray(agg), np.full(8, 2.0))
        names = {ev["name"] for ev in obs_tracing.tracer().events()}
        assert {"ps.round", "ps.gather", "ps.aggregate", "ps.broadcast"} <= names
        reg = obs_metrics.registry()
        assert (
            reg.counter("byzpy_ps_rounds_total", labels={"mode": "serial"}).value
            >= 1
        )
        assert reg.histogram("byzpy_ps_round_seconds").count >= 1


class TestWireCounters:
    def test_encode_decode_count_frames_and_bytes(self):
        from byzpy_tpu.engine.actor import wire

        obs.enable()
        reg = obs_metrics.registry()
        tx_f = reg.counter("byzpy_wire_frames_total", labels={"direction": "tx"})
        tx_b = reg.counter("byzpy_wire_bytes_total", labels={"direction": "tx"})
        rx_f = reg.counter("byzpy_wire_frames_total", labels={"direction": "rx"})
        f0, b0, r0 = tx_f.value, tx_b.value, rx_f.value
        frame = wire.encode({"kind": "submit", "gradient": np.ones(128)})
        wire.decode(frame[4:])
        assert tx_f.value == f0 + 1
        assert tx_b.value == b0 + len(frame)
        assert rx_f.value == r0 + 1

    def test_disabled_counts_nothing(self):
        from byzpy_tpu.engine.actor import wire

        reg = obs_metrics.registry()
        tx = reg.counter("byzpy_wire_frames_total", labels={"direction": "tx"})
        before = tx.value
        wire.encode({"x": 1})
        assert tx.value == before


class TestChaosTelemetry:
    def _scenario(self):
        from byzpy_tpu.chaos import ArrivalModel, AttackSpec, Scenario

        return Scenario(
            name="obs",
            seed=77,
            n_clients=6,
            n_byzantine=1,
            dim=8,
            rounds=4,
            aggregator="trimmed_mean",
            aggregator_params={"f": 1},
            attack=AttackSpec(name="sign_flip"),
            arrivals=ArrivalModel(kind="bernoulli", p=0.9),
        )

    def test_digest_identical_with_telemetry_on(self):
        from byzpy_tpu.chaos import ChaosHarness

        r_off = ChaosHarness(self._scenario()).run()
        obs.enable()
        r_on = ChaosHarness(self._scenario()).run()
        # the regression pin: mirroring events onto the tracer must not
        # perturb the replay/determinism contract
        assert r_off.trace.digest() == r_on.trace.digest()
        assert len(r_off.trace) == len(r_on.trace)
        chaos_events = [
            ev
            for ev in obs_tracing.tracer().events()
            if ev["name"].startswith("chaos.")
        ]
        assert len(chaos_events) == len(r_on.trace)
        kinds = {ev["name"] for ev in chaos_events}
        assert "chaos.round_close" in kinds and "chaos.arrive" in kinds

    def test_event_trace_chrome_export(self, tmp_path):
        from byzpy_tpu.chaos import ChaosHarness

        report = ChaosHarness(self._scenario()).run()
        path = str(tmp_path / "chaos.json")
        n = report.trace.to_chrome_trace(path)
        with open(path) as fh:
            doc = json.load(fh)
        assert n == len(doc["traceEvents"]) > 0
        rounds = [
            e for e in doc["traceEvents"] if e["name"] == "chaos.round"
        ]
        # every round_close (closed OR held) becomes a complete span
        assert len(rounds) == len(report.trace.of_kind("round_close"))
        # virtual time: round r spans start at r * window_s seconds (µs)
        s = self._scenario()
        for ev in rounds:
            r = ev["args"]["round"]
            assert ev["ts"] <= r * s.window_s * 1e6 + s.window_s * 1e6


class TestShardedTier:
    def _coordinator(self, tenant="shardobs", n_shards=2, dim=16):
        from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
        from byzpy_tpu.serving import ShardedCoordinator, TenantConfig

        return ShardedCoordinator(
            [
                TenantConfig(
                    name=tenant,
                    aggregator=CoordinateWiseTrimmedMean(f=1),
                    dim=dim,
                    window_s=0.01,
                    cohort_cap=16,
                )
            ],
            n_shards,
            quorum=1,
        )

    def _run_rounds(self, co, tenant="shardobs", dim=16, rounds=2):
        rng = np.random.default_rng(3)
        vecs = []
        for r in range(rounds):
            for i in range(8):
                ok, reason = co.submit(
                    tenant, f"c{i:02d}", r, rng.normal(size=dim).astype(np.float32),
                    seq=r,
                )
                assert ok, reason
            closed = co.close_round_nowait(tenant)
            assert closed is not None
            vecs.append(np.asarray(closed[2]))
        return vecs

    def test_sharded_round_stitches_into_one_tree(self):
        from byzpy_tpu.observability import critical_path as cp

        obs.enable()
        co = self._coordinator()
        self._run_rounds(co, rounds=2)
        events = obs_tracing.tracer().events()
        rounds = cp.round_roots(cp.build_forest(events))
        assert [r.name for r in rounds] == [
            "serving.sharded_round", "serving.sharded_round",
        ]
        tree = rounds[0]
        child_names = {c.name for c in tree.children}
        assert "serving.shard_close" in child_names
        assert "serving.fold_merge" in child_names
        # shard_close spans carry the shard dim; the merge span links
        # every partial's carried context
        shard_dims = {
            c.shard for c in tree.children
            if c.name == "serving.shard_close"
        }
        assert shard_dims == {0, 1}
        (merge,) = [
            c for c in tree.children if c.name == "serving.fold_merge"
        ]
        assert len(merge.args["links"]) == 2
        assert {"serving.device_step"} <= {
            c.name for c in merge.children
        }
        # blame partitions the round makespan
        summary = cp.summarize(events)
        assert summary["max_blame_residual"] < 1e-6
        stages = {r["stage"] for r in summary["stages"]}
        assert "serving.fold_merge" in stages

    def test_partial_fold_wire_carries_context_and_links_remote_root(self):
        from byzpy_tpu.serving.sharded import (
            decode_partial_fold, encode_partial_fold,
        )

        obs.enable()
        co = self._coordinator(tenant="shardwire")
        rng = np.random.default_rng(4)
        for i in range(8):
            co.submit(
                "shardwire", f"c{i:02d}", 0,
                rng.normal(size=16).astype(np.float32), seq=0,
            )
        partials = [
            s.close_partial("shardwire") for s in co.shards
        ]
        partials = [p for p in partials if p is not None]
        assert partials and all(p.trace_ctx is not None for p in partials)
        # the wire round-trip preserves the context (and the frame dict
        # exposes no telemetry key to the consumer)
        p = partials[0]
        q = decode_partial_fold(encode_partial_fold(p)[4:])
        assert q.trace_ctx == p.trace_ctx
        res = co.merge_partials("shardwire", partials)
        assert res is not None
        merges = [
            ev for ev in obs_tracing.tracer().events()
            if ev["name"] == "serving.fold_merge"
        ]
        assert merges[-1]["args"]["links"] == [
            f"{p.trace_ctx[0]}:{p.trace_ctx[1]}" for p in partials
        ]

    def test_aggregates_bit_identical_propagation_on_off(self):
        # the acceptance pin: trace-context propagation must never
        # perturb round arithmetic
        co_off = self._coordinator(tenant="paroff")
        off = self._run_rounds(co_off, tenant="paroff", rounds=2)
        obs.enable()
        co_on = self._coordinator(tenant="paron")
        on = self._run_rounds(co_on, tenant="paron", rounds=2)
        for a, b in zip(off, on, strict=True):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.slow
    def test_scrape_serves_shard_tenant_and_slo_families_together(self):
        from byzpy_tpu.observability.slo import SLOWatchdog, TenantSLO

        async def run():
            obs.enable()
            co = self._coordinator(tenant="shardslo")
            self._run_rounds(co, tenant="shardslo", rounds=2)
            watchdog = SLOWatchdog(
                [
                    TenantSLO(
                        tenant="shardslo", accepted_p99_s=5.0,
                        failed_round_rate=0.5,
                    )
                ]
            )
            watchdog.evaluate()
            # the ROOT ingress: shard 0's inner frontend's TCP port
            # (the registry is process-wide — one scrape sees the
            # whole tier)
            host, port = await co.shards[0].frontend.serve()
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            raw = await reader.read(-1)
            writer.close()
            await co.shards[0].frontend.close()
            watchdog.close()
            return raw

        raw = asyncio.run(run())
        head, _, body = raw.partition(b"\r\n\r\n")
        assert head.startswith(b"HTTP/1.0 200 OK")
        text = body.decode()
        # the three families the sharded tier's operators dashboard on,
        # in ONE scrape: per-shard, per-tenant, and SLO
        for needle in (
            'byzpy_shard_rounds_total{tenant="shardslo"}',
            'byzpy_shard_accepted_total{shard="0",tenant="shardslo"}',
            'byzpy_serving_submissions_total{outcome="accepted",tenant="shardslo"}',
            'byzpy_slo_burn_rate{objective="accepted_p99",tenant="shardslo"}',
            "# TYPE byzpy_slo_breaches_total counter",
        ):
            assert needle in text, f"scrape missing {needle!r}"


class TestOverheadBudget:
    def test_disabled_span_is_flag_check_cheap(self):
        # the disabled front door must be a flag check returning the
        # shared singleton — bound the per-call cost generously so CI
        # noise cannot flake this (measured ~0.1-0.3 µs)
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            with obs_tracing.span("hot"):
                pass
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 5e-6, f"disabled span cost {per_call * 1e9:.0f} ns"

    def test_enabled_overhead_projects_under_5pct_of_round_latency(self):
        # deterministic form of the <5% p99 budget: measure the enabled
        # span cost, count the spans a serving round emits, and compare
        # the projected telemetry cost against the measured round time.
        # Best-of-5 trials: the microbench runs inside a loaded test
        # process, and a GC pause mid-trial must not fail the budget —
        # the minimum is the cost the instrumentation actually has.
        obs.enable()
        n = 2_000
        span_cost = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n):
                with obs_tracing.span("hot", round=1, m=4):
                    pass
            span_cost = min(span_cost, (time.perf_counter() - t0) / n)
        obs_tracing.tracer().clear()

        # serving-bench-shaped round (dim 1024), not a toy one — the
        # budget is relative, so an artificially tiny round would fail
        # instrumentation that is fine at any realistic cohort
        fe = _frontend(name="m7", dim=1024)
        # warm the jit cache so the measured rounds are steady-state
        _submit_round(fe, dim=1024, m=8, tenant="m7")
        spans_per_round = len(obs_tracing.tracer().events())
        assert spans_per_round >= len(LIFECYCLE_SPANS)
        durations = []
        for _ in range(20):
            t0 = time.perf_counter()
            _submit_round(fe, dim=1024, m=8, tenant="m7")
            durations.append(time.perf_counter() - t0)
        durations.sort()
        p99 = obs_metrics.percentile_of_sorted(durations, 99)
        projected = span_cost * spans_per_round
        assert projected < 0.05 * p99, (
            f"telemetry projects {projected * 1e6:.1f} µs/round against a "
            f"{p99 * 1e6:.1f} µs p99 round"
        )

    def test_enabled_vs_disabled_round_latency_budget(self):
        # end-to-end guard with generous slack (CI boxes are noisy):
        # enabled must stay within 1.5x + 2 ms of the disabled median
        def measure(tenant):
            fe = _frontend(name=tenant, dim=256)
            _submit_round(fe, dim=256, m=4, tenant=tenant)  # warm compile
            durs = []
            for _ in range(15):
                t0 = time.perf_counter()
                _submit_round(fe, dim=256, m=4, tenant=tenant)
                durs.append(time.perf_counter() - t0)
            return sorted(durs)[len(durs) // 2]

        obs.disable()
        base = measure("m8")
        obs.enable()
        on = measure("m9")
        assert on <= base * 1.5 + 0.002, (base, on)

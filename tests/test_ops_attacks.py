import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from scipy_stub import ndtri_oracle  # noqa: F401  (defined below if scipy absent)

from byzpy_tpu.ops import attack_ops


def randx(n=8, d=15, seed=0):
    return np.random.default_rng(seed).normal(size=(n, d)).astype(np.float32)


def test_sign_flip():
    g = randx(1, 10)[0]
    got = np.asarray(attack_ops.sign_flip(jnp.asarray(g)))
    np.testing.assert_allclose(got, -g, rtol=1e-6)
    got2 = np.asarray(attack_ops.sign_flip(jnp.asarray(g), scale=2.5))
    np.testing.assert_allclose(got2, 2.5 * g, rtol=1e-6)


def test_empire():
    h = randx(6, 9)
    got = np.asarray(attack_ops.empire(jnp.asarray(h)))
    np.testing.assert_allclose(got, -h.mean(0), rtol=1e-5, atol=1e-6)


def test_little_formula():
    h = randx(9, 14, seed=1)
    f, n_total = 2, 11  # 9 honest + 2 byzantine
    got = np.asarray(attack_ops.little(jnp.asarray(h), f=f, n_total=n_total))
    s = n_total // 2 + 1 - f
    p = (n_total - s) / n_total
    z = ndtri_oracle(p)
    mu = h.mean(0)
    sigma = h.std(0)  # ddof=0, matching reference var = mean((x-mu)^2)
    np.testing.assert_allclose(got, mu + z * sigma, rtol=1e-4, atol=1e-4)


def test_gaussian_seeded_reproducible():
    key = jax.random.PRNGKey(42)
    a = np.asarray(attack_ops.gaussian(key, (100,), mu=1.0, sigma=2.0))
    b = np.asarray(attack_ops.gaussian(key, (100,), mu=1.0, sigma=2.0))
    np.testing.assert_array_equal(a, b)
    assert abs(a.mean() - 1.0) < 1.0


def test_inf_vector():
    v = np.asarray(attack_ops.inf_vector((7,)))
    assert np.all(np.isposinf(v))


def test_mimic():
    h = randx(5, 8, seed=2)
    got = np.asarray(attack_ops.mimic(jnp.asarray(h), epsilon=3))
    np.testing.assert_array_equal(got, h[3])


def test_label_flip_grad():
    # linear softmax model; flipping labels must change the gradient
    w = jnp.zeros((4, 3))
    x = jnp.asarray(randx(6, 4, seed=3))
    y = jnp.asarray(np.array([0, 1, 2, 0, 1, 2]))

    def loss(params, xb, yb):
        logits = xb @ params
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(logp[jnp.arange(xb.shape[0]), yb])

    grad_fn = jax.grad(loss)
    g_flip = attack_ops.label_flip_grad(grad_fn, w, x, y, num_classes=3)
    g_true = grad_fn(w, x, y)
    assert not np.allclose(np.asarray(g_flip), np.asarray(g_true))
    # mapping route: identity mapping == honest gradient
    ident = jnp.asarray(np.arange(3))
    g_ident = attack_ops.label_flip_grad(grad_fn, w, x, y, mapping=ident)
    np.testing.assert_allclose(np.asarray(g_ident), np.asarray(g_true), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_attack_ops_match_numpy_oracles(seed):
    """Seeded fuzz: empire / little / mimic / sign_flip against float64
    numpy oracles across random shapes, scales, and hyper-parameters."""
    rng = np.random.default_rng(6000 + seed)
    n = int(rng.integers(4, 20))
    d = int(rng.integers(8, 200))
    h64 = rng.normal(size=(n, d)) * 10.0 ** float(rng.integers(-2, 3))
    h = jnp.asarray(h64.astype(np.float32))
    scale = float(rng.uniform(-3.0, 3.0))
    np.testing.assert_allclose(
        np.asarray(attack_ops.empire(h, scale=scale)),
        scale * h64.mean(0), rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(attack_ops.sign_flip(h[0], scale=scale)),
        scale * h64[0], rtol=1e-5, atol=1e-6,
    )
    eps = int(rng.integers(0, n))
    np.testing.assert_array_equal(
        np.asarray(attack_ops.mimic(h, epsilon=eps)), np.asarray(h[eps])
    )
    n_total = n + int(rng.integers(1, 6))
    f = int(rng.integers(1, n_total // 2 + 1))
    got = np.asarray(attack_ops.little(h, f=f, n_total=n_total))
    s = n_total // 2 + 1 - f
    p = min(max((n_total - s) / n_total, 1e-12), 1 - 1e-12)
    from statistics import NormalDist

    z = NormalDist().inv_cdf(p)
    mu = h64.mean(0)
    sigma = np.sqrt(((h64 - mu) ** 2).mean(0))
    np.testing.assert_allclose(got, mu + z * sigma, rtol=1e-3, atol=1e-3)

import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from byzpy_tpu.ops import preagg


def randx(n=10, d=21, seed=0):
    return np.random.default_rng(seed).normal(size=(n, d)).astype(np.float32)


def test_clip_rows():
    x = randx()
    t = 1.5
    got = np.asarray(preagg.clip_rows(jnp.asarray(x), threshold=t))
    norms = np.linalg.norm(x, axis=1, keepdims=True)
    want = x * np.minimum(1.0, t / np.maximum(norms, 1e-12))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert np.all(np.linalg.norm(got, axis=1) <= t + 1e-4)


def test_bucket_means_ragged_last_bucket():
    x = randx(10, 7)
    perm = np.arange(10)  # identity permutation -> deterministic oracle
    got = np.asarray(preagg.bucket_means(jnp.asarray(x), jnp.asarray(perm), bucket_size=4))
    assert got.shape == (3, 7)  # ceil(10/4)
    np.testing.assert_allclose(got[0], x[0:4].mean(0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got[1], x[4:8].mean(0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got[2], x[8:10].mean(0), rtol=1e-5, atol=1e-6)


def test_bucket_means_respects_permutation():
    x = randx(6, 5, seed=1)
    perm = np.array([5, 4, 3, 2, 1, 0])
    got = np.asarray(preagg.bucket_means(jnp.asarray(x), jnp.asarray(perm), bucket_size=3))
    np.testing.assert_allclose(got[0], x[[5, 4, 3]].mean(0), rtol=1e-5, atol=1e-6)


def test_nnm():
    x = randx(8, 12, seed=2)
    f = 2
    got = np.asarray(preagg.nnm(jnp.asarray(x), f=f))
    k = 8 - f
    d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    idx = np.argsort(d2, axis=1, kind="stable")[:, :k]
    want = np.stack([x[idx[i]].mean(0) for i in range(8)])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_nnm_nonfinite_rule_documented():
    """Pin the documented deviation (PARITY.md "Documented deviations"):
    a mixed row whose k-nearest selection includes a non-finite neighbor
    becomes ALL-NaN — not coordinate-wise NaN / preserved ±inf as the
    reference's gather would give. Rows whose selection stays finite must
    be exactly the finite-neighborhood mean.

    Construction: one tainted row sorts last for every row (inf/NaN
    distance), so at f=1 each finite row selects exactly the 7 finite
    rows (one shared, unambiguous mean) while the tainted row itself
    goes NaN; at f=0 every selection includes the taint -> all NaN."""
    x = randx(8, 6, seed=7)
    x[1, 3] = np.inf  # tainted row (non-finite squared norm)
    finite_mean = np.delete(x, 1, axis=0).mean(0)

    got = np.asarray(preagg.nnm(jnp.asarray(x), f=1))
    assert np.isnan(got[1]).all(), "tainted row must be all-NaN"
    for i in (0, 2, 3, 4, 5, 6, 7):
        assert np.isfinite(got[i]).all(), f"row {i} selection is finite"
        np.testing.assert_allclose(got[i], finite_mean, rtol=1e-4, atol=1e-5)

    got0 = np.asarray(preagg.nnm(jnp.asarray(x), f=0))
    assert np.isnan(got0).all(), "f=0: every selection includes the taint"


def test_arc_clip():
    x = randx(10, 9, seed=3)
    x[7] *= 30  # large-norm outlier must get clipped
    f = 3
    got = np.asarray(preagg.arc_clip(jnp.asarray(x), f=f))
    n = 10
    nb_clipped = min(max(int(math.floor((2.0 * f / n) * (n - f))), 0), n - 1)
    cut_off = n - nb_clipped
    norms = np.linalg.norm(x, axis=1)
    threshold = np.sort(norms)[max(0, cut_off - 1)]
    want = x * np.minimum(1.0, threshold / np.maximum(norms, 1e-12))[:, None]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    assert np.linalg.norm(got[7]) <= threshold + 1e-3


def test_arc_f0_identity():
    x = randx(5, 6, seed=4)
    got = np.asarray(preagg.arc_clip(jnp.asarray(x), f=0))
    np.testing.assert_allclose(got, x, rtol=1e-6, atol=1e-6)


def test_preagg_stream_class_api_matches_per_round():
    """K buffered rounds through PreAggregator.pre_aggregate_stream must
    equal per-round pre_aggregate() calls (NNM has a fused stream
    override; Clipping uses the default scan)."""
    import jax.numpy as jnp

    from byzpy_tpu.pre_aggregators import Clipping, NearestNeighborMixing

    rng = np.random.default_rng(12)
    rounds = [
        [jnp.asarray(rng.normal(size=(24,)).astype(np.float32)) for _ in range(7)]
        for _ in range(3)
    ]
    for pre in (NearestNeighborMixing(f=2), Clipping(threshold=1.5)):
        got = pre.pre_aggregate_stream(rounds)
        assert len(got) == 3
        for k in range(3):
            want = pre.pre_aggregate(rounds[k])
            assert len(got[k]) == len(want)
            for a, b in zip(got[k], want, strict=True):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
                )
    assert NearestNeighborMixing(f=1).pre_aggregate_stream([]) == []


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_preagg_ops_match_numpy_oracles(seed):
    """Seeded fuzz: clip_rows / bucket_means / arc_clip against float64
    numpy oracles across random shapes and hyper-parameters."""
    import math as _math

    rng = np.random.default_rng(6500 + seed)
    n = int(rng.integers(4, 24))
    d = int(rng.integers(8, 120))
    x64 = rng.normal(size=(n, d)) * 10.0 ** float(rng.integers(-1, 3))
    x = jnp.asarray(x64.astype(np.float32))

    tau = float(rng.uniform(0.1, 50.0))
    norms = np.sqrt((x64 ** 2).sum(1))
    want = x64 * np.minimum(1.0, tau / np.maximum(norms, 1e-12))[:, None]
    np.testing.assert_allclose(
        np.asarray(preagg.clip_rows(x, threshold=tau)), want, rtol=1e-4,
        atol=1e-4,
    )

    b = int(rng.integers(1, n + 1))
    perm = rng.permutation(n)
    got = np.asarray(preagg.bucket_means(x, jnp.asarray(perm), bucket_size=b))
    xp = x64[perm]
    nb = _math.ceil(n / b)
    want = np.stack([xp[i * b : (i + 1) * b].mean(0) for i in range(nb)])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    f = int(rng.integers(0, n + 1))
    got = np.asarray(preagg.arc_clip(x, f=f))
    nb_clipped = min(max(int(_math.floor((2.0 * f / n) * (n - f))), 0), n - 1)
    cut_off = n - nb_clipped
    thr = np.sort(norms)[max(0, cut_off - 1)]
    want = x64 * np.minimum(1.0, thr / np.maximum(norms, 1e-12))[:, None]
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
